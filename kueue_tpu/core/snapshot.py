"""Per-tick snapshot of the admitted-state cache.

Counterpart of reference pkg/cache/snapshot.go: deep-copies active
ClusterQueues, rebuilds cohorts with accumulated requestable resources and
usage (lending-aware, snapshot.go:160-201), and exposes the
add/remove-workload simulation primitive used by preemption
(snapshot.go:41-67).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from kueue_tpu import features
from kueue_tpu import knobs
from kueue_tpu.api.types import ResourceFlavor
from kueue_tpu.core.cache import (
    Cache,
    CachedClusterQueue,
    Cohort,
    FlavorResourceQuantities,
    frq_clone,
)
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.tracing import TRACER
from kueue_tpu.utils import native_ledger

_ledger = native_ledger.load()


class Snapshot:
    __slots__ = ("cluster_queues", "resource_flavors",
                 "inactive_cluster_queues", "structure_version", "topology")

    def __init__(self):
        self.cluster_queues: Dict[str, CachedClusterQueue] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.inactive_cluster_queues: Set[str] = set()
        # Cache.structure_version at build time: the cheap invalidation key
        # for anything derived from specs (e.g. the solver's CQ encoding).
        self.structure_version = 0
        # Frozen topology leaf occupancy ({flavor: leaf_used}) when any
        # flavor declares a TopologySpec; None otherwise (the no-op gate).
        self.topology = None

    @staticmethod
    def build(cache: Cache) -> "Snapshot":
        snap = Snapshot()
        snap.structure_version = cache.structure_version
        snap.resource_flavors = dict(cache.resource_flavors)
        if cache.topology.flavors:
            snap.topology = cache.topology.view()
        for name, cq in cache.cluster_queues.items():
            if not cq.active():
                snap.inactive_cluster_queues.add(name)
                continue
            snap.cluster_queues[name] = _snapshot_cq(cq)
        cohort_copies: Dict[str, Cohort] = {}
        for cohort in cache.cohorts.values():
            cohort_copy = Cohort(cohort.name,
                                 spec=cache.cohort_specs.get(cohort.name))
            cohort_copies[cohort.name] = cohort_copy
            for member in cohort.members:
                if not member.active():
                    continue
                cq_copy = snap.cluster_queues[member.name]
                _accumulate(cq_copy, cohort_copy)
                cq_copy.cohort = cohort_copy
                cohort_copy.members.add(cq_copy)
                cohort_copy.allocatable_generation += cq_copy.allocatable_generation
        if cache.cohort_specs:
            _build_hierarchy(snap, cache, cohort_copies)
        return snap

    # Preemption simulation primitives (reference: snapshot.go:41-67).

    def remove_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.remove_workload_usage(wi, cohort_too=True)

    def add_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.add_workload_usage(wi, cohort_too=True)


def _snapshot_cq(cq: CachedClusterQueue) -> CachedClusterQueue:
    cc = CachedClusterQueue.__new__(CachedClusterQueue)
    cc.name = cq.name
    cc.cohort = None
    cc.cohort_name = cq.cohort_name
    cc.resource_groups = cq.resource_groups  # immutable per tick
    cc.rg_by_resource = cq.rg_by_resource
    cc.usage = frq_clone(cq.usage)
    # Snapshot consumers (solver, preemption sim, cohort aggregation) only
    # read reserving usage; the admitted split stays cache-side (it feeds
    # LocalQueue status, not the tick).
    cc.admitted_usage = {}
    cc.workloads = dict(cq.workloads)
    cc.namespace_selector = cq.namespace_selector
    cc.preemption = cq.preemption
    cc.flavor_fungibility = cq.flavor_fungibility
    cc.admission_checks = set(cq.admission_checks)
    cc.fair_weight = cq.fair_weight
    cc.guaranteed_quota = cq.guaranteed_quota if features.enabled(features.LENDING_LIMIT) else {}
    cc.allocatable_generation = cq.allocatable_generation
    cc.usage_version = cq.usage_version
    cc._dirty_sinks = None  # snapshot sim mutations never dirty the cache
    cc.has_missing_flavors = cq.has_missing_flavors
    cc.is_stopped = cq.is_stopped
    return cc


def _build_hierarchy(snap: "Snapshot", cache: Cache,
                     nodes: Dict[str, Cohort]) -> None:
    """Link the cohort tree (KEP-79): create nodes for spec-only cohorts
    and parent chains, wire parent/children, and deactivate every
    ClusterQueue in a structure that contains a cycle (the KEP's mandated
    failure mode: stop all new admissions in the affected tree)."""
    def get_node(name: str) -> Cohort:
        node = nodes.get(name)
        if node is None:
            node = Cohort(name, spec=cache.cohort_specs.get(name))
            nodes[name] = node
        return node

    # Materialize spec cohorts and their parent chains.
    pending = list(cache.cohort_specs)
    while pending:
        name = pending.pop()
        node = get_node(name)
        spec = node.spec
        if spec is not None and spec.parent and spec.parent not in nodes:
            pending.append(spec.parent)
            get_node(spec.parent)

    for node in nodes.values():
        if node.spec is not None and node.spec.parent:
            parent = nodes[node.spec.parent]
            node.parent = parent
            parent.children.append(node)

    # Cycle detection: each node has at most one parent, so walking up with
    # a visited set finds any rho-shaped structure.
    broken: set = set()
    for node in nodes.values():
        seen = []
        cur = node
        while cur is not None and cur.name not in broken:
            if cur in seen:
                broken.update(n.name for n in seen)
                break
            seen.append(cur)
            cur = cur.parent
        else:
            if cur is not None:  # reached an already-broken node
                broken.update(n.name for n in seen)

    if broken:
        for name in broken:
            for member in list(nodes[name].members):
                snap.inactive_cluster_queues.add(member.name)
                del snap.cluster_queues[member.name]
            nodes[name].members.clear()
            nodes[name].note_members_changed()
            nodes[name].parent = None
            nodes[name].children = []


class SnapshotMirror:
    """Incrementally maintained tick snapshot.

    The reference deep-copies the whole cache every tick
    (snapshot.go:95-129) — O(CQs x flavors x workloads), the scaling hazard
    SURVEY §3.2 flags at north-star scale. The mirror keeps ONE persistent
    Snapshot across ticks and re-clones only ClusterQueues whose cache
    `usage_version` moved since they were last mirrored, rebuilding cohort
    aggregates only for cohorts with a re-cloned member.

    Lockstep fast path: the scheduler mirrors every assume/forget it makes
    (`note_admission`/`note_removal`) using the *same* mutation functions
    the cache uses, so in the steady state a refresh is pure version
    comparison. External mutations (evictions, workload deletes, CQ spec
    updates) are caught by the version checks; structural changes
    (`Cache.structure_version`) or hierarchical cohort trees fall back to
    a full rebuild.

    Preemption-target search mutates the snapshot but restores it exactly
    (preemption.py _minimal_preemptions), so sim traffic needs no special
    handling — the mirrored state stays equal to the versions it recorded.
    """

    def __init__(self, cache: Cache):
        self.cache = cache
        self._snap: Optional[Snapshot] = None
        self._base: Dict[str, int] = {}   # cq name -> mirrored usage_version
        self._key = None
        # Admitted-usage view provider (duck-typed; set by the scheduler
        # when the solver keeps an AdmittedArena): a callable returning
        # (enc, arena, structure_version) or None. When available and the
        # generations line up, flush_pending rewrites each touched
        # ClusterQueue's usage dict straight from the arena's committed
        # per-CQ tensor — reading the clamped cohort delta off the
        # arrays — instead of walking every pending item's usage dicts.
        self._admitted_view = None
        # Startup capture of the rebuild-drill flag; it is only ever
        # BRANCHED on (flush vs incremental apply), and the two paths
        # are byte-identical by the arena A/B contract — the value never
        # shapes a decision record.
        self._arena_flush_forced = knobs.flag("KUEUE_TPU_ARENA_FLUSH")  # kueuelint: disable=TNT01
        # CQ names whose usage moved since the last refresh (fed by the
        # cache's dirty-sink hook) — the refresh visits only these.
        self._dirty: set = set()
        cache.register_dirty_sink(self._dirty)
        # Deferred lockstep mutations: the snapshot must stay FROZEN for
        # the duration of a tick (the admission cycle's cohort bookkeeping
        # counts this cycle's admissions separately, scheduler.go:204-275),
        # so note_admission/note_removal queue here and apply at the next
        # refresh.
        self._pending: List[
            Tuple[int, object, str, int, int, Optional[WorkloadInfo]]] = []
        # Monotonic count of snapshot mutations (lockstep applies and
        # re-clones). A pipelined tick records it at dispatch; a different
        # value at completion means the snapshot moved under the in-flight
        # solve and FIT decisions must be re-validated.
        self.mutation_count = 0
        # Ledger version last mirrored into the snapshot's topology view.
        self._topo_version: Optional[int] = None

    def detach(self) -> None:
        """Unsubscribe from the cache's dirty marks. Call when retiring a
        mirror whose cache lives on (scheduler replacement) — otherwise
        the abandoned sink keeps accumulating names on every mutation."""
        self.cache.unregister_dirty_sink(self._dirty)

    def bind_admitted_view(self, provider) -> None:
        """Attach the admitted-usage view provider (see __init__)."""
        self._admitted_view = provider

    def refresh(self) -> Snapshot:
        cache = self.cache
        key = (cache.structure_version,
               features.enabled(features.LENDING_LIMIT),
               features.enabled(features.FAIR_SHARING))
        # Hierarchical trees refresh incrementally too: the tree WIRING
        # (parents/children, spec quotas, cycle-breaking) is structural —
        # any change bumps structure_version and rebuilds wholesale — while
        # usage churn only moves member ClusterQueues, and the KEP-79
        # feasibility walk (core/hierarchy.py) reads member CQs through
        # cohort.members rather than pre-accumulated node fields, so the
        # dirty-CQ re-clone below keeps the tree view exact.
        if self._snap is None or key != self._key:
            self._pending.clear()
            self._dirty.clear()
            self.mutation_count += 1
            self._snap = Snapshot.build(cache)
            self._key = key
            self._base = {name: cq.usage_version
                          for name, cq in cache.cluster_queues.items()}
            self._topo_version = cache.topology.version
            return self._snap

        snap = self._snap
        if cache.topology.flavors or snap.topology is not None:
            # Topology leaf occupancy re-copies only when the ledger moved
            # (admissions/releases bearing topology assignments); the view
            # is a handful of small arrays.
            if self._topo_version != cache.topology.version:
                snap.topology = (cache.topology.view()
                                 if cache.topology.flavors else None)
                self._topo_version = cache.topology.version
        self.flush_pending()
        dirty_cohorts: Dict[str, Cohort] = {}
        dirty_names = self._dirty
        if not dirty_names:
            return snap
        reclones = 0
        with TRACER.phase("snapshot.dirty") as dirty_span:
            while dirty_names:
                # Atomic pop-drain: a concurrent mutator thread re-adding a
                # name AFTER the pop is preserved for this loop or the next
                # refresh — list()+clear() could drop a mark added between
                # the two and leave that CQ permanently stale.
                try:
                    name = dirty_names.pop()
                except KeyError:
                    break
                cq = cache.cluster_queues.get(name)
                if cq is None or self._base.get(name) == cq.usage_version:
                    continue
                if not cq.active() or name in snap.inactive_cluster_queues:
                    # Snapshot.build excludes inactive CQs entirely (the
                    # reference skips them in snapshot.go); a usage-only
                    # change on a stopped/broken CQ must not re-insert it —
                    # just track the version so we don't revisit every
                    # refresh. The snapshot-side exclusion check matters for
                    # cohort-cycle deactivation (KEP-79): the cache-side
                    # active() cannot see it, and re-inserting would leave a
                    # phantom cohortless CQ that a from-scratch build
                    # excludes.
                    self._base[name] = cq.usage_version
                    continue
                self.mutation_count += 1
                reclones += 1
                self._base[name] = cq.usage_version
                old = snap.cluster_queues.get(name)
                fresh = _snapshot_cq(cq)
                snap.cluster_queues[name] = fresh
                cohort = old.cohort if old is not None else None
                if cohort is None and cq.cohort is not None:
                    cohort = next(
                        (c.cohort for c in snap.cluster_queues.values()
                         if c.cohort is not None
                         and c.cohort.name == cq.cohort.name), None)
                if cohort is not None:
                    if old is not None:
                        cohort.members.discard(old)
                    cohort.members.add(fresh)
                    cohort.note_members_changed()
                    fresh.cohort = cohort
                    if old is not None and old.cohort is cohort \
                            and cohort.name not in dirty_cohorts:
                        # Delta path: only this member's usage moved, so
                        # fold (fresh - old) into the cohort aggregates
                        # instead of re-accumulating every member — the
                        # requestable side is structural (any quota change
                        # bumps structure_version and rebuilds wholesale).
                        _accumulate_member_delta(old, fresh, cohort)
                    else:
                        # Membership changed shape (first clone of a CQ
                        # the snapshot didn't hold, or a cohort already
                        # marked): re-accumulate the whole cohort below.
                        dirty_cohorts[cohort.name] = cohort

            for cohort in dirty_cohorts.values():
                cohort.requestable_resources = {}
                cohort.usage = {}
                cohort.allocatable_generation = 0
                for member in cohort.members:
                    _accumulate(member, cohort)
                    cohort.allocatable_generation += \
                        member.allocatable_generation
            dirty_span.set("reclones", reclones)
        if reclones:
            REGISTRY.tick_phase_seconds.observe(
                "snapshot.reclones", value=float(reclones))
        return snap

    # -- lockstep fast path (mirrors cache.assume/forget) -------------------

    def note_admission(self, wl, wi: Optional[WorkloadInfo] = None) -> None:
        """Record a just-assumed workload (call right after
        cache.assume_workload). The cache version captured here is the
        assume bump itself; any later external mutation moves the cache
        version past it and forces a re-clone — versions, not trust,
        decide (same contract as UsageEncoder.apply_delta). Pass the info
        returned by assume_workload to reuse its precomputed totals."""
        if self._snap is None or wl.admission is None:
            return
        cq_name = wl.admission.cluster_queue
        cache_cq = self.cache.cluster_queues.get(cq_name)
        if cache_cq is None:
            return
        self._pending.append((1, wl, cq_name, cache_cq.usage_version,
                              cache_cq.allocatable_generation, wi))

    def note_removal(self, wl, wi: Optional[WorkloadInfo] = None) -> None:
        """Mirror of cache.forget_workload / delete after an apply failure
        (call right after the cache mutation). Pass the info the cache
        released so the flush can subtract its exact accounted totals
        without re-deriving them."""
        if self._snap is None or wl.admission is None:
            return
        cq_name = wl.admission.cluster_queue
        cache_cq = self.cache.cluster_queues.get(cq_name)
        if cache_cq is None:
            return
        # The ClusterQueue name is captured NOW: eviction reconciling
        # clears wl.admission right after noting the removal, so deriving
        # the queue at flush time would silently drop the mutation — and
        # when a later same-CQ admission in the same batch records a newer
        # base version, the dirty-walk re-clone that would otherwise heal
        # the drop is masked, leaving the mirror overcounting usage.
        self._pending.append((-1, wl, cq_name, cache_cq.usage_version,
                              cache_cq.allocatable_generation, wi))

    def flush_pending(self) -> None:
        """Apply queued lockstep mutations to the snapshot. Called at every
        tick boundary (refresh) and, when ticks are pipelined, at the start
        of a tick's completion phase — so a finishing tick validates
        against state that includes every previously finished admission.

        The per-item walk is inlined (no add/remove_workload_usage
        wrappers, no dirty marks — clones have no sinks): at north-star
        scale this loop folds ~2k completion/admission mutations per tick."""
        if self._snap is None or not self._pending:
            return
        with TRACER.phase("snapshot.flush") as sp:
            pending, self._pending = self._pending, []
            self.mutation_count += len(pending)
            snap_cqs = self._snap.cluster_queues
            base = self._base
            self._flush_items(pending, snap_cqs, base)
            # How many distinct ClusterQueues this flush actually touched
            # — the delta-flush evidence an operator reads off a slow
            # snapshot phase (items vs fan-out).
            sp.set("cqs_flushed", len({item[2] for item in pending}))
            sp.set("items", len(pending))

    def _flush_items(self, pending, snap_cqs, base) -> None:
        # Path order, measured on the northstar shape: the C++ per-item
        # walk (flush_mirror) wins when built; the arena rewrite wins
        # over the pure-Python walk everywhere it applies — including
        # the LendingLimit path, which never had a native twin.
        # KUEUE_TPU_ARENA_FLUSH=1 forces the arena path first (the
        # differential goldens pin it decision-identical).
        native_ok = (_ledger is not None
                     and not features.enabled(features.LENDING_LIMIT)
                     and all(item[5] is not None or item[0] < 0
                             for item in pending))
        if not native_ok or self._arena_flush_forced:
            view = self._admitted_view() \
                if self._admitted_view is not None else None
            if view is not None and self._flush_items_arena(
                    pending, snap_cqs, base, view):
                return
        if native_ok:
            # Native walk (ledger.cpp flush_mirror): identical add/remove +
            # usage/cohort-usage/version bookkeeping; the Python loop below
            # stays the info-less-addition implementation.
            _ledger.flush_mirror(snap_cqs, base, pending)
            return
        for sign, wl, cq_name, version, alloc_gen, wi in pending:
            cq = snap_cqs.get(cq_name)
            if cq is None:
                continue
            if sign > 0:
                if wi is None:
                    wi = WorkloadInfo(wl, cluster_queue=cq.name)
                cq.workloads[wi.key] = wi
                cq.usage_version += 1
                cq._apply_usage(wi, 1, cq.cohort is not None, False)
            else:
                wi = cq.workloads.pop(wl.key, None)
                if wi is None:
                    continue
                cq.usage_version += 1
                cq._apply_usage(wi, -1, cq.cohort is not None, False)
                # The cache bumped allocatable_generation on the delete;
                # the mirrored clone must track it for resume-state
                # invalidation.
                cq.allocatable_generation = alloc_gen
            base[cq.name] = version


    def _flush_items_arena(self, pending, snap_cqs, base, view) -> bool:
        """Arena-backed flush: per-item work shrinks to the membership
        bookkeeping (one dict insert/remove each), and each touched
        ClusterQueue's usage dict is rewritten ONCE from the
        AdmittedArena's committed per-CQ tensor — the cache truth the
        same assume/forget events maintain — with the lending-clamped
        cohort delta folded per changed pair (the clamp deltas telescope,
        so the aggregate equals the per-item sequence exactly). Returns
        False when the view does not cover this snapshot (encoding
        rotated, or a pending ClusterQueue sits outside the encoding) —
        the caller falls back to the per-item walk.

        The arena rows are read without its lock: a torn read can only
        land values newer than the captured versions, which the dirty
        walk's version comparison re-clones next refresh (the same heal
        contract every lockstep path here relies on)."""
        enc, arena, structure_version = view
        snap = self._snap
        if snap is None or snap.structure_version != structure_version:
            return False
        cq_index = enc.cq_index
        # Atomicity pre-scan (nothing may be half-applied before a
        # fallback): every pending ClusterQueue must sit inside the
        # encoding or outside the snapshot entirely.
        seen_ok = set()
        for item in pending:
            nm = item[2]
            if nm not in seen_ok:
                if nm not in cq_index and nm in snap_cqs:
                    return False
                seen_ok.add(nm)
        touched: Dict[str, CachedClusterQueue] = {}
        for sign, wl, cq_name, version, alloc_gen, wi in pending:
            cq = snap_cqs.get(cq_name)
            if cq is None:
                continue
            if sign > 0:
                if wi is None:
                    wi = WorkloadInfo(wl, cluster_queue=cq.name)
                cq.workloads[wi.key] = wi
            else:
                if cq.workloads.pop(wl.key, None) is None:
                    # Not mirrored (already removed): leave the version
                    # mismatch in place so the dirty walk re-clones.
                    continue
                # The cache bumped allocatable_generation on the delete;
                # the mirrored clone must track it for resume-state
                # invalidation.
                cq.allocatable_generation = alloc_gen
            cq.usage_version += 1
            base[cq_name] = version
            touched[cq_name] = cq
        if not touched:
            return True
        lending = features.enabled(features.LENDING_LIMIT)
        for name, cq in touched.items():
            ci = cq_index.get(name)
            if ci is None:
                continue
            row = arena.cq_usage_row(ci)
            cohort = cq.cohort
            cuse = cohort.usage if cohort is not None else None
            usage = cq.usage
            for fname, rows in enc.flush_pairs(ci, cq):
                resources = usage.get(fname)
                if resources is None:
                    continue
                fres = cuse.get(fname) if cuse is not None else None
                for rname, fr in rows:
                    new = int(row[fr])
                    old = resources.get(rname)
                    if new == old or old is None:
                        continue
                    resources[rname] = new
                    if fres is None:
                        continue
                    if lending:
                        # Per-member lending clamp (max(0, used - g)):
                        # the aggregated delta is the clamped movement.
                        g = cq._guaranteed(fname, rname)
                        d = max(0, new - g) - max(0, old - g)
                    else:
                        d = new - old
                    if d and rname in fres:
                        fres[rname] += d
        return True


def _accumulate_member_delta(old: CachedClusterQueue,
                             fresh: CachedClusterQueue,
                             cohort: Cohort) -> None:
    """Fold one re-cloned member's usage movement into its cohort
    aggregates: the incremental twin of `_accumulate` for the refresh's
    dirty walk. Between snapshots of the same structure only `usage` and
    the allocatable-generation sum can move — the requestable side
    derives from quotas, and any quota/membership change bumps
    structure_version and rebuilds the snapshot wholesale. The usage key
    set is fixed per structure (CachedClusterQueue.update materializes
    every configured pair; accounting only mutates existing keys), so
    walking `fresh` covers the union."""
    lending = features.enabled(features.LENDING_LIMIT)
    used = cohort.usage
    old_usage = old.usage
    for fname, resources in fresh.usage.items():
        old_res = old_usage.get(fname)
        dst = None
        for rname, val in resources.items():
            ov = old_res.get(rname, 0) if old_res is not None else 0
            if lending:
                # The lending clamp (max(0, used - guaranteed)) is
                # per-member state, so the delta is the clamped movement;
                # guaranteed quota itself is structural.
                g = fresh._guaranteed(fname, rname)
                val = max(0, val - g)
                ov = max(0, ov - g)
            if val != ov:
                if dst is None:
                    dst = used.setdefault(fname, {})
                dst[rname] = dst.get(rname, 0) + (val - ov)
    cohort.allocatable_generation += (fresh.allocatable_generation
                                      - old.allocatable_generation)


def _accumulate(cq: CachedClusterQueue, cohort: Cohort) -> None:
    """Fold a member CQ into cohort requestable/usage totals
    (reference: snapshot.go:160-201 accumulateResources)."""
    lending = features.enabled(features.LENDING_LIMIT)
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            res = cohort.requestable_resources.setdefault(fq.name, {})
            for rname, quota in fq.resources:
                if lending and quota.lending_limit is not None:
                    res[rname] = res.get(rname, 0) + quota.lending_limit
                else:
                    res[rname] = res.get(rname, 0) + quota.nominal
    for fname, resources in cq.usage.items():
        used = cohort.usage.setdefault(fname, {})
        for rname, val in resources.items():
            if lending:
                val = max(0, val - cq._guaranteed(fname, rname))
            used[rname] = used.get(rname, 0) + val
