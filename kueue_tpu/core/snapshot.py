"""Per-tick snapshot of the admitted-state cache.

Counterpart of reference pkg/cache/snapshot.go: deep-copies active
ClusterQueues, rebuilds cohorts with accumulated requestable resources and
usage (lending-aware, snapshot.go:160-201), and exposes the
add/remove-workload simulation primitive used by preemption
(snapshot.go:41-67).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from kueue_tpu import features
from kueue_tpu.api.types import ResourceFlavor
from kueue_tpu.core.cache import (
    Cache,
    CachedClusterQueue,
    Cohort,
    FlavorResourceQuantities,
    frq_clone,
)
from kueue_tpu.core.workload import WorkloadInfo


class Snapshot:
    __slots__ = ("cluster_queues", "resource_flavors", "inactive_cluster_queues")

    def __init__(self):
        self.cluster_queues: Dict[str, CachedClusterQueue] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.inactive_cluster_queues: Set[str] = set()

    @staticmethod
    def build(cache: Cache) -> "Snapshot":
        snap = Snapshot()
        snap.resource_flavors = dict(cache.resource_flavors)
        for name, cq in cache.cluster_queues.items():
            if not cq.active():
                snap.inactive_cluster_queues.add(name)
                continue
            snap.cluster_queues[name] = _snapshot_cq(cq)
        for cohort in cache.cohorts.values():
            cohort_copy = Cohort(cohort.name)
            for member in cohort.members:
                if not member.active():
                    continue
                cq_copy = snap.cluster_queues[member.name]
                _accumulate(cq_copy, cohort_copy)
                cq_copy.cohort = cohort_copy
                cohort_copy.members.add(cq_copy)
                cohort_copy.allocatable_generation += cq_copy.allocatable_generation
        return snap

    # Preemption simulation primitives (reference: snapshot.go:41-67).

    def remove_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.remove_workload_usage(wi, cohort_too=True)

    def add_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.add_workload_usage(wi, cohort_too=True)


def _snapshot_cq(cq: CachedClusterQueue) -> CachedClusterQueue:
    cc = CachedClusterQueue.__new__(CachedClusterQueue)
    cc.name = cq.name
    cc.cohort = None
    cc.cohort_name = cq.cohort_name
    cc.resource_groups = cq.resource_groups  # immutable per tick
    cc.rg_by_resource = cq.rg_by_resource
    cc.usage = frq_clone(cq.usage)
    cc.admitted_usage = frq_clone(cq.admitted_usage)
    cc.workloads = dict(cq.workloads)
    cc.namespace_selector = cq.namespace_selector
    cc.preemption = cq.preemption
    cc.flavor_fungibility = cq.flavor_fungibility
    cc.admission_checks = set(cq.admission_checks)
    cc.fair_weight = cq.fair_weight
    cc.guaranteed_quota = cq.guaranteed_quota if features.enabled(features.LENDING_LIMIT) else {}
    cc.allocatable_generation = cq.allocatable_generation
    cc.usage_version = cq.usage_version
    cc.has_missing_flavors = cq.has_missing_flavors
    cc.is_stopped = cq.is_stopped
    return cc


def _accumulate(cq: CachedClusterQueue, cohort: Cohort) -> None:
    """Fold a member CQ into cohort requestable/usage totals
    (reference: snapshot.go:160-201 accumulateResources)."""
    lending = features.enabled(features.LENDING_LIMIT)
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            res = cohort.requestable_resources.setdefault(fq.name, {})
            for rname, quota in fq.resources:
                if lending and quota.lending_limit is not None:
                    res[rname] = res.get(rname, 0) + quota.lending_limit
                else:
                    res[rname] = res.get(rname, 0) + quota.nominal
    for fname, resources in cq.usage.items():
        used = cohort.usage.setdefault(fname, {})
        for rname, val in resources.items():
            if lending:
                val = max(0, val - cq._guaranteed(fname, rname))
            used[rname] = used.get(rname, 0) + val
