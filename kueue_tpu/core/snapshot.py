"""Per-tick snapshot of the admitted-state cache.

Counterpart of reference pkg/cache/snapshot.go: deep-copies active
ClusterQueues, rebuilds cohorts with accumulated requestable resources and
usage (lending-aware, snapshot.go:160-201), and exposes the
add/remove-workload simulation primitive used by preemption
(snapshot.go:41-67).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from kueue_tpu import features
from kueue_tpu.api.types import ResourceFlavor
from kueue_tpu.core.cache import (
    Cache,
    CachedClusterQueue,
    Cohort,
    FlavorResourceQuantities,
    frq_clone,
)
from kueue_tpu.core.workload import WorkloadInfo


class Snapshot:
    __slots__ = ("cluster_queues", "resource_flavors", "inactive_cluster_queues")

    def __init__(self):
        self.cluster_queues: Dict[str, CachedClusterQueue] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.inactive_cluster_queues: Set[str] = set()

    @staticmethod
    def build(cache: Cache) -> "Snapshot":
        snap = Snapshot()
        snap.resource_flavors = dict(cache.resource_flavors)
        for name, cq in cache.cluster_queues.items():
            if not cq.active():
                snap.inactive_cluster_queues.add(name)
                continue
            snap.cluster_queues[name] = _snapshot_cq(cq)
        cohort_copies: Dict[str, Cohort] = {}
        for cohort in cache.cohorts.values():
            cohort_copy = Cohort(cohort.name,
                                 spec=cache.cohort_specs.get(cohort.name))
            cohort_copies[cohort.name] = cohort_copy
            for member in cohort.members:
                if not member.active():
                    continue
                cq_copy = snap.cluster_queues[member.name]
                _accumulate(cq_copy, cohort_copy)
                cq_copy.cohort = cohort_copy
                cohort_copy.members.add(cq_copy)
                cohort_copy.allocatable_generation += cq_copy.allocatable_generation
        if cache.cohort_specs:
            _build_hierarchy(snap, cache, cohort_copies)
        return snap

    # Preemption simulation primitives (reference: snapshot.go:41-67).

    def remove_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.remove_workload_usage(wi, cohort_too=True)

    def add_workload(self, wi: WorkloadInfo) -> None:
        cq = self.cluster_queues[wi.cluster_queue]
        cq.add_workload_usage(wi, cohort_too=True)


def _snapshot_cq(cq: CachedClusterQueue) -> CachedClusterQueue:
    cc = CachedClusterQueue.__new__(CachedClusterQueue)
    cc.name = cq.name
    cc.cohort = None
    cc.cohort_name = cq.cohort_name
    cc.resource_groups = cq.resource_groups  # immutable per tick
    cc.rg_by_resource = cq.rg_by_resource
    cc.usage = frq_clone(cq.usage)
    cc.admitted_usage = frq_clone(cq.admitted_usage)
    cc.workloads = dict(cq.workloads)
    cc.namespace_selector = cq.namespace_selector
    cc.preemption = cq.preemption
    cc.flavor_fungibility = cq.flavor_fungibility
    cc.admission_checks = set(cq.admission_checks)
    cc.fair_weight = cq.fair_weight
    cc.guaranteed_quota = cq.guaranteed_quota if features.enabled(features.LENDING_LIMIT) else {}
    cc.allocatable_generation = cq.allocatable_generation
    cc.usage_version = cq.usage_version
    cc.has_missing_flavors = cq.has_missing_flavors
    cc.is_stopped = cq.is_stopped
    return cc


def _build_hierarchy(snap: "Snapshot", cache: Cache,
                     nodes: Dict[str, Cohort]) -> None:
    """Link the cohort tree (KEP-79): create nodes for spec-only cohorts
    and parent chains, wire parent/children, and deactivate every
    ClusterQueue in a structure that contains a cycle (the KEP's mandated
    failure mode: stop all new admissions in the affected tree)."""
    def get_node(name: str) -> Cohort:
        node = nodes.get(name)
        if node is None:
            node = Cohort(name, spec=cache.cohort_specs.get(name))
            nodes[name] = node
        return node

    # Materialize spec cohorts and their parent chains.
    pending = list(cache.cohort_specs)
    while pending:
        name = pending.pop()
        node = get_node(name)
        spec = node.spec
        if spec is not None and spec.parent and spec.parent not in nodes:
            pending.append(spec.parent)
            get_node(spec.parent)

    for node in nodes.values():
        if node.spec is not None and node.spec.parent:
            parent = nodes[node.spec.parent]
            node.parent = parent
            parent.children.append(node)

    # Cycle detection: each node has at most one parent, so walking up with
    # a visited set finds any rho-shaped structure.
    broken: set = set()
    for node in nodes.values():
        seen = []
        cur = node
        while cur is not None and cur.name not in broken:
            if cur in seen:
                broken.update(n.name for n in seen)
                break
            seen.append(cur)
            cur = cur.parent
        else:
            if cur is not None:  # reached an already-broken node
                broken.update(n.name for n in seen)

    if broken:
        for name in broken:
            for member in list(nodes[name].members):
                snap.inactive_cluster_queues.add(member.name)
                del snap.cluster_queues[member.name]
            nodes[name].members.clear()
            nodes[name].parent = None
            nodes[name].children = []


def _accumulate(cq: CachedClusterQueue, cohort: Cohort) -> None:
    """Fold a member CQ into cohort requestable/usage totals
    (reference: snapshot.go:160-201 accumulateResources)."""
    lending = features.enabled(features.LENDING_LIMIT)
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            res = cohort.requestable_resources.setdefault(fq.name, {})
            for rname, quota in fq.resources:
                if lending and quota.lending_limit is not None:
                    res[rname] = res.get(rname, 0) + quota.lending_limit
                else:
                    res[rname] = res.get(rname, 0) + quota.nominal
    for fname, resources in cq.usage.items():
        used = cohort.usage.setdefault(fname, {})
        for rname, val in resources.items():
            if lending:
                val = max(0, val - cq._guaranteed(fname, rname))
            used[rname] = used.get(rname, 0) + val
