"""Workload resource model: per-PodSet integer totals and assignment state.

Counterpart of reference pkg/workload/workload.go: WorkloadInfo precomputes
`total_requests` (per-PodSet requests scaled by count minus reclaimable pods,
workload.go:185-213,244-296), holds the flavor-search resume state
(AssignmentClusterQueueState, workload.go:45-92), and the queue-ordering
timestamp rule (eviction vs creation, workload.go Ordering).
"""

from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.api.types import (
    CONDITION_EVICTED,
    EVICTED_BY_PODS_READY_TIMEOUT,
    Workload,
)


@dataclass
class PodSetResources:
    """Total requests for one PodSet (requests scaled by count)."""

    name: str
    requests: Dict[str, int]
    count: int
    # Assigned flavors per resource, populated once admitted.
    flavors: Dict[str, str] = field(default_factory=dict)

    def scaled_to(self, count: int) -> "PodSetResources":
        """Per-pod rescaling used by partial admission
        (reference: pkg/workload/workload.go ScaledTo)."""
        if self.count == 0:
            return PodSetResources(self.name, dict(self.requests), count)
        per_pod = {r: v // self.count for r, v in self.requests.items()}
        return PodSetResources(
            name=self.name,
            requests={r: v * count for r, v in per_pod.items()},
            count=count,
        )


@dataclass(slots=True)
class AssignmentClusterQueueState:
    """Flavor-search resume state, invalidated by allocatable generations.

    reference: pkg/workload/workload.go:45-92.
    `last_tried_flavor_idx[podset][resource]` is the index (into the resource
    group's flavor list) of the last flavor tried; -1 means the whole list was
    exhausted and the next attempt starts from 0.
    """

    last_tried_flavor_idx: List[Dict[str, int]] = field(default_factory=list)
    cluster_queue_generation: int = 0
    cohort_generation: int = 0
    # Memoized content signature of last_tried_flavor_idx (the nominate
    # fingerprint's resume component): the index maps are filled during
    # decode and never mutated afterwards — a new solve mints a new
    # state object — so the tuple is computed once per object.
    resume_sig: Optional[tuple] = field(default=None, compare=False)

    def sig(self) -> tuple:
        # getattr: the native decoder builds these objects bare (no
        # __init__), so the slot may be unset on first read.
        s = getattr(self, "resume_sig", None)
        if s is None:
            s = self.resume_sig = tuple(
                tuple(d.items()) for d in self.last_tried_flavor_idx)
        return s

    def next_flavor_to_try(self, podset_idx: int, resource: str) -> int:
        if podset_idx >= len(self.last_tried_flavor_idx):
            return 0
        last = self.last_tried_flavor_idx[podset_idx].get(resource, -1)
        return last + 1

    def pending_flavors(self) -> bool:
        """True if any resource still has untried flavors
        (reference: workload.go PendingFlavors)."""
        return any(idx != -1
                   for ps in self.last_tried_flavor_idx
                   for idx in ps.values())


@dataclass
class WorkloadOrdering:
    """Which timestamp orders requeued workloads
    (reference: pkg/workload Ordering; config waitForPodsReady.requeuingStrategy)."""

    pods_ready_requeuing_timestamp: str = "Eviction"  # "Eviction" | "Creation"

    def queue_order_time(self, wl: Workload) -> float:
        # Memoized on the workload: the timestamp is read on every heap
        # push AND per entry in the nomination sort, several thousand
        # times per tick at scale, and only moves when the Evicted
        # condition does. The key pins the exact inputs: the conditions
        # list (identity + length catch wholesale replacement and
        # appends), the in-place mutation counter (set_condition bumps
        # it), and this ordering's timestamp mode.
        conds = wl.conditions
        memo = getattr(wl, "_qot_memo", None)
        mode = self.pods_ready_requeuing_timestamp
        if memo is not None and memo[0] is conds and memo[1] == len(conds) \
                and memo[2] == wl._cond_mut and memo[3] == mode:
            return memo[4]
        c = wl.find_condition(CONDITION_EVICTED)
        relevant = c is not None and c.status
        if relevant and mode == "Creation" \
                and c.reason == EVICTED_BY_PODS_READY_TIMEOUT:
            relevant = False
        value = c.last_transition_time if relevant else wl.creation_time
        wl._qot_memo = (conds, len(conds), wl._cond_mut, mode, value)
        return value


class WorkloadInfo:
    """A Workload plus its precomputed integer resource totals.

    reference: pkg/workload/workload.go:94-112 (Info).
    """

    __slots__ = ("obj", "cluster_queue", "_total_requests", "_usage_triples",
                 "last_assignment", "rev", "row_sig")

    # Monotonic instance stamp: a process-unique identity that, unlike
    # id(), is never recycled after GC — the solver's row cache keys
    # validity on it without pinning the WorkloadInfo alive.
    _rev_counter = itertools.count(1)

    def __init__(self, obj: Workload, cluster_queue: str = ""):
        self.obj = obj
        self.cluster_queue = cluster_queue
        # Computed on first use: WorkloadInfos are also created on hot
        # bookkeeping paths (assume/forget, snapshot-mirror lockstep) that
        # never read the totals.
        self._total_requests: Optional[List[PodSetResources]] = None
        self._usage_triples = None
        self.last_assignment: Optional[AssignmentClusterQueueState] = None
        self.rev = next(WorkloadInfo._rev_counter)
        # Lazily computed row-cache content signature (solver/schema.py
        # WorkloadRowCache._sig); False = unhashable, None = not computed.
        self.row_sig = None

    @property
    def total_requests(self) -> List[PodSetResources]:
        totals = self._total_requests
        if totals is None:
            # Totals are memoized on the Workload object itself: the hot
            # accounting paths (cache assume/forget, mirror lockstep)
            # build a fresh WorkloadInfo per call, and recomputing the
            # per-podset totals dominated the end-to-end tick at north-star
            # scale. The memo basis pins the exact inputs of
            # _compute_totals by identity (admission, pod_sets) and value
            # (reclaimable counts, podset counts); any replacement or
            # count change recomputes. The totals list is shared read-only
            # across infos — nothing mutates PodSetResources in place
            # (scaled_to returns new objects).
            wl = self.obj
            reclaim = tuple(sorted(wl.reclaimable_pods.items()))
            counts = tuple(ps.count for ps in wl.pod_sets)
            memo = getattr(wl, "_totals_memo", None)
            if (memo is not None and memo[0] is wl.admission
                    and memo[1] == reclaim and memo[2] is wl.pod_sets
                    and memo[3] == counts):
                totals = memo[4]
            else:
                totals = self._compute_totals(wl)
                wl._totals_memo = (wl.admission, reclaim, wl.pod_sets,
                                   counts, totals)
            self._total_requests = totals
            self._usage_triples = None
        return totals

    @property
    def usage_triples(self):
        """Flat [(flavor, resource, value)] of this workload's admitted
        usage — the hot shape for usage accounting: preemption simulation
        removes/adds workloads thousands of times per tick and the nested
        podset/dict walk dominates otherwise."""
        triples = self._usage_triples
        if triples is None:
            # Memoized on the Workload next to the totals they derive from
            # (same identity basis): the accounting paths build a fresh
            # WorkloadInfo per mutation (cache assume/forget, mirror
            # lockstep, usage-encoder delta) and each walked the nested
            # podset dicts otherwise.
            totals = self.total_requests
            wl = self.obj
            memo = getattr(wl, "_triples_memo", None)
            if memo is not None and memo[0] is totals:
                triples = memo[1]
            else:
                triples = []
                for ps in totals:
                    flavors = ps.flavors
                    for res, q in ps.requests.items():
                        flv = flavors.get(res)
                        if flv is not None:
                            triples.append((flv, res, q))
                wl._triples_memo = (totals, triples)
            self._usage_triples = triples
        return triples

    @staticmethod
    def _compute_totals(wl: Workload) -> List[PodSetResources]:
        # From admission if admitted (usage as admitted), else from the spec
        # (reference: totalRequestsFromAdmission / totalRequestsFromPodSets).
        counts = {ps.name: ps.count for ps in wl.pod_sets}
        after_reclaim = {
            name: c - wl.reclaimable_pods.get(name, 0) for name, c in counts.items()
        }
        if wl.admission is not None:
            out = []
            for psa in wl.admission.pod_set_assignments:
                res = PodSetResources(
                    name=psa.name,
                    requests=dict(psa.resource_usage),
                    count=psa.count if psa.count is not None else counts[psa.name],
                    flavors=dict(psa.flavors),
                )
                cur = after_reclaim.get(psa.name, res.count)
                if cur != res.count:
                    res = PodSetResources(
                        name=res.name,
                        requests=res.scaled_to(cur).requests,
                        count=cur,
                        flavors=res.flavors,
                    )
                out.append(res)
            return out
        out = []
        for ps in wl.pod_sets:
            count = after_reclaim[ps.name]
            out.append(PodSetResources(
                name=ps.name,
                requests={r: v * count for r, v in ps.requests.items()},
                count=count,
            ))
        return out

    @property
    def key(self) -> str:
        return self.obj.key

    @property
    def priority(self) -> int:
        return self.obj.priority

    def usage(self) -> Dict[str, Dict[str, int]]:
        """Flavor -> resource -> quantity used by this (admitted) workload."""
        out: Dict[str, Dict[str, int]] = {}
        for flv, res, q in self.usage_triples:
            fout = out.setdefault(flv, {})
            fout[res] = fout.get(res, 0) + q
        return out

    def clone(self) -> "WorkloadInfo":
        c = WorkloadInfo.__new__(WorkloadInfo)
        c.obj = self.obj
        c.cluster_queue = self.cluster_queue
        c._total_requests = copy.deepcopy(self.total_requests)
        c._usage_triples = None
        c.last_assignment = self.last_assignment
        c.rev = next(WorkloadInfo._rev_counter)
        c.row_sig = None
        return c
