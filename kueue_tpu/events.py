"""Event recording.

Counterpart of the reference's Kubernetes Event emissions on admission /
preemption / pending transitions (scheduler.go:520-522,605,
preemption.go:149): a bounded in-memory event log with the same
(type, reason, message) vocabulary, queryable per object — the embedded
analog of `kubectl get events`.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional

from kueue_tpu.metrics import REGISTRY

NORMAL = "Normal"
WARNING = "Warning"

# Reasons used by the scheduler/controllers (reference vocabulary).
REASON_QUOTA_RESERVED = "QuotaReserved"
REASON_ADMITTED = "Admitted"
REASON_PREEMPTED = "Preempted"
REASON_PENDING = "Pending"
REASON_EVICTED = "EvictedDueToPodsReadyTimeout"
REASON_FINISHED = "JobFinished"


@dataclass(frozen=True)
class Event:
    type: str       # Normal | Warning
    reason: str
    message: str
    object_key: str  # "namespace/name" of the involved workload
    timestamp: float


class EventRecorder:
    """Bounded event sink (newest kept, like the apiserver's event TTL).

    Events are stored as plain tuples and materialized into Event objects
    only on read: the scheduler emits one per admission/preemption on the
    hot path, while reads are rare debugging/API traffic."""

    def __init__(self, capacity: int = 10_000):
        self.capacity = capacity
        self.dropped = 0
        self._events: Deque[tuple] = deque(maxlen=capacity)
        # The bare deque.append was GIL-atomic; the occupancy check +
        # dropped increment below is check-then-act, and emitters span
        # the tick thread AND API-server handler threads (finish/delete
        # endpoints), so the drop accounting needs its own lock.
        self._lock = threading.Lock()

    def event(self, object_key: str, etype: str, reason: str,
              message: str, now: float = 0.0) -> None:
        # Messages are truncated like util/api's event-message cap.
        with self._lock:
            if len(self._events) >= self.capacity:
                # deque(maxlen) evicts silently; count the loss so
                # capacity sizing is observable
                # (kueue_events_dropped_total).
                self.dropped += 1
                REGISTRY.events_dropped_total.inc()
            self._events.append(
                (etype, reason, message[:1024], object_key, now))

    @property
    def occupancy(self) -> int:
        return len(self._events)

    def for_object(self, object_key: str,
                   reason: Optional[str] = None) -> List[Event]:
        return [Event(*t) for t in self._events
                if t[3] == object_key
                and (reason is None or t[1] == reason)]

    def all(self) -> List[Event]:
        return [Event(*t) for t in self._events]
