"""Feature gates (reference: pkg/features/kube_features.go:29-110).

Defaults mirror the reference snapshot: beta gates on, alpha gates off.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Iterator

PARTIAL_ADMISSION = "PartialAdmission"
QUEUE_VISIBILITY = "QueueVisibility"
FLAVOR_FUNGIBILITY = "FlavorFungibility"
PROVISIONING_ACC = "ProvisioningACC"
VISIBILITY_ON_DEMAND = "VisibilityOnDemand"
PRIORITY_SORTING_WITHIN_COHORT = "PrioritySortingWithinCohort"
MULTI_KUEUE = "MultiKueue"
LENDING_LIMIT = "LendingLimit"
# Greenfield (KEP-1714 / KEP-79): implemented natively by this framework.
FAIR_SHARING = "FairSharing"
# Topology-aware scheduling (slice/rack-packed admission): active only
# when a ResourceFlavor declares a TopologySpec, so the default-on gate
# is still a provable no-op on topology-free clusters.
TOPOLOGY_AWARE_SCHEDULING = "TopologyAwareScheduling"

_DEFAULTS: Dict[str, bool] = {
    PARTIAL_ADMISSION: True,
    QUEUE_VISIBILITY: False,
    FLAVOR_FUNGIBILITY: True,
    PROVISIONING_ACC: False,
    VISIBILITY_ON_DEMAND: False,
    PRIORITY_SORTING_WITHIN_COHORT: True,
    MULTI_KUEUE: False,
    LENDING_LIMIT: False,
    FAIR_SHARING: False,
    TOPOLOGY_AWARE_SCHEDULING: True,
}

_gates: Dict[str, bool] = dict(_DEFAULTS)


def enabled(name: str) -> bool:
    return _gates[name]


def set_enabled(name: str, value: bool) -> None:
    if name not in _gates:
        raise KeyError(f"unknown feature gate {name}")
    _gates[name] = value


def all_gates() -> Dict[str, bool]:
    return dict(_gates)


def reset() -> None:
    _gates.clear()
    _gates.update(_DEFAULTS)


@contextmanager
def override(name: str, value: bool) -> Iterator[None]:
    old = _gates[name]
    set_enabled(name, value)
    try:
        yield
    finally:
        _gates[name] = old
