"""kueuefuzz: randomized scenario corpus + decision-identity fuzzer.

The repo's strongest asset is its oracle density — every scheduling path
has a sequential referee twin, a kill switch, and churn goldens. This
package weaponizes those oracles into a randomized fuzzer over
policy/topology/traffic space (ROADMAP item 5, in the spirit of the
Mesos fair-allocation study's multi-framework workload mixes):

- `generator`  draws seeded scenarios: cluster topologies (flavor speed
  ladders, TopologySpecs, KEP-79 cohort trees with lending limits),
  policy mixes (queueing strategy x fair sharing x hetero x preemption
  x PodsReady) and traffic shapes (diurnal, heavy-tailed, adversarial
  churn, multi-framework mixes).
- `lattice`    replays each scenario across configuration points —
  sequential referee, batched engines, shards {1,2}, replicas {1,2},
  a kill-switch set, plus fail-over (journal replay) and capacity-loan
  drill points — with decision identity, repeat determinism,
  quota-never-oversubscribed and journal-replay equivalence as oracles.
- `shrink`     minimizes a diverging scenario (drop workloads/CQs/ticks,
  simplify policies, re-check divergence each step) and emits a
  self-contained reproducer that checks in under tests/fixtures/fuzz/.
- `corpus`     loads + replays those reproducer files (the seed corpus
  meta-test: every checked-in entry must replay green).
- `soak`       hours-scale churn run watching RSS / arena occupancy /
  nominate-cache hit ratio / dispatch counts for monotonic drift.

Entry point: `python -m kueue_tpu.fuzz` (see __main__.py; `make
fuzz-smoke` runs the CI budget).
"""

from kueue_tpu.fuzz.scenario import Scenario  # noqa: F401
