"""CLI: `python -m kueue_tpu.fuzz` — campaign, corpus replay, soak.

Default mode runs a seeded campaign: N scenarios, each replayed across
the full lattice (see lattice.default_lattice), writing a JSON report
with per-seed oracle results, the lattice axes covered, and the machine
environment block. Any violation shrinks to a reproducer file next to
the report and exits non-zero — `make fuzz-smoke` runs the CI budget.

  python -m kueue_tpu.fuzz --seeds 25 --out /tmp/fuzz.json
  python -m kueue_tpu.fuzz --corpus tests/fixtures/fuzz
  python -m kueue_tpu.fuzz --soak 7200 --out /tmp/soak.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu_backend() -> None:
    """CPU backend + >= 2 virtual host devices BEFORE jax initializes,
    so the shards lattice axis runs everywhere (same trick as
    tests/conftest.py and the multichip dryrun)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        os.environ["XLA_FLAGS"] = (
            xf + " --xla_force_host_platform_device_count=2").strip()


def parse_shard(spec: str) -> tuple:
    """"I/N" -> (i, n): shard i of n, 0-based, 0 <= i < n."""
    try:
        i_s, n_s = spec.split("/", 1)
        i, n = int(i_s), int(n_s)
    except ValueError:
        raise ValueError(f"--shard wants I/N (got {spec!r})")
    if n < 1 or not 0 <= i < n:
        raise ValueError(f"--shard {spec!r}: need 0 <= I < N")
    return i, n


def shard_range(start_seed: int, seeds: int, shard) -> tuple:
    """The contiguous [lo, hi) seed slice shard i of n owns. The slices
    partition the full range exactly (no seed dropped or doubled), so
    N processes running `--shard 0/N .. (N-1)/N` over the same
    --seeds/--start-seed jointly cover the same campaign one process
    would — the nightly 1k-seed budget split across runners."""
    if not shard:
        return start_seed, start_seed + seeds
    i, n = shard
    return (start_seed + (seeds * i) // n,
            start_seed + (seeds * (i + 1)) // n)


def run_campaign(seeds: int, start_seed: int, out: str,
                 shrink_on_failure: bool = True,
                 include_socket: bool = False,
                 shard=None) -> int:
    from kueue_tpu.fuzz import generator, lattice, shrink
    from kueue_tpu.utils.envinfo import environment_block

    reports = []
    all_violations = []
    axes_seen = {"engines": set(), "shards": set(), "replicas": set(),
                 "kill_switches": set(), "drills": set(),
                 "transports": set(), "micro": set()}
    # Per-oracle coverage: how many preemptions / revocations / micro
    # admissions each draw dimension produced across the campaign. A
    # dimension whose count stays zero lands on the "never" list — the
    # dead corpus regions ROADMAP 5a wants visible in every report.
    coverage = {"preemption": {}, "revocation": {},
                "micro_admission": {}}
    lo, hi = shard_range(start_seed, seeds, shard)
    if shard:
        print(f"# shard {shard[0]}/{shard[1]}: seeds [{lo}, {hi})",
              file=sys.stderr)
    for seed in range(lo, hi):
        sc = generator.draw_scenario(seed)
        report = lattice.check_scenario(sc, include_socket=include_socket)
        events = report.get("events") or {}
        hits = {"preemption": events.get("preempted", 0),
                "revocation": events.get("revocations", 0),
                "micro_admission": events.get("micro_admitted", 0)}
        for dim in generator.scenario_dimensions(sc):
            for family, n in hits.items():
                bucket = coverage[family]
                bucket[dim] = bucket.get(dim, 0) + n
        for ax in report["axes"]:
            axes_seen["engines"].add(ax["engine"])
            axes_seen["shards"].add(ax["shards"])
            axes_seen["replicas"].add(ax["replicas"])
            axes_seen["kill_switches"].add(ax["kill_switches"])
            if ax["drill"]:
                axes_seen["drills"].add(ax["drill"])
            if ax.get("transport"):
                axes_seen["transports"].add(ax["transport"])
            axes_seen["micro"].add(bool(ax.get("micro")))
        reports.append(report)
        status = "ok" if not report["violations"] else "DIVERGED"
        print(f"# seed {seed}: {status} "
              f"({len(report['points'])} lattice points, "
              f"shape {sc.policy.get('shape')})", file=sys.stderr)
        for vi in report["violations"]:
            all_violations.append({"seed": seed, **vi})
            print(f"#   violation: {vi}", file=sys.stderr)
        if report["violations"] and shrink_on_failure:
            def still_fails(cand):
                return bool(lattice.check_scenario(cand)["violations"])

            small, attempts = shrink.shrink(sc, still_fails)
            repro_path = (os.path.splitext(out)[0]
                          + f"_repro_seed{seed}.json")
            shrink.write_reproducer(
                repro_path, small,
                name=f"fuzz-seed-{seed}",
                description="shrunk from a live campaign divergence",
                found={"seed": seed,
                       "violations": report["violations"][:4],
                       "shrink_attempts": attempts})
            print(f"#   reproducer written: {repro_path} "
                  f"(size {small.size()})", file=sys.stderr)

    oracle_coverage = {
        family: {
            "events_by_dimension": dict(sorted(counts.items())),
            "never": sorted(d for d, c in counts.items() if c == 0),
        }
        for family, counts in coverage.items()}
    doc = {
        "scenarios": hi - lo,
        "start_seed": lo,
        "requested": {"seeds": seeds, "start_seed": start_seed},
        "shard": ({"index": shard[0], "of": shard[1],
                   "seed_lo": lo, "seed_hi": hi - 1} if shard else None),
        "violations": all_violations,
        "lattice_axes": {k: sorted(v, key=str)
                         for k, v in axes_seen.items()},
        "oracle_coverage": oracle_coverage,
        "environment": environment_block(),
        "reports": reports,
    }
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "fuzz_campaign", "scenarios": hi - lo,
        "shard": doc["shard"],
        "violations": len(all_violations),
        "lattice_axes": doc["lattice_axes"],
        "coverage_never": {f: c["never"]
                           for f, c in oracle_coverage.items()}}),
        flush=True)
    return 1 if all_violations else 0


def run_corpus(dirpath: str) -> int:
    from kueue_tpu.fuzz import corpus

    entries = corpus.load_corpus(dirpath)
    if not entries:
        print(f"# no corpus entries under {dirpath}", file=sys.stderr)
        return 1
    bad = 0
    for entry in entries:
        violations = corpus.replay_entry(entry)
        status = "ok" if not violations else "RED"
        print(f"# corpus {entry['name']}: {status}", file=sys.stderr)
        for vi in violations:
            bad += 1
            print(f"#   {vi}", file=sys.stderr)
    return 1 if bad else 0


def main(argv=None) -> int:
    _pin_cpu_backend()
    ap = argparse.ArgumentParser(
        prog="python -m kueue_tpu.fuzz",
        description="kueuefuzz: scenario corpus + decision-identity "
                    "fuzzer")
    ap.add_argument("--seeds", type=int, default=25)
    ap.add_argument("--start-seed", type=int, default=0)
    ap.add_argument("--out", default="/tmp/kueue-fuzz-report.json")
    ap.add_argument("--no-shrink", action="store_true",
                    help="report divergences without shrinking them")
    ap.add_argument("--corpus", metavar="DIR",
                    help="replay the reproducer corpus instead of "
                         "fuzzing")
    ap.add_argument("--soak", type=float, metavar="SECONDS",
                    help="run the long-run churn soak instead of "
                         "fuzzing")
    ap.add_argument("--shard", metavar="I/N", default=None,
                    help="run seed shard I of N (0-based): the "
                         "contiguous slice of [--start-seed, "
                         "--start-seed + --seeds) this process owns — "
                         "N processes with 0/N..N-1/N cover the full "
                         "range exactly once (the nightly split)")
    ap.add_argument("--lattice", choices=("default", "socket"),
                    default="default",
                    help="'socket' adds the multi-HOST lattice points "
                         "(real TCP replica drives + seeded packet "
                         "faults) — the make fuzz-nightly budget, "
                         "excluded from the 25-seed CI smoke")
    args = ap.parse_args(argv)
    if args.lattice == "socket" and (args.corpus or args.soak is not None):
        # The soak is a churn drive, not a lattice campaign: silently
        # accepting the flag would report ok with zero socket coverage.
        ap.error("--lattice socket applies to campaign mode only "
                 "(run `make fuzz-nightly` for the socket budget)")
    if args.shard is not None and (args.corpus or args.soak is not None):
        ap.error("--shard applies to campaign mode only")
    shard = None
    if args.shard is not None:
        try:
            shard = parse_shard(args.shard)
        except ValueError as exc:
            ap.error(str(exc))
    if args.corpus:
        return run_corpus(args.corpus)
    if args.soak is not None:
        from kueue_tpu.fuzz.soak import run_soak

        report = run_soak(args.soak, report_path=args.out)
        print(json.dumps({
            "metric": "fuzz_soak", "ok": report["ok"],
            "ticks": report["ticks"],
            "findings": len(report.get("findings") or []),
            "verdict": {k: v["ok"]
                        for k, v in report["verdict"].items()}}),
            flush=True)
        for finding in report.get("findings") or []:
            print(f"#   soak finding: {finding}", file=sys.stderr)
        return 0 if report["ok"] else 1
    return run_campaign(args.seeds, args.start_seed, args.out,
                        shrink_on_failure=not args.no_shrink,
                        include_socket=args.lattice == "socket",
                        shard=shard)


if __name__ == "__main__":
    sys.exit(main())
