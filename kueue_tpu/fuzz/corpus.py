"""Seed corpus: checked-in reproducer files replayed as goldens.

Every file under tests/fixtures/fuzz/ is a self-contained scenario
(shrunk from a real fuzz divergence, or hand-minimized from a known
churn-found bug) plus its replay contract:

- `lattice`: which lattice points to drive (names resolved against
  lattice.default_lattice, or the full default lattice when null);
- `expect`: behavioral assertions beyond the standard oracles —
  `admitted_final_contains` (workload keys that must hold quota at the
  end, the PR 9 quota-raise-requeue shape) and `min_preempted`
  (the drive must actually exercise preemption, so a reproducer can't
  silently decay into a no-op).

The corpus meta-test (tests/test_fuzz_corpus.py) replays every entry
green on the fixed build; the oracle-mutation drills prove each entry
goes RED under the env-gated revert of the bug it was minimized from.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from kueue_tpu.fuzz import lattice as lat
from kueue_tpu.fuzz.scenario import Scenario
from kueue_tpu.fuzz.shrink import REPRO_FORMAT

CORPUS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "tests", "fixtures", "fuzz")


def load_entry(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("format") != REPRO_FORMAT:
        raise ValueError(
            f"{path}: not a kueuefuzz reproducer "
            f"(format={doc.get('format')!r})")
    doc["scenario_obj"] = Scenario.from_dict(doc["scenario"])
    doc["path"] = path
    return doc


def load_corpus(dirpath: Optional[str] = None) -> List[dict]:
    dirpath = dirpath or CORPUS_DIR
    entries = []
    if not os.path.isdir(dirpath):
        return entries
    for fn in sorted(os.listdir(dirpath)):
        if fn.endswith(".json"):
            entries.append(load_entry(os.path.join(dirpath, fn)))
    return entries


def _resolve_points(entry: dict, sc: Scenario) -> list:
    points = lat.default_lattice(sc)
    wanted = entry.get("lattice")
    if not wanted:
        return points
    by_name = {p.name: p for p in points}
    out = []
    for name in wanted:
        if name in by_name:
            out.append(by_name[name])
    # The reference point always drives (trail comparisons need it).
    if points and points[0] not in out:
        out.insert(0, points[0])
    return out


def replay_entry(entry: dict) -> List[dict]:
    """Replay one corpus entry; returns the violation list (empty =
    green). Standard lattice oracles run first, then the entry's own
    `expect` block."""
    sc: Scenario = entry["scenario_obj"]
    points = _resolve_points(entry, sc)
    report = lat.check_scenario(sc, points=points, keep_results=True)
    violations = list(report["violations"])

    expect = entry.get("expect") or {}
    ref = report["results"].get(points[0].name) if expect else None
    if expect and ref is not None:
        # The reference drive check_scenario already paid — asserting
        # expect against the SAME drive the trails were compared on.
        admitted_keys = {key for keys in ref["final_admitted"].values()
                         for key in keys}
        for key in expect.get("admitted_final_contains", ()):
            if key not in admitted_keys:
                violations.append({
                    "oracle": "expect", "point": points[0].name,
                    "detail": f"{key} not admitted at end of replay "
                              f"(admitted: {sorted(admitted_keys)})"})
        min_preempted = expect.get("min_preempted")
        if min_preempted:
            n = sum(len(pre) for _adm, pre in ref["trail"])
            if n < min_preempted:
                violations.append({
                    "oracle": "expect", "point": points[0].name,
                    "detail": f"only {n} preemptions in replay "
                              f"(expected >= {min_preempted}): the "
                              "reproducer no longer exercises the "
                              "path it was minimized for"})
    return violations
