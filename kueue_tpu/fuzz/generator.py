"""Seeded scenario generator: topology x policy x traffic draws.

Each seed deterministically expands into one Scenario. The draw space
covers the dimensions the hand-written suites pin individually but never
cross-product:

- cluster structure: solo ClusterQueues / flat cohorts / KEP-79 trees
  (root + mid cohorts, optionally carrying their own shareable quota and
  lending limits);
- flavors: 1-3, optionally a hetero speed ladder (speed_class 1.0+0.5f
  with per-workload throughput overrides) or a TopologySpec
  (rack/host tree with slice-packing requests);
- policy mix: BestEffortFIFO/StrictFIFO per CQ, preemption combos
  (within LowerPriority, reclaim Any/LowerOrNewerEqualPriority,
  borrowWithinCohort), weighted fair sharing, LendingLimit clamps,
  waitForPodsReady;
- traffic shapes (à la the Mesos multi-framework study): `diurnal`
  (sinusoidal arrival rate), `heavy_tailed` (Pareto-ish sizes, rare
  spikes), `adversarial` (tie-heavy identical workloads + add/update/
  delete churn bursts + quota resizes), `multiframework` (interleaved
  per-framework populations with distinct shapes and priorities).

Workload sizes draw from the SAME distribution helpers bench.py's churn
uses (utils/synthetic.churn_arrival_draw and friends), so the fuzzer and
the bench exercise one population instead of drifting copies.
"""

from __future__ import annotations

import random
from typing import List

from kueue_tpu.fuzz.scenario import Scenario
from kueue_tpu.utils.synthetic import (
    churn_arrival_draw,
    diurnal_rate,
    heavy_tailed_int,
    hetero_profile_draw,
)

TRAFFIC_SHAPES = ("diurnal", "heavy_tailed", "adversarial",
                  "multiframework")


def draw_scenario(seed: int) -> Scenario:
    rnd = random.Random(0x5EED0000 + seed)

    # Stratified sampling over the lattice axes: every 4th seed draws a
    # replica-focused profile (inside the documented multi-process
    # identity envelope — scenario.replica_safe), so the replicas-{1,2}
    # axis and its fail-over / capacity-loan drill points get steady
    # coverage instead of depending on the conjunction of independent
    # policy draws coming up safe.
    replica_profile = seed % 4 == 3

    # -- flavors / topology -------------------------------------------------
    hetero = (not replica_profile) and rnd.random() < 0.18
    topology = (not hetero) and rnd.random() < 0.15
    num_flavors = rnd.randint(2, 3) if hetero else rnd.randint(1, 2)
    flavors = [{"name": f"flavor-{f}",
                "speed_class": (1.0 + 0.5 * f) if hetero else 1.0}
               for f in range(num_flavors)]
    topo = None
    if topology:
        topo = {"levels": ["rack", "host"], "counts": [2, 2],
                "leaf_capacity": rnd.choice([4, 8])}

    # -- cohort structure ---------------------------------------------------
    structure = rnd.choices(["solo", "flat", "tree"],
                            weights=[0.25, 0.45, 0.30])[0]
    lending = structure != "solo" and rnd.random() < 0.35
    cohorts: List[dict] = []
    if structure == "tree":
        cohorts.append({"name": "root", "parent": ""})
        n_mids = rnd.randint(1, 2)
        for m in range(n_mids):
            quota = None
            if rnd.random() < 0.5:
                # A mid cohort with its own shareable pool — lending
                # limits clamp what leaves outside it can take.
                nom = rnd.randint(4, 12)
                quota = {"flavor-0": {"cpu": [
                    nom, None, (nom // 2) if lending else None]}}
            cohorts.append({"name": f"mid-{m}", "parent": "root",
                            "quota": quota})
        leaf_names = [f"mid-{m}" for m in range(n_mids)]
    elif structure == "flat":
        n_cohorts = rnd.randint(1, 2)
        leaf_names = [f"cohort-{k}" for k in range(n_cohorts)]
    else:
        leaf_names = []

    # -- ClusterQueues + policy mix -----------------------------------------
    num_cqs = rnd.randint(2, 5)
    fair = structure != "solo" and rnd.random() < 0.25
    pods_ready = (not fair) and rnd.random() < 0.10
    preempt_style = rnd.choices(
        ["never", "within", "reclaim", "borrow"],
        weights=[0.35, 0.2, 0.3, 0.15])[0]
    if replica_profile:
        fair = False
        pods_ready = False
        preempt_style = "never"
    cqs: List[dict] = []
    for c in range(num_cqs):
        chosen = sorted(rnd.sample(range(num_flavors),
                                   rnd.randint(1, num_flavors)))
        quotas = {}
        for fi in chosen:
            nom_cpu = rnd.randint(4, 16)
            nom_mem = rnd.randint(8, 32)
            if lending:
                quotas[f"flavor-{fi}"] = {
                    "cpu": [nom_cpu, nom_cpu // 2,
                            max(1, (3 * nom_cpu) // 4)],
                    "memory_gi": [nom_mem, nom_mem // 2,
                                  max(1, (3 * nom_mem) // 4)]}
            else:
                quotas[f"flavor-{fi}"] = {"cpu": [nom_cpu, None, None],
                                          "memory_gi": [nom_mem, None,
                                                        None]}
        pre = {"within": "Never", "reclaim": "Never"}
        if preempt_style == "within":
            pre = {"within": "LowerPriority", "reclaim": "Never"}
        elif preempt_style == "reclaim":
            pre = {"within": "LowerPriority",
                   "reclaim": rnd.choice(
                       ["Any", "LowerOrNewerEqualPriority"])}
        elif preempt_style == "borrow":
            pre = {"within": "LowerPriority", "reclaim": "Any",
                   "borrow": {"policy": "LowerPriority",
                              "threshold": 0}}
        cqs.append({
            "name": f"cq-{c}",
            "cohort": rnd.choice(leaf_names) if leaf_names else "",
            "strategy": rnd.choices(["BestEffortFIFO", "StrictFIFO"],
                                    weights=[0.7, 0.3])[0],
            "quotas": quotas,
            "preemption": pre,
            "fair_weight": float(rnd.randint(1, 4)) if fair else None,
        })

    # -- traffic ------------------------------------------------------------
    shape = rnd.choice(TRAFFIC_SHAPES)
    ticks = rnd.randint(10, 24)
    seq = [0]

    # Adversarial tie storm: the population the PR 8 bug class hides in.
    # Equal-weight fair sharing + reclaimWithinCohort, every cohort
    # member holding an EQUAL borrower (same size, priority and creation
    # time), then high-priority reclaimers — the fair victim search must
    # pick among equal-share member queues, where only the deterministic
    # name-sorted member walk keeps the choice stable run to run.
    tie_storm = (shape == "adversarial" and not replica_profile
                 and structure != "solo")
    tie_cpu = 0
    if tie_storm:
        fair = True
        pods_ready = False
        # A REAL tie needs equal shares: ONE flavor, identical quotas,
        # one cohort, equal weights — only then does the fair victim
        # search have to break the tie by member-walk order.
        tie_flavor = sorted(cqs[0]["quotas"])[0]
        tie_cpu = max(cqs[0]["quotas"][tie_flavor]["cpu"][0], 5)
        for cq in cqs:
            cq["fair_weight"] = 1.0
            cq["preemption"] = {"within": "LowerPriority",
                                "reclaim": "Any"}
            cq["quotas"] = {tie_flavor: {
                "cpu": [tie_cpu, None, None],
                "memory_gi": [32, None, None]}}
            cq["cohort"] = leaf_names[0]
        while len(cqs) < 6:
            # Wide member sets: the bug class is identity-hash SET
            # iteration, and a 2-3 element set often lands in the same
            # bucket order across drives — 5+ equal members make the
            # walk order genuinely layout-sensitive.
            cqs.append({**cqs[0],
                        "name": f"cq-{len(cqs)}",
                        "quotas": {tie_flavor: {
                            "cpu": [tie_cpu, None, None],
                            "memory_gi": [32, None, None]}}})

    def wl_spec(*, framework: int = 0, tie: bool = False) -> dict:
        seq[0] += 1
        i = seq[0]
        # hetero=False: the throughput profile is drawn once, below —
        # a second draw inside churn_arrival_draw would be dead RNG.
        d = churn_arrival_draw(rnd, num_cqs, num_flavors)
        if tie:
            # Adversarial tie shape: identical size, priority and
            # near-identical names — the population where victim/order
            # bugs (PR 8's identity-hash flip) hide.
            d["priority"], d["count"], d["cpu"], d["memory_gi"] = \
                0, 1, 2, 2
        if shape == "heavy_tailed":
            d["cpu"] = heavy_tailed_int(rnd, 1, 12)
            d["count"] = heavy_tailed_int(rnd, 1, 4)
        if shape == "multiframework":
            # Per-framework populations: batch (big, low prio), service
            # (small, high prio), interactive (tiny, mid prio bursts).
            fw_shape = [(4, 8, -1), (1, 2, 2), (1, 1, 1)][framework % 3]
            d["count"], d["cpu"], d["priority"] = fw_shape
        topo_kw = None
        if topology and rnd.random() < 0.5:
            topo_kw = ["required" if i % 4 == 0 else "preferred", "rack"]
        return {
            "name": f"wl-{i}",
            "queue": f"lq-cq-{d['queue_index']}",
            "priority": d["priority"],
            "creation_time": float(1000 + i),
            "pod_sets": [{"name": "ps0", "count": d["count"],
                          "cpu": d["cpu"],
                          "memory_gi": d["memory_gi"],
                          "topo": topo_kw}],
            "tputs": (hetero_profile_draw(rnd, num_flavors)
                      if hetero else None),
        }

    workloads = [wl_spec(framework=k, tie=(shape == "adversarial"
                                           and rnd.random() < 0.5))
                 for k in range(rnd.randint(3, 8))]
    if tie_storm:
        # One equal borrower per ClusterQueue (cpu = own first-flavor
        # nominal + 2, so any admitted one is BORROWING and thus a
        # reclaim candidate), all at the same priority and creation
        # time; then early-tick high-priority reclaimers.
        # cqs[0] stays borrower-free: a preemptor whose OWN queue holds
        # candidates resolves there first, and the member-order tie the
        # storm exists to exercise is between OTHER equal-share
        # members. Borrower size soaks the whole pool exactly
        # (n_cqs * nominal split over n_cqs - 1 borrowers, each above
        # nominal so every admitted one is BORROWING), leaving less
        # free capacity than one reclaimer needs.
        borrow_cpu = (len(cqs) * tie_cpu) // (len(cqs) - 1)
        borrowers = []
        for cq in cqs[1:]:
            seq[0] += 1
            borrowers.append({
                "name": f"tie-borrow-{cq['name']}",
                "queue": f"lq-{cq['name']}",
                "priority": 0, "creation_time": 999.0,
                "pod_sets": [{"name": "ps0", "count": 1,
                              "cpu": borrow_cpu, "memory_gi": 2,
                              "topo": None}],
                "tputs": None})
        workloads = borrowers + workloads

    traffic: List[list] = []
    for t in range(ticks):
        ops: List[list] = []
        if shape == "diurnal":
            n_arrivals = int(diurnal_rate(t, period=max(ticks // 2, 4),
                                          lo=0.0, hi=3.0) + rnd.random())
        elif shape == "adversarial":
            n_arrivals = rnd.choice([0, 0, 1, 4])
        else:
            n_arrivals = rnd.randint(0, 2)
        for k in range(n_arrivals):
            ops.append(["submit", wl_spec(
                framework=t + k,
                tie=(shape == "adversarial" and rnd.random() < 0.6))])
        if tie_storm and 1 <= t <= max(len(cqs) - 1, 1):
            # The reclaimer wave: high-priority sub-nominal arrivals
            # into the borrower-free cqs[0], each forcing a fair victim
            # choice among the OTHER members' equal-share borrowers.
            cq = cqs[0]
            seq[0] += 1
            ops.append(["submit", {
                "name": f"tie-reclaim-{seq[0]}",
                "queue": f"lq-{cq['name']}",
                "priority": 5, "creation_time": float(2000 + t),
                "pod_sets": [{"name": "ps0", "count": 1,
                              "cpu": tie_cpu,
                              "memory_gi": 1, "topo": None}],
                "tputs": None}])
        if rnd.random() < 0.35:
            ops.append(["finish", rnd.randint(1, 3)])
        if rnd.random() < 0.15:
            ops.append(["delete", f"wl-{rnd.randint(1, max(seq[0], 1))}"])
        if shape == "adversarial" and rnd.random() < 0.15:
            ops.append(["update_cq", f"cq-{rnd.randrange(num_cqs)}",
                        rnd.choice([0.5, 2.0, 4.0])])
        if pods_ready and rnd.random() < 0.5:
            ops.append(["ready", rnd.randint(1, 4)])
        traffic.append(ops)

    return Scenario(
        seed=seed, ticks=ticks, settle_ticks=4,
        flavors=flavors, topology=topo, cohorts=cohorts,
        cluster_queues=cqs,
        policy={"fair": fair, "lending": lending, "hetero": hetero,
                "pods_ready": pods_ready, "shape": shape},
        workloads=workloads, traffic=traffic)


def scenario_dimensions(sc: Scenario) -> list:
    """Draw-dimension labels for one scenario — the keys of the
    campaign's per-oracle coverage rollup. Derived from the scenario
    itself (not the draw code paths), so loaded reproducers and
    hand-written scenarios label identically to fresh draws."""
    structure = ("tree" if sc.cohorts
                 else "flat" if any(c.get("cohort")
                                    for c in sc.cluster_queues)
                 else "solo")
    styles = set()
    for cq in sc.cluster_queues:
        pre = cq.get("preemption") or {}
        if pre.get("borrow"):
            styles.add("borrow")
        if pre.get("reclaim", "Never") != "Never":
            styles.add("reclaim")
        if pre.get("within", "Never") != "Never":
            styles.add("within")
    dims = [f"shape={sc.policy.get('shape')}",
            f"structure={structure}",
            f"preemption={'+'.join(sorted(styles)) or 'never'}"]
    for flag in ("fair", "lending", "hetero", "pods_ready"):
        if sc.policy.get(flag):
            dims.append(f"policy={flag}")
    if sc.topology:
        dims.append("policy=topology")
    if sc.seed % 4 == 3:
        dims.append("profile=replica")
    return dims
