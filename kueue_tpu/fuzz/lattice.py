"""Lattice driver: replay one scenario across configuration points.

Every scenario is driven through a lattice of configurations of the SAME
scheduler — the sequential referee, the batched device solve under
different victim-search engines, cohort shards {1,2}, multi-process
replicas {1,2}, the incremental-fast-path kill-switch set, and (on a
rotating subset of seeds) a replica fail-over drill (journal replay) and
an elastic capacity-loan drill. The repo's standing decision-identity
contracts become fuzz oracles:

  identity      every lattice point's decision trail equals the
                reference point's (per-tick admitted+preempted for
                in-process points; per-tick admitted + final admitted
                set for replica points)
  determinism   the reference point driven TWICE produces the identical
                trail (the oracle that catches PR 8's identity-hash
                victim flip class — run-to-run nondeterminism)
  quota         no cohort tree's total usage ever exceeds its total
                nominal capacity, and no solo CQ exceeds its own
                (checked after every tick)
  journal       the fail-over drill point kills a replica mid-run; the
                survivor adopts its shard groups by REPLAYING their
                journals, and the final admitted set must still equal
                the reference — journal-replay equivalence
  loan          the elastic drill migrates a live shard group between
                workers mid-run; decisions must be unchanged

Traffic ops apply through deterministic selectors (see scenario.py), so
all points replay identical traffic while their decisions agree.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import tempfile
from typing import Dict, List, Optional

from kueue_tpu.fuzz import scenario as sc_mod
from kueue_tpu.fuzz.scenario import Scenario


@dataclasses.dataclass(frozen=True)
class LatticePoint:
    name: str
    kind: str                      # "referee" | "framework" | "replica"
    engine: Optional[str] = None   # preemption_engine knob
    shards: int = 1
    replicas: int = 1
    kill_switches: bool = False    # incremental fast paths OFF
    # None | "failover" | "loan" | "degraded" | "snapshot"
    # ("snapshot" = the failover kill, but the survivor bootstraps the
    # dead worker's groups from a shipped compacted snapshot instead of
    # full line replay — same journal-replay-equivalence oracle)
    drill: Optional[str] = None
    env: tuple = ()                # extra (key, value) env pairs
    # Dirty-cohort micro-ticks interleaved with the traffic (the
    # event-driven fast path). Micro-ticks intentionally reorder vs the
    # barrier-paced trail, so a micro point with the kill switch CLEAR
    # is exempt from the identity oracle and pinned by the invariant
    # oracles instead (quota high-water, per-CQ FIFO, journal replay);
    # with KUEUE_TPU_NO_MICROTICK=1 in `env` the micro calls are no-ops
    # and byte identity with the reference must hold.
    micro: bool = False
    # Replica-point transport: None = the loopback queue pairs (the
    # smoke default); "socket" = the real framed TCP channel, with
    # seeded packet faults when `socket_faults` — the multi-HOST
    # lattice point. Budget-gated (--lattice socket / make fuzz-nightly):
    # a socket drive pays listener + reconnect machinery per scenario,
    # too much for the 25-seed smoke budget.
    transport: Optional[str] = None
    socket_faults: bool = False

    def axes(self) -> dict:
        return {"engine": self.engine or ("referee" if
                                          self.kind == "referee"
                                          else "host"),
                "shards": self.shards, "replicas": self.replicas,
                "kill_switches": self.kill_switches, "drill": self.drill,
                "micro": self.micro,
                "transport": self.transport or
                ("loopback" if self.kind == "replica" else None)}

    def identity_exempt(self) -> bool:
        """True when this point's decisions may legally reorder vs the
        reference (live micro-ticks, degraded windows): the identity /
        final-set oracles stand down and the invariant oracles rule."""
        if self.drill == "degraded":
            return True
        return self.micro and not any(
            k == "KUEUE_TPU_NO_MICROTICK" and v == "1"
            for k, v in self.env)


class TickClock:
    """Deterministic scheduler clock: frozen within a tick, advanced by
    the driver between ticks. Wall-clock condition timestamps
    (QuotaReserved/Evicted transition times feed candidate ordering)
    differ between two drives of the same scenario and would fake — or
    mask — a decision divergence (the fair-golden lesson)."""

    def __init__(self):
        self.now = 1_000_000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float = 1.0) -> None:
        self.now += dt


def _shards_available(n: int) -> bool:
    try:
        import jax

        return len(jax.devices()) >= n
    except Exception:
        return False


def socket_points(sc: Scenario) -> List[LatticePoint]:
    """The multi-HOST lattice points (budget-gated: `--lattice
    socket` / `make fuzz-nightly`, never the 25-seed smoke): the same replica
    drive over the REAL framed TCP channel — once clean, once under
    seeded packet delay + reorder faults (drop adds reconnect churn on
    a rotating third of seeds). Decision identity must hold across all
    of it: the transport is exactly-once in-order by construction, and
    these points are where that claim meets the fuzzer."""
    if not sc.replica_safe():
        return []
    pts = [LatticePoint(name="socket", kind="replica", replicas=2,
                        transport="socket")]
    pts.append(LatticePoint(name="socket-faults", kind="replica",
                            replicas=2, transport="socket",
                            socket_faults=True))
    return pts


def default_lattice(sc: Scenario,
                    include_socket: bool = False) -> List[LatticePoint]:
    """The smoke lattice for one scenario: engine x shards {1,2} x
    replicas {1,2} x one kill-switch set, plus drill points on a
    rotating third of the seeds. Hetero scenarios swap the sequential
    referee for a KUEUE_TPU_DEBUG_HETERO reference (the hetero referee
    asserts device-vs-sequential identity INSIDE every tick); scenarios
    outside the documented replica-identity envelope skip the replica
    points (scenario.replica_safe). `include_socket` appends the
    multi-HOST socket points (see socket_points — nightly budget)."""
    points: List[LatticePoint] = []
    if sc.policy.get("hetero"):
        points.append(LatticePoint(
            name="hetero-referee", kind="framework", engine="host",
            env=(("KUEUE_TPU_DEBUG_HETERO", "1"),)))
        points.append(LatticePoint(
            name="hetero-referee-repeat", kind="framework",
            engine="host",
            env=(("KUEUE_TPU_DEBUG_HETERO", "1"),)))
    else:
        points.append(LatticePoint(name="referee", kind="referee"))
        points.append(LatticePoint(name="referee-repeat",
                                   kind="referee"))
        points.append(LatticePoint(name="batched-host",
                                   kind="framework", engine="host"))
    points.append(LatticePoint(name="batched-jax", kind="framework",
                               engine="jax"))
    if _shards_available(2):
        points.append(LatticePoint(name="shards-2", kind="framework",
                                   engine="jax", shards=2))
    points.append(LatticePoint(name="kill-switches", kind="framework",
                               engine="jax", kill_switches=True,
                               env=(("KUEUE_TPU_NO_QUIET_TICK", "1"),)))
    # Event-driven admission: micro-ticks interleaved with the traffic.
    # The live point is identity-EXEMPT (intentional reorder; invariant
    # oracles rule); the kill-switch twin proves KUEUE_TPU_NO_MICROTICK=1
    # restores byte identity with the reference.
    points.append(LatticePoint(name="microtick", kind="framework",
                               engine="jax", micro=True))
    points.append(LatticePoint(
        name="microtick-off", kind="framework", engine="jax", micro=True,
        env=(("KUEUE_TPU_NO_MICROTICK", "1"),)))
    if sc.replica_safe():
        points.append(LatticePoint(name="replicas-2", kind="replica",
                                   replicas=2))
        if sc.seed % 3 == 0:
            points.append(LatticePoint(name="failover-journal",
                                       kind="replica", replicas=2,
                                       drill="failover"))
            # Snapshot-rejoin rides the SAME seeds as the full-replay
            # drill: both must match the uninterrupted reference, so
            # snapshot bootstrap == full replay == uninterrupted run.
            points.append(LatticePoint(
                name="snapshot-rejoin", kind="replica", replicas=2,
                drill="snapshot",
                env=(("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", "1"),)))
        if sc.seed % 3 == 1:
            points.append(LatticePoint(name="elastic-loan",
                                       kind="replica", replicas=2,
                                       drill="loan"))
            # Seeded disk faults on the snapshot write: the bootstrap
            # seed tears mid-write and the adoption must fall back to
            # line replay with zero records lost (same identity bar).
            points.append(LatticePoint(
                name="snapshot-rejoin-torn", kind="replica", replicas=2,
                drill="snapshot",
                env=(("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", "1"),
                     ("KUEUE_TPU_SNAPSHOT_BOOT_FAULTS",
                      f"torn_p=1.0,seed={sc.seed}"))))
        if sc.seed % 3 == 2:
            # The rotation's third slot: micro-ticks under the
            # journal-replay drill (a worker killed mid-run; its micro
            # admissions must replay without oversubscription), and the
            # degraded-window drill (coordinator silence + rejoin under
            # the revocation-bounded identity oracle).
            points.append(LatticePoint(name="microtick-failover",
                                       kind="replica", replicas=2,
                                       drill="failover", micro=True))
            points.append(LatticePoint(name="degraded-window",
                                       kind="replica", replicas=2,
                                       drill="degraded"))
    if include_socket:
        points.extend(socket_points(sc))
    return points


@contextlib.contextmanager
def _env_ctx(pairs):
    old = {}
    try:
        for k, v in pairs:
            old[k] = os.environ.get(k)
            os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _set_gates(sc: Scenario) -> None:
    from kueue_tpu import features

    features.set_enabled(features.FAIR_SHARING,
                         bool(sc.policy.get("fair")))
    features.set_enabled(features.LENDING_LIMIT,
                         bool(sc.policy.get("lending")))


def _merge_caps(hw: dict, caps: dict) -> dict:
    """Elementwise max of two capacity maps: the quota oracle bounds
    usage by the HIGH-WATER capacity, because a quota SHRINK (an
    update_cq with factor < 1) legitimately leaves already-committed
    usage above the new nominal — the reference never evicts on spec
    shrink; only NEW admissions see the reduced quota."""
    for root, by_flavor in caps.items():
        dst = hw.setdefault(root, {})
        for fname, res in by_flavor.items():
            d = dst.setdefault(fname, {})
            for rname, val in res.items():
                d[rname] = max(d.get(rname, 0), val)
    return hw


def _check_oversub(sc: Scenario, usage_by_cq: Dict[str, dict],
                   caps: dict, tick: int) -> List[dict]:
    """The quota oracle: per cohort-tree root (and per solo CQ), total
    usage must never exceed the total (high-water) nominal capacity —
    borrowing moves quota between members, it never mints any."""
    used: Dict[str, dict] = {}
    for cq in sc.cluster_queues:
        root = sc_mod.cq_root(sc, cq["name"])
        u = usage_by_cq.get(cq["name"]) or {}
        dst = used.setdefault(root, {})
        for fname, res in u.items():
            d = dst.setdefault(fname, {})
            for rname, val in res.items():
                d[rname] = d.get(rname, 0) + val
    out = []
    for root, by_flavor in used.items():
        for fname, res in by_flavor.items():
            for rname, val in res.items():
                cap = caps.get(root, {}).get(fname, {}).get(rname, 0)
                if val > cap:
                    out.append({
                        "oracle": "quota", "tick": tick,
                        "detail": f"root {root} {fname}/{rname}: "
                                  f"usage {val} > capacity {cap}"})
    return out


class _TrafficState:
    """Driver-side bookkeeping shared by the Framework and replica
    drives: which workloads are pending/admitted, in which deterministic
    order, so op selectors resolve identically everywhere."""

    def __init__(self):
        self.submitted: Dict[str, dict] = {}    # key -> wl spec
        self.pending: set = set()
        self.admit_order: List[tuple] = []      # (tick, key, cq)
        self.admitted: Dict[str, str] = {}      # key -> cq
        self.ready_marked: set = set()
        self.factors: Dict[str, float] = {}

    def note_admitted(self, tick: int, pairs) -> None:
        for key, cq in sorted(pairs):
            self.admit_order.append((tick, key, cq))
            self.admitted[key] = cq
            self.pending.discard(key)

    def note_preempted(self, keys) -> None:
        for key in keys:
            if key in self.admitted:
                del self.admitted[key]
                self.pending.add(key)

    def oldest_admitted(self, n: int) -> List[tuple]:
        out = []
        seen = set()
        for tick, key, cq in self.admit_order:
            # A preempted-then-readmitted workload appears twice in
            # admit_order; dedup so one finish op never double-counts
            # (or double-finishes) a key.
            if key in self.admitted and key not in seen:
                seen.add(key)
                out.append((key, cq))
                if len(out) >= n:
                    break
        return out


def drive(sc: Scenario, point: LatticePoint,
          state_dir: Optional[str] = None) -> dict:
    """Replay `sc` at `point`; returns {"trail", "final_admitted",
    "violations", "evidence"}. Raises nothing scenario-shaped — build
    or drive crashes propagate to the caller (crashes are findings)."""
    _set_gates(sc)
    try:
        with _env_ctx(point.env):
            if point.kind == "replica":
                return _drive_replica(sc, point, state_dir)
            return _drive_framework(sc, point)
    finally:
        from kueue_tpu import features

        features.reset()


# -- in-process drives ------------------------------------------------------


def _build_framework(sc: Scenario, point: LatticePoint, clock):
    from kueue_tpu.config import Configuration, TPUSolverConfig, \
        WaitForPodsReady
    from kueue_tpu.controllers.runtime import Framework

    wfpr = None
    if sc.policy.get("pods_ready"):
        # Huge timeout: the not-ready eviction pass reads wall-deltas
        # and would otherwise make drives time-dependent.
        wfpr = WaitForPodsReady(enable=True, timeout_seconds=1e9)
    if point.kind == "referee":
        cfg = Configuration(tpu_solver=TPUSolverConfig(enable=False),
                            wait_for_pods_ready=wfpr)
        solver = None
    else:
        from kueue_tpu.models.flavor_fit import BatchSolver

        cfg = Configuration(
            tpu_solver=TPUSolverConfig(
                preemption_engine=point.engine or "host"),
            wait_for_pods_ready=wfpr)
        inc = not point.kill_switches
        solver = BatchSolver(
            shards=point.shards if point.shards > 1 else None,
            hetero=True if sc.policy.get("hetero") else None,
            use_arena=inc, use_admit_arena=inc, use_nominate_cache=inc)
    fw = Framework(batch_solver=solver, config=cfg, pipeline_depth=1,
                   clock=clock)
    fw.create_namespace("default", labels={})
    for rf in sc_mod.flavor_objects(sc):
        fw.create_resource_flavor(rf)
    for spec in sc_mod.cohort_objects(sc):
        fw.create_cohort(spec)
    for cq in sc.cluster_queues:
        fw.create_cluster_queue(sc_mod.cq_object(cq))
        fw.create_local_queue(sc_mod.lq_object(cq))
    return fw


class FrameworkTrafficDriver:
    """Traffic application against a live Framework — the ONE home of
    the deterministic op selectors for in-process drives. Shared by the
    lattice's framework points and the digital twin's replay engine
    (kueue_tpu/twin/engine.py), so the twin applies exactly the op
    semantics the decision-identity oracles were proven on; a selector
    change lands in both or the byte-match cross-check goes red."""

    def __init__(self, fw, sc: Scenario,
                 st: Optional[_TrafficState] = None):
        self.fw = fw
        self.sc = sc
        self.st = st if st is not None else _TrafficState()
        self.objects: Dict[str, object] = {}
        self.cq_specs = {c["name"]: c for c in sc.cluster_queues}
        self.caps_hw = sc_mod.nominal_capacity(sc, {})

    def submit(self, spec: dict, wl=None, validate: bool = True):
        """`wl`/`validate` are the twin's bulk-ingest seam: a prebuilt
        (equal) workload object and a skipped pure-validation pass.
        Fuzz drives never pass them — the lattice keeps the full
        production submit path."""
        st = self.st
        if wl is None:
            wl = sc_mod.workload_object(spec)
        self.objects[wl.key] = wl
        st.submitted[wl.key] = spec
        st.pending.add(wl.key)
        self.fw.submit(wl, validate=validate)
        return wl

    def finish_key(self, key: str) -> bool:
        """Finish+delete one admitted workload by key — the same body
        as one step of the "finish" selector; the twin's duration-driven
        completions route through here."""
        st = self.st
        wl = self.objects.get(key)
        if wl is None or not wl.is_admitted or wl.is_finished:
            return False
        self.fw.finish(wl)
        self.fw.delete_workload(wl)
        st.admitted.pop(key, None)
        st.ready_marked.discard(key)
        return True

    def apply(self, op: list) -> None:
        st = self.st
        kind = op[0]
        if kind == "submit":
            self.submit(op[1])
        elif kind == "finish":
            for key, _cq in st.oldest_admitted(int(op[1])):
                self.finish_key(key)
        elif kind == "delete":
            key = f"default/{op[1]}"
            wl = self.objects.get(key)
            if wl is not None and key in st.pending \
                    and not wl.is_admitted and not wl.is_finished:
                self.fw.delete_workload(wl)
                st.pending.discard(key)
        elif kind == "update_cq":
            name, factor = op[1], float(op[2])
            st.factors[name] = st.factors.get(name, 1.0) * factor
            _merge_caps(self.caps_hw,
                        sc_mod.nominal_capacity(self.sc, st.factors))
            self.fw.update_cluster_queue(
                sc_mod.cq_object(self.cq_specs[name], st.factors[name]))
        elif kind == "ready":
            n = int(op[1])
            marked = 0
            for _tick, key, _cq in st.admit_order:
                if key in st.admitted and key not in st.ready_marked:
                    wl = self.objects.get(key)
                    if wl is not None and wl.is_admitted:
                        self.fw.mark_pods_ready(wl)
                        st.ready_marked.add(key)
                        marked += 1
                        if marked >= n:
                            break
        else:
            raise ValueError(f"unknown traffic op {op!r}")

    def note_tick(self, t: int, tick_admitted, tick_preempted) -> None:
        st = self.st
        st.note_admitted(t, [(k, st.submitted[k]["queue"][3:])
                             for k in tick_admitted])
        st.note_preempted(tick_preempted)


def _drive_framework(sc: Scenario, point: LatticePoint) -> dict:
    clock = TickClock()
    fw = _build_framework(sc, point, clock)
    drv = FrameworkTrafficDriver(fw, sc)
    st = drv.st
    caps_hw = drv.caps_hw

    tick_admitted: List[str] = []
    tick_preempted: List[str] = []
    orig_admit = fw.scheduler.apply_admission
    orig_preempt = fw.scheduler.apply_preemption

    def apply_admission(wl):
        ok = orig_admit(wl)
        if ok:
            tick_admitted.append(wl.key)
        return ok

    def apply_preemption(wl, msg):
        tick_preempted.append(wl.key)
        return orig_preempt(wl, msg)

    fw.scheduler.apply_admission = apply_admission
    fw.scheduler.apply_preemption = apply_preemption

    for spec in sc.workloads:
        drv.submit(spec)

    # Micro-point bookkeeping for the per-CQ FIFO invariant oracle:
    # per-CQ admission sequence (StrictFIFO queues only — BestEffortFIFO
    # legally lets smaller later workloads overtake a parked NoFit
    # head), with preempted/evicted keys excluded (a readmission's
    # position is policy, not queue order).
    admit_seq_by_cq: Dict[str, List[str]] = {}
    ever_preempted: set = set()

    trail = []
    violations: List[dict] = []
    evidence: dict = {}
    for t in range(sc.ticks + sc.settle_ticks):
        tick_admitted.clear()
        tick_preempted.clear()
        if t < sc.ticks:
            for op in sc.traffic[t] if t < len(sc.traffic) else ():
                drv.apply(op)
        if point.micro:
            # The event-driven path: dirty cohorts admit NOW, before
            # the tick (a no-op under KUEUE_TPU_NO_MICROTICK=1 — the
            # kill-switch twin must replay the reference byte for byte).
            fw.microtick()
        fw.tick()
        clock.advance()
        drv.note_tick(t, tick_admitted, tick_preempted)
        ever_preempted.update(tick_preempted)
        for k in tick_admitted:
            cq_name = st.submitted[k]["queue"][3:]
            admit_seq_by_cq.setdefault(cq_name, []).append(k)
        trail.append((tuple(sorted(tick_admitted)),
                      tuple(sorted(tick_preempted))))
        usage = {name: {f: dict(r) for f, r in cq.usage.items()}
                 for name, cq in fw.cache.cluster_queues.items()}
        violations.extend(_check_oversub(sc, usage, caps_hw, t))

    if point.micro and not any(k == "KUEUE_TPU_NO_MICROTICK"
                               for k, _v in point.env):
        violations.extend(_check_fifo(sc, st, admit_seq_by_cq,
                                      ever_preempted))
        evidence["microticks"] = fw.scheduler.metrics.microticks
        evidence["micro_admitted"] = fw.scheduler.metrics.micro_admitted

    final = {name: sorted(cq.workloads)
             for name, cq in fw.cache.cluster_queues.items()}
    return {"trail": trail, "final_admitted": final,
            "violations": violations, "evidence": evidence}


def _check_fifo(sc: Scenario, st: _TrafficState,
                admit_seq_by_cq: Dict[str, List[str]],
                ever_preempted: set) -> List[dict]:
    """The micro-tick FIFO invariant: within each StrictFIFO
    ClusterQueue, same-priority workloads that were never preempted
    must admit in queue order (priority desc, creation time asc is the
    heap order; micro-ticks pop heads exactly like the full sweep, so
    any inversion is a fast-path ordering bug)."""
    strict = {c["name"] for c in sc.cluster_queues
              if c.get("strategy") == "StrictFIFO"}
    out: List[dict] = []
    for cq_name, keys in admit_seq_by_cq.items():
        if cq_name not in strict:
            continue
        last_by_priority: Dict[int, float] = {}
        for key in keys:
            if key in ever_preempted:
                continue
            spec = st.submitted.get(key)
            if spec is None:
                continue
            prio = int(spec.get("priority", 0))
            ct = float(spec["creation_time"])
            prev = last_by_priority.get(prio)
            if prev is not None and ct < prev:
                out.append({
                    "oracle": "fifo", "tick": -1,
                    "detail": f"CQ {cq_name}: same-priority ({prio}) "
                              f"admission order inverted at {key} "
                              f"(creation {ct} after {prev})"})
            last_by_priority[prio] = max(
                ct, prev if prev is not None else ct)
        # (max keeps the watermark: an EARLIER creation admitted after
        # a later one is the inversion; equal times are fine.)
    return out


# -- replica drives ---------------------------------------------------------


def _drive_replica(sc: Scenario, point: LatticePoint,
                   state_dir: Optional[str]) -> dict:
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.controllers.store import KIND_CLUSTER_QUEUE, MODIFIED

    tmp = None
    if point.drill in ("failover", "snapshot") and state_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="kueuefuzz-journal-")
        state_dir = tmp.name
    faults = None
    if point.socket_faults:
        from kueue_tpu.transport.faults import FaultPlan

        # Seeded per scenario: identical schedule on every re-drive
        # (shrinking included). Drop only on a rotating third — it
        # severs connections, which is reconnect churn, not decisions.
        faults = FaultPlan(seed=sc.seed, delay_ms=1.0, delay_prob=0.3,
                           reorder_prob=0.1,
                           drop_prob=0.02 if sc.seed % 3 == 0 else 0.0)
    rt = ReplicaRuntime(
        point.replicas, spawn=False, engine=point.engine,
        state_dir=(state_dir if point.drill in ("failover", "snapshot")
                   else None),
        # The snapshot drill needs the coordinator-side replicator (the
        # per-host journal layout), so the adoption seed can come from
        # bootstrap_lines instead of the dead worker's local file.
        per_host=True if point.drill == "snapshot" else None,
        transport=point.transport, faults=faults,
        microtick=point.micro,
        degraded_after=(0.8 if point.drill == "degraded" else None),
        n_groups=(2 * point.replicas if point.drill == "loan" else None))
    st = _TrafficState()
    cq_specs = {c["name"]: c for c in sc.cluster_queues}
    caps_hw = sc_mod.nominal_capacity(sc, {})
    trail = []
    violations: List[dict] = []
    evidence: dict = {}
    try:
        for rf in sc_mod.flavor_objects(sc):
            rt.create_resource_flavor(rf)
        for spec in sc_mod.cohort_objects(sc):
            rt.create_cohort(spec)
        for cq in sc.cluster_queues:
            rt.create_cluster_queue(sc_mod.cq_object(cq))
            rt.create_local_queue(sc_mod.lq_object(cq))

        def submit(spec: dict) -> None:
            wl = sc_mod.workload_object(spec)
            st.submitted[wl.key] = spec
            st.pending.add(wl.key)
            rt.submit(wl)

        def apply_op(op: list) -> None:
            kind = op[0]
            if kind == "submit":
                submit(op[1])
            elif kind == "finish":
                pairs = st.oldest_admitted(int(op[1]))
                if pairs:
                    rt.finish_many(pairs)
                    for key, _cq in pairs:
                        del st.admitted[key]
            elif kind == "delete":
                key = f"default/{op[1]}"
                if key in st.pending and key not in st.admitted:
                    rt.delete_workload(key)
                    st.pending.discard(key)
            elif kind == "update_cq":
                name, factor = op[1], float(op[2])
                st.factors[name] = st.factors.get(name, 1.0) * factor
                _merge_caps(caps_hw,
                            sc_mod.nominal_capacity(sc, st.factors))
                rt.apply_event(
                    KIND_CLUSTER_QUEUE, MODIFIED,
                    obj=sc_mod.cq_object(cq_specs[name],
                                         st.factors[name]))
            elif kind == "ready":
                pass  # pods_ready scenarios never take replica points
            else:
                raise ValueError(f"unknown traffic op {op!r}")

        for spec in sc.workloads:
            submit(spec)

        for t in range(sc.ticks + sc.settle_ticks):
            if t < sc.ticks:
                for op in sc.traffic[t] if t < len(sc.traffic) else ():
                    apply_op(op)
            elif t == sc.ticks and point.drill in ("failover",
                                                   "snapshot"):
                # Journal-replay equivalence: kill one replica at the
                # settle boundary; the next tick reassigns its shard
                # groups to the survivor, which attach-replays their
                # journals — the final admitted set must still match.
                victim = rt.group_owner[
                    rt.gmap.cq_group[sc.cluster_queues[0]["name"]]]
                rt.kill_replica(victim)
                evidence["killed_replica"] = victim
            elif t == sc.ticks and point.drill == "loan":
                # Elastic capacity loan: migrate a live group from
                # worker 0 to worker 1 mid-run; decisions must be
                # unchanged (migration preserves admitted state).
                gid = next((g for g, w in sorted(
                    rt.group_owner.items()) if w == 0), None)
                if gid is not None:
                    rt.migrate_group(gid, 1 % point.replicas)
                    evidence["loaned_group"] = gid
            elif t == sc.ticks and point.drill == "degraded":
                # Degraded window: the coordinator goes SILENT long
                # enough for every worker's deadline to fire (they
                # self-tick flat cohorts under the journaled safe
                # mode), then rejoin runs the catch-up reconcile. The
                # revocation-bounded identity oracle closes the drive:
                # workloads the reference run admitted may only be
                # missing from this final set if a counted rejoin
                # revocation took them back.
                rt.degraded_window(1.8)
                ev = rt.rejoin()
                evidence["degraded"] = {
                    "window_ticks": ev["degraded_window_ticks"],
                    "admissions": ev["degraded_admissions"],
                    "parked": ev["parked"],
                    "revocations": ev["rejoin_revocations"],
                    "revoked_keys": ev.get("revoked_keys") or [],
                }
            stats = rt.tick()
            admitted_pairs = sorted(stats["admitted"])
            st.note_admitted(t, admitted_pairs)
            st.note_preempted(sorted(stats["preempted"]))
            trail.append((tuple(k for k, _cq in admitted_pairs),
                          tuple(sorted(stats["preempted"]))))
            # Per-tick quota oracle, same cadence as the in-process
            # drive — a TRANSIENT oversubscription during the drill
            # windows (migration, journal replay) must not hide behind
            # a legal final state. Best-effort mid-drill: a dump racing
            # a just-killed worker is skipped (the final check below
            # always runs).
            try:
                mid = rt.dump().get("usage") or {}
            except Exception:
                mid = None
            if mid is not None:
                violations.extend(_check_oversub(sc, mid, caps_hw, t))
        dump = rt.dump()
        violations.extend(_check_oversub(
            sc, dump.get("usage") or {}, caps_hw,
            sc.ticks + sc.settle_ticks - 1))
        final = {name: sorted(keys)
                 for name, keys in (dump.get("admitted") or {}).items()}
        evidence["coordinator"] = rt.coordinator.evidence()
        if point.drill == "snapshot":
            boot = rt.bootstrap_evidence
            if boot is None:
                # The kill happened but adoption never took the
                # replicator-seeded path: the snapshot drill was
                # vacuous — a wiring failure, not a passing run.
                violations.append({
                    "oracle": "snapshot-bootstrap",
                    "tick": sc.ticks,
                    "detail": "adoption produced no bootstrap evidence "
                              "(replicator seed path never engaged)"})
            else:
                evidence["snapshot_bootstrap"] = dict(boot)
                torn_armed = any(
                    k == "KUEUE_TPU_SNAPSHOT_BOOT_FAULTS"
                    for k, v in point.env)
                if torn_armed and boot.get("snapshot") \
                        and not boot.get("torn_fallback"):
                    violations.append({
                        "oracle": "snapshot-bootstrap",
                        "tick": sc.ticks,
                        "detail": "torn-write faults armed and a "
                                  "snapshot shipped, but the adoption "
                                  "never fell back to line replay"})
    finally:
        rt.close()
        if tmp is not None:
            tmp.cleanup()
    return {"trail": trail, "final_admitted": final,
            "violations": violations, "evidence": evidence}


# -- scenario-level check ---------------------------------------------------


def _first_divergence(ref_trail, got_trail, admitted_only: bool):
    for t, (a, b) in enumerate(zip(ref_trail, got_trail)):
        ra = a[0] if admitted_only else a
        rb = b[0] if admitted_only else b
        if ra != rb:
            return t, ra, rb
    if len(ref_trail) != len(got_trail):
        return min(len(ref_trail), len(got_trail)), None, None
    return None


def _check_degraded_bound(sc: Scenario, ref: dict, got: dict,
                          point_name: str) -> List[dict]:
    """The revocation-bounded identity oracle for the degraded-window
    drill: after rejoin + settle, every (cq, workload) pair the
    uninterrupted reference holds admitted must either be admitted here
    too, or appear among the rejoin reconcile's counted revocations —
    an UNEXPLAINED loss is a violation (a silent take-back, exactly
    what the journaled-verdict invariant forbids)."""
    ref_pairs = {(cq, k) for cq, keys in ref["final_admitted"].items()
                 for k in keys}
    got_pairs = {(cq, k) for cq, keys in got["final_admitted"].items()
                 for k in keys}
    revoked = set((got.get("evidence") or {}).get(
        "degraded", {}).get("revoked_keys") or [])
    missing = {(cq, k) for cq, k in ref_pairs - got_pairs
               if k not in revoked}
    if not missing:
        return []
    return [{
        "oracle": "degraded-identity", "point": point_name,
        "tick": sc.ticks + sc.settle_ticks,
        "detail": f"workloads lost without a counted revocation: "
                  f"{sorted(missing)[:4]}"}]


def check_scenario(sc: Scenario,
                   points: Optional[List[LatticePoint]] = None,
                   keep_results: bool = False,
                   include_socket: bool = False) -> dict:
    """Drive `sc` across the lattice and return the oracle report:
    {"seed", "points", "violations": [...], "axes"}. An empty
    violations list means every oracle held at every point.
    `keep_results=True` attaches each point's raw drive result under
    "results" (the corpus replay reads the reference drive from there
    instead of paying a second one)."""
    points = points if points is not None else default_lattice(
        sc, include_socket=include_socket)
    results: Dict[str, dict] = {}
    violations: List[dict] = []
    for p in points:
        try:
            results[p.name] = drive(sc, p)
        except Exception as exc:  # crashes are findings, not aborts
            violations.append({"oracle": "crash", "point": p.name,
                               "detail": f"{type(exc).__name__}: {exc}"})
            results[p.name] = None
    # Per-point oracle violations (quota, drive-local) stand on their
    # own — collect them even when the reference point crashed.
    for p in points:
        r = results.get(p.name)
        if r is not None:
            for vi in r["violations"]:
                violations.append({**vi, "point": p.name})
    ref_point = points[0]
    ref = results.get(ref_point.name)
    if ref is not None:
        for p in points[1:]:
            r = results.get(p.name)
            if r is None:
                continue
            if p.identity_exempt():
                # Live micro-ticks / degraded windows intentionally
                # reorder vs the barrier-paced reference: the per-point
                # invariant oracles (quota high-water, FIFO, crash)
                # already ran above. The degraded drill additionally
                # gets the revocation-bounded identity check: anything
                # the reference's final set holds that this drive lost
                # must be covered by a counted rejoin revocation.
                if p.drill == "degraded":
                    violations.extend(_check_degraded_bound(
                        sc, ref, r, p.name))
                continue
            admitted_only = p.kind == "replica"
            div = _first_divergence(ref["trail"], r["trail"],
                                    admitted_only)
            oracle = ("determinism" if p.name.endswith("-repeat")
                      else "journal" if p.drill in ("failover",
                                                    "snapshot")
                      else "loan" if p.drill == "loan"
                      else "identity")
            if div is not None:
                t, a, b = div
                violations.append({
                    "oracle": oracle, "point": p.name, "tick": t,
                    "detail": f"tick {t}: {ref_point.name}={a!r} "
                              f"vs {p.name}={b!r}"})
            elif r["final_admitted"] != ref["final_admitted"]:
                diff = {
                    name for name in set(r["final_admitted"])
                    | set(ref["final_admitted"])
                    if r["final_admitted"].get(name)
                    != ref["final_admitted"].get(name)}
                violations.append({
                    "oracle": oracle, "point": p.name,
                    "tick": sc.ticks + sc.settle_ticks,
                    "detail": f"final admitted sets differ on "
                              f"{sorted(diff)[:4]}"})
    report = {"seed": sc.seed,
              "points": [p.name for p in points],
              "axes": [p.axes() for p in points],
              "violations": violations,
              "events": _event_rollup(points, results)}
    if keep_results:
        report["results"] = results
    return report


def _event_rollup(points: List[LatticePoint],
                  results: Dict[str, dict]) -> dict:
    """What the scenario actually EXERCISED, rolled up across the
    lattice: admission / preemption counts from the reference trail,
    micro admissions and replica revocations from the point evidence.
    The campaign aggregates these per draw dimension so dead corpus
    regions (a dimension that never produced a preemption, revocation,
    or micro admission) are visible in every report."""
    ev = {"admitted": 0, "preempted": 0, "micro_admitted": 0,
          "revocations": 0, "snapshot_bootstraps": 0,
          "torn_fallbacks": 0}
    ref = results.get(points[0].name) if points else None
    if ref is not None:
        for adm, pre in ref["trail"]:
            ev["admitted"] += len(adm)
            ev["preempted"] += len(pre)
    for p in points:
        r = results.get(p.name)
        if r is None:
            continue
        evidence = r.get("evidence") or {}
        ev["micro_admitted"] += int(evidence.get("micro_admitted") or 0)
        coord = evidence.get("coordinator") or {}
        ev["revocations"] += int(coord.get("revocations") or 0)
        deg = evidence.get("degraded") or {}
        ev["revocations"] += int(deg.get("revocations") or 0)
        boot = evidence.get("snapshot_bootstrap") or {}
        if boot.get("snapshot") or boot.get("torn_fallback"):
            ev["snapshot_bootstraps"] += 1
        if boot.get("torn_fallback"):
            ev["torn_fallbacks"] += 1
    return ev
