"""Scenario model: a fully JSON-serializable scheduling scenario.

A Scenario is the fuzzer's unit of work AND the reproducer file format:
everything the lattice driver needs to rebuild a cluster (flavors,
cohort tree, ClusterQueues, policy gates) and replay a traffic script is
plain data, so a diverging draw can be shrunk structurally and checked
in under tests/fixtures/fuzz/ as a self-contained golden.

Traffic is a per-tick op script. Ops reference live state only through
DETERMINISTIC selectors ("finish the n oldest admitted", "delete this
workload if still pending"), so two drives that have made identical
decisions so far apply identical traffic — the property the
decision-identity oracles rest on (after the first divergence the
streams may differ, but the oracle has already fired).

Op forms (each a JSON list):
  ["submit", workload_spec]   submit a fresh workload
  ["finish", n]               finish+delete the n oldest still-admitted
  ["delete", name]            delete "default/<name>" if still pending
  ["update_cq", name, factor] re-apply the CQ spec with quotas scaled
  ["ready", n]                mark the n oldest not-ready admitted
                              workloads PodsReady (pods_ready policy)
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

GI = 1024 ** 3
FORMAT = "kueuefuzz/v1"


@dataclasses.dataclass
class Scenario:
    seed: int
    ticks: int
    settle_ticks: int
    flavors: List[dict]          # [{"name", "speed_class"}]
    topology: Optional[dict]     # {"levels", "counts", "leaf_capacity"}
    cohorts: List[dict]          # [{"name", "parent", "quota"}]
    cluster_queues: List[dict]
    policy: dict                 # {"fair","lending","hetero","pods_ready"}
    workloads: List[dict]        # initial submissions (before tick 0)
    traffic: List[list]          # traffic[t] = list of ops for tick t

    # -- (de)serialization --------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["format"] = FORMAT
        return d

    @staticmethod
    def from_dict(d: dict) -> "Scenario":
        fmt = d.get("format", FORMAT)
        if not str(fmt).startswith("kueuefuzz/"):
            raise ValueError(f"not a kueuefuzz scenario (format={fmt!r})")
        return Scenario(
            seed=int(d["seed"]), ticks=int(d["ticks"]),
            settle_ticks=int(d.get("settle_ticks", 3)),
            flavors=list(d["flavors"]), topology=d.get("topology"),
            cohorts=list(d.get("cohorts", ())),
            cluster_queues=list(d["cluster_queues"]),
            policy=dict(d["policy"]), workloads=list(d["workloads"]),
            traffic=[list(ops) for ops in d["traffic"]])

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Scenario":
        return Scenario.from_dict(json.loads(text))

    # -- size metric (the shrinker minimizes this lexicographically) --------

    def size(self) -> tuple:
        n_submits = len(self.workloads) + sum(
            1 for ops in self.traffic for op in ops if op[0] == "submit")
        return (len(self.cluster_queues), n_submits, self.ticks,
                sum(len(ops) for ops in self.traffic))

    def replica_safe(self) -> bool:
        """True when the scenario avoids every DOCUMENTED multi-process
        divergence and nondeterminism source: split-root preemption
        candidates and fair-share denominators are subtree-local,
        PodsReady gates per replica, hetero rides an env the referee
        comparison can't share, and replica workers run on wall-clock
        condition timestamps (same-priority preemption tiebreaks would
        flake). Replica lattice points only run when this holds."""
        if self.policy.get("pods_ready") or self.policy.get("hetero"):
            return False
        if self.policy.get("fair") and self.cohorts:
            return False
        for cq in self.cluster_queues:
            pre = cq.get("preemption") or {}
            if pre.get("within", "Never") != "Never" \
                    or pre.get("reclaim", "Never") != "Never":
                return False
        return True


# -- API-object builders ----------------------------------------------------


def _topo_spec(sc: Scenario):
    if not sc.topology:
        return None
    from kueue_tpu.api.types import TopologySpec

    t = sc.topology
    return TopologySpec.uniform(
        tuple(t["levels"]), tuple(t["counts"]), t["leaf_capacity"])


def flavor_objects(sc: Scenario) -> list:
    from kueue_tpu.api.types import ResourceFlavor

    topo = _topo_spec(sc)
    return [ResourceFlavor.make(
        f["name"], topology=topo,
        speed_class=float(f.get("speed_class", 1.0)))
        for f in sc.flavors]


def _quota_tuple(vals, unit: int = 1):
    """[nom, borrow, lend] (borrow/lend may be None) -> FlavorQuotas arg."""
    nom, borrow, lend = (list(vals) + [None, None])[:3]
    if borrow is None and lend is None:
        return nom * unit
    return (nom * unit,
            None if borrow is None else borrow * unit,
            None if lend is None else lend * unit)


def _resource_groups(quotas: dict) -> tuple:
    from kueue_tpu.api.types import FlavorQuotas, ResourceGroup

    fqs = []
    for fname in sorted(quotas):
        res = quotas[fname]
        kwargs = {}
        if "cpu" in res:
            kwargs["cpu"] = _quota_tuple(res["cpu"])
        if "memory_gi" in res:
            kwargs["memory"] = _quota_tuple(res["memory_gi"], unit=GI)
        fqs.append(FlavorQuotas.make(fname, **kwargs))
    covered = tuple(r for r in ("cpu", "memory")
                    if any(("memory_gi" if r == "memory" else r) in q
                           for q in quotas.values()))
    return (ResourceGroup(covered_resources=covered, flavors=tuple(fqs)),)


def cohort_objects(sc: Scenario) -> list:
    from kueue_tpu.api.types import CohortSpec

    out = []
    for c in sc.cohorts:
        rgs = _resource_groups(c["quota"]) if c.get("quota") else ()
        out.append(CohortSpec(name=c["name"], parent=c.get("parent", ""),
                              resource_groups=rgs))
    return out


def cq_object(spec: dict, quota_factor: float = 1.0):
    """Build the ClusterQueue API object; `quota_factor` != 1 rebuilds
    with every nominal (and borrow/lend limit) scaled — the update_cq
    traffic op."""
    from kueue_tpu.api.types import (
        BorrowWithinCohort, ClusterQueue, ClusterQueuePreemption,
        FairSharing)

    quotas = spec["quotas"]
    if quota_factor != 1.0:
        def _scale(v):
            return None if v is None else max(1, int(v * quota_factor))
        quotas = {f: {r: [_scale(x) for x in vals]
                      for r, vals in res.items()}
                  for f, res in quotas.items()}
    pre = spec.get("preemption") or {}
    borrow = None
    if pre.get("borrow"):
        borrow = BorrowWithinCohort(
            policy=pre["borrow"]["policy"],
            max_priority_threshold=pre["borrow"].get("threshold"))
    preemption = ClusterQueuePreemption(
        within_cluster_queue=pre.get("within", "Never"),
        reclaim_within_cohort=pre.get("reclaim", "Never"),
        borrow_within_cohort=borrow)
    fair = None
    if spec.get("fair_weight") is not None:
        fair = FairSharing(weight=float(spec["fair_weight"]))
    return ClusterQueue(
        name=spec["name"],
        resource_groups=_resource_groups(quotas),
        cohort=spec.get("cohort", ""),
        queueing_strategy=spec.get("strategy", "BestEffortFIFO"),
        preemption=preemption,
        fair_sharing=fair)


def lq_object(spec: dict):
    from kueue_tpu.api.types import LocalQueue

    return LocalQueue(name=f"lq-{spec['name']}", namespace="default",
                      cluster_queue=spec["name"])


def workload_object(w: dict):
    from kueue_tpu.api.types import PodSet, Workload

    pod_sets = []
    for ps in w["pod_sets"]:
        kwargs = {}
        topo = ps.get("topo")
        if topo:
            mode, level = topo
            kwargs["topology_required" if mode == "required"
                   else "topology_preferred"] = level
        if w.get("tputs"):
            kwargs["flavor_throughputs"] = dict(w["tputs"])
        pod_sets.append(PodSet.make(
            ps.get("name", "ps0"), count=int(ps["count"]),
            cpu=int(ps["cpu"]), memory=f"{int(ps['memory_gi'])}Gi",
            **kwargs))
    return Workload(
        name=w["name"], namespace="default", queue_name=w["queue"],
        priority=int(w.get("priority", 0)),
        creation_time=float(w["creation_time"]),
        pod_sets=pod_sets)


def nominal_capacity(sc: Scenario, factors: dict) -> dict:
    """Total nominal capacity per cohort-tree root (plus one pseudo-root
    per solo ClusterQueue): {root: {flavor: {resource: canonical_units}}}.
    `factors` carries the live update_cq quota scales. This is the
    quota-never-oversubscribed oracle's bound — borrowing moves usage
    between members but the sum over a tree can never exceed the sum of
    nominals (clusterqueue.go borrowing semantics)."""
    parent = {c["name"]: c.get("parent", "") for c in sc.cohorts}

    def root_of(cohort: str) -> str:
        seen = set()
        while cohort in parent and parent[cohort] and cohort not in seen:
            seen.add(cohort)
            cohort = parent[cohort]
        return cohort

    caps: dict = {}

    def add(root: str, quotas: dict, factor: float = 1.0):
        dst = caps.setdefault(root, {})
        for fname, res in quotas.items():
            d = dst.setdefault(fname, {})
            for rname, vals in res.items():
                unit = GI if rname == "memory_gi" else 1000  # cpu -> milli
                r = "memory" if rname == "memory_gi" else rname
                nom = max(1, int(vals[0] * factor)) if factor != 1.0 \
                    else vals[0]
                d[r] = d.get(r, 0) + nom * unit

    for cq in sc.cluster_queues:
        root = root_of(cq.get("cohort", "")) if cq.get("cohort") \
            else f"__solo__/{cq['name']}"
        add(root, cq["quotas"], factors.get(cq["name"], 1.0))
    for c in sc.cohorts:
        if c.get("quota"):
            add(root_of(c["name"]), c["quota"])
    return caps


def cq_root(sc: Scenario, cq_name: str) -> str:
    parent = {c["name"]: c.get("parent", "") for c in sc.cohorts}
    for cq in sc.cluster_queues:
        if cq["name"] == cq_name:
            cohort = cq.get("cohort", "")
            if not cohort:
                return f"__solo__/{cq_name}"
            seen = set()
            while cohort in parent and parent[cohort] \
                    and cohort not in seen:
                seen.add(cohort)
                cohort = parent[cohort]
            return cohort
    return f"__solo__/{cq_name}"
