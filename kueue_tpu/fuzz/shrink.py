"""Scenario shrinker: minimize a diverging draw into a reproducer.

Greedy structural delta-debugging over the scenario's own shape: drop
whole traffic ticks, drop submit ops and initial workloads (halves, then
singles), drop ClusterQueues (with their workloads), and simplify
policies (fair off, hetero off, lending off, topology off, preemption
down) — re-checking the failure predicate after every candidate and
keeping any candidate that still fails. The result is the smallest
scenario the passes could reach, written as a self-contained reproducer
file that checks in under tests/fixtures/fuzz/ as a new golden (green on
a fixed build; red again the day the bug class returns).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Callable, List, Optional

from kueue_tpu.fuzz.scenario import FORMAT, Scenario

REPRO_FORMAT = "kueuefuzz-repro/v1"


def _with(sc: Scenario, **patch) -> Scenario:
    d = sc.to_dict()
    d.update(patch)
    return Scenario.from_dict(d)


def _used_queues(sc: Scenario) -> set:
    used = {w["queue"] for w in sc.workloads}
    for ops in sc.traffic:
        for op in ops:
            if op[0] == "submit":
                used.add(op[1]["queue"])
    return used


def _drop_cq(sc: Scenario, name: str) -> Scenario:
    lq = f"lq-{name}"
    cqs = [c for c in sc.cluster_queues if c["name"] != name]
    workloads = [w for w in sc.workloads if w["queue"] != lq]
    traffic = []
    for ops in sc.traffic:
        kept = []
        for op in ops:
            if op[0] == "submit" and op[1]["queue"] == lq:
                continue
            if op[0] == "update_cq" and op[1] == name:
                continue
            kept.append(op)
        traffic.append(kept)
    return _with(sc, cluster_queues=cqs, workloads=workloads,
                 traffic=traffic)


def _merge_cq(sc: Scenario, src: str, dst: str) -> Scenario:
    """Drop ClusterQueue `src` but RETARGET its workloads onto `dst`
    instead of dropping them — the pass that collapses a divergence
    spread over many queues onto fewer (a plain CQ drop would lose the
    workloads that make it diverge)."""
    src_lq, dst_lq = f"lq-{src}", f"lq-{dst}"

    def retarget(w: dict) -> dict:
        return {**w, "queue": dst_lq} if w["queue"] == src_lq else w

    cqs = [c for c in sc.cluster_queues if c["name"] != src]
    workloads = [retarget(w) for w in sc.workloads]
    traffic = []
    for ops in sc.traffic:
        kept = []
        for op in ops:
            if op[0] == "submit":
                kept.append(["submit", retarget(op[1])])
            elif op[0] == "update_cq" and op[1] == src:
                continue
            else:
                kept.append(op)
        traffic.append(kept)
    return _with(sc, cluster_queues=cqs, workloads=workloads,
                 traffic=traffic)


def _submit_positions(sc: Scenario) -> List[tuple]:
    """Every submission site: ("init", i) or ("tick", t, j)."""
    out: List[tuple] = [("init", i) for i in range(len(sc.workloads))]
    for t, ops in enumerate(sc.traffic):
        for j, op in enumerate(ops):
            if op[0] == "submit":
                out.append(("tick", t, j))
    return out


def _drop_submits(sc: Scenario, positions: List[tuple]) -> Scenario:
    drop_init = {p[1] for p in positions if p[0] == "init"}
    drop_tick = {(p[1], p[2]) for p in positions if p[0] == "tick"}
    workloads = [w for i, w in enumerate(sc.workloads)
                 if i not in drop_init]
    traffic = [[op for j, op in enumerate(ops)
                if not (op[0] == "submit" and (t, j) in drop_tick)]
               for t, ops in enumerate(sc.traffic)]
    return _with(sc, workloads=workloads, traffic=traffic)


def shrink(sc: Scenario, still_fails: Callable[[Scenario], bool],
           budget: int = 250) -> tuple:
    """Minimize `sc` under the predicate; returns (scenario, attempts).
    `still_fails` must re-run the diverging check (the caller typically
    closes over the lattice-point pair that diverged). The predicate is
    never trusted blindly: a candidate is kept only when it STILL
    fails, so the result always reproduces the original divergence."""
    attempts = [0]

    def check(cand: Scenario) -> bool:
        if attempts[0] >= budget:
            return False
        attempts[0] += 1
        try:
            return bool(still_fails(cand))
        except Exception:
            # A candidate that crashes the harness is not a valid
            # reproducer of the ORIGINAL divergence; skip it.
            return False

    best = sc
    improved = True
    while improved and attempts[0] < budget:
        improved = False

        # 1. Truncate the tail: divergences live at some first tick;
        #    everything after it is dead weight.
        ticks = best.ticks
        for frac in (0.25, 0.5, 0.75):
            t = max(1, int(ticks * frac))
            if t >= ticks:
                continue
            cand = _with(best, ticks=t,
                         traffic=[list(o) for o in best.traffic[:t]])
            if check(cand):
                best, improved = cand, True
                break

        # 2. Drop ClusterQueues one at a time (smallest axis first:
        #    the acceptance bound is <= 3 CQs / <= 10 workloads), then
        #    try MERGING each into a sibling (retargeting its
        #    workloads) — a drop loses the workloads, a merge keeps the
        #    contention they create.
        for cq in list(best.cluster_queues):
            if len(best.cluster_queues) <= 1:
                break
            cand = _drop_cq(best, cq["name"])
            if not cand.cluster_queues:
                continue
            if check(cand):
                best, improved = cand, True
        for cq in list(best.cluster_queues):
            if len(best.cluster_queues) <= 1:
                break
            others = [c["name"] for c in best.cluster_queues
                      if c["name"] != cq["name"]]
            for dst in others[:2]:
                cand = _merge_cq(best, cq["name"], dst)
                if check(cand):
                    best, improved = cand, True
                    break

        # 3. Drop submissions: halves, then singles.
        positions = _submit_positions(best)
        chunk = max(len(positions) // 2, 1)
        while chunk >= 1 and positions:
            i = 0
            while i < len(positions):
                batch = positions[i:i + chunk]
                cand = _drop_submits(best, batch)
                if check(cand):
                    best, improved = cand, True
                    positions = _submit_positions(best)
                    i = 0
                    continue
                i += chunk
            if chunk == 1:
                break
            chunk //= 2

        # 4. Drop non-submit traffic ops (finish/delete/update/ready).
        for t in range(len(best.traffic)):
            for j in range(len(best.traffic[t]) - 1, -1, -1):
                if best.traffic[t][j][0] == "submit":
                    continue
                traffic = [list(ops) for ops in best.traffic]
                del traffic[t][j]
                cand = _with(best, traffic=traffic)
                if check(cand):
                    best, improved = cand, True

        # 5. Simplify policy dimensions. Each transform is built IN
        #    FULL before the no-op check — flat-cohort scenarios have
        #    cohorts == [] already but still carry per-CQ cohort names,
        #    so the cohort-clearing rewrite must run before deciding
        #    the candidate changed nothing.
        def _simplify(patch):
            cand = _with(best, **patch)
            if patch.get("policy", {}).get("fair") is False:
                cand = _with(cand, cluster_queues=[
                    {**c, "fair_weight": None}
                    for c in cand.cluster_queues])
            if "cohorts" in patch:
                cand = _with(cand, cluster_queues=[
                    {**c, "cohort": ""} for c in cand.cluster_queues])
            return cand

        # Patches are built LAZILY from the current best: a tuple of
        # pre-built dicts would snapshot best.policy at pass start, so
        # accepting {fair: False} and then applying a stale
        # {hetero: False} patch would resurrect fair=True — the pass
        # ping-pongs and burns the whole attempt budget instead of
        # converging.
        for make_patch in (
                lambda: {"policy": {**best.policy, "fair": False}},
                lambda: {"policy": {**best.policy, "hetero": False}},
                lambda: {"policy": {**best.policy, "lending": False}},
                lambda: {"policy": {**best.policy,
                                    "pods_ready": False}},
                lambda: {"topology": None},
                lambda: {"cohorts": []},
                lambda: {"settle_ticks": 1},
        ):
            cand = _simplify(make_patch())
            if cand.to_dict() == best.to_dict():
                continue
            if check(cand):
                best, improved = cand, True

        # 6. Simplify preemption per CQ.
        for i, cq in enumerate(best.cluster_queues):
            pre = cq.get("preemption") or {}
            if pre.get("within", "Never") == "Never" \
                    and pre.get("reclaim", "Never") == "Never":
                continue
            cqs = copy.deepcopy(best.cluster_queues)
            cqs[i]["preemption"] = {"within": "Never",
                                    "reclaim": "Never"}
            cand = _with(best, cluster_queues=cqs)
            if check(cand):
                best, improved = cand, True
    return best, attempts[0]


def write_reproducer(path: str, sc: Scenario, *, name: str,
                     description: str, found: Optional[dict] = None,
                     lattice: Optional[list] = None,
                     expect: Optional[dict] = None) -> dict:
    """Emit a self-contained reproducer file (the corpus entry format —
    see corpus.py for the replay contract)."""
    doc = {
        "format": REPRO_FORMAT,
        "name": name,
        "description": description,
        "found": found or {},
        "lattice": lattice,
        "expect": expect or {},
        "scenario": {**sc.to_dict(), "format": FORMAT},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc
