"""Long-run soak: churn for hours, watch resource curves for drift.

The bug class the soak exists for (PR 8's iteration-order victim flip,
PR 9's gen-2 GC barrier stall) only shows under churn VOLUME — no
20-tick golden finds a free-list leak, a cache that slowly stops
hitting, or RSS that creeps 1MB/minute. The soak drives the bench's
churn loop (same synthetic distributions) for a wall-clock budget and
samples, per window of ticks:

  rss_mb                    resident set (the leak curve)
  arena_occupancy           live rows / pool capacity (free-list leaks)
  arena_reuse_ratio         windowed gather reuse (incrementality decay)
  nominate_hit_ratio        windowed cache hit rate (fingerprint churn)
  dispatches_per_tick       solver dispatch rate (quiescence decay)
  backlog                   pending population (equilibrium check)

Verdict: after a warmup quarter, the run is split into an early and a
late half; a MONOTONIC drift beyond tolerance between them (late RSS /
occupancy / dispatch rate meaningfully above early, late hit/reuse
ratios meaningfully below) fails the soak. Registered behind the `slow`
pytest marker (tests/test_fuzz_soak.py) and `make fuzz-soak`
(KUEUE_FUZZ_SOAK_SECONDS sets the hours-scale budget).

Divergences auto-file, same as campaign divergences: every
`oracle_every` sample windows the soak interleaves one lattice
scenario spot-check (the campaign's oracles at a small point budget);
a violation shrinks through shrink.shrink and lands as a reproducer
file next to the report, and a failed drift verdict writes a
self-contained soak-repro doc (params + samples + verdict) — soak
findings used to die in the log (ROADMAP 5a).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

SOAK_REPRO_FORMAT = "kueuefuzz-soak-repro/v1"

# Drift tolerances: absolute floors absorb small-number noise, the
# ratios catch the monotonic creep the soak exists to find.
RSS_RATIO, RSS_FLOOR_MB = 1.25, 48.0
OCC_RATIO, OCC_FLOOR = 1.25, 0.05
RATIO_DROP = 0.15          # hit/reuse ratios may degrade at most this
DISPATCH_RATIO, DISPATCH_FLOOR = 1.5, 0.5


def _rss_mb() -> float:
    from kueue_tpu.controllers.replica_runtime import _rss_bytes

    return _rss_bytes() / (1024.0 ** 2)


def _mean(vals: List[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _findings_dir(findings_dir: Optional[str],
                  report_path: Optional[str]) -> str:
    if findings_dir:
        return findings_dir
    if report_path:
        return os.path.dirname(os.path.abspath(report_path)) or "."
    return "."


def _oracle_spot_check(seed: int, findings_dir: str,
                       check=None, shrinker=None,
                       points=None) -> List[dict]:
    """One interleaved lattice spot-check: draw a scenario, run the
    campaign's oracles over a small point budget, and on any violation
    auto-file a shrunk reproducer exactly like a campaign divergence.
    `check` / `shrinker` / `points` are injectable for the tier-1 tests
    (a real shrink loop is minutes, not tier-1 budget)."""
    from kueue_tpu.fuzz import generator, lattice, shrink

    if check is None:
        check = lattice.check_scenario
    sc = generator.draw_scenario(seed)
    if points is None:
        # Reference + repeat + one batched engine: the determinism,
        # identity, and quota oracles at soak-lane cost (the full
        # replica/drill budget stays with the campaign).
        points = lattice.default_lattice(sc)[:4]
    report = check(sc, points=points)
    if not report["violations"]:
        return []

    def still_fails(cand):
        return bool(check(cand, points=points)["violations"])

    if shrinker is None:
        def shrinker(s, pred):
            return shrink.shrink(s, pred, budget=80)

    small, attempts = shrinker(sc, still_fails)
    path = os.path.join(findings_dir, f"soak-repro-seed{seed}.json")
    shrink.write_reproducer(
        path, small, name=f"soak-seed-{seed}",
        description="shrunk from a soak oracle spot-check divergence",
        found={"seed": seed, "lane": "soak-oracle",
               "violations": report["violations"][:4],
               "shrink_attempts": attempts})
    return [{"kind": "oracle", "seed": seed, "reproducer": path,
             "violations": report["violations"][:4]}]


def _file_drift_repro(findings_dir: str, params: dict, samples: list,
                      verdict: dict) -> dict:
    """A failed drift verdict files a self-contained repro doc: the
    exact run_soak params to re-drive it plus the curves and the
    verdict that went red — the soak equivalent of a shrunk scenario
    (there is no smaller scenario than "these params, this long")."""
    path = os.path.join(findings_dir, "soak-drift-repro.json")
    doc = {"format": SOAK_REPRO_FORMAT,
           "name": "soak-drift",
           "description": "soak drift verdict failure: re-run "
                          "run_soak(**params) to reproduce",
           "params": params,
           "verdict": verdict,
           "samples": samples}
    os.makedirs(findings_dir or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return {"kind": "drift", "reproducer": path,
            "failed": sorted(k for k, v in verdict.items()
                             if not v["ok"])}


def run_soak(duration_s: float, *, seed: int = 0, num_cqs: int = 32,
             backlog: int = 512, sample_every: int = 25,
             report_path: Optional[str] = None,
             gc_every: int = 50, oracle_every: int = 8,
             findings_dir: Optional[str] = None) -> dict:
    """Run the churn soak for `duration_s` wall seconds; returns the
    report dict (also written to `report_path` when given). The verdict
    lives under report["verdict"]; report["ok"] is the rollup (drift
    verdict AND zero oracle findings). Every `oracle_every` sample
    windows one lattice scenario spot-check interleaves with the churn;
    its divergences (and a failed drift verdict) auto-file reproducers
    under `findings_dir` (default: next to the report) and land in
    report["findings"]."""
    import random

    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.models.flavor_fit import BatchSolver
    from kueue_tpu.utils.envinfo import environment_block
    from kueue_tpu.utils.synthetic import (churn_arrival_draw,
                                           synthetic_framework)

    fw = synthetic_framework(
        num_cqs=num_cqs, num_cohorts=max(num_cqs // 4, 1), num_flavors=4,
        num_pending=backlog, usage_fill=0.5, seed=seed,
        batch_solver=BatchSolver(), pipeline_depth=2)
    solver = fw.scheduler.batch_solver
    rnd = random.Random(seed + 1)

    admitted: List[tuple] = []
    seq = [0]
    orig_apply = fw.scheduler.apply_admission

    def apply_admission(wl):
        ok = orig_apply(wl)
        if ok:
            admitted.append((tick_no[0] + rnd.choice((4, 5, 6)), wl))
        return ok

    fw.scheduler.apply_admission = apply_admission
    tick_no = [0]

    def churn():
        keep = []
        for due, wl in admitted:
            if wl.is_finished or not wl.is_admitted:
                # Finished already, or preempted/evicted: drop the
                # entry now — a readmission appends a FRESH entry, so
                # keeping this one would pin the dead Workload (and
                # rescan it every tick) for the rest of an hours-scale
                # run; the harness itself would then produce the RSS
                # creep the drift verdict gates on.
                continue
            if due <= tick_no[0]:
                fw.finish(wl)
                fw.delete_workload(wl)
                seq[0] += 1
                d = churn_arrival_draw(rnd, num_cqs, 4, seq=seq[0])
                fw.submit(Workload(
                    name=f"soak-{seq[0]}", namespace="default",
                    queue_name=f"lq-{d['queue_index']}",
                    priority=d["priority"],
                    creation_time=float(100_000 + seq[0]),
                    pod_sets=[PodSet.make(
                        "ps0", count=d["count"], cpu=d["cpu"],
                        memory=f"{d['memory_gi']}Gi")]))
            else:
                keep.append((due, wl))
        admitted[:] = keep
        fw.prewarm_idle()

    samples: List[dict] = []
    findings: List[dict] = []
    fdir = _findings_dir(findings_dir, report_path)
    spot_no = [0]
    t_end = time.monotonic() + duration_s
    window_base = solver.fuzz_counters()
    window_ticks = 0
    while time.monotonic() < t_end:
        tick_no[0] += 1
        window_ticks += 1
        fw.tick()
        churn()
        if tick_no[0] % gc_every == 0:
            import gc

            gc.collect()
        if window_ticks >= sample_every:
            now = solver.fuzz_counters()
            hits = now["nominate_cache_hits"] \
                - window_base["nominate_cache_hits"]
            misses = now["nominate_cache_misses"] \
                - window_base["nominate_cache_misses"]
            reused = now["arena_rows_reused"] \
                - window_base["arena_rows_reused"]
            missed = now["arena_rows_missed"] \
                - window_base["arena_rows_missed"]
            samples.append({
                "tick": tick_no[0],
                "rss_mb": round(_rss_mb(), 1),
                "arena_occupancy": now["arena_occupancy"],
                "arena_reuse_ratio": (
                    reused / (reused + missed)
                    if reused + missed else None),
                "nominate_hit_ratio": (
                    hits / (hits + misses) if hits + misses else None),
                "dispatches_per_tick": (
                    (now["dispatches"] - window_base["dispatches"])
                    / window_ticks),
                "backlog": sum(
                    fw.queues.pending(f"cq-{i}")
                    for i in range(num_cqs)),
            })
            window_base = now
            window_ticks = 0
            if oracle_every and len(samples) % oracle_every == 0:
                # The divergence lane: one lattice scenario through
                # the campaign's oracles, auto-filing any finding.
                # Seeded off the soak's own seed + a running counter —
                # a distinct base keeps the lane from re-walking the
                # campaign's seed space.
                spot_no[0] += 1
                findings.extend(_oracle_spot_check(
                    7_700_000 + seed * 1_000 + spot_no[0], fdir))
    report = {
        "ticks": tick_no[0],
        "duration_s": round(duration_s, 1),
        "samples": samples,
        "environment": environment_block(),
        "verdict": drift_verdict(samples),
    }
    drift_ok = all(v["ok"] for v in report["verdict"].values()) \
        if report["verdict"] else False
    if report["verdict"] and not drift_ok:
        findings.append(_file_drift_repro(
            fdir,
            {"duration_s": duration_s, "seed": seed,
             "num_cqs": num_cqs, "backlog": backlog,
             "sample_every": sample_every, "gc_every": gc_every},
            samples, report["verdict"]))
    report["findings"] = findings
    report["ok"] = drift_ok and not any(
        f["kind"] == "oracle" for f in findings)
    if report_path:
        with open(report_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
    return report


def drift_verdict(samples: List[dict]) -> dict:
    """Monotonic-drift detection over the sample curves: drop the first
    quarter (warmup), split the rest into an early and a late half, and
    compare window means against per-metric tolerances. Pure function of
    the samples so the unit tests can exercise it directly."""
    if len(samples) < 4:
        return {}
    body = samples[len(samples) // 4:]
    early = body[:len(body) // 2]
    late = body[len(body) // 2:]

    def series(key):
        return (_mean([s[key] for s in early]),
                _mean([s[key] for s in late]))

    out = {}

    e, l = series("rss_mb")
    out["rss_mb"] = {
        "early": e, "late": l,
        "ok": e is None or l is None
        or l <= max(e * RSS_RATIO, e + RSS_FLOOR_MB)}
    e, l = series("arena_occupancy")
    out["arena_occupancy"] = {
        "early": e, "late": l,
        "ok": e is None or l is None
        or l <= max(e * OCC_RATIO, e + OCC_FLOOR)}
    for key in ("arena_reuse_ratio", "nominate_hit_ratio"):
        e, l = series(key)
        out[key] = {"early": e, "late": l,
                    "ok": e is None or l is None or l >= e - RATIO_DROP}
    e, l = series("dispatches_per_tick")
    out["dispatches_per_tick"] = {
        "early": e, "late": l,
        "ok": e is None or l is None
        or l <= max(e * DISPATCH_RATIO, e + DISPATCH_FLOOR)}
    return out


def soak_seconds_from_env(default: float = 7200.0) -> float:
    return float(os.environ.get("KUEUE_FUZZ_SOAK_SECONDS", "") or default)
