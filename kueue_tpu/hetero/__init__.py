"""Heterogeneity-aware flavor scoring (the `hetero` solve mode).

Gavel-style max-effective-throughput flavor assignment (arxiv
2008.09213) over the existing quota/borrowing constraints:

  profile.py  ThroughputProfileStore — the [N,F] fixed-point throughput
              matrix over the pending backlog, fed by the same queue
              dirty events as the WorkloadArena, plus the per-flavor
              speed-class defaults and the bench's aggregate metric.
  solve.py    The Gavel LP relaxation as a jit dense projected dual
              iteration (all-integer — the numpy referee twin is
              bitwise identical), plus the per-flavor capacity proxy.
  referee.py  The sequential host oracle the batched device solve is
              pinned decision-identical to.

Selected via `tpuSolver.mode: hetero` (kill switch
KUEUE_TPU_NO_HETERO=1); with the mode off — or on with no profiled
workload and a homogeneous speed-class vocabulary — every decision is
byte-identical to the default first-fit mode.
"""

from kueue_tpu.hetero.profile import (  # noqa: F401
    ThroughputProfileStore,
    aggregate_effective_throughput,
    speed_vector,
    workload_throughputs,
)
from kueue_tpu.hetero.solve import (  # noqa: F401
    DEFAULT_ITERS,
    SCORE_SCALE,
    flavor_capacity,
    hetero_scores,
    hetero_scores_core,
    hetero_scores_np,
)
