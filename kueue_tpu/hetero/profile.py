"""Per-workload x per-flavor throughput profiles (the hetero model side).

The `ThroughputProfileStore` is the hetero twin of the WorkloadArena
(solver/schema.py): one pooled row per pending workload holding its
fixed-point [F] throughput vector, aligned to the solver's CQ-encoding
generation (the F axis is the encoding's flavor vocabulary; the store is
rebuilt on every encoding rotation) and fed by the SAME queue-manager
dirty events — `note` on add/update, `forget` on delete — so the matrix
is fresh before the tick without any per-tick backlog walk.

Throughput semantics (the spec both the device kernel and the sequential
referee implement):

  * a flavor's baseline is its `ResourceFlavor.speed_class` (1.0 when
    unset — a homogeneous cluster);
  * a pod set may override per flavor via `PodSet.flavor_throughputs`;
    when several pod sets of one workload override the same flavor the
    MINIMUM wins (synchronous pods run at the slowest member's pace);
  * a value of 0 means "cannot run on this flavor" (the hetero choice
    never picks it; quota feasibility is unaffected);
  * a workload is PROFILED when any pod set carries an override or any
    flavor in the vocabulary declares a non-default speed class.
    Unprofiled workloads keep the default first-fit decision byte for
    byte — hetero-on-but-unprofiled is a provable no-op.

`generation` bumps on any row-content change; the BatchSolver keys its
score refresh and the nominate fingerprints on it (plus the global
usage generation), so a hetero steady state still replays every cached
verdict and dispatches zero solves.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.hetero.solve import SCORE_SCALE


def workload_throughputs(pod_sets, speed_q: np.ndarray,
                         flavor_index: Dict[str, int]) -> np.ndarray:
    """[F] i64 fixed-point throughput row for one workload's pod sets —
    the ONE home of the min-over-overriding-podsets rule, shared by the
    store, the sequential referee and the bench's aggregate metric.
    A reference to a flavor outside the current vocabulary falls back
    to that slot's speed-class default (the webhook rejects MALFORMED
    names, but a well-formed name matching no live flavor — a typo, or
    a flavor created later — cannot be scored and is deliberately
    inert rather than fatal)."""
    row = speed_q.copy()
    seen: Dict[int, int] = {}
    for ps in pod_sets:
        for fname, val in getattr(ps, "flavor_throughputs", ()):
            fi = flavor_index.get(fname)
            if fi is None:
                continue
            q = int(round(float(val) * SCORE_SCALE))
            prev = seen.get(fi)
            seen[fi] = q if prev is None else min(prev, q)
    for fi, q in seen.items():
        row[fi] = q
    return row


def speed_vector(flavor_names: Sequence[str],
                 resource_flavors: Dict[str, "ResourceFlavor"],
                 ) -> np.ndarray:
    """[F] i64 fixed-point speed-class defaults in encoding flavor
    order (1.0 for flavors missing from the live set)."""
    out = np.empty(len(flavor_names), dtype=np.int64)
    for fi, name in enumerate(flavor_names):
        rf = resource_flavors.get(name)
        sc = rf.speed_class if rf is not None else 1.0
        out[fi] = int(round(float(sc) * SCORE_SCALE))
    return out


class ThroughputProfileStore:
    """Dense [capacity, F] fixed-point throughput matrix over the
    pending backlog, plus per-row primary-resource demand and the
    profiled mask — the score kernel's inputs."""

    def __init__(self, enc, resource_flavors: Dict[str, "ResourceFlavor"],
                 capacity: int = 1024):
        F = len(enc.flavor_names)
        self.enc = enc
        self.flavor_index = enc.flavor_index
        self.primary_resource = enc.resource_names[0] \
            if enc.resource_names else ""
        self.speed_q = speed_vector(enc.flavor_names, resource_flavors)
        self.speed_hetero = bool((self.speed_q != SCORE_SCALE).any())
        self.capacity = capacity
        self.tput = np.tile(self.speed_q, (capacity, 1))
        self.demand = np.zeros(capacity, dtype=np.int64)
        self.profiled = np.zeros(capacity, dtype=bool)
        self.valid = np.zeros(capacity, dtype=bool)
        self._row_of: Dict[str, int] = {}
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self.generation = 0

    # -- row encoding -------------------------------------------------------

    def _encode(self, wi) -> Tuple[np.ndarray, int, bool]:
        wl = wi.obj
        row = workload_throughputs(wl.pod_sets, self.speed_q,
                                   self.flavor_index)
        demand = 0
        for ps in wi.total_requests:
            demand += int(ps.requests.get(self.primary_resource, 0))
        has_override = any(getattr(ps, "flavor_throughputs", ())
                           for ps in wl.pod_sets)
        profiled = has_override or self.speed_hetero
        return row, max(demand, 1), profiled

    def _grow(self) -> None:
        old = self.capacity
        new = old * 2
        self.tput = np.concatenate(
            [self.tput, np.tile(self.speed_q, (old, 1))], axis=0)
        self.demand = np.concatenate(
            [self.demand, np.zeros(old, dtype=np.int64)])
        self.profiled = np.concatenate(
            [self.profiled, np.zeros(old, dtype=bool)])
        self.valid = np.concatenate(
            [self.valid, np.zeros(old, dtype=bool)])
        self._free.extend(range(new - 1, old - 1, -1))
        self.capacity = new
        self.generation += 1

    # -- dirty-event sink (same protocol as the WorkloadArena) --------------

    def note(self, wi) -> int:
        """(Re-)encode one pending workload's row; returns the row index.
        Bumps `generation` exactly when the stored content changes."""
        uid = wi.obj.uid
        row_new, demand, profiled = self._encode(wi)
        ri = self._row_of.get(uid)
        if ri is None:
            if not self._free:
                self._grow()
            ri = self._free.pop()
            self._row_of[uid] = ri
            self.valid[ri] = True
            self.tput[ri] = row_new
            self.demand[ri] = demand
            self.profiled[ri] = profiled
            self.generation += 1
            return ri
        if (self.demand[ri] != demand or self.profiled[ri] != profiled
                or not np.array_equal(self.tput[ri], row_new)):
            self.tput[ri] = row_new
            self.demand[ri] = demand
            self.profiled[ri] = profiled
            self.generation += 1
        return ri

    def forget(self, uid: str) -> None:
        ri = self._row_of.pop(uid, None)
        if ri is None:
            return
        self.valid[ri] = False
        self.profiled[ri] = False
        self.tput[ri] = self.speed_q
        self.demand[ri] = 0
        self._free.append(ri)
        self.generation += 1

    def seed(self, infos) -> None:
        """Whole-backlog (re-)seed on encoding rotation — off the
        measured path, like WorkloadArena.seed."""
        for wi in infos:
            self.note(wi)

    # -- readers ------------------------------------------------------------

    def rows_for(self, workloads) -> np.ndarray:
        """[n] i64 row indices, encoding any uid the sink events missed
        (a workload submitted before the solver bound its queues)."""
        out = np.empty(len(workloads), dtype=np.int64)
        row_of = self._row_of
        for i, wi in enumerate(workloads):
            ri = row_of.get(wi.obj.uid)
            if ri is None:
                ri = self.note(wi)
            out[i] = ri
        return out

    def any_profiled(self) -> bool:
        return bool((self.profiled & self.valid).any())

    def active_mask(self) -> np.ndarray:
        return self.profiled & self.valid

    def throughput_of(self, row: int, fi: int) -> float:
        return float(self.tput[row, fi]) / SCORE_SCALE


def aggregate_effective_throughput(
        cache, resource_flavors: Optional[Dict[str, "ResourceFlavor"]] = None,
        ) -> float:
    """Sum over currently-admitted workloads of their relative throughput
    on the flavor they were ASSIGNED — Gavel's objective, measured on the
    live admitted set (the bench records it for every config and gates
    the hetero config's gain over its first-fit twin).

    A workload's factor is min over pod sets of the assigned flavor's
    throughput (override if declared, flavor speed class otherwise) — the
    same rule as `workload_throughputs`, read through the Admission's
    pod-set assignments."""
    flavors = resource_flavors if resource_flavors is not None \
        else cache.resource_flavors
    speed = {name: float(rf.speed_class) for name, rf in flavors.items()}
    total = 0.0
    for cq in cache.cluster_queues.values():
        for wi in cq.workloads.values():
            wl = wi.obj
            adm = wl.admission
            if adm is None:
                continue
            by_name = {ps.name: ps for ps in wl.pod_sets}
            factor = None
            for psa in adm.pod_set_assignments:
                fnames = set(psa.flavors.values())
                if not fnames:
                    continue
                ps = by_name.get(psa.name)
                overrides = dict(getattr(ps, "flavor_throughputs", ())) \
                    if ps is not None else {}
                # A pod set split across flavors runs at its slowest part.
                ps_factor = min(
                    float(overrides.get(f, speed.get(f, 1.0)))
                    for f in fnames)
                factor = ps_factor if factor is None \
                    else min(factor, ps_factor)
            if factor is not None:
                total += factor
    return total
