"""Sequential host referee for the hetero solve mode (the pinned oracle).

Mirrors the device kernel's heterogeneity-aware flavor choice (models/
flavor_fit.solve_core with `hetero=`) one workload at a time against the
same snapshot, reusing the reference referee's quota primitives
(solver/referee._fits_resource_quota, flavor_eligible) verbatim:

  * the DEFAULT walk (resume slot, eligibility, fungibility stop rule,
    tried-flavor bookkeeping) runs exactly as in the reference referee —
    including which reasons accumulate and where the walk would stop;
  * the walk then CONTINUES past the default stop to enumerate every
    currently-FIT slot, and when the workload is profiled the slot with
    the maximum effective score wins (ties to the earliest slot — the
    kernel's argmax-first-occurrence);
  * when nothing fits, or the workload is unprofiled, the default result
    is returned byte for byte.

tests/test_hetero.py pins the batched device solve decision-identical to
this referee on weighted / borrowing / KEP-79 scenarios, and
`KUEUE_TPU_DEBUG_HETERO=1` re-runs the comparison inside every tick.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from kueue_tpu import features
from kueue_tpu.core.cache import CachedClusterQueue
from kueue_tpu.core.workload import AssignmentClusterQueueState, WorkloadInfo
from kueue_tpu.solver.eligibility import flavor_eligible
from kueue_tpu.solver.modes import FIT, NO_FIT
from kueue_tpu.solver.referee import (
    Assignment,
    FlavorAssignment,
    PodSetAssignmentResult,
    _append_podset,
    _fits_resource_quota,
    _last_assignment_outdated,
    _should_try_next_flavor,
)
from kueue_tpu.hetero.solve import NEG_SCORE

PODS_RESOURCE = "pods"


def hetero_assign_flavors(wi: WorkloadInfo, cq: CachedClusterQueue,
                          resource_flavors: Dict[str, "ResourceFlavor"],
                          score_row: np.ndarray,
                          flavor_index: Dict[str, int],
                          profiled: bool,
                          counts: Optional[List[int]] = None) -> Assignment:
    """The hetero twin of solver/referee.assign_flavors: identical outer
    structure (podset loop, usage carry, resume-state stamping), with the
    per-group flavor search swapped for the score-aware walk."""
    if wi.last_assignment is not None and _last_assignment_outdated(wi, cq):
        wi.last_assignment = None

    if counts is None:
        requests = wi.total_requests
    else:
        requests = [wi.total_requests[i].scaled_to(c)
                    for i, c in enumerate(counts)]

    assignment = Assignment(
        usage={},
        last_state=AssignmentClusterQueueState(
            cluster_queue_generation=cq.allocatable_generation,
            cohort_generation=(cq.cohort.allocatable_generation
                               if cq.cohort is not None else 0),
        ),
    )

    for ps_idx, podset in enumerate(requests):
        ps_requests = dict(podset.requests)
        if PODS_RESOURCE in cq.rg_by_resource:
            ps_requests[PODS_RESOURCE] = podset.count

        psa = PodSetAssignmentResult(
            name=podset.name, requests=ps_requests, count=podset.count)

        for res_name in ps_requests:
            if res_name in psa.flavors:
                continue
            flavors, reasons, error = _find_flavor_hetero(
                wi, cq, resource_flavors, ps_idx, ps_requests, res_name,
                assignment.usage, score_row, flavor_index, profiled)
            if error is not None or not flavors:
                psa.flavors = {}
                psa.reasons = reasons
                psa.error = error
                break
            psa.flavors.update(flavors)
            psa.reasons.extend(reasons)

        _append_podset(assignment, ps_requests, psa)
        if psa.error is not None or (ps_requests and not psa.flavors):
            break
    return assignment


def _find_flavor_hetero(
        wi: WorkloadInfo, cq: CachedClusterQueue,
        resource_flavors: Dict[str, "ResourceFlavor"],
        ps_idx: int, requests: Dict[str, int], res_name: str,
        assignment_usage, score_row: np.ndarray,
        flavor_index: Dict[str, int], profiled: bool,
) -> Tuple[Dict[str, FlavorAssignment], List[str], Optional[str]]:
    """One resource group's search: the reference walk's bookkeeping up
    to its stop slot, a full continuation to enumerate FIT slots, then
    the score argmax."""
    rg = cq.rg_by_resource.get(res_name)
    if rg is None:
        return {}, [f"resource {res_name} unavailable in ClusterQueue"], None

    grouped = {r: v for r, v in requests.items()
               if r in rg.covered_resources}
    podset = wi.obj.pod_sets[ps_idx]
    allowed_keys = cq.label_keys(rg, resource_flavors)
    fungibility = features.enabled(features.FLAVOR_FUNGIBILITY)

    idx0 = 0
    if wi.last_assignment is not None:
        idx0 = wi.last_assignment.next_flavor_to_try(ps_idx, res_name)
    num_flavors = len(rg.flavors)

    # Default-walk state (frozen the moment the default walk would stop).
    reasons: List[str] = []
    best_assignment: Dict[str, FlavorAssignment] = {}
    best_mode = NO_FIT
    assigned_flavor_idx = -1
    stopped = False
    # Every currently-FIT slot from the resume point on, walk order.
    fit_slots: List[Tuple[int, Dict[str, FlavorAssignment]]] = []

    for idx in range(idx0, num_flavors):
        fq = rg.flavors[idx]
        flavor = resource_flavors.get(fq.name)
        if flavor is None:
            if not stopped:
                reasons.append(f"flavor {fq.name} not found")
            continue
        ok, why = flavor_eligible(podset, flavor, allowed_keys)
        if not ok:
            if not stopped:
                reasons.append(why)
            continue

        needs_borrowing = False
        assignments: Dict[str, FlavorAssignment] = {}
        representative_mode = FIT
        quotas = fq.resources_dict
        for rname, val in grouped.items():
            quota = quotas.get(rname)
            prev = assignment_usage.get(fq.name, {}).get(rname, 0)
            mode, borrow, reason = _fits_resource_quota(
                cq, fq.name, rname, val + prev, quota)
            if reason is not None and not stopped:
                reasons.append(reason)
            representative_mode = min(representative_mode, mode)
            needs_borrowing = needs_borrowing or borrow
            if representative_mode == NO_FIT:
                break
            assignments[rname] = FlavorAssignment(
                name=fq.name, mode=mode, borrow=borrow)

        if representative_mode == FIT:
            fit_slots.append((idx, assignments))

        if stopped:
            continue
        assigned_flavor_idx = idx
        if fungibility:
            if not _should_try_next_flavor(
                    representative_mode, cq.flavor_fungibility,
                    needs_borrowing):
                best_assignment = assignments
                best_mode = representative_mode
                stopped = True
            elif representative_mode > best_mode:
                best_assignment = assignments
                best_mode = representative_mode
        else:
            if representative_mode > best_mode:
                best_assignment = assignments
                best_mode = representative_mode
                if best_mode == FIT:
                    stopped = True

    # The device kernel's default `tried` bookkeeping (identical to the
    # reference referee: the stop slot, else the last eligible slot).
    tried = 0
    if fungibility:
        tried = assigned_flavor_idx
        if assigned_flavor_idx in (-1, num_flavors - 1):
            tried = -1

    chosen: Optional[Dict[str, FlavorAssignment]] = None
    if profiled and fit_slots:
        # Slots scoring exactly NEG_SCORE are "cannot run here" (a 0
        # throughput, or a flavor outside the score matrix): they are
        # never chosen, and when EVERY fit slot scores NEG_SCORE the
        # override is skipped entirely — the default decision stands
        # (the kernel's strict `best_score > neg` gate).
        best_score = int(NEG_SCORE)
        for idx, assignments in fit_slots:
            fi = flavor_index.get(rg.flavors[idx].name)
            s = int(score_row[fi]) if fi is not None else int(NEG_SCORE)
            if s > best_score:
                best_score = s
                chosen = assignments
    if chosen is not None:
        for fa in chosen.values():
            if fungibility:
                fa.tried_flavor_idx = tried
        return chosen, [], None

    if fungibility:
        for fa in best_assignment.values():
            fa.tried_flavor_idx = tried
        if best_mode == FIT:
            return best_assignment, [], None
    elif best_mode == FIT:
        return best_assignment, [], None
    return best_assignment, reasons, None
