"""Gavel-style max-effective-throughput scoring (the hetero solve mode).

Gavel ("Heterogeneity-Aware Cluster Scheduling Policies for Deep Learning
Workloads", arxiv 2008.09213) frames heterogeneous-cluster scheduling as
an LP: maximize the aggregate effective throughput sum_{w,f} x_{wf} *
T_{wf} subject to per-accelerator-type capacity and sum_f x_{wf} <= 1.
This module relaxes that LP onto the device as a dense projected dual
iteration over the SAME lockstep tensors the flavor-fit solve reads:

  * `T` is the [N,F] fixed-point throughput matrix maintained by the
    ThroughputProfileStore (kueue_tpu/hetero/profile.py) over the whole
    pending backlog — Gavel's rounds also score every runnable job, not
    just the current heads;
  * the capacity vector is the per-flavor free quota in the primary
    resource (nominal - usage, clamped at 0, summed over ClusterQueues);
  * each iteration is a best-response assignment (every profiled row
    picks its current-max-score flavor) followed by a dual price ascent
    on overloaded flavors — a tatonnement on the LP's capacity duals.

The iteration is ALL INTEGER (fixed-point SCORE_SCALE units): integer
adds and floor-divides are associative and identical on every backend,
so the jit kernel and the numpy referee twin below are BITWISE equal —
the decision-identity contract costs nothing.

The deterministic rounding to an integral assignment happens inside the
flavor-fit kernel (models/flavor_fit.solve_core `hetero=` argument): per
(workload, podset, group), the slot with the maximum effective score
among the currently-FIT slots wins, ties break to the earliest slot
(first-fit order), and when nothing fits the default rules (including
preemption stops) apply unchanged — so the hetero mode is quota- and
borrowing-respecting by construction, and the host admission cycle
arbitrates cross-workload races exactly as in the default mode.
"""

from __future__ import annotations

import functools

import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 before tracing)
import jax
import jax.numpy as jnp

# Fixed-point unit: a relative throughput of 1.0 encodes as 1024.
SCORE_SCALE = 1024
# Dual-step numerator: price moves by over/capacity * PRICE_STEP per
# iteration (a quarter of a throughput unit at full overload).
PRICE_STEP = 256
# Projected-iteration depth. The dual converges geometrically on the
# bench shapes; 8 steps separate contended from free flavors by whole
# score units, far beyond the rounding granularity.
DEFAULT_ITERS = 8
# "Cannot run here" score for masked slots; far below any real score and
# far above int64 overflow when summed with prices.
NEG_SCORE = np.int64(-(np.int64(1) << 62))
# Capacity ceiling: flavor_capacity sums nominal quotas, and a nominal
# can be the schema's BIG/NO_LIMIT sentinel (2^62) — `over * PRICE_STEP`
# on a sentinel capacity wraps int64. 2^53 still exceeds any in-contract
# aggregate demand (canonical units are <= 2^50), so a clamped flavor is
# never overloaded, exactly as with the raw sentinel capacity.
CAP_CEIL = np.int64(np.int64(1) << 53)
# Dual-price ceiling: the tatonnement is self-limiting at equilibrium
# (an over-priced flavor attracts no rows, so its price decays), but
# nothing bounds the price structurally between iterations. 2^55 is far
# above any reachable score (fixed-point throughputs are canonical-unit
# sized), so the clamp never binds on in-contract inputs; it makes the
# no-wrap property hold unconditionally. Mirrored in the numpy referee,
# so decision identity is unaffected.
PRICE_CEIL = np.int64(np.int64(1) << 55)


def hetero_scores_core(tput_q, demand, active, capacity, *,
                       iters: int = DEFAULT_ITERS):
    """The jit score kernel: [N,F] i64 fixed-point throughputs, [N] i64
    primary-resource demand, [N] bool profiled-and-valid mask, [F] i64
    free capacity -> [N,F] i64 effective scores (NEG_SCORE where the row
    cannot run on the flavor).

    Pure dense integer math — no data-dependent shapes — so one compile
    serves every tick of a store capacity bucket.
    """
    allowed = tput_q > 0
    runnable = active & allowed.any(axis=1)
    capacity = jnp.minimum(capacity, jnp.int64(CAP_CEIL))
    cap_safe = jnp.maximum(capacity, 1)
    farange = jnp.arange(capacity.shape[0])

    def body(price, _):
        score = tput_q - price[None, :]
        masked = jnp.where(allowed, score, NEG_SCORE)
        best = jnp.argmax(masked, axis=1)
        onehot = (best[:, None] == farange[None, :]) \
            & runnable[:, None] & allowed
        load = jnp.sum(jnp.where(onehot, demand[:, None],
                                 jnp.int64(0)), axis=0)
        over = load - capacity
        price = jnp.clip(price + (over * PRICE_STEP) // cap_safe,
                         jnp.int64(0), jnp.int64(PRICE_CEIL))
        return price, None

    price0 = jnp.zeros(capacity.shape, dtype=jnp.int64)
    price, _ = jax.lax.scan(body, price0, None, length=iters)
    return jnp.where(allowed, tput_q - price[None, :], NEG_SCORE)


_scores_kernel = functools.partial(jax.jit,
                                   static_argnames=("iters",))(
    hetero_scores_core)


def hetero_scores(tput_q: np.ndarray, demand: np.ndarray,
                  active: np.ndarray, capacity: np.ndarray,
                  iters: int = DEFAULT_ITERS) -> np.ndarray:
    """Dispatch the jit score kernel and materialize the [N,F] i64 score
    matrix on host (the BatchSolver's per-(store,usage)-generation score
    refresh)."""
    out = _scores_kernel(jnp.asarray(tput_q), jnp.asarray(demand),
                         jnp.asarray(active), jnp.asarray(capacity),
                         iters=iters)
    return np.asarray(jax.device_get(out))


def hetero_scores_np(tput_q: np.ndarray, demand: np.ndarray,
                     active: np.ndarray, capacity: np.ndarray,
                     iters: int = DEFAULT_ITERS) -> np.ndarray:
    """The sequential referee twin of `hetero_scores_core`: the same
    integer iteration in numpy, bitwise-identical to the device kernel
    (all-integer arithmetic is associative — there is no float drift to
    tolerate). Pinned by tests/test_hetero.py."""
    tput_q = np.asarray(tput_q, dtype=np.int64)
    demand = np.asarray(demand, dtype=np.int64)
    capacity = np.asarray(capacity, dtype=np.int64)
    allowed = tput_q > 0
    runnable = np.asarray(active, dtype=bool) & allowed.any(axis=1)
    capacity = np.minimum(capacity, CAP_CEIL)
    cap_safe = np.maximum(capacity, 1)
    F = capacity.shape[0]
    farange = np.arange(F)
    price = np.zeros(F, dtype=np.int64)
    for _ in range(iters):
        score = tput_q - price[None, :]
        masked = np.where(allowed, score, NEG_SCORE)
        best = np.argmax(masked, axis=1)
        onehot = (best[:, None] == farange[None, :]) \
            & runnable[:, None] & allowed
        load = np.sum(np.where(onehot, demand[:, None],
                               np.int64(0)), axis=0)
        over = load - capacity
        price = np.clip(price + (over * PRICE_STEP) // cap_safe,
                        np.int64(0), PRICE_CEIL)
    return np.where(allowed, tput_q - price[None, :], NEG_SCORE)


def flavor_capacity(enc, usage: np.ndarray) -> np.ndarray:
    """[F] i64 free-capacity vector in the PRIMARY resource (the
    encoding's first resource name — cpu under the sorted vocabulary):
    sum over ClusterQueues of max(nominal - usage, 0). A proxy for the
    LP's per-accelerator-type capacity — the hetero mode only needs a
    congestion signal per flavor; exact feasibility stays with the
    flavor-fit quota math."""
    free = np.maximum(enc.nominal[:, :, 0] - usage[:, :, 0], 0)
    return free.sum(axis=0, dtype=np.int64)
