"""Importer: adopt pre-existing running pods into the queueing system.

Counterpart of reference cmd/importer/: pods already running outside the
framework's control are mapped to LocalQueues (label value -> queue mapping,
cmd/importer/README.md), checked (queue/CQ/flavor/priority-class existence,
cmd/importer/pod/check.go:32-75), then imported (cmd/importer/pod/import.go):
each pod becomes a single-PodSet Workload admitted *directly* into the first
flavor of its ClusterQueue's first resource group — bypassing the scheduler,
since the pod is already running and its capacity is already consumed.

Usable as a library (`check`, `import_pods`) or a CLI
(`python -m kueue_tpu.importer --setup cluster.json --pods pods.json ...`).
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    Admission,
    CONDITION_ADMITTED,
    CONDITION_QUOTA_RESERVED,
    PodSet,
    PodSetAssignment,
    Workload,
)


@dataclass
class ImportPod:
    """A pre-existing pod to adopt (corev1.Pod subset)."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, object] = field(default_factory=dict)
    priority_class: str = ""


@dataclass
class ImportSummary:
    """util.ConcurrentProcessPod's tally (cmd/importer/util/util.go)."""

    total: int = 0
    imported: int = 0
    skipped: int = 0
    failed: int = 0
    errors: List[str] = field(default_factory=list)

    def ok(self) -> bool:
        return self.failed == 0


def _map_queue(pod: ImportPod, queue_label: str,
               mapping: Mapping[str, str]) -> Optional[str]:
    """label value -> LocalQueue name (simple mapping, importer README)."""
    value = pod.labels.get(queue_label)
    if value is None:
        return None
    return mapping.get(value)


def _resolve(fw, pod: ImportPod, queue_label: str,
             mapping: Mapping[str, str]) -> Tuple[Optional[str], Optional[str],
                                                  Optional[str], int, str]:
    """Returns (lq_name, cq_name, flavor, priority, error)."""
    lq_name = _map_queue(pod, queue_label, mapping)
    if lq_name is None:
        return None, None, None, 0, "skip"  # no mapping -> skipped
    lq = fw.cache.local_queues.get(f"{pod.namespace}/{lq_name}")
    if lq is None:
        return lq_name, None, None, 0, f"LocalQueue {lq_name} not found"
    cq = fw.cache.cluster_queues.get(lq.cluster_queue)
    if cq is None:
        return lq_name, lq.cluster_queue, None, 0, \
            f"ClusterQueue {lq.cluster_queue} not found"
    if not cq.resource_groups:
        return lq_name, cq.name, None, 0, \
            f"ClusterQueue {cq.name} has no resource groups"
    rg = cq.resource_groups[0]
    if not rg.flavors:
        return lq_name, cq.name, None, 0, \
            f"ClusterQueue {cq.name} has no flavors"
    flavor = rg.flavors[0].name
    if flavor not in fw.cache.resource_flavors:
        return lq_name, cq.name, flavor, 0, \
            f"ResourceFlavor {flavor} not found"
    priority = 0
    if pod.priority_class:
        pc = fw.priority_classes.get(pod.priority_class)
        if pc is None:
            return lq_name, cq.name, flavor, 0, \
                f"priority class {pod.priority_class} not found"
        priority = pc.value
    return lq_name, cq.name, flavor, priority, ""


def check(fw, pods: Sequence[ImportPod], queue_label: str,
          mapping: Mapping[str, str]) -> ImportSummary:
    """The pre-import validation pass (cmd/importer/pod/check.go)."""
    summary = ImportSummary(total=len(pods))
    for pod in pods:
        _, _, _, _, err = _resolve(fw, pod, queue_label, mapping)
        if err == "skip":
            summary.skipped += 1
        elif err:
            summary.failed += 1
            summary.errors.append(f"{pod.namespace}/{pod.name}: {err}")
    return summary


def import_pods(fw, pods: Sequence[ImportPod], queue_label: str,
                mapping: Mapping[str, str],
                add_labels: Optional[Mapping[str, str]] = None,
                ) -> ImportSummary:
    """Adopt the pods (cmd/importer/pod/import.go): per pod, create a
    Workload with its requests, admit it directly (Imported reason) into
    the first flavor, and account its usage in the cache."""
    summary = ImportSummary(total=len(pods))
    now = fw.clock()
    for pod in pods:
        lq_name, cq_name, flavor, priority, err = _resolve(
            fw, pod, queue_label, mapping)
        if err == "skip":
            summary.skipped += 1
            continue
        if err:
            summary.failed += 1
            summary.errors.append(f"{pod.namespace}/{pod.name}: {err}")
            continue
        requests = {r: resource_value(r, q) for r, q in pod.requests.items()}
        wl = Workload(
            name=f"pod-{pod.name}", namespace=pod.namespace,
            queue_name=lq_name,
            pod_sets=[PodSet(name="main", count=1, requests=dict(requests))],
            priority=priority, priority_class=pod.priority_class)
        wl.admission = Admission(
            cluster_queue=cq_name,
            pod_set_assignments=[PodSetAssignment(
                name="main",
                flavors={r: flavor for r in requests},
                resource_usage=dict(requests),
                count=1)])
        wl.set_condition(CONDITION_QUOTA_RESERVED, True, reason="Imported",
                         message=f"Imported into ClusterQueue {cq_name}",
                         now=now)
        wl.set_condition(CONDITION_ADMITTED, True, reason="Imported",
                         message=f"Imported into ClusterQueue {cq_name}",
                         now=now)
        fw.workloads[wl.key] = wl
        fw.cache.add_or_update_workload(wl)
        if add_labels:
            pod.labels.update(add_labels)
        summary.imported += 1
    fw.update_metrics_gauges()
    return summary


# ---------------------------------------------------------------------------
# CLI (cmd/importer/main.go analog, against a JSON-described in-memory
# cluster instead of a kubeconfig)
# ---------------------------------------------------------------------------


def _parse_mapping(args: argparse.Namespace) -> Dict[str, str]:
    mapping: Dict[str, str] = {}
    for entry in (args.queuemapping or "").split(","):
        if not entry:
            continue
        k, _, v = entry.partition("=")
        mapping[k] = v
    if args.queuemapping_file:
        with open(args.queuemapping_file) as f:
            mapping.update(json.load(f))
    return mapping


def _load_framework(setup_path: str):
    from kueue_tpu.api.types import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        ResourceFlavor,
        ResourceGroup,
        WorkloadPriorityClass,
    )
    from kueue_tpu.controllers.runtime import Framework

    with open(setup_path) as f:
        spec = json.load(f)
    fw = Framework()
    for rf in spec.get("resource_flavors", []):
        fw.create_resource_flavor(ResourceFlavor.make(rf["name"]))
    for cq in spec.get("cluster_queues", []):
        fw.create_cluster_queue(ClusterQueue(
            name=cq["name"], cohort=cq.get("cohort", ""),
            resource_groups=tuple(
                ResourceGroup(
                    covered_resources=tuple(rg["covered_resources"]),
                    flavors=tuple(
                        FlavorQuotas.make(fq["name"], **fq["quotas"])
                        for fq in rg["flavors"]))
                for rg in cq.get("resource_groups", []))))
    for lq in spec.get("local_queues", []):
        fw.create_local_queue(LocalQueue(
            name=lq["name"], namespace=lq.get("namespace", "default"),
            cluster_queue=lq["cluster_queue"]))
    for pc in spec.get("priority_classes", []):
        fw.create_workload_priority_class(
            WorkloadPriorityClass(name=pc["name"], value=pc["value"]))
    return fw


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kueue-importer",
        description="Import pre-existing pods into the queueing system.")
    parser.add_argument("mode", choices=["check", "import"])
    parser.add_argument("--setup", required=True,
                        help="JSON file describing flavors/queues")
    parser.add_argument("--pods", required=True,
                        help="JSON file: list of pods "
                             "(name/namespace/labels/requests)")
    parser.add_argument("--queuelabel", required=True)
    parser.add_argument("--queuemapping", default="",
                        help="val=queue[,val=queue...]")
    parser.add_argument("--queuemapping-file", default="")
    args = parser.parse_args(argv)

    fw = _load_framework(args.setup)
    with open(args.pods) as f:
        pods = [ImportPod(**p) for p in json.load(f)]
    mapping = _parse_mapping(args)
    if args.mode == "check":
        summary = check(fw, pods, args.queuelabel, mapping)
    else:
        summary = import_pods(fw, pods, args.queuelabel, mapping)
    print(json.dumps({
        "mode": args.mode, "total": summary.total,
        "imported": summary.imported, "skipped": summary.skipped,
        "failed": summary.failed, "errors": summary.errors}))
    return 0 if summary.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
