"""Job integrations (counterpart of reference pkg/controller/jobs/).

Importing this package registers the built-in integrations:
  batch     single-PodSet parallel jobs (jobs/job)
  multirole launcher/worker- and head/worker-group jobs, covering the
            MPIJob, kubeflow *Job and RayJob/RayCluster shapes
            (jobs/mpijob, jobs/kubeflow, jobs/rayjob, jobs/raycluster)
  jobset    lists of replicated jobs (jobs/jobset)
  podgroup  plain pods grouped by annotation (jobs/pod, KEP-976)
"""

from kueue_tpu.jobs.batch_job import BatchJob
from kueue_tpu.jobs.multi_role_job import MultiRoleJob, Role
from kueue_tpu.jobs.jobset import JobSet, ReplicatedJob
from kueue_tpu.jobs.pod_group import PodGroup, GroupedPod
