"""Job integrations (counterpart of reference pkg/controller/jobs/).

Importing this package registers the built-in integrations:
  batch               single-PodSet parallel jobs (jobs/job)
  multirole           generic heterogeneous-role jobs
  jobset              lists of replicated jobs (jobs/jobset)
  podgroup            plain pods grouped by annotation (jobs/pod, KEP-976)
  mpijob              kubeflow mpi-operator launcher/worker (jobs/mpijob)
  kubeflow.pytorchjob / tfjob / paddlejob / xgboostjob / mxjob
                      kubeflow training-operator family (jobs/kubeflow)
  rayjob / raycluster Ray head + worker groups (jobs/rayjob, jobs/raycluster)
  noop                stub for parent-managed kinds (jobs/noop)
"""

from kueue_tpu.jobs.batch_job import BatchJob
from kueue_tpu.jobs.multi_role_job import MultiRoleJob, Role
from kueue_tpu.jobs.jobset import JobSet, ReplicatedJob
from kueue_tpu.jobs.pod_group import PodGroup, GroupedPod
from kueue_tpu.jobs.kubeflow import (
    KubeflowJob,
    MXJob,
    PaddleJob,
    PyTorchJob,
    ReplicaSpec,
    TFJob,
    XGBoostJob,
)
from kueue_tpu.jobs.mpijob import MPIJob
from kueue_tpu.jobs.noop import NoopJob
from kueue_tpu.jobs.ray import RayCluster, RayJob, WorkerGroup
from kueue_tpu.jobs.taints_job import TaintsTolerationsPod
