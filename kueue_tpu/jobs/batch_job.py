"""Single-PodSet batch job integration (reference: pkg/controller/jobs/job/).

Supports suspend/resume, partial admission via parallelism rewrite
(job_controller.go partial-admission handling), reclaimable pods from the
completion count (KEP-78), and PodsReady reporting.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@register_integration("batch")
class BatchJob(GenericJob):
    def __init__(self, name: str, queue_name: str, parallelism: int,
                 requests: Optional[Dict[str, object]] = None,
                 completions: Optional[int] = None,
                 min_parallelism: Optional[int] = None,
                 namespace: str = "default",
                 priority: int = 0,
                 annotations: Optional[Dict[str, str]] = None,
                 on_run: Optional[Callable[["BatchJob"], None]] = None,
                 **podset_kwargs):
        self._name = name
        self._namespace = namespace
        self._annotations = dict(annotations or {})
        self._queue_name = queue_name
        self.parallelism = parallelism
        self.original_parallelism = parallelism
        self.completions = completions if completions is not None else parallelism
        self.min_parallelism = min_parallelism
        self._priority = priority
        self._suspended = True
        self._requests = dict(requests or {})
        self._podset_kwargs = podset_kwargs
        self._on_run = on_run
        self.ready_pods = 0
        self.succeeded = 0
        self.failed = False
        self.podset_info: Optional[PodSetInfo] = None

    # -- GenericJob ---------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def annotations(self) -> Dict[str, str]:
        return self._annotations

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        self.ready_pods = 0

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        info = podset_infos[0]
        # Partial admission rewrites parallelism (job.go RunWithPodSetsInfo).
        self.parallelism = info.count
        self._applied_parallelism = info.count
        self.podset_info = info
        self._suspended = False
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.parallelism = self.original_parallelism
        self._applied_parallelism = None
        self.podset_info = None

    def validate_update(self, guard: dict):
        """Per-framework update webhook (job_webhook.go:147-160): with
        partial admission enabled, parallelism cannot change while the
        job is running (the admitted count is authoritative)."""
        applied = getattr(self, "_applied_parallelism", None)
        if (self.min_parallelism is not None and not self.is_suspended()
                and applied is not None and self.parallelism != applied):
            return ["spec.parallelism: cannot change when partial admission "
                    "is enabled and the job is not suspended"]
        return []

    def pod_sets(self) -> List[PodSet]:
        return [PodSet.make(
            "main", count=self.parallelism,
            min_count=self.min_parallelism,
            **self._requests, **self._podset_kwargs)]

    def finished(self) -> Tuple[bool, bool]:
        if self.failed:
            return True, False
        return self.succeeded >= self.completions, True

    def pods_ready(self) -> bool:
        return not self._suspended and self.ready_pods >= self.parallelism

    def reclaimable_pods(self) -> Dict[str, int]:
        # Completed pods no longer hold quota (KEP-78).
        if self.succeeded == 0:
            return {}
        remaining = max(self.parallelism - self.succeeded, 0)
        return {"main": self.parallelism - remaining} if remaining < self.parallelism else {}

    def priority(self) -> int:
        return self._priority
