"""JobSet integration (reference: pkg/controller/jobs/jobset/): a list of
replicated jobs, each mapping to one PodSet with count = replicas *
parallelism (jobset_controller.go PodSets)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@dataclass
class ReplicatedJob:
    name: str
    replicas: int
    parallelism: int
    requests: Dict[str, object] = field(default_factory=dict)
    podset_kwargs: Dict[str, object] = field(default_factory=dict)

    @property
    def pod_count(self) -> int:
        return self.replicas * self.parallelism


@register_integration("jobset")
class JobSet(GenericJob):
    def __init__(self, name: str, queue_name: str,
                 replicated_jobs: Sequence[ReplicatedJob],
                 namespace: str = "default", priority: int = 0,
                 on_run: Optional[Callable[["JobSet"], None]] = None):
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.replicated_jobs = list(replicated_jobs)
        self._priority = priority
        self._suspended = True
        self._on_run = on_run
        self.ready_jobs: Dict[str, bool] = {}
        self.succeeded = False
        self.failed = False
        self.podset_infos: List[PodSetInfo] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        self.ready_jobs.clear()

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        self._suspended = False
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet.make(rj.name, count=rj.pod_count,
                        **rj.requests, **rj.podset_kwargs)
            for rj in self.replicated_jobs
        ]

    def finished(self) -> Tuple[bool, bool]:
        if self.failed:
            return True, False
        return self.succeeded, True

    def pods_ready(self) -> bool:
        return not self._suspended and all(
            self.ready_jobs.get(rj.name, False) for rj in self.replicated_jobs)

    def priority(self) -> int:
        return self._priority
