"""Kubeflow training-operator job family.

Counterpart of reference pkg/controller/jobs/kubeflow/: a shared adapter
(`KubeflowJob`, kubeflowjob/kubeflowjob_controller.go) over per-framework
replica-spec maps, plus the five concrete integrations — PyTorchJob, TFJob,
PaddleJob, XGBoostJob, MXJob (jobs/{pytorchjob,tfjob,paddlejob,xgboostjob,
mxjob}/..._controller.go:98 OrderedReplicaTypes).

Each present replica type becomes one PodSet, emitted in the framework's
canonical order; the whole job is admitted atomically. Priority-class
resolution follows kubeflowjob_controller.go:146-165: the run policy's
scheduling-policy priority class wins, else the first replica template (in
canonical order) that names one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@dataclass
class ReplicaSpec:
    """One replica type's spec (kftraining.ReplicaSpec analog)."""

    replicas: int
    requests: Dict[str, object] = field(default_factory=dict)
    priority_class: str = ""
    ready: int = 0  # replicas currently ready (status mirror)
    podset_kwargs: Dict[str, object] = field(default_factory=dict)


class KubeflowJob(GenericJob):
    """Shared adapter over a replica-spec map (kubeflowjob_controller.go)."""

    # Canonical replica-type order; subclasses override.
    REPLICA_ORDER: Tuple[str, ...] = ()

    def __init__(self, name: str, queue_name: str,
                 replica_specs: Dict[str, ReplicaSpec],
                 namespace: str = "default",
                 scheduling_priority_class: str = "",
                 priority: int = 0,
                 on_run: Optional[Callable[["KubeflowJob"], None]] = None):
        unknown = set(replica_specs) - set(self.REPLICA_ORDER)
        if unknown:
            raise ValueError(
                f"unknown replica types {sorted(unknown)}; "
                f"{type(self).__name__} supports {list(self.REPLICA_ORDER)}")
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.replica_specs = dict(replica_specs)
        self.scheduling_priority_class = scheduling_priority_class
        self._priority = priority
        self._suspended = True
        self._on_run = on_run
        self.succeeded = False
        self.failed = False
        self.podset_infos: List[PodSetInfo] = []

    # -- GenericJob ---------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def ordered_replica_types(self) -> List[str]:
        """Present replica types in canonical order
        (OrderedReplicaTypes filtered to the spec, kubeflowjob ReplicaSpecs)."""
        return [rt for rt in self.REPLICA_ORDER if rt in self.replica_specs]

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        for spec in self.replica_specs.values():
            spec.ready = 0

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        self._suspended = False
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet.make(rt.lower(), count=self.replica_specs[rt].replicas,
                        **self.replica_specs[rt].requests,
                        **self.replica_specs[rt].podset_kwargs)
            for rt in self.ordered_replica_types()
        ]

    def finished(self) -> Tuple[bool, bool]:
        if self.failed:
            return True, False
        return self.succeeded, True

    def pods_ready(self) -> bool:
        """All replicas of all types ready (kubeflowjob PodsReady)."""
        return not self._suspended and all(
            spec.ready >= spec.replicas
            for spec in self.replica_specs.values())

    def priority_class(self) -> str:
        if self.scheduling_priority_class:
            return self.scheduling_priority_class
        for rt in self.ordered_replica_types():
            if self.replica_specs[rt].priority_class:
                return self.replica_specs[rt].priority_class
        return ""

    def priority(self) -> int:
        return self._priority


@register_integration("kubeflow.pytorchjob")
class PyTorchJob(KubeflowJob):
    """jobs/kubeflow/jobs/pytorchjob/pytorchjob_controller.go:98."""

    REPLICA_ORDER = ("Master", "Worker")


@register_integration("kubeflow.tfjob")
class TFJob(KubeflowJob):
    """jobs/kubeflow/jobs/tfjob/tfjob_controller.go:98."""

    REPLICA_ORDER = ("Chief", "Master", "PS", "Worker", "Eval")


@register_integration("kubeflow.paddlejob")
class PaddleJob(KubeflowJob):
    """jobs/kubeflow/jobs/paddlejob/paddlejob_controller.go:98."""

    REPLICA_ORDER = ("Master", "Worker")


@register_integration("kubeflow.xgboostjob")
class XGBoostJob(KubeflowJob):
    """jobs/kubeflow/jobs/xgboostjob/xgboostjob_controller.go:98."""

    REPLICA_ORDER = ("Master", "Worker")


@register_integration("kubeflow.mxjob")
class MXJob(KubeflowJob):
    """jobs/kubeflow/jobs/mxjob/mxjob_controller.go:98 — the replica order
    depends on the job mode (MXTrain vs MXTune)."""

    TRAIN_ORDER = ("Scheduler", "Server", "Worker")
    TUNE_ORDER = ("TunerTracker", "TunerServer", "Tuner")
    REPLICA_ORDER = TRAIN_ORDER + TUNE_ORDER  # superset for validation

    def __init__(self, *args, job_mode: str = "MXTrain", **kwargs):
        self.job_mode = job_mode
        super().__init__(*args, **kwargs)

    def ordered_replica_types(self) -> List[str]:
        order = self.TRAIN_ORDER if self.job_mode == "MXTrain" else self.TUNE_ORDER
        return [rt for rt in order if rt in self.replica_specs]
