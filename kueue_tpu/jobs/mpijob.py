"""MPIJob integration (reference: pkg/controller/jobs/mpijob/).

Launcher + Worker replica types (mpijob_controller.go:107 orderedReplicaTypes),
admitted atomically; priority class resolves from the run policy's
scheduling policy first, then the launcher template, then the worker
template (mpijob_controller.go priorityClass handling).
"""

from __future__ import annotations

from typing import Dict, List

from kueue_tpu.controllers.jobframework import register_integration
from kueue_tpu.jobs.kubeflow import KubeflowJob, ReplicaSpec

LAUNCHER = "Launcher"
WORKER = "Worker"


@register_integration("mpijob")
class MPIJob(KubeflowJob):
    """kubeflow mpi-operator v2beta1 MPIJob."""

    REPLICA_ORDER = (LAUNCHER, WORKER)

    @staticmethod
    def simple(name: str, queue_name: str, workers: int,
               worker_requests: Dict[str, object],
               launcher_requests: Dict[str, object] | None = None,
               **kwargs) -> "MPIJob":
        """Common shape: one launcher + N workers."""
        return MPIJob(
            name=name, queue_name=queue_name,
            replica_specs={
                LAUNCHER: ReplicaSpec(
                    replicas=1,
                    requests=dict(launcher_requests or {"cpu": 1})),
                WORKER: ReplicaSpec(replicas=workers,
                                    requests=dict(worker_requests)),
            }, **kwargs)
