"""Heterogeneous-role job integration.

One class covers the reference's launcher/worker and head/worker-group job
shapes -- MPIJob (jobs/mpijob), the kubeflow *Job family
(jobs/kubeflow/kubeflowjob + pytorchjob/tfjob/paddlejob/xgboostjob/mxjob),
and RayJob/RayCluster (jobs/rayjob, jobs/raycluster): each role becomes one
PodSet and the whole job is admitted atomically (the all-or-nothing
invariant of multi-PodSet workloads, flavorassigner.go:282-329).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@dataclass
class Role:
    """One homogeneous role (launcher, worker, head, worker-group...)."""

    name: str
    count: int
    requests: Dict[str, object] = field(default_factory=dict)
    min_count: Optional[int] = None
    podset_kwargs: Dict[str, object] = field(default_factory=dict)


@register_integration("multirole")
class MultiRoleJob(GenericJob):
    def __init__(self, name: str, queue_name: str, roles: Sequence[Role],
                 namespace: str = "default", priority: int = 0,
                 on_run: Optional[Callable[["MultiRoleJob"], None]] = None):
        if not roles:
            raise ValueError("MultiRoleJob needs at least one role")
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.roles = list(roles)
        self._priority = priority
        self._suspended = True
        self._on_run = on_run
        self.ready_roles: Dict[str, bool] = {}
        self.succeeded = False
        self.failed = False
        self.podset_infos: List[PodSetInfo] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        self.ready_roles.clear()

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        by_name = {i.name: i for i in podset_infos}
        for role in self.roles:
            info = by_name.get(role.name)
            if info is not None:
                role.count = info.count
        self._suspended = False
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet.make(role.name, count=role.count, min_count=role.min_count,
                        **role.requests, **role.podset_kwargs)
            for role in self.roles
        ]

    def finished(self) -> Tuple[bool, bool]:
        if self.failed:
            return True, False
        return self.succeeded, True

    def pods_ready(self) -> bool:
        return not self._suspended and all(
            self.ready_roles.get(r.name, False) for r in self.roles)

    def priority(self) -> int:
        return self._priority
