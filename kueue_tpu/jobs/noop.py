"""No-op integration stub (reference: pkg/controller/jobs/noop/).

Used for kinds whose lifecycle a parent object manages (e.g. the pods of a
framework-managed job): it contributes no PodSets and never starts or stops
anything; the reconciler effectively skips it.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@register_integration("noop")
class NoopJob(GenericJob):
    def __init__(self, name: str, namespace: str = "default"):
        self._name = name
        self._namespace = namespace

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return ""

    def is_suspended(self) -> bool:
        return True

    def suspend(self) -> None:
        pass

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        pass

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        pass

    def pod_sets(self) -> List[PodSet]:
        return []

    def finished(self) -> Tuple[bool, bool]:
        return False, False
