"""Plain-pod and pod-group integration (reference: pkg/controller/jobs/pod/,
KEP-976).

Pods carry an admission gate (the scheduling-gate analog,
pod_controller.go:161-232); a group is the set of pods sharing a group name
with an expected total count. Pods with the same requests shape form one
PodSet (role hashing, pod_controller.go:526-587); the group is admitted
atomically and pods are ungated together. A single ungrouped pod is a group
of one.

Heavyweight group semantics from the reference:
  * excess-pod cleanup — more members than the expected total are trimmed,
    newest ungated first (pod_controller.go excess-pod handling)
  * replacement pods — a failed member may be replaced without losing the
    group's reservation (KEP-976 "Failed pods replacement")
  * reclaimable pods — finished members release their share of the quota
    (KEP-78 via jobframework's reclaimable sync)
  * expectations store — in-flight deletions are tracked so a stale view
    never double-processes a group (expectations.go:30-75)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from kueue_tpu.api.types import PodSet, Workload
from kueue_tpu.controllers.jobframework import (
    ComposableJob,
    GenericJob,
    PodSetInfo,
    register_integration,
)


class ExpectationsStore:
    """Tracks expected-but-unobserved deletions per group
    (reference: jobs/pod/expectations.go:30-75). A group is only
    reprocessed when every expected deletion has been observed, guarding
    against stale informer-cache reads."""

    def __init__(self):
        self._pending: Dict[str, Set[str]] = {}

    def expect_deletions(self, group: str, pod_names: Sequence[str]) -> None:
        self._pending.setdefault(group, set()).update(pod_names)

    def observed_deletion(self, group: str, pod_name: str) -> None:
        keys = self._pending.get(group)
        if keys is None:
            return
        keys.discard(pod_name)
        if not keys:
            del self._pending[group]

    def satisfied(self, group: str) -> bool:
        return not self._pending.get(group)


@dataclass
class GroupedPod:
    name: str
    requests: Dict[str, object] = field(default_factory=dict)
    group: str = ""  # empty = single-pod group
    gated: bool = True
    finished: bool = False
    succeeded: bool = True
    running: bool = False
    node_selector: Dict[str, str] = field(default_factory=dict)

    def role_key(self) -> Tuple:
        return tuple(sorted((k, str(v)) for k, v in self.requests.items()))


@register_integration("podgroup")
class PodGroup(GenericJob, ComposableJob):
    def __init__(self, name: str, queue_name: str,
                 pods: Sequence[GroupedPod],
                 total_count: Optional[int] = None,
                 namespace: str = "default", priority: int = 0,
                 on_run: Optional[Callable[["PodGroup"], None]] = None):
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.pods = list(pods)
        self.total_count = total_count if total_count is not None else len(self.pods)
        self._priority = priority
        self._on_run = on_run
        self.podset_infos: List[PodSetInfo] = []
        self.expectations = ExpectationsStore()

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def add_pod(self, pod: GroupedPod) -> None:
        """Late-arriving group members (pod_controller.go group assembly)."""
        self.pods.append(pod)

    def has_all_members(self) -> bool:
        return len(self.active_pods()) >= self.total_count

    def active_pods(self) -> List[GroupedPod]:
        return [p for p in self.pods if not p.finished]

    def cleanup_excess(self) -> List[GroupedPod]:
        """Trim members beyond the expected total, ungated/newest first
        (pod_controller.go excess-pod cleanup); removals are registered in
        the expectations store and returned for the caller to delete."""
        excess = len(self.active_pods()) - self.total_count
        if excess <= 0:
            return []
        candidates = sorted(
            self.active_pods(),
            key=lambda p: (not p.gated, self.pods.index(p)), reverse=True)
        removed = candidates[:excess]
        self.expectations.expect_deletions(
            self._name, [p.name for p in removed])
        for p in removed:
            self.pods.remove(p)
            self.expectations.observed_deletion(self._name, p.name)
        return removed

    def replace_pod(self, failed_name: str, replacement: GroupedPod) -> bool:
        """Swap a failed member for a fresh pod without dropping the
        group's reservation (KEP-976 failed-pod replacement)."""
        for i, p in enumerate(self.pods):
            if p.name == failed_name and p.finished and not p.succeeded:
                replacement.gated = p.gated
                self.pods[i] = replacement
                return True
        return False

    def reclaimable_pods(self) -> Dict[str, int]:
        """Finished members release quota per role (KEP-78)."""
        out: Dict[str, int] = {}
        for key, members in self._roles().items():
            done = sum(1 for p in members if p.finished and p.succeeded)
            if done:
                out[self._role_name(key)] = done
        return out

    def is_suspended(self) -> bool:
        # Suspension = all non-finished pods still gated.
        return all(p.gated for p in self.pods if not p.finished)

    def suspend(self) -> None:
        for p in self.pods:
            if not p.finished:
                p.gated = True
                p.running = False

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        by_name = {i.name: i for i in podset_infos}
        roles = self._roles()
        for role_key, members in roles.items():
            info = by_name.get(self._role_name(role_key))
            for p in members:
                if info is not None:
                    p.node_selector.update(info.node_selector)
                p.gated = False
                p.running = True
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []
        for p in self.pods:
            p.node_selector.clear()

    def _roles(self) -> Dict[Tuple, List[GroupedPod]]:
        roles: Dict[Tuple, List[GroupedPod]] = {}
        for p in self.pods:
            roles.setdefault(p.role_key(), []).append(p)
        return roles

    @staticmethod
    def _role_name(role_key: Tuple) -> str:
        return f"role-{abs(hash(role_key)) % 10**8:08d}"

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet.make(self._role_name(key), count=len(members),
                        **members[0].requests)
            for key, members in sorted(self._roles().items())
        ]

    def finished(self) -> Tuple[bool, bool]:
        if not self.pods:
            return False, True
        if all(p.finished for p in self.pods):
            return True, all(p.succeeded for p in self.pods)
        return False, True

    def pods_ready(self) -> bool:
        return all(p.running or p.finished for p in self.pods)

    def priority(self) -> int:
        return self._priority

    # -- ComposableJob (interface.go:99-114; the pod integration is the
    # reference's canonical composable job, pod_controller.go:588-1108) ----

    def construct_composable_workload(self) -> Optional[Workload]:
        """Assemble the group Workload once every expected member has
        arrived (the reference defers workload creation until the group is
        complete, pod_controller.go group assembly)."""
        if not self.has_all_members():
            return None
        self._applied_total = self.total_count
        return Workload(
            name=f"job-{self._name}",
            namespace=self._namespace,
            queue_name=self._queue_name,
            pod_sets=self.pod_sets(),
            priority=self._priority,
        )

    def validate_update(self, guard: dict):
        """Per-framework update webhook (pod_webhook.go group rules): the
        expected group total is immutable once the group workload was
        constructed and the group is running."""
        applied = getattr(self, "_applied_total", None)
        if (applied is not None and not self.is_suspended()
                and self.total_count != applied):
            return ["metadata.annotations[kueue.x-k8s.io/pod-group-total-"
                    "count]: immutable while the pod group is running"]
        return []

    def find_matching_workloads(self, owned):
        from kueue_tpu.controllers.jobframework import \
            find_matching_workloads_default
        return find_matching_workloads_default(self, owned)
