"""Plain-pod and pod-group integration (reference: pkg/controller/jobs/pod/,
KEP-976).

Pods carry an admission gate (the scheduling-gate analog,
pod_controller.go:161-232); a group is the set of pods sharing a group name
with an expected total count. Pods with the same requests shape form one
PodSet (role hashing, pod_controller.go:526-587); the group is admitted
atomically and pods are ungated together. A single ungrouped pod is a group
of one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)


@dataclass
class GroupedPod:
    name: str
    requests: Dict[str, object] = field(default_factory=dict)
    group: str = ""  # empty = single-pod group
    gated: bool = True
    finished: bool = False
    succeeded: bool = True
    running: bool = False
    node_selector: Dict[str, str] = field(default_factory=dict)

    def role_key(self) -> Tuple:
        return tuple(sorted((k, str(v)) for k, v in self.requests.items()))


@register_integration("podgroup")
class PodGroup(GenericJob):
    def __init__(self, name: str, queue_name: str,
                 pods: Sequence[GroupedPod],
                 total_count: Optional[int] = None,
                 namespace: str = "default", priority: int = 0,
                 on_run: Optional[Callable[["PodGroup"], None]] = None):
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.pods = list(pods)
        self.total_count = total_count if total_count is not None else len(self.pods)
        self._priority = priority
        self._on_run = on_run
        self.podset_infos: List[PodSetInfo] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def add_pod(self, pod: GroupedPod) -> None:
        """Late-arriving group members (pod_controller.go group assembly)."""
        self.pods.append(pod)

    def has_all_members(self) -> bool:
        return len(self.pods) >= self.total_count

    def is_suspended(self) -> bool:
        # Suspension = all non-finished pods still gated.
        return all(p.gated for p in self.pods if not p.finished)

    def suspend(self) -> None:
        for p in self.pods:
            if not p.finished:
                p.gated = True
                p.running = False

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        by_name = {i.name: i for i in podset_infos}
        roles = self._roles()
        for role_key, members in roles.items():
            info = by_name.get(self._role_name(role_key))
            for p in members:
                if info is not None:
                    p.node_selector.update(info.node_selector)
                p.gated = False
                p.running = True
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []
        for p in self.pods:
            p.node_selector.clear()

    def _roles(self) -> Dict[Tuple, List[GroupedPod]]:
        roles: Dict[Tuple, List[GroupedPod]] = {}
        for p in self.pods:
            roles.setdefault(p.role_key(), []).append(p)
        return roles

    @staticmethod
    def _role_name(role_key: Tuple) -> str:
        return f"role-{abs(hash(role_key)) % 10**8:08d}"

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet.make(self._role_name(key), count=len(members),
                        **members[0].requests)
            for key, members in sorted(self._roles().items())
        ]

    def finished(self) -> Tuple[bool, bool]:
        if not self.pods:
            return False, True
        if all(p.finished for p in self.pods):
            return True, all(p.succeeded for p in self.pods)
        return False, True

    def pods_ready(self) -> bool:
        return all(p.running or p.finished for p in self.pods)

    def priority(self) -> int:
        return self._priority
