"""RayJob / RayCluster integrations (reference: pkg/controller/jobs/rayjob/,
pkg/controller/jobs/raycluster/).

Both map to a "head" PodSet plus one PodSet per worker group (group name
lowercased, raycluster_controller.go:90-115); the whole cluster is admitted
atomically. RayJob wraps a cluster spec and finishes with the job's
succeed/fail status (rayjob_controller.go Finished from JobDeploymentStatus);
RayCluster is long-running — it "finishes" only when deleted, and supports
suspend by tearing down pods (raycluster suspend semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import PodSet
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)

HEAD_GROUP = "head"


@dataclass
class WorkerGroup:
    """One Ray worker group (raycluster WorkerGroupSpecs entry)."""

    name: str
    replicas: int
    requests: Dict[str, object] = field(default_factory=dict)
    ready: int = 0
    podset_kwargs: Dict[str, object] = field(default_factory=dict)


class _RayBase(GenericJob):
    def __init__(self, name: str, queue_name: str,
                 head_requests: Dict[str, object],
                 worker_groups: Sequence[WorkerGroup],
                 namespace: str = "default", priority: int = 0,
                 on_run: Optional[Callable[["_RayBase"], None]] = None):
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self.head_requests = dict(head_requests)
        self.worker_groups = list(worker_groups)
        self._priority = priority
        self._suspended = True
        self._on_run = on_run
        self.head_ready = False
        self.podset_infos: List[PodSetInfo] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        self.head_ready = False
        for wg in self.worker_groups:
            wg.ready = 0

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = list(podset_infos)
        self._suspended = False
        if self._on_run is not None:
            self._on_run(self)

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        self.podset_infos = []

    def pod_sets(self) -> List[PodSet]:
        sets = [PodSet.make(HEAD_GROUP, count=1, **self.head_requests)]
        for wg in self.worker_groups:
            sets.append(PodSet.make(wg.name.lower(), count=wg.replicas,
                                    **wg.requests, **wg.podset_kwargs))
        return sets

    def pods_ready(self) -> bool:
        return (not self._suspended and self.head_ready
                and all(wg.ready >= wg.replicas for wg in self.worker_groups))

    def priority(self) -> int:
        return self._priority


@register_integration("rayjob")
class RayJob(_RayBase):
    """A Ray job with an ephemeral cluster (jobs/rayjob/)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.succeeded = False
        self.failed = False

    def finished(self) -> Tuple[bool, bool]:
        if self.failed:
            return True, False
        return self.succeeded, True


@register_integration("raycluster")
class RayCluster(_RayBase):
    """A long-running Ray cluster (jobs/raycluster/): never self-finishes;
    quota is released by deleting it (jobframework delete path)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.deleted = False

    def finished(self) -> Tuple[bool, bool]:
        return self.deleted, True
