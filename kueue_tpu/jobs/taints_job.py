"""Taints/tolerations pod integration (out-of-tree extension sample).

Counterpart of the reference's experimental standalone controller
``cmd/experimental/podtaintstolerations``: bare pods on clusters whose
nodes carry an admission taint (``kueue.x-k8s.io/kueue-admission``).
Suspension is *encoded in the tolerations* rather than a suspend field
(controller/pod_jobs.go:55-62): a pod without the admission toleration
cannot schedule anywhere, so it is queued; admission adds the toleration
plus one toleration per flavor node-selector label
(pod_jobs.go RunWithPodSetsInfo), and stop removes them again.

Like the reference, this doubles as the template for building an
integration out-of-tree: it is ordinary `register_integration` usage with
no special hooks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import PodSet, Toleration
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    register_integration,
)

ADMISSION_TAINT_KEY = "kueue.x-k8s.io/kueue-admission"


@register_integration("taintspod")
class TaintsTolerationsPod(GenericJob):
    """A single bare pod admitted by toleration rewriting."""

    def __init__(self, name: str, queue_name: str,
                 requests: Optional[Dict[str, object]] = None,
                 namespace: str = "default",
                 tolerations: Sequence[Toleration] = (),
                 priority: int = 0, priority_class: str = ""):
        self._name = name
        self._namespace = namespace
        self._queue_name = queue_name
        self._requests = {r: resource_value(r, q)
                          for r, q in (requests or {}).items()}
        self.tolerations: List[Toleration] = list(tolerations)
        self._priority = priority
        self._priority_class = priority_class
        self.phase = "Pending"  # Pending | Running | Succeeded | Failed
        self.deleted = False

    # -- GenericJob ----------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue_name

    def is_suspended(self) -> bool:
        """Suspended = no Exists-toleration for the admission taint
        (pod_jobs.go:55-62)."""
        return not any(t.key == ADMISSION_TAINT_KEY and t.operator == "Exists"
                       for t in self.tolerations)

    def suspend(self) -> None:
        # Not used directly: stop deletes the pod (JobWithCustomStop,
        # pod_jobs.go Stop); restore() strips the admission tolerations.
        pass

    def is_active(self) -> bool:
        return self.phase == "Running"

    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Admission: ensure the admission toleration and one per flavor
        node-selector label (pod_jobs.go RunWithPodSetsInfo)."""
        info = podset_infos[0]
        have = {t.key for t in self.tolerations}
        if ADMISSION_TAINT_KEY not in have:
            self.tolerations.append(
                Toleration(key=ADMISSION_TAINT_KEY, operator="Exists"))
        else:
            self.tolerations = [
                Toleration(key=t.key, operator="Exists")
                if t.key == ADMISSION_TAINT_KEY else t
                for t in self.tolerations]
        for k, v in info.node_selector.items():
            matched = False
            out = []
            for t in self.tolerations:
                if t.key == k:
                    out.append(Toleration(key=k, operator="Equal", value=v))
                    matched = True
                else:
                    out.append(t)
            if not matched:
                out.append(Toleration(key=k, operator="Equal", value=v))
            self.tolerations = out
        self.phase = "Running"

    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Stop: the reference deletes the pod (it cannot be un-admitted);
        mirror by marking deleted and stripping injected tolerations."""
        selector_keys = set()
        for info in podset_infos:
            selector_keys.update(info.node_selector)
        self.tolerations = [
            t for t in self.tolerations
            if t.key != ADMISSION_TAINT_KEY and t.key not in selector_keys]
        self.phase = "Pending"
        self.deleted = True

    def pod_sets(self) -> List[PodSet]:
        return [PodSet(name="main", count=1, requests=dict(self._requests))]

    def finished(self) -> Tuple[bool, bool]:
        return self.phase in ("Succeeded", "Failed"), self.phase == "Succeeded"

    def pods_ready(self) -> bool:
        return self.phase == "Running"

    def priority_class(self) -> str:
        return self._priority_class

    def priority(self) -> int:
        return self._priority
