"""The KUEUE_TPU_* environment-knob contract registry.

Every environment variable the package consults is declared HERE, once,
with its kind, default, read discipline, and a doc line. Read sites go
through the accessors (`raw` / `flag`) instead of `os.environ` so that:

  * an undeclared knob cannot ship: KNOB01 (kueuelint) flags raw
    `os.environ` reads of `KUEUE_TPU_*` names and accessor calls naming
    an unregistered knob — and registry entries nothing reads;
  * the README's knob table is GENERATED from this registry
    (`markdown_table()`) and checked against it in CI, so the docs
    cannot drift from the code;
  * the read discipline is explicit: a `live` knob is consulted at
    every decision point (the fuzz lattice and the A/B drills rely on
    flipping these per run), a `startup` knob is captured once at
    import or construction — moving a read between disciplines is a
    contract change, not an accident.

Kinds:
  * kill-switch — reverts a feature to its pre-feature behavior
    (`KUEUE_TPU_NO_*=1`, or an opt-out like `KUEUE_TPU_NATIVE_HEAP=0`);
    every one must keep a green A/B twin somewhere in the suite.
  * debug      — extra verification/telemetry or test-only injection
    (fault plans, oracle mutations); never changes decisions when unset.
  * tuning     — selects topology/limits/modes (replica count,
    transport, timeouts).

This module imports nothing beyond the stdlib and is imported from
everywhere, including package `__init__` paths — keep it dependency-free.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

KILL_SWITCH = "kill-switch"
DEBUG = "debug"
TUNING = "tuning"
_KINDS = (KILL_SWITCH, DEBUG, TUNING)

LIVE = "live"        # consulted at every decision point
STARTUP = "startup"  # captured once at import or construction
_READS = (LIVE, STARTUP)

# The decision contract (checked statically by TNT01):
#   * a NEUTRAL knob's VALUE never reaches decision state — it may
#     branch (enable a tracer, a cross-check, a drill) but may not be
#     stored into decision-core objects, passed into decision-record
#     constructors, or used in sort keys;
#   * a GATE knob deliberately selects between decision paths and is
#     read ONLY at its registered gate sites (`gates=` path fragments)
#     — a new read site is a declared contract change, never an
#     accident that silently widens the switch's blast radius.
NEUTRAL = "neutral"
GATE = "gate"
_DECISIONS = (NEUTRAL, GATE)


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str
    default: Optional[str]  # value the read site assumes when unset
    read: str
    doc: str
    decision: str = ""            # NEUTRAL or GATE — required
    gates: Tuple[str, ...] = ()   # path fragments of the gate sites

    def __post_init__(self):
        if not self.name.startswith("KUEUE_TPU_"):
            raise ValueError(f"knob {self.name!r}: not a KUEUE_TPU_* name")
        if self.kind not in _KINDS:
            raise ValueError(f"knob {self.name}: kind {self.kind!r}")
        if self.read not in _READS:
            raise ValueError(f"knob {self.name}: read {self.read!r}")
        if self.decision not in _DECISIONS:
            raise ValueError(
                f"knob {self.name}: decision {self.decision!r} "
                f"(declare {NEUTRAL!r} or {GATE!r})")
        if self.kind == KILL_SWITCH and self.decision != GATE:
            raise ValueError(
                f"knob {self.name}: a kill-switch selects between "
                "decision paths by definition — declare decision=GATE")
        if self.decision == GATE and not self.gates:
            raise ValueError(
                f"knob {self.name}: a gate knob must register its "
                "gate sites (gates=(path fragment, ...))")
        if self.decision == NEUTRAL and self.gates:
            raise ValueError(
                f"knob {self.name}: a neutral knob gates nothing — "
                "drop gates= or declare decision=GATE")


REGISTRY: Tuple[Knob, ...] = (
    # -- kill switches (feature reverts; each keeps an A/B twin) ------------
    Knob("KUEUE_TPU_NO_ARENA", KILL_SWITCH, "", LIVE,
         "=1 disables the incremental workload arena (from-scratch "
         "encode every solve).",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_ADMIT_ARENA", KILL_SWITCH, "", LIVE,
         "=1 disables the admitted-workload arena (full re-encode of "
         "admitted state).",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_NOMINATE_CACHE", KILL_SWITCH, "", LIVE,
         "=1 disables the nominate cache (every head re-solved every "
         "tick).",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_DEVICE_FAIR", KILL_SWITCH, "", LIVE,
         "=1 restores the per-CQ host dict DRF walk instead of the "
         "device fair-share stage.",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_HETERO", KILL_SWITCH, "", LIVE,
         "=1 disables heterogeneity-aware scoring even when profiles "
         "are loaded.",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_QUIET_TICK", KILL_SWITCH, "", LIVE,
         "=1 disables the quiescent-tick replay fast path (full "
         "pipeline every tick).",
         decision=GATE, gates=("scheduler/scheduler.py",)),
    Knob("KUEUE_TPU_NO_MICROTICK", KILL_SWITCH, "", LIVE,
         "=1 disables event-driven micro-ticks between full ticks.",
         decision=GATE, gates=("scheduler/scheduler.py",)),
    Knob("KUEUE_TPU_NO_EAGER_ENCODE", KILL_SWITCH, "", LIVE,
         "=1 disables eager arena encode at the replica barrier.",
         decision=GATE, gates=("controllers/replica_runtime.py",)),
    Knob("KUEUE_TPU_NO_SHARD", KILL_SWITCH, "", LIVE,
         "=1 forces single-device solves even when a cohort mesh is "
         "available.",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_NO_REPLICA", KILL_SWITCH, "", STARTUP,
         "=1 forces the single-process runtime regardless of "
         "KUEUE_TPU_REPLICAS.",
         decision=GATE, gates=("controllers/replica_runtime.py",
                               "kueue_tpu/__main__.py")),
    Knob("KUEUE_TPU_NO_SOCKET", KILL_SWITCH, "", STARTUP,
         "=1 forbids the socket transport (pipe/queue loopback only).",
         decision=GATE, gates=("controllers/replica_runtime.py",)),
    Knob("KUEUE_TPU_NATIVE_HEAP", KILL_SWITCH, "1", STARTUP,
         "=0 disables the C++ keyed heap (pure-Python queue ordering); "
         "opt-out, default on.",
         decision=GATE, gates=("queue/manager.py",)),
    Knob("KUEUE_TPU_NO_BATCH_INGEST", KILL_SWITCH, "", LIVE,
         "=1 reverts batch ingest to per-object create/submit and "
         "synchronous watch fan-out.",
         decision=GATE, gates=("controllers/store.py",
                               "controllers/replica_runtime.py")),
    Knob("KUEUE_TPU_NO_SNAPSHOT_BOOT", KILL_SWITCH, "", LIVE,
         "=1 ships full journal history on rejoin/takeover instead of "
         "a compacted snapshot.",
         decision=GATE, gates=("controllers/replica_runtime.py",)),
    # -- debug / test injection --------------------------------------------
    Knob("KUEUE_TPU_TRACE", DEBUG, "", STARTUP,
         "=1 enables span tracing (Chrome trace-event export).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DEBUG_ARENA", DEBUG, "", STARTUP,
         "=1 cross-checks every arena row against a from-scratch "
         "encode.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DEBUG_ADMIT_ARENA", DEBUG, "", STARTUP,
         "=1 cross-checks the admitted arena against a full re-encode.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DEBUG_DRIFT", DEBUG, "", STARTUP,
         "=1 verifies the incremental usage drift against a recompute.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DEBUG_FAIR", DEBUG, "", LIVE,
         "=1 cross-checks device fair-share preemption against the "
         "host referee.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DEBUG_HETERO", DEBUG, "", LIVE,
         "=1 cross-checks hetero scoring against the NumPy twin per "
         "solve.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_ARENA_FLUSH", DEBUG, "", LIVE,
         "=1 flushes the arena every snapshot (drills the rebuild "
         "path).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_FUZZ_MUTATION", DEBUG, None, LIVE,
         "Arms an env-gated oracle mutation (e.g. unsorted-cohort-walk) "
         "for the fuzzer self-test.",
         decision=GATE, gates=("core/cache.py", "queue/manager.py")),
    Knob("KUEUE_TPU_FAULTS", DEBUG, None, STARTUP,
         "Packet-fault plan for the socket transport "
         "(drop_p=..,delay_ms=..,seed=..).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_DISK_FAULTS", DEBUG, None, STARTUP,
         "Disk-fault plan for the durable journals "
         "(enospc_p=..,fsync_p=..,torn_p=..,seed=..).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_SNAPSHOT_BOOT_FAULTS", DEBUG, None, LIVE,
         "Disk-fault plan armed only on the snapshot-seed write of an "
         "adopting worker (same format as KUEUE_TPU_DISK_FAULTS).",
         decision=NEUTRAL),
    # -- tuning -------------------------------------------------------------
    Knob("KUEUE_TPU_REPLICAS", TUNING, "0", STARTUP,
         "Replica count for the multi-process runtime (0/unset = "
         "single process).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_TRANSPORT", TUNING, "", STARTUP,
         "Replica channel transport: pipe, queue, or socket (unset = "
         "per-mode default).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_SHARDS", TUNING, "", LIVE,
         "Cohort-mesh shard count override (unset = device count).",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_HETERO", TUNING, "", LIVE,
         "=1 opts the packed solver into hetero scoring when profiles "
         "exist.",
         decision=GATE, gates=("models/flavor_fit.py",)),
    Knob("KUEUE_TPU_ROUND_TIMEOUT", TUNING, "60", STARTUP,
         "Replica barrier round timeout in seconds.",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_BARRIER_DEADLINE", TUNING, "", STARTUP,
         "Barrier-stall watchdog deadline in seconds (unset = derived "
         "from the round timeout).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_CSR_ASSUME", TUNING, "", LIVE,
         "Pre-seeds the cohort-state-root cache (advanced: skips the "
         "first-tick probe).",
         decision=GATE, gates=("scheduler/scheduler.py",)),
    Knob("KUEUE_TPU_DURABLE_FSYNC", TUNING, "", STARTUP,
         "=1 fsyncs every journal append (durability over append "
         "latency).",
         decision=NEUTRAL),
    Knob("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", TUNING, "256", LIVE,
         "Journal-history line count below which a rejoin ships raw "
         "lines instead of building a snapshot.",
         decision=NEUTRAL),
)

_BY_NAME: Dict[str, Knob] = {k.name: k for k in REGISTRY}
if len(_BY_NAME) != len(REGISTRY):
    raise RuntimeError("duplicate knob registration")


def get(name: str) -> Knob:
    return _BY_NAME[name]


def raw(name: str) -> Optional[str]:
    """The knob's environment value, or its registered default when
    unset. KeyError on an unregistered name — the runtime twin of
    KNOB01 (declare the knob in REGISTRY first)."""
    return os.environ.get(name, _BY_NAME[name].default)


def flag(name: str) -> bool:
    """True iff the boolean knob is set to "1" — the single opt-in
    idiom every `KUEUE_TPU_*=1` site uses. Kill-switch guards read
    `not flag(...)`; opt-out knobs (NATIVE_HEAP) compare `raw(...)`
    against their off value explicitly."""
    return raw(name) == "1"


def markdown_table() -> str:
    """The README knob table, generated from the registry (checked
    against the README in CI so the docs cannot drift)."""
    lines = ["| Knob | Kind | Default | Read | Decision | What it does |",
             "| --- | --- | --- | --- | --- | --- |"]
    for k in REGISTRY:
        default = "_unset_" if k.default in (None, "") else f"`{k.default}`"
        lines.append(f"| `{k.name}` | {k.kind} | {default} | {k.read} "
                     f"| {k.decision} | {k.doc} |")
    return "\n".join(lines)
