"""Metrics registry (counterpart of reference pkg/metrics/metrics.go).

A dependency-free Prometheus-style registry: counters, gauges and
histograms with labels, exportable in the text exposition format. The
metric names and label sets mirror the reference
(metrics.go:55-178), plus the per-tick phase timings the TPU build adds
(snapshot / tensorize / device solve / apply).
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0)


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: Tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._lock = threading.Lock()


class Counter(_Metric):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self.values: Dict[Tuple, float] = defaultdict(float)

    def inc(self, *labels, by: float = 1.0) -> None:
        with self._lock:
            self.values[tuple(labels)] += by

    def inc_bulk(self, items) -> None:
        """`[(label_tuple, delta)]` folded under one lock acquisition."""
        with self._lock:
            values = self.values
            for key, by in items:
                values[key] += by

    def get(self, *labels) -> float:
        return self.values.get(tuple(labels), 0.0)

    def collect(self):
        for labels, v in sorted(self.values.items()):
            yield self.name, labels, v


class Gauge(_Metric):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self.values: Dict[Tuple, float] = {}

    def set(self, *labels, value: float) -> None:
        with self._lock:
            self.values[tuple(labels)] = value

    def get(self, *labels) -> float:
        return self.values.get(tuple(labels), 0.0)

    def clear(self, *labels) -> None:
        with self._lock:
            self.values.pop(tuple(labels), None)

    def prune(self, keep) -> None:
        """Drop series whose label tuple fails the predicate (stale-object
        cleanup; reference metrics.ClearClusterQueueMetrics)."""
        with self._lock:
            for key in [k for k in self.values if not keep(k)]:
                del self.values[key]

    def collect(self):
        for labels, v in sorted(self.values.items()):
            yield self.name, labels, v


class Histogram(_Metric):
    def __init__(self, name, help_text, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(buckets)
        self.counts: Dict[Tuple, List[int]] = {}
        self.sums: Dict[Tuple, float] = defaultdict(float)
        self.totals: Dict[Tuple, int] = defaultdict(int)

    def observe(self, *labels, value: float) -> None:
        key = tuple(labels)
        with self._lock:
            counts = self.counts.get(key)
            if counts is None:
                counts = self.counts[key] = [0] * (len(self.buckets) + 1)
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self.sums[key] += value
            self.totals[key] += 1

    def observe_bulk(self, items) -> None:
        """Fold many observations (`[(label_tuple, value)]`) under ONE
        lock acquisition — the admission commit records a wait-time sample
        per admitted workload and per-sample locking showed up at
        north-star scale."""
        with self._lock:
            bisect_left = bisect.bisect_left
            buckets = self.buckets
            n_counts = len(buckets) + 1
            for key, value in items:
                counts = self.counts.get(key)
                if counts is None:
                    counts = self.counts[key] = [0] * n_counts
                counts[bisect_left(buckets, value)] += 1
                self.sums[key] += value
                self.totals[key] += 1

    def percentile(self, q: float, *labels) -> float:
        """Approximate percentile from bucket boundaries."""
        key = tuple(labels)
        counts = self.counts.get(key)
        if not counts:
            return 0.0
        total = self.totals[key]
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    def collect(self):
        for key in sorted(self.counts):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self.counts[key][i]
                yield f"{self.name}_bucket", key + (f'le="{b}"',), cum
            yield f"{self.name}_bucket", key + ('le="+Inf"',), self.totals[key]
            yield f"{self.name}_sum", key, self.sums[key]
            yield f"{self.name}_count", key, self.totals[key]


class Registry:
    """All framework metrics (names mirror metrics.go)."""

    def __init__(self):
        p = "kueue_"
        self.admission_attempts_total = Counter(
            p + "admission_attempts_total",
            "Total scheduling attempts", ("result",))
        self.admission_attempt_duration_seconds = Histogram(
            p + "admission_attempt_duration_seconds",
            "Latency of a scheduling attempt", ("result",))
        self.pending_workloads = Gauge(
            p + "pending_workloads",
            "Pending workloads per CQ", ("cluster_queue", "status"))
        self.admitted_workloads_total = Counter(
            p + "admitted_workloads_total",
            "Admitted workloads per CQ", ("cluster_queue",))
        self.admission_wait_time_seconds = Histogram(
            p + "admission_wait_time_seconds",
            "Queued-to-admitted wait time", ("cluster_queue",),
            buckets=(1, 5, 10, 30, 60, 300, 600, 1800, 3600))
        self.evicted_workloads_total = Counter(
            p + "evicted_workloads_total",
            "Evictions per CQ and reason", ("cluster_queue", "reason"))
        self.preempted_workloads_total = Counter(
            p + "preempted_workloads_total",
            "Preemptions per CQ", ("cluster_queue",))
        self.reserving_active_workloads = Gauge(
            p + "reserving_active_workloads",
            "Workloads holding quota per CQ", ("cluster_queue",))
        self.admitted_active_workloads = Gauge(
            p + "admitted_active_workloads",
            "Admitted workloads per CQ", ("cluster_queue",))
        self.cluster_queue_status = Gauge(
            p + "cluster_queue_status",
            "CQ active status", ("cluster_queue", "status"))
        self.cluster_queue_resource_usage = Gauge(
            p + "cluster_queue_resource_usage",
            "Quota usage", ("cluster_queue", "flavor", "resource"))
        self.cluster_queue_nominal_quota = Gauge(
            p + "cluster_queue_nominal_quota",
            "Nominal quota", ("cluster_queue", "flavor", "resource"))
        self.cluster_queue_fair_share = Gauge(
            p + "cluster_queue_fair_sharing_weighted_share",
            "Fair-sharing share value", ("cluster_queue",))
        # Optional per-CQ quota gauges (metrics.go:137-177), reported only
        # with metrics.enableClusterQueueResources — reference label order
        # (cohort first).
        self.cluster_queue_resource_reservation = Gauge(
            p + "cluster_queue_resource_reservation",
            "Total resource reservation per CQ and flavor",
            ("cohort", "cluster_queue", "flavor", "resource"))
        self.cluster_queue_borrowing_limit = Gauge(
            p + "cluster_queue_borrowing_limit",
            "Resource borrowing limit per CQ and flavor",
            ("cohort", "cluster_queue", "flavor", "resource"))
        self.cluster_queue_lending_limit = Gauge(
            p + "cluster_queue_lending_limit",
            "Resource lending limit per CQ and flavor",
            ("cohort", "cluster_queue", "flavor", "resource"))
        # Bounded-recorder overflow: events evicted from the EventRecorder
        # ring before anyone read them (capacity-sizing signal — a nonzero
        # rate means the debugging surface is silently losing history).
        self.events_dropped_total = Counter(
            p + "events_dropped_total",
            "Events dropped by the bounded recorder")
        # Multi-host replica runtime: pending backlog per shard group
        # (the elastic-scaling signal — transport/elastic.py reads the
        # same feed), barrier stalls surfaced by the watchdog, and the
        # coordinator incarnation arbitrating reconcile rounds.
        self.replica_backlog_depth = Gauge(
            p + "replica_backlog_depth",
            "Pending-workload backlog depth per shard group",
            ("shard_group",))
        self.replica_barrier_stalls_total = Counter(
            p + "replica_barrier_stalls_total",
            "Barrier deadlines missed by a stalled replica", ("replica",))
        self.reconcile_round_epoch = Gauge(
            p + "reconcile_round_epoch",
            "Coordinator incarnation (lease transitions) arbitrating "
            "reconcile rounds")
        # Fleet-grade control plane: degraded-mode admission (the
        # coordinator is dead and no re-election succeeded — replicas
        # keep admitting flat cohorts shard-locally under a journaled
        # safe mode), disk-fault hardening on the durable journals, the
        # lease-transition audit trail, and listener hello rejections
        # (TLS / auth / malformed greetings on the control-plane port).
        self.coordinator_degraded = Gauge(
            p + "coordinator_degraded",
            "1 while this replica admits in degraded (coordinator-"
            "unreachable) safe mode, 0 otherwise", ("host",))
        self.degraded_admissions_total = Counter(
            p + "degraded_admissions_total",
            "Workloads admitted shard-locally during degraded windows",
            ("host",))
        self.journal_write_errors_total = Counter(
            p + "journal_write_errors_total",
            "Durable-journal append failures surfaced (not swallowed)",
            ("reason",))
        self.lease_transitions_total = Counter(
            p + "lease_transitions_total",
            "Lease holder changes (the coordinator epoch source)",
            ("lease",))
        self.channel_rejected_hellos_total = Counter(
            p + "channel_rejected_hellos_total",
            "Hellos the ChannelListener rejected", ("reason",))
        # TPU-build additions: per-tick phase timings.
        self.tick_phase_seconds = Histogram(
            p + "tick_phase_seconds",
            "Per-phase tick latency (snapshot/tensorize/solve/apply)",
            ("phase",))
        # Event-driven admission fast path: micro-ticks solve ONLY the
        # cohorts dirtied since the last full tick (flat cohorts are
        # solve-independent), cutting submit->admitted latency from
        # p99-tick-ms to p99-micro-tick-ms. The histogram buckets sit an
        # order of magnitude below the tick buckets — a micro-tick that
        # costs a full tick is a regression the buckets must resolve.
        self.microticks_total = Counter(
            p + "microticks_total",
            "Dirty-cohort micro-ticks run between full scheduling ticks")
        self.microtick_latency_seconds = Histogram(
            p + "microtick_latency_seconds",
            "Latency of one dirty-cohort micro-tick (dispatch to flush)",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                     0.025, 0.05, 0.1, 0.25, 1.0))
        # Topology-aware scheduling: free-capacity fragmentation per
        # (flavor, level) — 1 - largest free domain / total free slots.
        # 0 = all free capacity sits in one domain (any fitting podset can
        # pack); ->1 = free slots are shredded across domains.
        self.topology_fragmentation = Gauge(
            p + "topology_fragmentation",
            "Free-slot fragmentation per flavor topology level",
            ("flavor", "level"))

    def all_metrics(self) -> Iterable[_Metric]:
        return [v for v in vars(self).values() if isinstance(v, _Metric)]

    def export_text(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        for m in self.all_metrics():
            lines.append(f"# HELP {m.name} {m.help}")
            kind = {"Counter": "counter", "Gauge": "gauge",
                    "Histogram": "histogram"}[type(m).__name__]
            lines.append(f"# TYPE {m.name} {kind}")
            for name, labels, value in m.collect():
                rendered = []
                for i, lv in enumerate(labels):
                    if isinstance(lv, str) and "=" in lv:
                        rendered.append(lv)
                    else:
                        rendered.append(f'{m.label_names[i]}="{lv}"')
                label_str = "{" + ",".join(rendered) + "}" if rendered else ""
                lines.append(f"{name}{label_str} {value}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()
