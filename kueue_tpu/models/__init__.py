"""Batched solver models (the device-side hot path).

Quota arithmetic is exact int64; enable x64 before any jax array exists.
"""

import jax

jax.config.update("jax_enable_x64", True)

from kueue_tpu.models.flavor_fit import BatchSolver, solve_flavor_fit
from kueue_tpu.models.fair_share import share_values
