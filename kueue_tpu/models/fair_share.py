"""Batched weighted-DRF share values on the accelerator (KEP-1714).

Computes the share value of every ClusterQueue in one program: usage above
nominal summed over flavors per resource, divided by the cohort's lendable
capacity, max over resources, divided by weight. Integer parts-per-1024,
exactly matching `kueue_tpu.solver.fair_share.dominant_resource_share`.

At the north-star scale (1k CQs) the host loop is per-CQ Python; this model
scores all CQs in one fused XLA program. Since PR 8 it is also the building
block for device-side fair ORDERING of the admission batch:
`FairShareState` derives a dense order-preserving RANK per ClusterQueue
from the shares (one np.unique pass, redone only when a share changes) —
the quantized share component of the scheduler's int64 lexsort nomination
key (`FairShareState.rank`), so `nominate.sort` under FairSharing rides
the same two-pass memoized lexsort as the default mode.

`FairShareState` maintains the shares INCREMENTALLY across ticks, memoized
on the per-cohort usage-VALUE generations the fingerprinted nominate cache
already tracks (solver/schema.UsageEncoder.cohort_gens): an untouched
cohort's shares replay from the previous tick, and a fully-quiescent tick
recomputes nothing. Shares are cohort-local (a CQ's share reads only its
OWN usage row plus a structural capacity denominator), so the full-pass
kernel also runs per-shard over the PR-7 `CohortMesh` with zero
collectives (parallel/mesh.sharded_fair_shares).

Kill switch: KUEUE_TPU_NO_DEVICE_FAIR=1 restores the per-CQ dict DRF
walks everywhere (share_of fallback, host fair-preemption referee).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.fair_share import SHARE_SCALE

_BIG = np.float64(np.inf)


def _weighted_shares_xp(xp, above, cap, weight):
    """The ONE home of the KEP-1714 weighted-share arithmetic —
    parameterized over the array module (np / jnp) so the numpy referee
    twin, the jit kernel and the per-shard mesh pass cannot drift; the
    "bitwise-identical" contract between them rests on this being a
    single function. Returns (weighted [n] f64, ratio_f [n,R] f64 — the
    per-resource scaled ratios the dominant-resource argmax reads)."""
    ratio = xp.where(cap > 0, (above * SHARE_SCALE) // xp.maximum(cap, 1), 0)
    # Zero capacity but positive overage is an infinite share.
    ratio_f = xp.where((cap <= 0) & (above > 0), xp.inf,
                       ratio.astype(xp.float64))
    share = ratio_f.max(axis=1)
    weighted = xp.where(share == 0.0, 0.0,
                        xp.where(weight > 0, share / weight, xp.inf))
    return weighted, ratio_f


@functools.partial(jax.jit, static_argnames=("num_cohorts",))
def _share_kernel(nominal, lendable, usage, cohort_id, weight,
                  num_cohorts: int):
    """[C,F,R] quota/usage -> per-CQ share values (scaled int ratio / weight).

    Returns (share[C] f64, dominant[C] i32). The int64-lexsort RANK of
    the shares lives on `FairShareState.rank` (a dense np.unique pass,
    recomputed only when a share changes), not here.
    """
    # Usage above nominal, summed over flavors: [C,R].
    above = jnp.maximum(usage - nominal, 0).sum(axis=1)
    # Cohort lendable capacity per resource: [K,R] -> per CQ [C,R].
    lend_r = lendable.sum(axis=1)
    cohort_lendable = jax.ops.segment_sum(lend_r, cohort_id,
                                          num_segments=num_cohorts)
    cap = cohort_lendable[cohort_id]
    weighted, ratio_f = _weighted_shares_xp(jnp, above, cap, weight)
    dominant = jnp.argmax(ratio_f, axis=1).astype(jnp.int32)
    return weighted, dominant


def share_values(snapshot: Snapshot,
                 enc: sch.CQEncoding = None) -> Dict[str, Tuple[float, str]]:
    """Share value + dominant resource for every ClusterQueue."""
    if enc is None:
        enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    weight = np.array(
        [snapshot.cluster_queues[n].fair_weight for n in enc.cq_names],
        dtype=np.float64)
    share, dominant = jax.device_get(_share_kernel(
        jnp.asarray(enc.nominal), jnp.asarray(enc.lendable),
        jnp.asarray(usage.usage), jnp.asarray(enc.cohort_id),
        jnp.asarray(weight), num_cohorts=enc.num_cohorts))
    out = {}
    for i, name in enumerate(enc.cq_names):
        cq = snapshot.cluster_queues[name]
        if cq.cohort is None:
            out[name] = (0.0, "")
        else:
            dom = enc.resource_names[int(dominant[i])] if share[i] > 0 else ""
            out[name] = (float(share[i]), dom)
    return out


def fair_structural(enc: sch.CQEncoding, snapshot: Snapshot):
    """(cap [C,R], weight [C], cohorted [C]) — the structural half of the
    KEP-1714 share value, cached for the encoding's lifetime.

    Capacity denominators: flat cohorts sum member lendable quota
    (enc.lendable pooled per cohort); hierarchical trees use the whole
    structure under the root (hierarchy.tree_capacity via Cohort.tree_cap).
    Both depend only on specs/quotas, which rotate the encoding on change.
    """
    cached = getattr(enc, "_fair_cache", None)
    if cached is not None:
        return cached
    C, F, R = enc.nominal.shape
    cap = np.zeros((C, R), dtype=np.int64)
    weight = np.zeros(C, dtype=np.float64)
    cohorted = np.zeros(C, dtype=bool)
    # Flat-cohort capacity: lendable summed over flavors, pooled per
    # cohort.
    lend_r = enc.lendable.sum(axis=1)              # [C,R]
    pool = np.zeros((enc.num_cohorts + 1, R), dtype=np.int64)
    np.add.at(pool, enc.cohort_id, lend_r)
    cap_flat = pool[enc.cohort_id]
    r_index = enc.resource_index
    for i, name in enumerate(enc.cq_names):
        cq = snapshot.cluster_queues.get(name)
        if cq is None or cq.cohort is None:
            continue
        cohorted[i] = True
        weight[i] = cq.fair_weight
        if cq.cohort.is_hierarchical():
            tc = cq.cohort.tree_cap()
            for resources in tc.values():
                for rname, val in resources.items():
                    ri = r_index.get(rname)
                    if ri is not None:
                        cap[i, ri] += val
        else:
            cap[i] = cap_flat[i]
    enc._fair_cache = (cap, weight, cohorted)
    return enc._fair_cache


def weighted_shares_np(above: np.ndarray, cap: np.ndarray,
                       weight: np.ndarray) -> np.ndarray:
    """[n,R] usage-above-nominal + [n,R] capacity + [n] weight -> [n]
    weighted share values, exactly `dominant_resource_share`'s arithmetic
    (integer ratio parts-per-1024, inf on zero-capacity overage or zero
    weight)."""
    if above.size == 0:
        return np.zeros(len(above), dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        return _weighted_shares_xp(np, above, cap, weight)[0]


class FairShareState:
    """Incremental per-CQ weighted-DRF shares + their lexsort quantization.

    One instance per CQ-encoding generation (owned by BatchSolver,
    rebuilt on rotation). `refresh()` recomputes shares ONLY for the
    member rows of cohorts whose usage-VALUE generation moved since the
    last call (solver/schema.UsageEncoder.cohort_gens — bumped on every
    row movement, value-stable under the preemption sim's restore-exactly
    churn), so a quiescent tick's refresh is one [K] array compare.

    `rank` is the dense order-preserving quantization of `share` (equal
    floats share a rank), recomputed in one np.unique pass only when a
    share actually changed; `version` bumps with it — the share term of
    the quiescent-tick signature.
    """

    def __init__(self, enc: sch.CQEncoding, usage_enc, snapshot: Snapshot,
                 cohort_mesh=None):
        self.enc = enc
        self._ue = usage_enc
        self.cap, self.weight, self.cohorted = fair_structural(enc, snapshot)
        C = enc.nominal.shape[0]
        self.share = np.zeros(C, dtype=np.float64)
        self.rank = np.zeros(C, dtype=np.int64)
        self.version = 0
        self._gens: Optional[np.ndarray] = None
        self._dict: Optional[Dict[str, float]] = None
        self._mesh = cohort_mesh
        # Scrape-safe publication: a COPY of the shares, swapped in
        # atomically at the end of refresh(), so the off-thread metrics
        # scrape can never observe a half-written refresh (mixed-tick
        # values); it sees either the previous fully-refreshed state or
        # the new one.
        self._pub: Optional[np.ndarray] = None
        self._pub_dict: Optional[tuple] = None

    def _compute_rows(self, rows: np.ndarray) -> np.ndarray:
        u = self._ue.usage[rows]
        above = np.maximum(u - self.enc.nominal[rows], 0).sum(axis=1)
        return weighted_shares_np(above, self.cap[rows], self.weight[rows])

    def _full_pass(self) -> None:
        """Seed pass over every cohorted row. With a CohortMesh bound the
        kernel runs per-shard over the mesh (shares are cohort-local —
        zero collectives; parallel/mesh.sharded_fair_shares is pinned
        bitwise-identical to the numpy arithmetic); otherwise one
        vectorized numpy pass."""
        rows = np.nonzero(self.cohorted)[0]
        if not rows.size:
            return
        if self._mesh is not None and self._mesh.n_shards > 1:
            from kueue_tpu.parallel.mesh import sharded_fair_shares
            full = sharded_fair_shares(
                self._mesh, self.enc.nominal, self._ue.usage,
                self.cap, self.weight)
            self.share[rows] = full[rows]
        else:
            self.share[rows] = self._compute_rows(rows)

    def refresh(self) -> "FairShareState":
        gens = self._ue.cohort_gens
        if self._gens is None:
            self._full_pass()
            self._rerank()
            self._pub = self.share.copy()
        else:
            moved = gens != self._gens
            if not moved.any():
                return self
            rows = np.nonzero(moved[self.enc.cohort_id] & self.cohorted)[0]
            if rows.size:
                fresh = self._compute_rows(rows)
                if not np.array_equal(fresh, self.share[rows]):
                    self.share[rows] = fresh
                    self._rerank()
                    # Republish ONLY on a value change: gen movement
                    # with equal values (the preemption sim's
                    # restore-exactly churn) must not invalidate the
                    # scrape memo or pay the copy.
                    self._pub = self.share.copy()
        self._gens = gens.copy()
        return self

    def _rerank(self) -> None:
        # Dense rank via one unique pass: equal shares (exact float
        # compare, inf included) collapse to one rank, so the int64 key
        # orders entries exactly as the float share would.
        _, inv = np.unique(self.share, return_inverse=True)
        self.rank = inv.astype(np.int64)
        self.version += 1
        self._dict = None

    def share_of_ci(self, ci: int) -> float:
        return float(self.share[ci])

    def as_dict(self) -> Dict[str, float]:
        d = self._dict
        if d is None:
            d = self._dict = {name: float(self.share[i])
                              for i, name in enumerate(self.enc.cq_names)}
        return d

    def published_dict(self) -> Optional[Dict[str, float]]:
        """The last fully-refreshed shares, for the off-thread metrics
        scrape: reads only the atomically-swapped publication copy, never
        the live `share` array a concurrent refresh() may be mid-write
        on. None before the first refresh."""
        pub = self._pub
        if pub is None:
            return None
        cached = self._pub_dict
        if cached is not None and cached[0] is pub:
            return cached[1]
        d = {name: float(pub[i])
             for i, name in enumerate(self.enc.cq_names)}
        self._pub_dict = (pub, d)
        return d

    def verify(self, snapshot: Snapshot) -> None:
        """Assert the incremental shares equal a from-scratch referee pass
        (KUEUE_TPU_DEBUG_FAIR=1 drives this from the scheduler)."""
        from kueue_tpu.solver.fair_share import dominant_resource_share
        for i, name in enumerate(self.enc.cq_names):
            cq = snapshot.cluster_queues.get(name)
            if cq is None:
                continue
            # Debug-only referee walk (the loop PERF01 exists to banish
            # from the tick path).
            want = dominant_resource_share(cq)[0]  # kueuelint: disable=PERF01
            if self.share[i] != want:
                raise AssertionError(
                    f"FairShareState drift: {name} share {self.share[i]} "
                    f"!= referee {want} (generation memo out of lockstep)")
