"""Batched weighted-DRF share values on the accelerator (KEP-1714).

Computes the share value of every ClusterQueue in one program: usage above
nominal summed over flavors per resource, divided by the cohort's lendable
capacity, max over resources, divided by weight. Integer parts-per-1024,
exactly matching `kueue_tpu.solver.fair_share.dominant_resource_share`.

At the north-star scale (1k CQs) the host loop is per-CQ Python; this model
scores all CQs in one fused XLA program -- it is also the building block
for device-side fair ordering of the admission batch.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.fair_share import SHARE_SCALE

_BIG = np.float64(np.inf)


@functools.partial(jax.jit, static_argnames=("num_cohorts",))
def _share_kernel(nominal, lendable, usage, cohort_id, weight,
                  num_cohorts: int):
    """[C,F,R] quota/usage -> per-CQ share values (scaled int ratio / weight).

    Returns (share[C] f64, dominant[C] i32).
    """
    # Usage above nominal, summed over flavors: [C,R].
    above = jnp.maximum(usage - nominal, 0).sum(axis=1)
    # Cohort lendable capacity per resource: [K,R] -> per CQ [C,R].
    lend_r = lendable.sum(axis=1)
    cohort_lendable = jax.ops.segment_sum(lend_r, cohort_id,
                                          num_segments=num_cohorts)
    cap = cohort_lendable[cohort_id]
    ratio = jnp.where(cap > 0, (above * SHARE_SCALE) // jnp.maximum(cap, 1), 0)
    # Zero capacity but positive overage is an infinite share.
    inf_mask = (cap <= 0) & (above > 0)
    ratio_f = jnp.where(inf_mask, jnp.inf, ratio.astype(jnp.float64))
    share = ratio_f.max(axis=1)
    dominant = jnp.argmax(ratio_f, axis=1).astype(jnp.int32)
    weighted = jnp.where(
        share == 0.0, 0.0,
        jnp.where(weight > 0, share / weight, jnp.inf))
    return weighted, dominant


def share_values(snapshot: Snapshot,
                 enc: sch.CQEncoding = None) -> Dict[str, Tuple[float, str]]:
    """Share value + dominant resource for every ClusterQueue."""
    if enc is None:
        enc = sch.encode_cluster_queues(snapshot)
    usage = sch.encode_usage(snapshot, enc)
    weight = np.array(
        [snapshot.cluster_queues[n].fair_weight for n in enc.cq_names],
        dtype=np.float64)
    share, dominant = jax.device_get(_share_kernel(
        jnp.asarray(enc.nominal), jnp.asarray(enc.lendable),
        jnp.asarray(usage.usage), jnp.asarray(enc.cohort_id),
        jnp.asarray(weight), num_cohorts=enc.num_cohorts))
    out = {}
    for i, name in enumerate(enc.cq_names):
        cq = snapshot.cluster_queues[name]
        if cq.cohort is None:
            out[name] = (0.0, "")
        else:
            dom = enc.resource_names[int(dominant[i])] if share[i] > 0 else ""
            out[name] = (float(share[i]), dom)
    return out
