"""Batched flavor assignment on the accelerator.

One XLA program solves flavor assignment for EVERY pending workload at once,
replacing the reference's sequential per-head loops
(flavorassigner.go:363-600). The workload axis is embarrassingly parallel --
each head is solved against the same immutable snapshot
(scheduler.go:317-351), which is what makes the dense batched formulation
decision-equivalent: cross-workload interactions (one-admission-per-cohort)
stay in the host admission loop exactly as in the reference.

Shapes (see solver/schema.py): the kernel is [W] x scan over P podsets x
dense [G,S,R] flavor/mode math. All control flow is masks and reductions --
no data-dependent branching -- so XLA tiles it onto the MXU/VPU and the
compiled program is reused across ticks of the same padded shape.

Integer semantics are exact (int64; TPU emulates i64 on the VPU).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kueue_tpu.utils import native_decode

from kueue_tpu import features
from kueue_tpu import knobs
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import AssignmentClusterQueueState, WorkloadInfo
from kueue_tpu.solver import schema as sch
from kueue_tpu.solver.modes import FIT, NO_FIT, PREEMPT
from kueue_tpu.solver.referee import (
    Assignment,
    FlavorAssignment,
    PodSetAssignmentResult,
)

MODE_SENTINEL = FIT + 1  # "no resource in group" marker for masked mins

# The hetero score matrix's "cannot run here" sentinel. Imported (not
# re-derived) because exact bitwise equality with the scores the
# ThroughputProfileStore/score kernel emit is load-bearing: the rounding
# masks non-FIT slots with this value and overrides only on a strictly
# greater max.
from kueue_tpu.hetero.solve import NEG_SCORE as HETERO_NEG_SCORE  # noqa: E402


def solve_core(
    # CQ-side [C,F,R] and friends
    nominal, borrow_limit, guaranteed, usage,
    cohort_requestable, cohort_usage, cohort_id,
    group_of_resource, slot_flavor, num_flavors,
    bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
    # workload-side; elig is per (workload, podset, group, slot) because
    # affinity matching is restricted to each group's label keys
    # (flavorassigner.go:498-542)
    wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
    num_slots: int,
    fungibility_enabled: bool = True,
    hier=None,
    hetero=None,
):
    """Returns per-(W,P) assignment tensors; see outputs dict at the end.

    `hier` (optional) carries the dense cohort-forest tensors for
    hierarchical cohorts (KEP-79): per-node T balances are aggregated on
    device (segment-sum of lending-clamped leaf balances, then one clamped
    scatter-add per tree level), and each candidate value runs the
    ancestor-path delta walk of core/hierarchy.py fully vectorized.

    `hetero` (optional) is the heterogeneity-aware solve mode
    (kueue_tpu/hetero): an `(effective_score [W,F] i64, profiled [W]
    bool)` pair. For profiled rows the chosen slot becomes the
    currently-FIT slot with the maximum score (Gavel's deterministic
    rounding; ties to the earliest slot — first-fit order); rows without
    a FIT slot, and unprofiled rows, keep the default decision exactly.
    The default first-fit choice rides along as the `group_ff` output so
    the scheduler can explain "why flavor B". None (the default) leaves
    the jaxpr — and every decision — byte-identical to the pre-hetero
    kernel."""
    W = wl_cq.shape[0]
    P = req.shape[1]
    F = nominal.shape[1]
    R = nominal.shape[2]
    G = slot_flavor.shape[1]
    S = num_slots

    # Gather the per-workload view of its ClusterQueue (one gather, reused
    # by every podset iteration).
    nomW = nominal[wl_cq]              # [W,F,R]
    blimW = borrow_limit[wl_cq]        # [W,F,R]
    guarW = guaranteed[wl_cq]          # [W,F,R]
    usedW = usage[wl_cq]               # [W,F,R]
    kW = cohort_id[wl_cq]              # [W]
    creqW = cohort_requestable[kW]     # [W,F,R]
    cuseW = cohort_usage[kW]           # [W,F,R]
    gorW = group_of_resource[wl_cq]    # [W,R]
    slotW = slot_flavor[wl_cq]         # [W,G,S]
    nfW = num_flavors[wl_cq]           # [W,G]
    bwcW = bwc_enabled[wl_cq]          # [W]
    bPolW = borrow_policy_is_borrow[wl_cq]    # [W]
    pPolW = preempt_policy_is_preempt[wl_cq]  # [W]

    # Cohort-available quota per (flavor, resource), from this CQ's seat:
    # requestable lendable pool + own guaranteed (clusterqueue.go:583-600).
    cohort_avail = creqW + guarW                       # [W,F,R]
    # Used cohort quota: above-guaranteed pool usage + own within-guaranteed.
    cohort_used = cuseW + jnp.minimum(usedW, guarW)    # [W,F,R]

    slot_ok = slotW >= 0                               # [W,G,S]
    sf = jnp.maximum(slotW, 0)                         # safe gather index
    wix = jnp.arange(W)

    def gather_fr(x):
        """[W,F,R] -> [W,G,S,R]: the CQ quantity at each slot's flavor."""
        return x[wix[:, None, None], sf, :]

    nom_s = gather_fr(nomW)
    blim_s = gather_fr(blimW)
    guar_s = gather_fr(guarW)
    used_s = gather_fr(usedW)
    cav_s = gather_fr(cohort_avail)
    cus_s = gather_fr(cohort_used)

    member = has_req[:, :, None, :] & (gorW[:, None, :] ==
                                       jnp.arange(G)[None, :, None])[:, None, :, :]
    # member: [W,P,G,R] -- resource r belongs to group g and is requested.
    group_has_req = member.any(axis=3)                 # [W,P,G]

    # --- hierarchical cohort forest: per-tick T balances (KEP-79) ---------
    if hier is not None:
        (h_own, h_blim, h_lend, h_cq_node, h_cq_lend, h_cq_hier,
         h_cq_path, h_levels) = hier
        K2 = h_own.shape[0]
        D = h_cq_path.shape[1]

        def aggregate_t(t_cq):
            """[C,F,R] leaf balances -> [K2,F,R] per-node T, bottom-up."""
            seg = jnp.where(h_cq_node >= 0, h_cq_node, K2)
            contrib = jnp.minimum(h_cq_lend, t_cq)
            m = jax.ops.segment_sum(contrib, seg, num_segments=K2 + 1)[:K2]
            t_node = h_own + m
            for nodes, parents in h_levels:
                vals = jnp.minimum(h_lend[nodes], t_node[nodes])
                t_node = t_node.at[parents].add(vals)
            return t_node

        T_node = aggregate_t(nominal - usage)
        T0_node = aggregate_t(nominal)       # empty tree: preemption ceiling
        tcq_s = gather_fr((nominal - usage)[wl_cq])       # [W,G,S,R]
        t0cq_s = nom_s
        cq_lend_s = gather_fr(h_cq_lend[wl_cq])
        pathW = h_cq_path[wl_cq]                          # [W,D]
        hier_mask = h_cq_hier[wl_cq][:, None, None, None]

        def hier_ok(t_node, t_old_s, val):
            """The ancestor-path T-invariant walk, per candidate value."""
            delta = (jnp.minimum(cq_lend_s, t_old_s)
                     - jnp.minimum(cq_lend_s, t_old_s - val))
            ok = jnp.ones(val.shape, dtype=bool)
            for d in range(D):
                nodeW = pathW[:, d]
                valid = (nodeW >= 0)[:, None, None, None]
                ns_node = jnp.maximum(nodeW, 0)
                t_n = t_node[ns_node][wix[:, None, None], sf, :]
                blim_n = h_blim[ns_node][wix[:, None, None], sf, :]
                lend_n = h_lend[ns_node][wix[:, None, None], sf, :]
                t_new = t_n - delta
                ok &= jnp.where(valid, t_new >= -blim_n, True)
                delta = jnp.where(
                    valid,
                    jnp.minimum(lend_n, t_n) - jnp.minimum(lend_n, t_new),
                    delta)
            return ok

    arangeS = jnp.arange(S)

    def podset_step(carry_usage, p):
        r_req = jax.lax.dynamic_index_in_dim(req, p, axis=1, keepdims=False)
        r_has = jax.lax.dynamic_index_in_dim(has_req, p, axis=1, keepdims=False)
        p_valid = jax.lax.dynamic_index_in_dim(podset_valid, p, axis=1,
                                               keepdims=False)
        p_unsat = jax.lax.dynamic_index_in_dim(podset_unsat, p, axis=1,
                                               keepdims=False)
        e_p = jax.lax.dynamic_index_in_dim(elig, p, axis=1, keepdims=False)
        res_p = jax.lax.dynamic_index_in_dim(resume_slot, p, axis=1,
                                             keepdims=False)
        memb = jax.lax.dynamic_index_in_dim(member, p, axis=1, keepdims=False)
        ghr = jax.lax.dynamic_index_in_dim(group_has_req, p, axis=1,
                                           keepdims=False)

        # Requested value incl. earlier podsets' usage on the same flavor
        # (flavorassigner.go:420).
        carry_s = carry_usage[wix[:, None, None], sf, :]  # [W,G,S,R]
        val = r_req[:, None, None, :] + carry_s                     # [W,G,S,R]

        # --- fitsResourceQuota, vectorized (flavorassigner.go:550-600) ---
        mode = jnp.where(val <= nom_s, PREEMPT, NO_FIT)
        if hier is not None:
            bwc_cohort_ok = jnp.where(hier_mask,
                                      hier_ok(T0_node, t0cq_s, val),
                                      val <= cav_s)
        else:
            bwc_cohort_ok = val <= cav_s
        bwc_ok = (bwcW[:, None, None, None]
                  & (val <= nom_s + blim_s) & bwc_cohort_ok)
        mode = jnp.where(bwc_ok, PREEMPT, mode)
        borrow = bwc_ok & (val > nom_s)
        over_blim = used_s + val > nom_s + blim_s
        lack = cus_s + val - cav_s
        cohort_fits = lack <= 0
        if hier is not None:
            cohort_fits = jnp.where(hier_mask,
                                    hier_ok(T_node, tcq_s, val),
                                    cohort_fits)
        fit = (~over_blim) & cohort_fits
        mode = jnp.where(fit, FIT, mode)
        borrow = jnp.where(fit, used_s + val > nom_s, borrow)

        # --- per-slot representative mode over the group's resources ---
        mode_masked = jnp.where(memb[:, :, None, :], mode, MODE_SENTINEL)
        rep = mode_masked.min(axis=3)                  # [W,G,S]
        rep = jnp.minimum(rep, FIT)
        needs_borrow = (borrow & memb[:, :, None, :]).any(axis=3)

        sv = (slot_ok & e_p
              & (arangeS[None, None, :] < nfW[..., None])
              & (arangeS[None, None, :] >= res_p[..., None]))

        if fungibility_enabled:
            # --- fungibility stop rule (flavorassigner.go:478-496) ---
            pPol = pPolW[:, None, None]
            bPol = bPolW[:, None, None]
            stop = ((rep == PREEMPT) & pPol & (~needs_borrow | bPol)) \
                | ((rep == FIT) & needs_borrow & bPol) \
                | ((rep == FIT) & ~needs_borrow)
        else:
            # Gate off: stop at the first Fit, borrowing or not
            # (flavorassigner.go:450-458).
            stop = rep == FIT
        stop = stop & sv

        first_stop = jnp.where(stop, arangeS[None, None, :], S).min(axis=2)
        stopped = first_stop < S                        # [W,G]
        rep_valid = jnp.where(sv, rep, -1)
        best_idx = jnp.argmax(rep_valid, axis=2)        # first occurrence of max
        best_mode = rep_valid.max(axis=2)
        chosen = jnp.where(stopped, first_stop,
                           jnp.where(best_mode > NO_FIT, best_idx, -1))

        if hetero is not None:
            # Heterogeneity-aware rounding: profiled rows take the
            # max-score slot among the currently-FIT slots (argmax ==
            # first occurrence of the max, so equal scores fall back to
            # first-fit order); everything else keeps the default
            # choice, so quota/borrowing/preemption semantics are
            # untouched. The mask value is exactly HETERO_NEG_SCORE —
            # the score matrix's "cannot run here" sentinel — so a FIT
            # slot whose profile says 0 throughput ties the mask and the
            # strict `best_score > neg` gate falls back to the default
            # decision (the referee's rule) instead of letting argmax
            # land on slot 0 blind.
            h_score, h_prof = hetero
            chosen_ff = chosen
            score_s = h_score[wix[:, None, None], sf]       # [W,G,S]
            fit_ok = (rep == FIT) & sv
            neg = jnp.int64(HETERO_NEG_SCORE)
            masked_score = jnp.where(fit_ok, score_s, neg)
            best_fit = jnp.argmax(masked_score, axis=2)
            best_score = masked_score.max(axis=2)
            # `ghr` keeps requestless groups on the default choice:
            # their chosen slot is decision-inert (decode only reads
            # requested resources) but a moved slot would read as a
            # spurious "override" in the group_ff diff the explain
            # records are built from.
            use = h_prof[:, None] & (best_score > neg) & ghr
            chosen = jnp.where(use, best_fit, chosen_ff)

        # Resume bookkeeping (flavorassigner.go:412,462-470): the last slot
        # whose eligibility checks passed, or the stop slot. With the
        # FlavorFungibility gate off the referee leaves TriedFlavorIdx at
        # its zero value (the recording loop is skipped).
        if fungibility_enabled:
            last_elig = jnp.where(sv, arangeS[None, None, :], -1).max(axis=2)
            assigned_idx = jnp.where(stopped, first_stop, last_elig)
            tried = jnp.where(assigned_idx == nfW - 1, -1, assigned_idx)
            tried = jnp.where(assigned_idx < 0, -1, tried)
        else:
            tried = jnp.zeros_like(first_stop)

        chosen_safe = jnp.maximum(chosen, 0)
        gix = jnp.arange(G)
        # Per-group mode at the chosen slot.
        g_mode = rep[wix[:, None], gix[None, :], chosen_safe]   # [W,G]
        g_mode = jnp.where(chosen >= 0, g_mode, NO_FIT)

        group_ok = (~ghr) | ((chosen >= 0) & (g_mode > NO_FIT))
        # A requested resource no group of this CQ covers fails the podset
        # ("resource unavailable in ClusterQueue", flavorassigner.go:370-375).
        uncovered = (r_has & (gorW < 0)).any(axis=1)
        ps_ok = p_valid & (~p_unsat) & (~uncovered) & group_ok.all(axis=1)

        # Per-resource outputs at the chosen slot of the resource's group.
        mode_at_chosen = mode[wix[:, None], gix[None, :], chosen_safe, :]
        borrow_at_chosen = borrow[wix[:, None], gix[None, :], chosen_safe, :]
        flavor_at_chosen = slotW[wix[:, None], gix[None, :], chosen_safe]

        gor_safe = jnp.maximum(gorW, 0)                         # [W,R]
        rix = jnp.arange(R)
        chosen_g = chosen[wix[:, None], gor_safe]               # [W,R]
        res_flavor = flavor_at_chosen[wix[:, None], gor_safe]
        res_mode = mode_at_chosen[wix[:, None], gor_safe, rix[None, :]]
        res_borrow = borrow_at_chosen[wix[:, None], gor_safe, rix[None, :]]

        res_assigned = r_has & (gorW >= 0) & (chosen_g >= 0) & ps_ok[:, None]
        res_flavor = jnp.where(res_assigned, res_flavor, -1)
        res_mode = jnp.where(res_assigned, res_mode, NO_FIT)
        res_borrow = res_borrow & res_assigned

        # Podset representative mode (referee PodSetAssignmentResult).
        g_mode_req = jnp.where(ghr, g_mode, MODE_SENTINEL)
        ps_mode = jnp.minimum(g_mode_req.min(axis=1), FIT)
        ps_mode = jnp.where(ps_ok, ps_mode, NO_FIT)
        ps_mode = jnp.where(p_valid, ps_mode, MODE_SENTINEL)

        # Usage contribution: only podsets with a full assignment add usage
        # (flavorassigner.go:324-327 clears flavors on failure).
        one_hot_f = (jnp.maximum(res_flavor, 0)[..., None]
                     == jnp.arange(F)[None, None, :])   # [W,R,F]
        contrib = one_hot_f & res_assigned[..., None]   # ps_ok already folded in
        addFR = jnp.swapaxes(contrib, 1, 2) * r_req[:, None, :]  # [W,F,R]
        carry_usage = carry_usage + addFR

        # Compact dtypes: the whole output pytree is fetched host-side once
        # per tick, and device->host latency dominates on remote links.
        outputs = dict(
            res_flavor=res_flavor.astype(jnp.int16),
            res_mode=res_mode.astype(jnp.int8),
            res_borrow=res_borrow,
            group_chosen=chosen.astype(jnp.int16),
            group_tried=tried.astype(jnp.int16),
            ps_ok=ps_ok,
            ps_mode=ps_mode.astype(jnp.int8),
        )
        if hetero is not None:
            # The first-fit twin choice, for the `nominate.hetero`
            # explain records ("chose flavor B over first-fit A").
            outputs["group_ff"] = chosen_ff.astype(jnp.int16)
        return carry_usage, outputs

    carry0 = jnp.zeros((W, F, R), dtype=req.dtype)
    _, outs = jax.lax.scan(podset_step, carry0, jnp.arange(P))
    # outs arrays are [P,W,...]; transpose to [W,P,...].
    outs = {k: jnp.moveaxis(v, 0, 1) for k, v in outs.items()}

    ps_mode = outs["ps_mode"]
    wl_mode = jnp.minimum(ps_mode, MODE_SENTINEL).min(axis=1)
    wl_mode = jnp.where(wl_mode == MODE_SENTINEL, NO_FIT, wl_mode)
    has_ps = podset_valid.any(axis=1)
    outs["wl_mode"] = jnp.where(has_ps, wl_mode, NO_FIT).astype(jnp.int8)
    return outs


_solve_kernel = functools.partial(
    jax.jit, static_argnames=("num_slots", "fungibility_enabled"))(solve_core)


@functools.partial(jax.jit,
                   static_argnames=("num_slots", "shapes",
                                    "fungibility_enabled"))
def _solve_kernel_packed(
    nominal, borrow_limit, guaranteed, lendable, cohort_id,
    group_of_resource, slot_flavor, num_flavors,
    bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
    hier, buf, hetero=None, *, num_slots: int, shapes,
    fungibility_enabled: bool = True,
):
    """Transfer-minimal entry: statics live on device across ticks; the
    whole dynamic side arrives as ONE byte buffer (i64 usage+requests,
    i32 cq index+resume slots, u8 masks — bitcast apart on device) and
    cohort aggregates are computed on device. Device->host RPCs, not
    FLOPs, bound the tick, so the tick ships exactly one transfer."""
    W, P, R, G, K = shapes
    C, F = nominal.shape[0], nominal.shape[1]
    S = num_slots

    nb64 = (C * F * R + W * P * R) * 8
    nb32 = (W + W * P * G) * 4
    buf_i64 = jax.lax.bitcast_convert_type(
        buf[:nb64].reshape(-1, 8), jnp.int64)
    buf_i32 = jax.lax.bitcast_convert_type(
        buf[nb64:nb64 + nb32].reshape(-1, 4), jnp.int32)
    buf_u8 = buf[nb64 + nb32:]

    usage = buf_i64[:C * F * R].reshape(C, F, R)
    req = buf_i64[C * F * R:].reshape(W, P, R)
    wl_cq = buf_i32[:W]
    resume_slot = buf_i32[W:].reshape(W, P, G)
    off = 0
    has_req = buf_u8[off:off + W * P * R].reshape(W, P, R).astype(bool)
    off += W * P * R
    podset_valid = buf_u8[off:off + W * P].reshape(W, P).astype(bool)
    off += W * P
    podset_unsat = buf_u8[off:off + W * P].reshape(W, P).astype(bool)
    off += W * P
    elig = buf_u8[off:off + W * P * G * S].reshape(W, P, G, S).astype(bool)

    # Cohort aggregation (snapshot.go:160-201), on device.
    above = jnp.maximum(usage - guaranteed, 0)
    cohort_usage = jax.ops.segment_sum(above, cohort_id, num_segments=K)
    cohort_requestable = jax.ops.segment_sum(lendable, cohort_id,
                                             num_segments=K)

    return solve_core(
        nominal, borrow_limit, guaranteed, usage,
        cohort_requestable, cohort_usage, cohort_id,
        group_of_resource, slot_flavor, num_flavors,
        bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
        wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
        num_slots=num_slots, fungibility_enabled=fungibility_enabled,
        hier=hier, hetero=hetero)


def device_static(enc: sch.CQEncoding) -> tuple:
    """Move the generation-stable CQ-side tensors to the device once; they
    are reused across ticks (the snapshot-copy avoidance called out in
    SURVEY §7: incremental re-encoding keyed on allocatable generations).
    The last element is the hierarchical cohort-forest pytree, or None when
    every cohort is flat."""
    base = tuple(jnp.asarray(x) for x in (
        enc.nominal, enc.borrow_limit, enc.guaranteed, enc.lendable,
        enc.cohort_id, enc.group_of_resource, enc.slot_flavor,
        enc.num_flavors, enc.bwc_enabled, enc.borrow_policy_is_borrow,
        enc.preempt_policy_is_preempt))
    h = enc.hier
    if h is None:
        return base + (None,)
    hier = (jnp.asarray(h.node_own_nominal), jnp.asarray(h.node_blim),
            jnp.asarray(h.node_lend), jnp.asarray(h.cq_node),
            jnp.asarray(h.cq_lend), jnp.asarray(h.cq_hier),
            jnp.asarray(h.cq_path),
            tuple((jnp.asarray(n), jnp.asarray(p)) for n, p in h.levels))
    return base + (hier,)


def pack_dynamic(usage_cfr: np.ndarray, wl: sch.WorkloadTensors) -> np.ndarray:
    """Pack the per-tick dynamic tensors into ONE byte buffer (i64 section,
    i32 section, u8 masks): every host->device transfer is a round trip on
    remote-attached TPUs, so the tick ships exactly one. The device side
    bitcasts the sections apart (host and TPU are both little-endian)."""
    return np.concatenate([
        np.ascontiguousarray(usage_cfr).view(np.uint8).ravel(),
        np.ascontiguousarray(wl.req).view(np.uint8).ravel(),
        np.ascontiguousarray(wl.wl_cq).view(np.uint8).ravel(),
        np.ascontiguousarray(wl.resume_slot).view(np.uint8).ravel(),
        wl.has_req.ravel().view(np.uint8),
        wl.podset_valid.ravel().view(np.uint8),
        wl.podset_unsat.ravel().view(np.uint8),
        wl.elig.ravel().view(np.uint8),
    ])


def solve_flavor_fit_async(enc: sch.CQEncoding, usage: sch.UsageTensors,
                           wl: sch.WorkloadTensors,
                           static: Optional[tuple] = None,
                           hetero=None) -> Dict[str, "jax.Array"]:
    """Dispatch the batched solve without synchronizing.

    Everything up to the fetch is fire-and-forget: three packed host->device
    transfers, one dispatch, then `copy_to_host_async` on each output so the
    device->host copies ride the same in-flight window. On remote-attached
    TPUs a synchronized round trip costs ~2 orders of magnitude more than
    the solve itself, so the scheduler dispatches tick i+1 (and decodes tick
    i-1) while tick i is in flight; `fetch_outputs` materializes the
    results. This is the device-side mirror of the reference's async
    admission applies (scheduler.go:512 runs SSA off the loop thread).
    """
    if static is None:
        static = device_static(enc)
    W, P, R = wl.req.shape
    G = wl.resume_slot.shape[2]
    buf = pack_dynamic(usage.usage, wl)
    if hetero is not None:
        hetero = (jnp.asarray(hetero[0]), jnp.asarray(hetero[1]))
    out = _solve_kernel_packed(
        *static, jnp.asarray(buf), hetero,
        num_slots=enc.num_slots,
        shapes=(W, P, R, G, enc.num_cohorts),
        fungibility_enabled=features.enabled(features.FLAVOR_FUNGIBILITY),
    )
    for leaf in jax.tree_util.tree_leaves(out):
        leaf.copy_to_host_async()
    return out


def fetch_outputs(out: Dict[str, "jax.Array"]) -> Dict[str, np.ndarray]:
    """Materialize a dispatched solve's outputs on host (blocks)."""
    return jax.device_get(out)


def solve_flavor_fit(enc: sch.CQEncoding, usage: sch.UsageTensors,
                     wl: sch.WorkloadTensors,
                     static: Optional[tuple] = None) -> Dict[str, np.ndarray]:
    """Run the batched solve; returns numpy output tensors.

    Per tick: three packed host->device transfers, one dispatch, one batched
    device_get of the compact output pytree.
    """
    return fetch_outputs(solve_flavor_fit_async(enc, usage, wl, static=static))


def decode_assignments(workloads: Sequence[WorkloadInfo], snapshot: Snapshot,
                       enc: sch.CQEncoding,
                       out: Dict[str, np.ndarray],
                       counts: Optional[Sequence[Sequence[int]]] = None,
                       ) -> List[Assignment]:
    """Materialize referee-compatible Assignment objects from the kernel
    outputs (truncating at the first failed podset, like
    flavorassigner.go:323-327).

    Dispatches to the native decoder (kueue_tpu/native/decode.cpp) when the
    toolchain built it -- the decode sits on the critical path between two
    device dispatches and is interpreter-bound otherwise -- with the
    vectorized Python loop below as the always-available fallback.
    `counts` (partial-admission probes) scales the decoded totals and
    always takes the Python path.
    """
    if counts is not None:
        return _decode_assignments_py(workloads, snapshot, enc, out,
                                      counts=counts)
    if not os.environ.get("KUEUE_NO_NATIVE_DECODE"):
        mod = native_decode.load()
        if mod is not None:
            n = len(workloads)
            P = out["ps_ok"].shape[1]
            R = out["res_flavor"].shape[2]
            G = out["group_tried"].shape[2]
            c = np.ascontiguousarray
            return mod.decode(
                (Assignment, PodSetAssignmentResult, FlavorAssignment,
                 AssignmentClusterQueueState),
                list(workloads), snapshot.cluster_queues, enc.cq_index,
                enc.flavor_names, enc.resource_names,
                c(enc.group_of_resource),
                c(out["ps_ok"][:n]), c(out["ps_mode"][:n]),
                c(out["res_flavor"][:n]), c(out["res_mode"][:n]),
                c(out["res_borrow"][:n]), c(out["group_tried"][:n]),
                P, R, G)
    return _decode_assignments_py(workloads, snapshot, enc, out)


def _decode_assignments_py(workloads: Sequence[WorkloadInfo],
                           snapshot: Snapshot, enc: sch.CQEncoding,
                           out: Dict[str, np.ndarray],
                           counts: Optional[Sequence[Sequence[int]]] = None,
                           ) -> List[Assignment]:
    """Vectorized-coordinate Python decode (fallback + referee for the
    native decoder's equivalence tests)."""
    n = len(workloads)
    ps_ok_np = out["ps_ok"][:n]                         # [n,P]
    P = ps_ok_np.shape[1]
    # Podsets decoded per workload: everything before the first failure plus
    # the failing podset itself (the referee stops there). Padding rows have
    # ps_ok False, so all-real-ok workloads cut at their podset count.
    not_ok = ~ps_ok_np
    has_fail = not_ok.any(axis=1)
    first_fail = np.where(has_fail, not_ok.argmax(axis=1), P)

    # Assigned-resource coordinates, one nonzero over the whole batch.
    # A podset past the first failure is never decoded even if it fits on
    # its own (the referee's early break), hence the first_fail gate.
    res_flavor_np = out["res_flavor"][:n]               # [n,P,R]
    decode_mask = (ps_ok_np
                   & (np.arange(P)[None, :] <= first_fail[:, None])
                   )[:, :, None] & (res_flavor_np >= 0)
    ws, pp, rr = np.nonzero(decode_mask)
    ci_arr = np.fromiter((enc.cq_index[wi.cluster_queue] for wi in workloads),
                         dtype=np.int64, count=n)
    flav_l = res_flavor_np[ws, pp, rr].tolist()
    mode_l = out["res_mode"][:n][ws, pp, rr].tolist()
    borrow_l = out["res_borrow"][:n][ws, pp, rr].tolist()
    tried_l = out["group_tried"][:n][
        ws, pp, enc.group_of_resource[ci_arr[ws], rr]].tolist()
    ws_l = ws.tolist()
    pp_l = pp.tolist()
    rr_l = rr.tolist()
    ps_mode_l = out["ps_mode"][:n].tolist()
    ps_ok_l = ps_ok_np.tolist()
    first_fail_l = first_fail.tolist()

    flavor_names = enc.flavor_names
    resource_names = enc.resource_names

    # Skeleton pass: Assignment + PodSetAssignmentResult per decoded podset.
    assignments: List[Assignment] = []
    psa_rows: List[List[Optional[PodSetAssignmentResult]]] = []
    for w, wi in enumerate(workloads):
        cq = snapshot.cluster_queues[wi.cluster_queue]
        a = Assignment(
            usage={},
            last_state=AssignmentClusterQueueState(
                cluster_queue_generation=cq.allocatable_generation,
                cohort_generation=(cq.cohort.allocatable_generation
                                   if cq.cohort is not None else 0),
            ),
        )
        track_pods = sch.PODS_RESOURCE in cq.rg_by_resource
        cut = first_fail_l[w]
        row: List[Optional[PodSetAssignmentResult]] = []
        ok_row = ps_ok_l[w]
        pm_row = ps_mode_l[w]
        lti = a.last_state.last_tried_flavor_idx
        totals = wi.total_requests
        if counts is not None and counts[w] is not None:
            totals = [t.scaled_to(c) for t, c in zip(totals, counts[w])]
        for p, ps in enumerate(totals):
            if p > cut:
                break
            requests = dict(ps.requests)
            if track_pods:
                requests[sch.PODS_RESOURCE] = ps.count
            psa = PodSetAssignmentResult(
                name=ps.name, requests=requests, count=ps.count)
            if ok_row[p]:
                if pm_row[p] < FIT:
                    # Non-Fit assignments always carry reasons in the referee
                    # (fitsResourceQuota appends one per shortfall); the
                    # presence of reasons is what makes representative_mode
                    # read the per-flavor modes.
                    psa.reasons = ["insufficient unused quota"]
            else:
                psa.reasons = ["insufficient quota or no eligible flavor"]
            a.pod_sets.append(psa)
            lti.append({})
            row.append(psa)
        psa_rows.append(row)
        assignments.append(a)

    # Fill pass: one flat loop over the assigned entries.
    for a in assignments:
        a.usage_idx = ([], [], [])
    for i in range(len(ws_l)):
        w = ws_l[i]
        a = assignments[w]
        psa = psa_rows[w][pp_l[i]]
        ri = rr_l[i]
        fi = flav_l[i]
        rname = resource_names[ri]
        fname = flavor_names[fi]
        tried = tried_l[i]
        fa = FlavorAssignment(name=fname, mode=mode_l[i], borrow=borrow_l[i],
                              tried_flavor_idx=tried)
        psa.flavors[rname] = fa
        if fa.borrow:
            a.borrowing = True
        val = psa.requests[rname]
        fusage = a.usage.setdefault(fname, {})
        fusage[rname] = fusage.get(rname, 0) + val
        u_f, u_r, u_v = a.usage_idx
        for t in range(len(u_f)):
            if u_f[t] == fi and u_r[t] == ri:
                u_v[t] += val
                break
        else:
            u_f.append(fi)
            u_r.append(ri)
            u_v.append(val)
        a.last_state.last_tried_flavor_idx[pp_l[i]][rname] = tried
    return assignments


def fit_usage_delta(out: Dict[str, np.ndarray], wt: sch.WorkloadTensors,
                    enc: sch.CQEncoding):
    """Vectorized [C,F,R] usage delta of all Fit workloads in a solved batch,
    plus the indices of the ClusterQueues touched.

    This is the batched mirror of the cache mutations that assume_workload
    performs per admission (cache.go:498-524): the tick folds every admitted
    head's usage into the incremental tensor in one scatter-add instead of
    1k dict walks.
    """
    n = wt.num_real
    C, F, R = enc.nominal.shape
    wl_fit = out["wl_mode"][:n] == FIT
    res_flavor = out["res_flavor"][:n]
    mask = (res_flavor >= 0) & wl_fit[:, None, None] & out["ps_ok"][:n][:, :, None]
    ws, pp, rr = np.nonzero(mask)
    delta = np.zeros((C, F, R), dtype=np.int64)
    if len(ws) == 0:
        return delta, np.empty(0, dtype=np.int64)
    cis = wt.wl_cq[:n][ws].astype(np.int64)
    fis = res_flavor[ws, pp, rr].astype(np.int64)
    vals = wt.req[:n][ws, pp, rr]
    flat = (cis * F + fis) * R + rr
    np.add.at(delta.ravel(), flat, vals)
    return delta, np.unique(cis)


class BatchSolver:
    """Scheduler plug-in: batched device solve for all heads of a tick.

    Drop-in for the sequential referee path
    (`Scheduler(batch_solver=BatchSolver())`); preemption-target search
    stays host-side on the snapshot, as in the reference
    (scheduler.go:390-429).

    The CQ-side encoding and its device tensors are cached across ticks and
    invalidated by the same signals that invalidate flavor-search resume
    state: allocatable generations, cohort membership, policies, and the
    flavor set.
    """

    _profiler_started = False

    def __init__(self, mesh=None, use_arena: Optional[bool] = None,
                 use_admit_arena: Optional[bool] = None,
                 use_nominate_cache: Optional[bool] = None,
                 shards: Optional[int] = None,
                 hetero: Optional[bool] = None):
        """`mesh` (a jax.sharding.Mesh, e.g. parallel.mesh.make_mesh())
        shards every solve over the mesh's devices: ClusterQueue usage is
        partitioned on the CQ axis with on-device cohort aggregation
        (psum/all_gather over ICI) and the workload batch is
        data-parallel — the multi-chip scale-out path of
        kueue_tpu.parallel.mesh, selected in production via
        Configuration.tpuSolver.shardDevices. None = single-device.

        `use_arena` toggles the incremental workload tensor arena
        (sch.WorkloadArena; default on, or KUEUE_TPU_NO_ARENA=1 to force
        the from-scratch encode — the differential goldens drive both).

        `use_admit_arena` toggles the admitted-set arena
        (sch.AdmittedArena; default on, or KUEUE_TPU_NO_ADMIT_ARENA=1) —
        the pooled committed-usage rows the preemption victim search and
        the snapshot mirror's flush consume instead of re-deriving usage
        dicts per tick.

        `use_nominate_cache` toggles the fingerprinted nominate cache
        (default on, or KUEUE_TPU_NO_NOMINATE_CACHE=1): a head whose
        usage-dependency fingerprint is unchanged since its last solve
        skips tensorize+solve+decode and replays its cached verdict.

        `shards` activates the cohort-sharded solve (parallel/mesh.
        CohortMesh): every solve runs as per-shard compacted blocks over
        a cohort-hash device mesh (no collectives — cohorts never split),
        and the scheduler's admit cycle goes two-phase for the
        hierarchical trees that DO span shards (optimistic per-shard
        solve, then the lending-clamp reconcile). -1 = all visible
        devices; 0/1/None = single-device. Env: KUEUE_TPU_SHARDS sets a
        default, KUEUE_TPU_NO_SHARD=1 kills the path entirely.

        `hetero` selects the heterogeneity-aware solve mode
        (kueue_tpu/hetero; config `tpuSolver.mode: hetero`, env default
        KUEUE_TPU_HETERO=1): flavor choice maximizes Gavel-style
        effective throughput among fitting flavors, scored by the
        ThroughputProfileStore's [N,F] matrix through the projected dual
        iteration. Kill switch KUEUE_TPU_NO_HETERO=1 (read live, so A/B
        drives can flip it per run); with the mode off — or on with no
        profiled workload — every decision is byte-identical to the
        default first-fit mode."""
        self._key = None
        self._enc: Optional[sch.CQEncoding] = None
        self._static: Optional[tuple] = None
        self._usage_enc: Optional[sch.UsageEncoder] = None
        self._row_cache: Optional[sch.WorkloadRowCache] = None
        self._preempt_ctx = None
        # Device-side fair sharing (KEP-1714): the incremental share
        # state (models/fair_share.FairShareState) and the vectorized
        # fair-preemption context (ops/fair_preempt), both rebuilt with
        # the encoding. KUEUE_TPU_NO_DEVICE_FAIR=1 kills the whole fair
        # fast path (share_of falls back to the dict DRF walk and the
        # victim search to the host referee).
        self._fair_state = None
        self._fair_preempt_ctx = None
        self._mesh = mesh
        # Heterogeneity-aware solve mode (kueue_tpu/hetero): the
        # throughput profile store (rebuilt with the encoding, fed by
        # the same queue dirty events as the workload arena), the
        # memoized [cap,F] score matrix keyed on (store generation,
        # global usage generation), and the per-tick activity flag
        # (False whenever nothing is profiled — the provable no-op).
        if hetero is None:
            hetero = knobs.flag("KUEUE_TPU_HETERO")
        self._hetero_mode = bool(hetero)
        if self._hetero_mode and mesh is not None:
            raise ValueError(
                "the hetero solve mode runs single-device or over the "
                "cohort mesh — the legacy wl-axis device mesh is not a "
                "supported combination")
        self._hetero_store = None
        self._hetero_scores: Optional[np.ndarray] = None
        self._hetero_scores_key = None
        self._hetero_rows: Optional[np.ndarray] = None
        self._hetero_active_tick = False
        # Bumped whenever the score matrix is recomputed from changed
        # inputs — the nominate-fingerprint and quiescent-signature term.
        self.hetero_version = 0
        # Per-window evidence: how many decided heads took a different
        # flavor than first-fit would have (the bench reads the delta).
        self.hetero_overrides_total = 0
        # Cohort-sharded solve (the production scale-out path). Built
        # eagerly so a misconfigured shard count fails at construction,
        # not inside the first tick.
        if not shards and mesh is None:
            # Unset (None/0) falls back to the env default, so operators
            # can turn the mesh on without a config edit — but never
            # behind an explicitly configured legacy `mesh`: the two
            # sharding modes are mutually exclusive (the config layer
            # rejects the pair, and a stray bench env var must not
            # silently flip the engine).
            env = knobs.raw("KUEUE_TPU_SHARDS")
            shards = int(env) if env else 0
        if knobs.flag("KUEUE_TPU_NO_SHARD"):
            shards = 0
        self._cohort_mesh = None
        if shards == -1 or shards > 1:
            if mesh is not None:
                raise ValueError(
                    "cohort shards and a wl-axis mesh are mutually "
                    "exclusive sharding modes — pass one of them")
            from kueue_tpu.parallel.mesh import CohortMesh
            self._cohort_mesh = CohortMesh(
                None if shards == -1 else shards)
        # Per-shard dispatch evidence (the `shard` bench config reads the
        # deltas per window): dispatch count, per-shard head sums, and
        # the running sum of per-dispatch imbalance ratios
        # (max_shard_heads / mean_shard_heads).
        self.shard_dispatches = 0
        self.shard_heads_sum: Optional[np.ndarray] = None
        self.shard_imbalance_sum = 0.0
        self.shard_bucket_last = 0
        # Incremental workload arena (the tensorize.encode fast path).
        if use_arena is None:
            use_arena = not knobs.flag("KUEUE_TPU_NO_ARENA")
        self._use_arena = use_arena
        self._arena: Optional[sch.WorkloadArena] = None
        self._arena_rebuilt = False
        # Admitted-set arena (committed usage rows; fed by cache events).
        if use_admit_arena is None:
            use_admit_arena = not knobs.flag("KUEUE_TPU_NO_ADMIT_ARENA")
        self._use_admit_arena = use_admit_arena
        self._admit_arena: Optional[sch.AdmittedArena] = None
        self._cache = None
        # Fingerprinted nominate cache: uid -> (fingerprint, Assignment).
        if use_nominate_cache is None:
            use_nominate_cache = \
                not knobs.flag("KUEUE_TPU_NO_NOMINATE_CACHE")
        self._use_nominate_cache = use_nominate_cache
        self._nominate_cache: dict = {}
        self.nominate_cache_hits = 0
        self.nominate_cache_misses = 0
        # Actual device dispatches (a fully cache-hit tick dispatches
        # nothing — the bench's quiescent-tick gate reads this).
        self.dispatches = 0
        # Pending-backlog supplier + event plumbing, wired by the
        # scheduler (bind_queues): arena rebuilds re-encode the whole
        # pending backlog off the measured path, and queue add/update/
        # delete events keep rows fresh between ticks.
        self._queues = None
        self.arena_full_rebuilds = 0
        # Compile-proofing (VERDICT r5 Weak #2): every padded solve shape
        # compiles once; a head-count bucket rotation mid-run must not
        # land that compile inside a measured tick. `_warm_keys` tracks
        # shapes already compiled (cold_dispatches counts the misses — the
        # regression test's assertion). When the live head count drifts
        # within 1/8 bucket of a rotation boundary, `_maybe_prewarm`
        # QUEUES the neighbor bucket and `prewarm_idle()` (called from the
        # scheduler's idle window — the serve loop's inter-tick gap, the
        # bench's churn slot) compiles it synchronously OFF the measured
        # path. No background thread: on small hosts a concurrent XLA
        # compile contends with the measured tick and moves the very p99
        # this exists to protect.
        self._warm_keys: set = set()
        self._warm_lock = threading.Lock()
        self._prewarm_pending: set = set()
        # Largest podset count seen this encoding generation: the P axis
        # is floored to it so batch composition (a tick without any
        # multi-podset head) cannot rotate P downward and recompile.
        self._p_floor = 1
        self.cold_dispatches = 0
        # Optional XLA profiler hook (SURVEY §5): point TensorBoard at this
        # port to trace the device solves.
        port = os.environ.get("KUEUE_XLA_PROFILER_PORT")
        if port and not BatchSolver._profiler_started:
            try:
                jax.profiler.start_server(int(port))
                BatchSolver._profiler_started = True
            except Exception:
                pass

    def _encoding_for(self, snapshot: Snapshot) -> sch.CQEncoding:
        key = (
            # Specs/cohorts/flavors identity: bumped by the cache on every
            # structural mutation (Cache.structure_version) — and NOT by
            # workload churn, so admissions/evictions never force the
            # O(CQs x flavors) re-encode.
            snapshot.structure_version,
            # The encoding bakes in gate-dependent quota splits and the
            # fair-sharing preempt-while-borrowing flag.
            features.enabled(features.LENDING_LIMIT),
            features.enabled(features.FAIR_SHARING),
        )
        if key != self._key:
            self._enc = sch.encode_cluster_queues(snapshot)
            self._static = device_static(self._enc)
            self._usage_enc = sch.UsageEncoder(self._enc)
            # Row cache indices/eligibility are relative to the encoding.
            self._row_cache = sch.WorkloadRowCache()
            self._preempt_ctx = None
            self._fair_state = None
            self._fair_preempt_ctx = None
            # P-axis stickiness restarts with the encoding generation.
            self._p_floor = 1
            # The jit cache keys on the static arrays' SHAPES too ([C,F,R]
            # etc.): a structural change can rotate those, so every
            # previously-warm bucket may recompile — reset the warm set so
            # cold_dispatches stays truthful and prewarm re-queues.
            with self._warm_lock:
                self._warm_keys.clear()
                self._prewarm_pending.clear()
            # Fingerprints and cached verdicts are minted in the old
            # index space; any rotation (which every structural mutation
            # — quota edit, cohort membership change, flavor delete —
            # forces through structure_version) drops them wholesale.
            self._nominate_cache.clear()
            self._key = key
            if self._use_arena:
                self._rebuild_arena(snapshot)
            if self._use_admit_arena:
                self._rebuild_admit_arena()
            if self._hetero_mode:
                self._rebuild_hetero_store(snapshot)
            if self._cohort_mesh is not None:
                # One shard assignment per encoding generation; both
                # arenas maintain per-shard views off the same sink
                # events from here on.
                a = self._cohort_mesh.assignment(self._enc)
                if self._arena is not None:
                    self._arena.bind_shards(a.shard_of_cq, a.n_shards)
                if self._admit_arena is not None:
                    self._admit_arena.bind_shards(a.shard_of_cq,
                                                  a.n_shards)
        return self._enc

    def _rebuild_admit_arena(self) -> None:
        """Admitted-arena rebuild on encoding rotation: new pool seeded
        from the cache's current admitted set (off the measured path)."""
        cache = self._cache
        if cache is None:
            self._admit_arena = None
            return
        with cache._lock:
            n = sum(len(cq.workloads)
                    for cq in cache.cluster_queues.values())
            arena = sch.AdmittedArena(
                self._enc, capacity=sch._pad_pow2(max(n, 1), floor=1024))
            arena.seed(cache.cluster_queues)
            old = self._admit_arena
            self._admit_arena = arena
            cache.register_admitted_sink(arena)
            if old is not None:
                cache.unregister_admitted_sink(old)

    def _rebuild_hetero_store(self, snapshot: Snapshot) -> None:
        """Throughput-profile store rebuild on encoding rotation: the F
        axis is the encoding's flavor vocabulary, so rows are re-encoded
        against the new speed-class vector and re-seeded from the whole
        pending backlog (off the measured path, like the arena)."""
        from kueue_tpu.hetero.profile import ThroughputProfileStore

        infos = []
        queues = self._queues
        if queues is not None:
            pending = getattr(queues, "pending_infos", None)
            if pending is not None:
                infos = pending()
        self._hetero_store = ThroughputProfileStore(
            self._enc, snapshot.resource_flavors,
            capacity=sch._pad_pow2(max(len(infos), 1), floor=1024))
        if infos:
            self._hetero_store.seed(infos)
        self._hetero_scores = None
        self._hetero_scores_key = None

    def _rebuild_arena(self, snapshot: Snapshot) -> None:
        """Full arena rebuild (encoding-generation change): new pool, the
        whole pending backlog re-encoded NOW so the following ticks'
        gathers are pure row reuse. Counted in `arena_full_rebuilds` —
        the bench asserts zero of these inside the measured window."""
        infos = []
        queues = self._queues
        if queues is not None:
            pending = getattr(queues, "pending_infos", None)
            if pending is not None:
                infos = pending()
        self._arena = sch.WorkloadArena(
            self._enc, snapshot,
            capacity=sch._pad_pow2(max(len(infos), 1), floor=1024))
        if infos:
            self._arena.seed(infos)
        self.arena_full_rebuilds += 1
        self._arena_rebuilt = True

    # -- queue-manager event plumbing (scheduler wires this) ----------------

    def bind_queues(self, queues) -> None:
        """Subscribe to the queue manager's pending-workload events and
        remember it as the arena's backlog supplier. Idempotent."""
        if self._queues is queues:
            return
        if self._queues is not None:
            unreg = getattr(self._queues, "unregister_workload_sink", None)
            if unreg is not None:
                unreg(self)
        self._queues = queues
        reg = getattr(queues, "register_workload_sink", None)
        if reg is not None:
            reg(self)

    def unbind_queues(self) -> None:
        """Release the queue-manager subscription (scheduler retirement)."""
        if self._queues is not None:
            unreg = getattr(self._queues, "unregister_workload_sink", None)
            if unreg is not None:
                unreg(self)
            self._queues = None

    def bind_cache(self, cache) -> None:
        """Remember the admitted-workload cache as the admitted arena's
        seed source (the arena itself subscribes to the cache's
        assume/add/forget/delete events on each rebuild). Idempotent."""
        self._cache = cache

    def unbind_cache(self) -> None:
        """Release the admitted-arena subscription (scheduler
        retirement)."""
        if self._cache is not None and self._admit_arena is not None:
            self._cache.unregister_admitted_sink(self._admit_arena)
        self._admit_arena = None
        self._cache = None

    @property
    def admit_arena(self) -> Optional[sch.AdmittedArena]:
        return self._admit_arena

    def admitted_view(self):
        """(enc, AdmittedArena, structure_version) for the snapshot
        mirror's flush fast path, or None when unavailable (arena off,
        no encoding yet, or the encoding no longer matches the cache's
        structure — a rotation is pending and the rows are in the old
        index space)."""
        arena = self._admit_arena
        enc = self._enc
        cache = self._cache
        if arena is None or enc is None or cache is None:
            return None
        key = (cache.structure_version,
               features.enabled(features.LENDING_LIMIT),
               features.enabled(features.FAIR_SHARING))
        if key != self._key:
            return None
        if arena.debug_verify:
            with cache._lock:
                arena.verify(cache.cluster_queues)
        return enc, arena, cache.structure_version

    def note_pending_workload(self, wi: WorkloadInfo) -> None:
        """Queue add/update event: (re-)encode the workload's arena row
        (and its throughput-profile row) off the measured tick path."""
        arena = self._arena
        if arena is not None:
            arena.note(wi)
        store = self._hetero_store
        if store is not None:
            store.note(wi)

    def forget_pending_workload(self, uid: str) -> None:
        """Queue delete event: free the workload's arena row (and its
        cached nominate verdict — deleted workloads never replay)."""
        arena = self._arena
        if arena is not None:
            arena.forget(uid)
        store = self._hetero_store
        if store is not None:
            store.forget(uid)
        self._nominate_cache.pop(uid, None)

    def forget_verdict(self, uid: str) -> None:
        """Drop a head's cached verdicts: called by the flush for every
        workload that actually assumed quota (it left the queue; keeping
        its ring would pin dead Assignment objects until deletion)."""
        self._nominate_cache.pop(uid, None)

    @property
    def arena_rows_reused(self) -> int:
        arena = self._arena
        return arena.rows_reused if arena is not None else 0

    @property
    def arena_rows_missed(self) -> int:
        """Gather misses: rows (re-)encoded INSIDE a tick — the reuse
        ratio's denominator counterpart (event/seed encodes run off the
        measured path and are not misses)."""
        arena = self._arena
        return arena.rows_missed if arena is not None else 0

    @property
    def arena_rows_encoded(self) -> int:
        arena = self._arena
        return arena.rows_encoded if arena is not None else 0

    def arena_occupancy(self) -> Optional[float]:
        """Live rows / pool capacity of the workload arena (None when
        the arena is off). The soak harness watches this for monotonic
        drift: a leak in the free-list (rows never returned on
        delete/admit) shows up as occupancy creeping toward 1.0 while
        the backlog stays flat."""
        arena = self._arena
        if arena is None or not arena.cap:
            return None
        return (arena.cap - len(arena._free)) / arena.cap

    def fuzz_counters(self) -> dict:
        """One snapshot of the cumulative solver counters the fuzz
        lattice driver and the soak harness difference across windows
        (the lattice drive hook: everything here is already maintained
        on the hot path, this just reads it)."""
        return {
            "dispatches": self.dispatches,
            "cold_dispatches": self.cold_dispatches,
            "nominate_cache_hits": self.nominate_cache_hits,
            "nominate_cache_misses": self.nominate_cache_misses,
            "arena_rows_reused": self.arena_rows_reused,
            "arena_rows_missed": self.arena_rows_missed,
            "arena_rows_encoded": self.arena_rows_encoded,
            "arena_full_rebuilds": self.arena_full_rebuilds,
            "arena_occupancy": self.arena_occupancy(),
        }

    def encoding_matches(self, snapshot: Snapshot) -> bool:
        """True when the solver's current encoding was built from exactly
        this snapshot's structure (and feature bits). Index-space state
        minted against an encoding (Assignment.usage_idx, BatchContext
        tensors) is only valid while this holds — in pipelined mode a
        structural change (CQ/flavor/cohort mutation) can rotate the
        encoding between a tick's dispatch and its finish, permuting
        flavor/resource indices. Consumers must fall back to the
        name-based walks when this returns False."""
        return self._enc is not None and self._key == (
            snapshot.structure_version,
            features.enabled(features.LENDING_LIMIT),
            features.enabled(features.FAIR_SHARING),
        )

    def encoding_names(self):
        """(cq_names, flavor_names, resource_names, cq_index) of the
        current encoding, or None — the name vocabulary the scheduler
        hands the cache's CSR commit so integer coordinates map back to
        dict keys."""
        enc = self._enc
        if enc is None:
            return None
        return enc.cq_names, enc.flavor_names, enc.resource_names, \
            enc.cq_index

    @staticmethod
    def device_fair_enabled() -> bool:
        """The device-side fair-sharing kill switch (read live so the
        differential goldens can flip it per run)."""
        return not knobs.flag("KUEUE_TPU_NO_DEVICE_FAIR")

    def fair_share_state(self, snapshot: Snapshot):
        """The refreshed incremental share state
        (models/fair_share.FairShareState) — per-CQ weighted-DRF share
        values plus their int64-lexsort rank quantization, memoized on
        the per-cohort usage-VALUE generations so an untouched cohort's
        shares replay across ticks. None when no current encoding
        matches the snapshot or KUEUE_TPU_NO_DEVICE_FAIR=1 (the
        scheduler falls back to per-CQ dict DRF walks)."""
        enc = self._enc
        ue = self._usage_enc
        if enc is None or ue is None or not self.device_fair_enabled() \
                or not self.encoding_matches(snapshot):
            return None
        st = self._fair_state
        if st is None:
            from kueue_tpu.models.fair_share import FairShareState
            st = self._fair_state = FairShareState(
                enc, ue, snapshot, self._cohort_mesh)
        return st.refresh()

    def fair_shares(self, snapshot: Snapshot) -> Optional[dict]:
        """{cq name: share value} for every ClusterQueue, served from the
        incremental share state (KEP-1714 weighted DRF;
        dominant_resource_share is the dict referee). None when no
        current encoding matches the snapshot or the device-fair kill
        switch is set."""
        st = self.fair_share_state(snapshot)
        return st.as_dict() if st is not None else None

    def fair_shares_last(self) -> Optional[dict]:
        """The last tick's END-OF-TICK bulk share output (the scheduler
        republishes after the cycle's commits — `fair.publish`), for the
        metrics scrape — no refresh here (scrapes run off-thread), and
        None whenever the encoding no longer matches the cache structure
        (a rotation is pending; the scraper falls back to the referee
        walk so deleted ClusterQueues cannot serve stale series)."""
        st = self._fair_state
        cache = self._cache
        if st is None or cache is None or not self.device_fair_enabled():
            return None
        key = (cache.structure_version,
               features.enabled(features.LENDING_LIMIT),
               features.enabled(features.FAIR_SHARING))
        if key != self._key:
            return None
        # The publication copy, not the live arrays: scrapes run off the
        # tick thread and must never see a half-written refresh.
        return st.published_dict()

    def fair_preempt_context(self, snapshot: Optional[Snapshot] = None):
        """The vectorized fair-preemption context (ops/fair_preempt.
        FairPreemptContext) with live usage/arena refs, or None
        (no/stale encoding, or the kill switch) — the caller falls back
        to the host fair referee."""
        enc = self._enc
        ue = self._usage_enc
        if enc is None or ue is None or not self.device_fair_enabled():
            return None
        if snapshot is not None and not self.encoding_matches(snapshot):
            return None
        ctx = self._fair_preempt_ctx
        if ctx is None:
            if snapshot is None:
                return None
            from kueue_tpu.models.fair_share import fair_structural
            from kueue_tpu.ops.fair_preempt import FairPreemptContext
            ctx = self._fair_preempt_ctx = FairPreemptContext(
                enc, fair_structural(enc, snapshot))
        ctx.usage = ue.usage
        ctx.arena = self._admit_arena
        return ctx

    # -- heterogeneity-aware solve mode (kueue_tpu/hetero) ------------------

    def hetero_enabled(self) -> bool:
        """Mode requested AND the kill switch clear (read live so A/B
        identity drives can flip KUEUE_TPU_NO_HETERO per run)."""
        return self._hetero_mode \
            and not knobs.flag("KUEUE_TPU_NO_HETERO")

    def _hetero_prepare(self, workloads: Sequence[WorkloadInfo]) -> None:
        """Per-tick hetero refresh, BEFORE fingerprinting: ensure every
        head has a profile row, then recompute the score matrix iff its
        inputs moved — (store generation, global usage generation) pins
        both the [N,F] throughput matrix and the capacity vector, so a
        hetero steady state recomputes nothing and replays every cached
        verdict. Leaves `_hetero_active_tick` False whenever nothing is
        profiled: the dispatch then passes `hetero=None` and the solve
        is byte-identical to the default mode."""
        if not self.hetero_enabled():
            self._hetero_active_tick = False
            self._hetero_rows = None
            return
        store = self._hetero_store
        if store is None:
            self._hetero_active_tick = False
            self._hetero_rows = None
            return
        rows = store.rows_for(workloads)
        if not store.any_profiled():
            self._hetero_active_tick = False
            self._hetero_rows = None
            return
        key = (store.generation, self._usage_enc.global_gen)
        if key != self._hetero_scores_key:
            from kueue_tpu.hetero import solve as hetero_solve
            capacity = hetero_solve.flavor_capacity(
                self._enc, self._usage_enc.usage)
            self._hetero_scores = hetero_solve.hetero_scores(
                store.tput, store.demand, store.active_mask(), capacity)
            self._hetero_scores_key = key
            self.hetero_version += 1
        self._hetero_active_tick = True
        self._hetero_rows = rows

    def _hetero_batch(self, miss_idx, wt: sch.WorkloadTensors):
        """(score [W,F] i64, profiled [W] bool) for the miss batch, or
        None when no row of the batch is profiled (identity fast path:
        the kernel then runs without the hetero argument at all)."""
        rows = self._hetero_rows
        scores = self._hetero_scores
        if rows is None or scores is None:
            return None, None
        if miss_idx is not None:
            rows = rows[np.asarray(miss_idx, dtype=np.int64)] \
                if len(miss_idx) else rows[:0]
        store = self._hetero_store
        W = wt.wl_cq.shape[0]
        F = scores.shape[1]
        h_score = np.zeros((W, F), dtype=np.int64)
        h_prof = np.zeros(W, dtype=bool)
        n = len(rows)
        h_score[:n] = scores[rows]
        h_prof[:n] = store.profiled[rows] & store.valid[rows]
        if not h_prof.any():
            return None, None
        return (h_score, h_prof), rows

    def _hetero_overrides(self, inflight: dict,
                          out: Dict[str, np.ndarray]) -> dict:
        """{miss-batch row: (flavor, first_fit_flavor, throughput,
        score, score_rank, podset_idx)} for every head whose hetero
        choice differs from the first-fit twin — the `nominate.hetero`
        explain payload."""
        het = inflight.get("hetero")
        ff = out.get("group_ff")
        if het is None or ff is None:
            return {}
        h_score, h_prof = het
        wt = inflight["wt"]
        enc = inflight["enc"]
        ch = np.asarray(out["group_chosen"])
        ff = np.asarray(ff)
        n = wt.num_real
        # ps_ok keeps podsets past the first failure out of the explain
        # payload — decode never materializes them, so a moved slot
        # there is not a decision.
        diff = (ch[:n] != ff[:n]) & (ch[:n] >= 0) \
            & h_prof[:n, None, None] \
            & np.asarray(out["ps_ok"])[:n][:, :, None]
        ws, pp, gg = np.nonzero(diff)
        rows = inflight.get("hetero_rows")
        store = self._hetero_store
        res: dict = {}
        for w, p, g in zip(ws.tolist(), pp.tolist(), gg.tolist()):
            if w in res:
                continue   # first differing (podset, group) per head
            ci = int(wt.wl_cq[w])
            s1 = int(ch[w, p, g])
            s0 = int(ff[w, p, g])
            fi1 = int(enc.slot_flavor[ci, g, s1]) if s1 >= 0 else -1
            fi0 = int(enc.slot_flavor[ci, g, s0]) if s0 >= 0 else -1
            if fi1 < 0:
                continue
            row = int(rows[w]) if rows is not None and w < len(rows) \
                else -1
            tput = store.throughput_of(row, fi1) if row >= 0 else 1.0
            sc = int(h_score[w, fi1])
            rank = int((h_score[w] > sc).sum()) + 1
            res[w] = (enc.flavor_names[fi1],
                      enc.flavor_names[fi0] if fi0 >= 0 else "",
                      tput, sc, rank, p)
        self.hetero_overrides_total += len(res)
        return res

    def _debug_verify_hetero(self, inflight: dict, miss_wls,
                             fresh) -> None:
        """KUEUE_TPU_DEBUG_HETERO=1: re-derive every fresh verdict with
        the sequential hetero referee and assert the flavor choices
        match — the oracle comparison run inside the live tick."""
        from kueue_tpu.hetero.referee import hetero_assign_flavors

        het = inflight.get("hetero")
        if het is None:
            return
        h_score, h_prof = het
        snapshot = inflight["snapshot"]
        enc = inflight["enc"]
        for j, wi in enumerate(miss_wls):
            cq = snapshot.cluster_queues.get(wi.cluster_queue)
            if cq is None:
                continue
            saved = wi.last_assignment
            try:
                ref = hetero_assign_flavors(
                    wi, cq, snapshot.resource_flavors, h_score[j],
                    enc.flavor_index, bool(h_prof[j]))
            finally:
                wi.last_assignment = saved
            got = fresh[j]
            ref_trail = [
                sorted((r, fa.name, fa.mode, fa.borrow)
                       for r, fa in ps.flavors.items())
                for ps in ref.pod_sets]
            got_trail = [
                sorted((r, fa.name, fa.mode, fa.borrow)
                       for r, fa in ps.flavors.items())
                for ps in got.pod_sets]
            if ref_trail != got_trail:
                raise AssertionError(
                    f"hetero device/referee divergence for "
                    f"{wi.obj.name}: device {got_trail} vs referee "
                    f"{ref_trail}")

    def hetero_signature_term(self) -> int:
        """The quiescent-tick signature's hetero term: the score-matrix
        version while the mode is actively overriding, 0 otherwise
        (inactive hetero decides exactly like the default mode, so the
        0 key may alias it safely)."""
        return self.hetero_version if self._hetero_active_tick else 0

    def flavor_utilization(self) -> dict:
        """{flavor: {used, nominal, ratio}} in the PRIMARY resource,
        summed over ClusterQueues — the bench's per-flavor utilization
        histogram (heterogeneous clusters show whether fast flavors
        actually fill)."""
        enc = self._enc
        ue = self._usage_enc
        if enc is None or ue is None:
            return {}
        used = ue.usage[:, :, 0].sum(axis=0)
        nom = enc.nominal[:, :, 0].sum(axis=0)
        return {
            name: {"used": int(used[fi]), "nominal": int(nom[fi]),
                   "ratio": (round(float(used[fi]) / float(nom[fi]), 4)
                             if nom[fi] else None)}
            for fi, name in enumerate(enc.flavor_names)}

    def hier_cycle_state(self, snapshot: Snapshot):
        """Admission-cycle bookkeeping for hierarchical cohorts
        (ops/hier_cycle.HierCycleState) built on this solver's dense
        tensors, or None when unavailable (no hierarchy, no encoding, or
        a stale encoding — the scheduler falls back to the per-entry
        fits_in_hierarchy dict walk)."""
        enc = self._enc
        if enc is None or enc.hier is None or self._usage_enc is None:
            return None
        if not self.encoding_matches(snapshot):
            return None
        from kueue_tpu.ops.hier_cycle import HierCycleState
        return HierCycleState(enc, self._usage_enc.usage)

    def preemption_context(self, snapshot: Optional[Snapshot] = None):
        """(BatchContext, usage tensor) for the batched device victim
        search (ops/preemption_batch), or None when unavailable (no
        encoding yet, a stale encoding relative to the caller's snapshot,
        or hierarchical cohorts — the tree walk lives only in the host
        referee)."""
        enc = self._enc
        if enc is None or self._usage_enc is None or enc.hier is not None:
            return None
        if snapshot is not None and not self.encoding_matches(snapshot):
            return None
        if self._preempt_ctx is None:
            from kueue_tpu.ops.preemption_batch import BatchContext
            self._preempt_ctx = BatchContext(
                enc, features.enabled(features.LENDING_LIMIT))
        # The admitted arena lets run_batch gather candidate usage rows
        # with one fancy-index read instead of a triples walk per
        # candidate; refreshed here because the arena rotates with the
        # encoding while the context may be cached across calls.
        self._preempt_ctx.admitted_arena = self._admit_arena
        # Cohort-mesh victim search: the packed-XLA batch scan shards
        # over the same cohort-hash mesh (a search's whole member/
        # candidate set lives in its target's cohort, hence one shard).
        self._preempt_ctx.cohort_mesh = self._cohort_mesh
        self._preempt_ctx.shard_assignment = (
            self._cohort_mesh.assignment(enc)
            if self._cohort_mesh is not None else None)
        return self._preempt_ctx, self._usage_enc.usage

    def shard_view(self, snapshot: Snapshot):
        """(ShardAssignment, cq_index) for the admit cycle's two-phase
        reconcile, or None when the cohort mesh is off, the encoding does
        not match this snapshot, or topology is active (the topology
        cycle ledger charges in strict entry order, so those snapshots
        keep the single-phase cycle)."""
        cm = self._cohort_mesh
        enc = self._enc
        if cm is None or enc is None or snapshot.topology is not None:
            return None
        if not self.encoding_matches(snapshot):
            return None
        return cm.assignment(enc), enc.cq_index

    def shard_stats(self) -> dict:
        """Cumulative per-shard dispatch evidence for the bench (window
        deltas are the caller's job)."""
        heads = self.shard_heads_sum
        return {
            "shard_dispatches": self.shard_dispatches,
            "shard_heads_sum": ([] if heads is None
                                else heads.tolist()),
            "shard_imbalance_sum": self.shard_imbalance_sum,
            "shard_bucket_last": self.shard_bucket_last,
        }

    # Nominate-cache backstop (cleared wholesale, the row-cache
    # discipline); entries are also pruned by queue delete events.
    NOMINATE_CACHE_MAX = 200_000

    def _fingerprints(self, workloads: Sequence[WorkloadInfo],
                      snapshot: Snapshot) -> list:
        """Per-head usage-dependency fingerprint: the head's row identity
        (rev), the usage-VALUE generation of every ClusterQueue its fit
        can read (its cohort's members — one counter per cohort,
        maintained by the UsageEncoder in lockstep with every row
        movement; the whole forest for hierarchical trees), the
        effective resume state (with the same allocatable-generation
        staleness drop the encode applies, flavorassigner.go:244-247 —
        a dropped-stale resume fingerprints as None, so an allocatable
        bump flips the fingerprint exactly when it flips the solve
        input), and the fungibility gate. Equal fingerprint == equal
        solve inputs == replayable verdict (each head of the batch is
        solved independently against the same frozen snapshot)."""
        enc = self._enc
        ue = self._usage_enc
        gens = ue.cohort_gens
        cid = enc.cohort_id
        hier = enc.hier
        hmask = hier.cq_hier if hier is not None else None
        gg = ue.global_gen
        fung = features.enabled(features.FLAVOR_FUNGIBILITY)
        cq_index = enc.cq_index
        cqs = snapshot.cluster_queues
        # Active hetero widens every head's usage dependency to the
        # global generation (the score matrix's dual prices read the
        # WHOLE usage tensor — exactly the hierarchical-tree precedent)
        # and adds the score-matrix version, so a verdict replays only
        # while both the throughput inputs and every price input are
        # provably unchanged.
        hetero_v = self.hetero_version if self._hetero_active_tick \
            else None
        out = []
        for wi in workloads:
            ci = cq_index.get(wi.cluster_queue)
            cq = cqs.get(wi.cluster_queue)
            if ci is None or cq is None:
                out.append(None)
                continue
            gen = gg if (hetero_v is not None
                         or (hmask is not None and hmask[ci])) \
                else int(gens[cid[ci]])
            last = wi.last_assignment
            resume = None
            if last is not None:
                cohort = cq.cohort
                if not (cq.allocatable_generation
                        > last.cluster_queue_generation
                        or (cohort is not None
                            and cohort.allocatable_generation
                            > last.cohort_generation)):
                    resume = last.sig()
            if hetero_v is not None:
                out.append((wi.rev, gen, resume, fung, hetero_v))
            else:
                out.append((wi.rev, gen, resume, fung))
        return out

    def solve_async(self, workloads: Sequence[WorkloadInfo],
                    snapshot: Snapshot) -> dict:
        """Dispatch the tick's batched solve; returns an in-flight handle.

        The device program runs while the caller does host-side work
        (admission cycle of the previous tick, preemption search);
        `collect` fetches and decodes. This is the production pipelining
        path — dispatch tick i+1 while tick i is completed host-side.

        Heads whose usage-dependency fingerprint is unchanged since
        their last solve skip the gather/solve/decode entirely and
        replay their cached verdict at collect time; a tick whose heads
        ALL hit dispatches nothing (the quiescent tick)."""
        from kueue_tpu.tracing import TRACER, trace_now

        with TRACER.phase("tensorize") as sp:
            with TRACER.phase("tensorize.refresh"):
                enc = self._encoding_for(snapshot)
                usage = self._usage_enc.refresh(snapshot)
            workloads = list(workloads)
            # Hetero score refresh BEFORE fingerprinting: the verdict
            # cache must key on the final score-matrix version.
            if self._hetero_mode:
                self._hetero_prepare(workloads)
            cached = None
            miss_idx = None
            fps = None
            miss_workloads = workloads
            # The topology stage re-derives placement candidates per tick
            # against live leaf occupancy (and mutates the assignments),
            # so verdict replay is gated to topology-free snapshots.
            if self._use_nominate_cache and snapshot.topology is None:
                nc = self._nominate_cache
                all_fps = self._fingerprints(workloads, snapshot)
                cached = []
                miss_idx = []
                fps = []
                miss_workloads = []
                cqs_by_name = snapshot.cluster_queues
                for i, (wi, fp) in enumerate(zip(workloads, all_fps)):
                    # Each head keeps its last few verdicts (a tiny
                    # fp-keyed ring): the resume-from-last-flavor
                    # protocol makes a NoFit head's solve input CYCLE
                    # (try flavors -> exhausted -> start over), so the
                    # steady state is a short fp cycle, not a fixed
                    # point — one slot would miss forever.
                    ring = None if fp is None else nc.get(wi.obj.uid)
                    a = None
                    if ring is not None:
                        for rfp, ra in ring:
                            if rfp == fp:
                                a = ra
                                break
                    if a is not None:
                        ls = a.last_state
                        if ls is not None:
                            # A fresh decode stamps the resume state with
                            # the CURRENT allocatable generations; the
                            # replay must too, or the next tick's
                            # staleness drop would diverge from the
                            # no-cache trail.
                            cq = cqs_by_name[wi.cluster_queue]
                            ls.cluster_queue_generation = \
                                cq.allocatable_generation
                            ls.cohort_generation = \
                                cq.cohort.allocatable_generation \
                                if cq.cohort is not None else 0
                        cached.append((i, a))
                    else:
                        miss_idx.append(i)
                        fps.append(fp)
                        miss_workloads.append(wi)
                self.nominate_cache_hits += len(cached)
                self.nominate_cache_misses += len(miss_workloads)
            wt = None
            handle = None
            out = None
            cold = False
            het = None
            hrows = None
            if miss_workloads:
                with TRACER.phase("tensorize.encode") as esp:
                    if self._arena is not None:
                        wt, stats = self._arena.gather(
                            miss_workloads, snapshot,
                            min_podsets=self._p_floor)
                        esp.set("rows_dirty", stats["rows_dirty"])
                        esp.set("rows_total", stats["rows_total"])
                        esp.set("full_rebuild", self._arena_rebuilt)
                        self._arena_rebuilt = False
                    else:
                        wt = sch.encode_workloads(
                            miss_workloads, snapshot, enc,
                            row_cache=self._row_cache,
                            min_podsets=self._p_floor)
                        esp.set("rows_dirty", wt.num_real)
                        esp.set("rows_total", wt.num_real)
                        esp.set("full_rebuild", True)
                    self._p_floor = max(self._p_floor, wt.req.shape[1])
                if self._hetero_active_tick:
                    het, hrows = self._hetero_batch(miss_idx, wt)
                with TRACER.phase("tensorize.dispatch"):
                    self.dispatches += 1
                    if self._cohort_mesh is not None:
                        # Cohort-sharded: per-shard compacted blocks over
                        # the cohort-hash mesh (no collectives; outputs
                        # return in original row order, so everything
                        # downstream is byte-identical).
                        from kueue_tpu.parallel.mesh import \
                            cohort_sharded_solve
                        out, sstats = cohort_sharded_solve(
                            enc, usage, wt, self._cohort_mesh,
                            hetero=het)
                        counts = sstats["shard_heads"]
                        Ws = sstats["shard_bucket"]
                        self.shard_dispatches += 1
                        if self.shard_heads_sum is None or \
                                len(self.shard_heads_sum) != len(counts):
                            self.shard_heads_sum = np.zeros(
                                len(counts), dtype=np.int64)
                        self.shard_heads_sum += counts
                        total = int(counts.sum())
                        if total:
                            self.shard_imbalance_sum += float(
                                counts.max() * len(counts)) / total
                        self.shard_bucket_last = Ws
                        key = ("cs", sstats["n_shards"], Ws,
                               wt.req.shape[1],
                               features.enabled(
                                   features.FLAVOR_FUNGIBILITY),
                               het is not None)
                        with self._warm_lock:
                            if key not in self._warm_keys:
                                cold = True
                                self.cold_dispatches += 1
                                self._warm_keys.add(key)
                        self._maybe_prewarm_sharded(
                            key, int(counts.max()))
                    elif self._mesh is not None:
                        # Multi-chip: the sharded program runs to
                        # completion here (its collectives ride ICI, not
                        # the host link, so there is no tunnel round trip
                        # to hide; the workload batch is data-parallel
                        # over the mesh).
                        from kueue_tpu.parallel.mesh import \
                            sharded_flavor_fit
                        out = sharded_flavor_fit(enc, usage, wt,
                                                 self._mesh)
                    else:
                        handle = solve_flavor_fit_async(
                            enc, usage, wt, static=self._static,
                            hetero=het)
                        W, P, R = wt.req.shape
                        C, F = enc.nominal.shape[0], enc.nominal.shape[1]
                        key = (W, P, R, wt.resume_slot.shape[2],
                               enc.num_cohorts, enc.num_slots,
                               features.enabled(
                                   features.FLAVOR_FUNGIBILITY),
                               C, F, het is not None)
                        with self._warm_lock:
                            if key not in self._warm_keys:
                                cold = True
                                self.cold_dispatches += 1
                                self._warm_keys.add(key)
                        self._maybe_prewarm(key, wt.num_real)
            # Span attributes name the one-compile-per-bucket evidence:
            # an operator reading a slow tick sees WHICH padded shape
            # dispatched and whether it compiled in-tick — plus the
            # nominate-cache split (hit heads never reached the device).
            sp.set("engine", "cohort-shard"
                   if self._cohort_mesh is not None
                   else "sharded-mesh" if self._mesh is not None
                   else "batch-packed-xla")
            if self._cohort_mesh is not None and wt is not None:
                sp.set("shard_bucket", self.shard_bucket_last)
            sp.set("bucket", list(wt.req.shape) if wt is not None else [])
            sp.set("heads", len(miss_workloads))
            sp.set("heads_cached",
                   len(cached) if cached is not None else 0)
            sp.set("cold", cold)
            sp.set("cold_dispatches", self.cold_dispatches)
        return {"workloads": workloads, "snapshot": snapshot,
                "enc": enc, "wt": wt, "handle": handle, "out": out,
                "cached": cached, "miss_idx": miss_idx, "fps": fps,
                "hetero": het, "hetero_rows": hrows,
                "dispatched": trace_now()}

    # -- bucket prewarm (compile-proof ticks) -------------------------------

    # Auto-prewarm only buckets up to this width (KUEUE_PREWARM_MAX_BUCKET
    # overrides). Rotation compile cliffs hurt most at small/medium shapes
    # (the smoke-shape p99 was 300x p50 on a rotation); very wide buckets
    # are half-a-bucket wide and rarely rotate, while their background
    # compile is expensive enough to contend with measured ticks on small
    # hosts. Explicit Scheduler.prewarm covers known large shapes.
    PREWARM_MAX_BUCKET = int(
        os.environ.get("KUEUE_PREWARM_MAX_BUCKET", "512"))

    def _maybe_prewarm(self, key: tuple, n_real: int) -> None:
        """Queue neighbor head-count buckets for idle compilation when a
        rotation is imminent: n within 1/8 bucket of the grow boundary (W)
        or of the shrink boundary (W/2)."""
        W = key[0]
        targets = []
        if n_real >= W - max(1, W // 8) and W * 2 <= self.PREWARM_MAX_BUCKET:
            targets.append(W * 2)
        if W > 8 and n_real <= W // 2 + max(1, W // 8):
            targets.append(W // 2)
        for Wn in targets:
            nkey = (Wn,) + key[1:]
            with self._warm_lock:
                if nkey not in self._warm_keys:
                    self._prewarm_pending.add(nkey)

    def _maybe_prewarm_sharded(self, key: tuple, max_shard_n: int) -> None:
        """The cohort-sharded twin of `_maybe_prewarm`: queue neighbor
        PER-SHARD buckets when the largest shard's head count drifts
        within 1/8 bucket of a rotation boundary."""
        Ws = key[2]
        targets = []
        if max_shard_n >= Ws - max(1, Ws // 8) \
                and Ws * 2 <= self.PREWARM_MAX_BUCKET:
            targets.append(Ws * 2)
        if Ws > 8 and max_shard_n <= Ws // 2 + max(1, Ws // 8):
            targets.append(Ws // 2)
        for Wn in targets:
            nkey = key[:2] + (Wn,) + key[3:]
            with self._warm_lock:
                if nkey not in self._warm_keys:
                    self._prewarm_pending.add(nkey)

    def prewarm_idle(self) -> int:
        """Compile any queued neighbor buckets NOW (synchronously) — call
        from the idle window between ticks (Scheduler.prewarm_idle /
        Framework.prewarm_idle), so the compile lands in the jit cache
        before the rotated tick dispatches and never inside a measured
        tick. Returns how many shapes were compiled."""
        done = 0
        while True:
            with self._warm_lock:
                if not self._prewarm_pending:
                    return done
                nkey = self._prewarm_pending.pop()
                if nkey in self._warm_keys:
                    continue
            self._prewarm_one(nkey)
            done += 1

    def _prewarm_one(self, nkey: tuple) -> None:
        """Compile the packed solve kernel for one bucket shape (an
        all-zeros buffer — compilation depends only on shapes/dtypes).
        A failed compile does NOT mark the shape warm — the real dispatch
        would compile in-tick, and cold_dispatches must say so."""
        from kueue_tpu.tracing import TRACER

        with TRACER.span("solver.prewarm_compile") as sp:
            if nkey[0] == "cs":
                # Cohort-sharded bucket:
                # ("cs", n_shards, Ws, P, fung[, hetero]).
                sp.set("bucket", list(nkey[1:4]))
                try:
                    from kueue_tpu.parallel.mesh import \
                        prewarm_cohort_program
                    prewarm_cohort_program(
                        self._enc, self._cohort_mesh,
                        nkey[2], nkey[3], nkey[4],
                        hetero=len(nkey) > 5 and bool(nkey[5]))
                except Exception:
                    sp.set("failed", True)
                    return
                with self._warm_lock:
                    self._warm_keys.add(nkey)
                return
            sp.set("bucket", list(nkey[:3]))
            try:
                W, P, R, G, K, S, fung = nkey[:7]
                static = self._static
                C, F = static[0].shape[0], static[0].shape[1]
                nb = ((C * F * R + W * P * R) * 8 + (W + W * P * G) * 4
                      + W * P * R + 2 * W * P + W * P * G * S)
                hetero = None
                if len(nkey) > 9 and nkey[9]:
                    hetero = (jnp.zeros((W, F), dtype=jnp.int64),
                              jnp.zeros(W, dtype=bool))
                out = _solve_kernel_packed(
                    *static, jnp.zeros(nb, dtype=jnp.uint8), hetero,
                    num_slots=S, shapes=(W, P, R, G, K),
                    fungibility_enabled=fung)
                jax.block_until_ready(out)
            except Exception:
                sp.set("failed", True)
                return
        with self._warm_lock:
            self._warm_keys.add(nkey)

    def warmup(self, snapshot: Snapshot, head_counts: Sequence[int],
               podsets: int = 1) -> None:
        """Synchronously compile the solve for the given head-count
        buckets against this snapshot's structure — the scheduler warmup
        hook (Scheduler.prewarm) calls this at attach/startup so the first
        real ticks of each expected bucket are compile-free."""
        if self._mesh is not None:
            return
        enc = self._encoding_for(snapshot)
        fung = features.enabled(features.FLAVOR_FUNGIBILITY)
        # Compile the default-shape program, plus the hetero-flavored
        # twin when the mode is on (a profiled tick dispatches the
        # hetero jaxpr — a different compile).
        het_flags = (False, True) if self._hetero_mode else (False,)
        if self._cohort_mesh is not None:
            # Per-shard buckets: an even split is the best startup guess
            # (the real bucket is pow2 of the LARGEST shard's heads; the
            # first warm ticks and _maybe_prewarm_sharded cover drift).
            n_sh = self._cohort_mesh.n_shards
            done_s = set()
            for hc in head_counts:
                Ws = sch._pad_pow2(max((int(hc) + n_sh - 1) // n_sh, 1))
                for het in het_flags:
                    key = ("cs", n_sh, Ws, max(podsets, 1), fung, het)
                    if key in done_s:
                        continue
                    done_s.add(key)
                    with self._warm_lock:
                        if key in self._warm_keys:
                            continue
                    self._prewarm_one(key)
            return
        R = len(enc.resource_names)
        C, F = enc.nominal.shape[0], enc.nominal.shape[1]
        done = set()
        for hc in head_counts:
            W = sch._pad_pow2(max(int(hc), 1))
            for het in het_flags:
                key = (W, max(podsets, 1), R, enc.num_groups,
                       enc.num_cohorts, enc.num_slots, fung, C, F, het)
                if key in done:
                    continue
                done.add(key)
                with self._warm_lock:
                    if key in self._warm_keys:
                        continue
                self._prewarm_one(key)

    def collect(self, inflight: dict) -> List[Assignment]:
        """Fetch + decode a solve dispatched by solve_async; cached heads
        replay their stored verdict and fresh ones enter the cache."""
        from kueue_tpu.tracing import TRACER

        dispatched = inflight["handle"] is not None \
            or inflight.get("out") is not None
        out = None
        if dispatched:
            with TRACER.phase("device_solve"):
                out = inflight["out"] if inflight.get("out") is not None \
                    else fetch_outputs(inflight["handle"])
        cached = inflight.get("cached")
        with TRACER.phase("decode"):
            if cached is None:
                # Nominate cache off: the classic whole-batch decode.
                assignments = decode_assignments(
                    inflight["workloads"], inflight["snapshot"],
                    inflight["enc"], out)
                # Batch-level usage coordinates (CSR over the solve): the
                # admission cycle's re-validation and usage commit consume
                # array slices of these instead of per-workload list
                # walks.
                inflight["usage_csr"] = sch.batch_usage_csr(
                    out, inflight["wt"])
                if out is not None and inflight.get("hetero") is not None:
                    inflight["hetero_overrides"] = \
                        self._hetero_overrides(inflight, out)
                    if knobs.flag("KUEUE_TPU_DEBUG_HETERO"):
                        self._debug_verify_hetero(
                            inflight, inflight["workloads"], assignments)
                return assignments
            workloads = inflight["workloads"]
            n = len(workloads)
            assignments: List[Optional[Assignment]] = [None] * n
            miss_idx = inflight["miss_idx"]
            if dispatched:
                miss_wls = [workloads[i] for i in miss_idx]
                fresh = decode_assignments(
                    miss_wls, inflight["snapshot"], inflight["enc"], out)
                inflight["usage_csr"] = sch.batch_usage_csr(
                    out, inflight["wt"])
                if inflight.get("hetero") is not None:
                    inflight["hetero_overrides"] = \
                        self._hetero_overrides(inflight, out)
                    if knobs.flag("KUEUE_TPU_DEBUG_HETERO"):
                        self._debug_verify_hetero(inflight, miss_wls,
                                                  fresh)
                nc = self._nominate_cache
                if len(nc) >= self.NOMINATE_CACHE_MAX:
                    nc.clear()
                for j, i in enumerate(miss_idx):
                    a = fresh[j]
                    assignments[i] = a
                    fp = inflight["fps"][j]
                    # Every verdict enters the cache; a head that
                    # actually ADMITS is pruned right back out by the
                    # flush (`forget_verdict`) — it left the queue, so
                    # its ring would only pin dead Assignment objects
                    # (at the 50k-backlog northstar shape that pinned
                    # hundreds of MB). What stays cached are the heads
                    # that re-pop: NoFit/Preempt losers AND
                    # Fit-but-cycle-blocked heads (a cohort-mate's
                    # reservation skipped them — a persistent steady
                    # state shape).
                    if fp is not None:
                        ring = nc.get(miss_wls[j].obj.uid)
                        if ring is None:
                            nc[miss_wls[j].obj.uid] = [(fp, a)]
                        else:
                            # Most-recent-first, bounded: the resume
                            # protocol's steady-state cycle is short
                            # (multi-podset heads cycle through up to
                            # ~4 distinct resume states).
                            ring[:] = [(fp, a)] + [
                                e for e in ring if e[0] != fp][:3]
            else:
                # Fully cache-hit (quiescent) tick: nothing decoded.
                inflight["usage_csr"] = None
            # Map each entry back to its row in the (miss-only) solve —
            # -1 for replayed heads, whose commit/re-validation falls
            # back to the assignment's own usage coordinates.
            rows = np.full(n, -1, dtype=np.int64)
            if miss_idx:
                rows[np.asarray(miss_idx)] = np.arange(len(miss_idx))
            inflight["solve_rows"] = rows
            for i, a in cached:
                assignments[i] = a
        return assignments

    def solve(self, workloads: Sequence[WorkloadInfo],
              snapshot: Snapshot) -> List[Assignment]:
        return self.collect(self.solve_async(workloads, snapshot))

    def solve_with_counts(self, workloads: Sequence[WorkloadInfo],
                          snapshot: Snapshot,
                          counts: Sequence[Sequence[int]],
                          ) -> List[Assignment]:
        """Synchronous batched solve with per-workload podset-count
        overrides — one device dispatch per partial-admission search ROUND
        for every searching workload at once, instead of one referee run
        per probe per workload (podset_reducer.go:86; scheduler
        _batch_partial_admission).

        Partial-admission probes deliberately run the DEFAULT first-fit
        ordering even in hetero mode: the reducer's binary search only
        asks "does any count fit", and a downsized workload is already
        off the throughput-optimal path — keeping the probes
        mode-independent keeps the reducer's monotonicity contract
        simple (documented in the README's hetero section)."""
        enc = self._encoding_for(snapshot)
        usage = self._usage_enc.refresh(snapshot)
        wt = sch.encode_workloads(workloads, snapshot, enc, counts=counts,
                                  min_podsets=self._p_floor)
        self._p_floor = max(self._p_floor, wt.req.shape[1])
        out = solve_flavor_fit(enc, usage, wt, static=self._static)
        return decode_assignments(workloads, snapshot, enc, out,
                                  counts=counts)

    # Scheduler admit/forget fast path (see UsageEncoder.apply_delta): keeps
    # the persistent usage tensor in lockstep with cache.assume/forget so the
    # next tick's refresh is all version hits.
    def note_admission(self, cq_name: str, usage_frq) -> None:
        if self._usage_enc is not None:
            self._usage_enc.apply_delta(cq_name, usage_frq, 1)

    def note_admissions(self, items) -> None:
        """Bulk twin of note_admission for the end-of-cycle commit:
        [(cq_name, usage_frq)] folded in one scatter-add."""
        if self._usage_enc is not None:
            self._usage_enc.apply_delta_batch(items, 1)

    def note_removal(self, cq_name: str, usage_frq) -> None:
        if self._usage_enc is not None:
            self._usage_enc.apply_delta(cq_name, usage_frq, -1)

    def note_admissions_csr(self, csr, rows, cq_names) -> None:
        """Vectorized twin of note_admissions for decode-CSR batches: the
        whole cycle's admitted usage lands in ONE scatter-add over the
        solve's CSR coordinate slices (`rows` — solve rows of the
        admitted entries), plus one version bump per admitted workload
        (`cq_names`, duplicates included) — the same per-assume lockstep
        contract as apply_delta_batch."""
        ue = self._usage_enc
        enc = self._enc
        if ue is None or enc is None:
            return
        _, ci, fi, ri, val = sch.csr_gather(csr, np.asarray(rows,
                                                            dtype=np.int64))
        if len(ci):
            np.add.at(ue.usage, (ci, fi, ri), val)
        versions = ue._versions
        cq_index = enc.cq_index
        for name in cq_names:
            ci_ = cq_index.get(name)
            if ci_ is not None:
                if versions[ci_] is not None:
                    versions[ci_] += 1
                # Keep the nominate-cache fingerprints truthful: each
                # committed admission moves its cohort's usage generation
                # exactly like the apply_delta twin.
                ue._bump_gen(ci_)

    def revalidate_fits(self, items,
                        snapshot: Optional[Snapshot] = None,
                        hier_state=None,
                        coords=None,
                        ) -> Optional[np.ndarray]:
        """Batched staleness re-validation of FIT assignments.

        `items`: sequence of (cq_name, assignment) — one per in-doubt FIT
        entry. Assignments decoded from this solver carry integer usage
        coordinates (`usage_idx`, filled by decode_assignments) that skip
        the name→index dict walks; referee-built ones fall back to the
        usage-dict walk. Returns a [n] bool mask (True = still fits
        against current usage), or None when the vectorized path cannot
        answer (no encoding yet, a stale encoding, or an unknown
        CQ/flavor/resource) and the caller must fall back to the
        per-entry referee. Hierarchical rows run the KEP-79 ancestor
        walk on the dense node balances (ops/hier_cycle).

        This replaces ~one referee walk per admitted head per tick in
        pipelined mode (scheduler._assignment_still_fits) with one
        vectorized pass over the same quota arithmetic the device kernel
        runs (fitsResourceQuota, flavorassigner.go:550-600): CQ-local
        nominal+borrowingLimit, and flat-cohort requestable/used pools
        with lending-aware splits. The usage tensor is kept in lockstep
        with the cache by note_admission/note_removal, so the answer
        matches the referee on the snapshot dicts."""
        enc = self._enc
        ue = self._usage_enc
        if enc is None or ue is None:
            return None
        if snapshot is not None and not self.encoding_matches(snapshot):
            # The encoding rotated under an in-flight tick (structural
            # mutation mid-pipeline): the items' usage_idx coordinates are
            # in the OLD index space. Fall back to the referee walk.
            return None
        n = len(items)
        if coords is not None:
            # Batch path: the scheduler pre-gathered every item's
            # coordinates from the solve's CSR (csr_gather) — no
            # per-item Python walk at all.
            ent, ci, fi, ri, val = coords
            ok = np.ones(n, dtype=bool)
            if not len(ent):
                return ok
        else:
            ent, cis, fis, ris, vals = [], [], [], [], []
            cq_index = enc.cq_index
            f_index = enc.flavor_index
            r_index = enc.resource_index
            for i, (cq_name, assignment) in enumerate(items):
                ci = cq_index.get(cq_name)
                if ci is None:
                    return None
                idx = getattr(assignment, "usage_idx", None)
                if idx is not None:
                    i_f, i_r, i_v = idx
                    k = len(i_f)
                    ent.extend([i] * k)
                    cis.extend([ci] * k)
                    fis.extend(i_f)
                    ris.extend(i_r)
                    vals.extend(i_v)
                    continue
                for fname, resources in assignment.usage.items():
                    fi = f_index.get(fname)
                    if fi is None:
                        return None
                    for rname, val in resources.items():
                        ri = r_index.get(rname)
                        if ri is None:
                            return None
                        ent.append(i)
                        cis.append(ci)
                        fis.append(fi)
                        ris.append(ri)
                        vals.append(val)
            ok = np.ones(n, dtype=bool)
            if not ent:
                return ok
            ent = np.asarray(ent)
            ci = np.asarray(cis)
            fi = np.asarray(fis)
            ri = np.asarray(ris)
            val = np.asarray(vals, dtype=np.int64)
        U = ue.usage
        used = U[ci, fi, ri]
        nom = enc.nominal[ci, fi, ri]
        blim = enc.borrow_limit[ci, fi, ri]
        guar = enc.guaranteed[ci, fi, ri]
        k = enc.cohort_id[ci]
        above = np.maximum(U - enc.guaranteed, 0)
        cohort_usage = enc.cohort_sum(above)
        cohort_req = enc.cohort_requestable()
        cohort_avail = cohort_req[k, fi, ri] + guar
        cohort_used = cohort_usage[k, fi, ri] + np.minimum(used, guar)
        cohort_ok = cohort_used + val <= cohort_avail
        if enc.hier is not None:
            # Hierarchical rows: the flat pool arithmetic does not model
            # the tree; run the KEP-79 ancestor walk on the dense node
            # balances instead (O(depth) per pair — the per-entry dict
            # referee was O(tree) per pair and dominated pipelined fair-
            # sharing ticks).
            hmask = enc.hier.cq_hier[ci]
            rows = np.nonzero(hmask)[0]
            if rows.size:
                # `hier_state` (a fold-free HierCycleState the caller will
                # reuse for the admission cycle) avoids rebuilding the
                # node balances twice per tick.
                state = hier_state
                if state is None or state.folds:
                    from kueue_tpu.ops.hier_cycle import HierCycleState
                    state = HierCycleState(enc, U)
                cohort_ok[rows] = state.fits_many(
                    ci[rows], fi[rows], ri[rows], val[rows])
        fits = (used + val <= nom + blim) & cohort_ok
        np.logical_and.at(ok, ent, fits)
        return ok
