// Native decision decoder: kernel output tensors -> Assignment objects.
//
// The per-tick decode loop (kueue_tpu/models/flavor_fit.py
// decode_assignments) materializes ~1k Assignment trees per scheduling
// cycle. In CPython that loop is interpreter-bound (~13us/workload on the
// bench host) and sits on the critical path between two device dispatches.
// This extension runs the same loop at C speed against the raw output
// buffers, constructing the exact same Python objects (the slots
// dataclasses of kueue_tpu/solver/referee.py).
//
// The reference's entire scheduler is compiled (Go); this is the
// native-runtime counterpart for the hot host-side glue around the TPU
// solve (reference: scheduler.go:174-288 nominate/admit plumbing).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -I<python-include> decode.cpp
//        -o _kueue_decode.so   (driven by kueue_tpu/utils/native_decode.py)

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

namespace {

constexpr int kFit = 2;  // solver/modes.py FIT

struct Buf {
  Py_buffer view{};
  bool ok = false;
  ~Buf() {
    if (ok) PyBuffer_Release(&view);
  }
  bool acquire(PyObject* obj, Py_ssize_t itemsize) {
    if (PyObject_GetBuffer(obj, &view, PyBUF_C_CONTIGUOUS) != 0) return false;
    ok = true;
    if (view.itemsize != itemsize) {
      PyErr_SetString(PyExc_TypeError, "unexpected buffer itemsize");
      return false;
    }
    return true;
  }
  template <typename T>
  const T* data() const {
    return static_cast<const T*>(view.buf);
  }
};

// Interned attribute names + shared constants, created once at module init.
struct Names {
  PyObject* cluster_queue;
  PyObject* allocatable_generation;
  PyObject* cohort;
  PyObject* rg_by_resource;
  PyObject* total_requests;
  PyObject* name;
  PyObject* requests;
  PyObject* count;
  PyObject* pod_sets;
  PyObject* borrowing;
  PyObject* usage;
  PyObject* last_state;
  PyObject* flavors;
  PyObject* reasons;
  PyObject* error;
  PyObject* mode;
  PyObject* tried_flavor_idx;
  PyObject* borrow;
  PyObject* last_tried_flavor_idx;
  PyObject* cluster_queue_generation;
  PyObject* cohort_generation;
  PyObject* pods;           // "pods" resource name
  PyObject* msg_no_quota;   // "insufficient unused quota"
  PyObject* msg_no_fit;     // "insufficient quota or no eligible flavor"
  PyObject* mode_memo;      // "_mode" lazy representative_mode memo slot
  PyObject* msg_memo;       // "_msg" lazy message() memo slot
  PyObject* resume_sig;     // lazy resume-content signature slot
  PyObject* usage_idx;      // integer-coordinate usage twin
};
Names N;

// Construct an instance of a slots dataclass without running its (Python)
// __init__: object.__new__(cls) + per-slot SetAttr.
PyObject* bare_new(PyObject* cls) {
  PyTypeObject* tp = reinterpret_cast<PyTypeObject*>(cls);
  return tp->tp_alloc(tp, 0);
}

bool set_steal(PyObject* obj, PyObject* attr, PyObject* value) {
  // SetAttr + drop our reference to value; false on error (value released).
  if (value == nullptr) return false;
  int rc = PyObject_SetAttr(obj, attr, value);
  Py_DECREF(value);
  return rc == 0;
}

bool set_keep(PyObject* obj, PyObject* attr, PyObject* value) {
  return value != nullptr && PyObject_SetAttr(obj, attr, value) == 0;
}

// decode(classes, workloads, snapshot_cqs, cq_index, flavor_names,
//        resource_names, group_of_resource, ps_ok, ps_mode, res_flavor,
//        res_mode, res_borrow, group_tried, P, R, G)
//
// classes = (Assignment, PodSetAssignmentResult, FlavorAssignment,
//            AssignmentClusterQueueState)
// Buffers are C-contiguous: ps_ok/res_borrow u8, ps_mode/res_mode i8,
// res_flavor/group_tried i16, group_of_resource i32 with shape [C,R].
PyObject* decode(PyObject*, PyObject* args) {
  PyObject *classes, *workloads, *snapshot_cqs, *cq_index, *flavor_names,
      *resource_names;
  PyObject *gor_o, *ps_ok_o, *ps_mode_o, *res_flavor_o, *res_mode_o,
      *res_borrow_o, *group_tried_o;
  int P, R, G;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOiii", &classes, &workloads,
                        &snapshot_cqs, &cq_index, &flavor_names,
                        &resource_names, &gor_o, &ps_ok_o, &ps_mode_o,
                        &res_flavor_o, &res_mode_o, &res_borrow_o,
                        &group_tried_o, &P, &R, &G))
    return nullptr;

  PyObject* cls_assignment = PyTuple_GetItem(classes, 0);
  PyObject* cls_psa = PyTuple_GetItem(classes, 1);
  PyObject* cls_fa = PyTuple_GetItem(classes, 2);
  PyObject* cls_acqs = PyTuple_GetItem(classes, 3);
  if (cls_acqs == nullptr) return nullptr;

  Buf gor, ps_ok, ps_mode, res_flavor, res_mode, res_borrow, group_tried;
  if (!gor.acquire(gor_o, 4) || !ps_ok.acquire(ps_ok_o, 1) ||
      !ps_mode.acquire(ps_mode_o, 1) || !res_flavor.acquire(res_flavor_o, 2) ||
      !res_mode.acquire(res_mode_o, 1) || !res_borrow.acquire(res_borrow_o, 1) ||
      !group_tried.acquire(group_tried_o, 2))
    return nullptr;
  const int32_t* gor_d = gor.data<int32_t>();
  const uint8_t* ok_d = ps_ok.data<uint8_t>();
  const int8_t* pm_d = ps_mode.data<int8_t>();
  const int16_t* rf_d = res_flavor.data<int16_t>();
  const int8_t* rm_d = res_mode.data<int8_t>();
  const uint8_t* rb_d = res_borrow.data<uint8_t>();
  const int16_t* gt_d = group_tried.data<int16_t>();

  Py_ssize_t n = PyList_Size(workloads);
  if (n < 0) return nullptr;
  PyObject* result = PyList_New(n);
  if (result == nullptr) return nullptr;

  for (Py_ssize_t w = 0; w < n; ++w) {
    PyObject* wi = PyList_GET_ITEM(workloads, w);  // borrowed
    PyObject* cq_name = PyObject_GetAttr(wi, N.cluster_queue);
    if (cq_name == nullptr) goto fail;
    PyObject* cq = PyDict_GetItem(snapshot_cqs, cq_name);  // borrowed
    PyObject* ci_o = PyDict_GetItem(cq_index, cq_name);    // borrowed
    Py_DECREF(cq_name);
    if (cq == nullptr || ci_o == nullptr) {
      PyErr_SetString(PyExc_KeyError, "workload ClusterQueue not in snapshot");
      goto fail;
    }
    long ci = PyLong_AsLong(ci_o);

    // last_state = AssignmentClusterQueueState(...)
    PyObject* acqs = bare_new(cls_acqs);
    if (acqs == nullptr) goto fail;
    PyObject* lti = PyList_New(0);
    if (!set_keep(acqs, N.resume_sig, Py_None) ||
        !set_keep(acqs, N.last_tried_flavor_idx, lti)) {
      Py_XDECREF(lti);
      Py_DECREF(acqs);
      goto fail;
    }
    {
      PyObject* cq_gen = PyObject_GetAttr(cq, N.allocatable_generation);
      bool ok1 = set_steal(acqs, N.cluster_queue_generation, cq_gen);
      PyObject* cohort = ok1 ? PyObject_GetAttr(cq, N.cohort) : nullptr;
      bool ok2 = false;
      if (cohort != nullptr) {
        PyObject* cg = (cohort == Py_None)
                           ? PyLong_FromLong(0)
                           : PyObject_GetAttr(cohort, N.allocatable_generation);
        Py_DECREF(cohort);
        ok2 = set_steal(acqs, N.cohort_generation, cg);
      }
      if (!ok1 || !ok2) {
        Py_DECREF(lti);
        Py_DECREF(acqs);
        goto fail;
      }
    }

    // a = Assignment(...)
    PyObject* a = bare_new(cls_assignment);
    PyObject* pod_sets = a ? PyList_New(0) : nullptr;
    PyObject* usage = pod_sets ? PyDict_New() : nullptr;
    if (usage == nullptr || !set_keep(a, N.pod_sets, pod_sets) ||
        !set_keep(a, N.usage, usage) ||
        !set_keep(a, N.borrowing, Py_False) ||
        !set_keep(a, N.mode_memo, Py_None) ||
        !set_keep(a, N.msg_memo, Py_None) ||
        !set_keep(a, N.last_state, acqs)) {
      Py_XDECREF(usage);
      Py_XDECREF(pod_sets);
      Py_XDECREF(a);
      Py_DECREF(lti);
      Py_DECREF(acqs);
      goto fail;
    }
    Py_DECREF(acqs);
    bool a_borrowing = false;
    // Integer usage coordinates ((f,r) deduped across podsets, values
    // summed) — the index-space twin of a.usage for revalidate/scatter
    // consumers. Tiny per workload (≤ requested resources), linear scan.
    std::vector<long> u_f, u_r;
    std::vector<long long> u_v;

    PyObject* rg_by_resource = PyObject_GetAttr(cq, N.rg_by_resource);
    int track_pods =
        rg_by_resource ? PyDict_Contains(rg_by_resource, N.pods) : -1;
    Py_XDECREF(rg_by_resource);
    PyObject* totals =
        track_pods >= 0 ? PyObject_GetAttr(wi, N.total_requests) : nullptr;
    if (totals == nullptr) {
      Py_DECREF(a);
      Py_DECREF(lti);
      Py_DECREF(pod_sets);
      Py_DECREF(usage);
      goto fail;
    }

    // first failing podset (ps_ok is False on padding rows).
    const uint8_t* ok_row = ok_d + w * P;
    long first_fail = P;
    for (long p = 0; p < P; ++p) {
      if (!ok_row[p]) {
        first_fail = p;
        break;
      }
    }

    Py_ssize_t n_ps = PySequence_Size(totals);
    bool wl_ok = n_ps >= 0;
    for (Py_ssize_t p = 0; wl_ok && p < n_ps && p <= first_fail; ++p) {
      PyObject* ps = PySequence_GetItem(totals, p);
      if (ps == nullptr) {
        wl_ok = false;
        break;
      }
      PyObject* ps_requests = PyObject_GetAttr(ps, N.requests);
      PyObject* requests = ps_requests ? PyDict_Copy(ps_requests) : nullptr;
      Py_XDECREF(ps_requests);
      PyObject* count = requests ? PyObject_GetAttr(ps, N.count) : nullptr;
      if (count != nullptr && track_pods == 1)
        if (PyDict_SetItem(requests, N.pods, count) != 0) {
          Py_DECREF(count);
          count = nullptr;
        }

      // psa = PodSetAssignmentResult(...)
      PyObject* psa = count ? bare_new(cls_psa) : nullptr;
      PyObject* flavors = psa ? PyDict_New() : nullptr;
      PyObject* ps_name = flavors ? PyObject_GetAttr(ps, N.name) : nullptr;
      Py_DECREF(ps);
      bool ok_psa = ps_name != nullptr && set_steal(psa, N.name, ps_name) &&
                    set_keep(psa, N.flavors, flavors) &&
                    set_keep(psa, N.requests, requests) &&
                    set_steal(psa, N.count, count) &&
                    set_keep(psa, N.mode_memo, Py_None) &&
                    set_keep(psa, N.error, Py_None);
      bool ok_here = ok_row[p] != 0;
      if (ok_psa) {
        PyObject* reason = nullptr;  // shared constant, or none
        if (!ok_here)
          reason = N.msg_no_fit;
        else if (pm_d[w * P + p] < kFit)
          reason = N.msg_no_quota;
        PyObject* reasons = PyList_New(reason ? 1 : 0);
        if (reasons != nullptr && reason != nullptr) {
          Py_INCREF(reason);
          PyList_SET_ITEM(reasons, 0, reason);
        }
        ok_psa = set_steal(psa, N.reasons, reasons);
      }
      PyObject* lti_dict = ok_psa ? PyDict_New() : nullptr;
      if (lti_dict == nullptr || PyList_Append(lti, lti_dict) != 0 ||
          PyList_Append(pod_sets, psa) != 0) {
        Py_XDECREF(lti_dict);
        Py_XDECREF(flavors);
        Py_XDECREF(requests);
        Py_XDECREF(psa);
        wl_ok = false;
        break;
      }

      if (ok_here) {
        const int16_t* rf_row = rf_d + (w * P + p) * R;
        const int8_t* rm_row = rm_d + (w * P + p) * R;
        const uint8_t* rb_row = rb_d + (w * P + p) * R;
        const int16_t* gt_row = gt_d + (w * P + p) * G;
        const int32_t* gor_row = gor_d + ci * R;
        for (long r = 0; wl_ok && r < R; ++r) {
          int f = rf_row[r];
          if (f < 0) continue;
          PyObject* rname = PyList_GET_ITEM(resource_names, r);  // borrowed
          PyObject* fname = PyList_GET_ITEM(flavor_names, f);    // borrowed
          long tried = gt_row[gor_row[r]];
          bool borrow = rb_row[r] != 0;

          PyObject* fa = bare_new(cls_fa);
          PyObject* tried_o = fa ? PyLong_FromLong(tried) : nullptr;
          bool ok_fa =
              tried_o != nullptr && set_keep(fa, N.name, fname) &&
              set_steal(fa, N.mode, PyLong_FromLong(rm_row[r])) &&
              set_keep(fa, N.tried_flavor_idx, tried_o) &&
              set_keep(fa, N.borrow, borrow ? Py_True : Py_False) &&
              PyDict_SetItem(flavors, rname, fa) == 0;
          Py_XDECREF(fa);
          if (!ok_fa) {
            Py_XDECREF(tried_o);
            wl_ok = false;
            break;
          }
          if (borrow) a_borrowing = true;

          // a.usage[fname][rname] += requests[rname]
          PyObject* fusage = PyDict_GetItem(usage, fname);  // borrowed
          if (fusage == nullptr) {
            PyObject* d = PyDict_New();
            if (d == nullptr || PyDict_SetItem(usage, fname, d) != 0) {
              Py_XDECREF(d);
              Py_DECREF(tried_o);
              wl_ok = false;
              break;
            }
            fusage = d;  // borrowed after SetItem
            Py_DECREF(d);
          }
          PyObject* val = PyDict_GetItem(requests, rname);  // borrowed
          PyObject* prev = PyDict_GetItem(fusage, rname);   // borrowed
          if (val == nullptr) {
            PyErr_SetString(PyExc_KeyError, "assigned resource not requested");
            Py_DECREF(tried_o);
            wl_ok = false;
            break;
          }
          if (prev == nullptr) {
            wl_ok = PyDict_SetItem(fusage, rname, val) == 0;
          } else {
            PyObject* sum = PyNumber_Add(prev, val);
            wl_ok = sum != nullptr && PyDict_SetItem(fusage, rname, sum) == 0;
            Py_XDECREF(sum);
          }
          if (wl_ok) {
            long long v = PyLong_AsLongLong(val);
            if (v == -1 && PyErr_Occurred()) {
              wl_ok = false;
            } else {
              bool merged = false;
              for (size_t t = 0; t < u_f.size(); ++t) {
                if (u_f[t] == f && u_r[t] == r) {
                  u_v[t] += v;
                  merged = true;
                  break;
                }
              }
              if (!merged) {
                u_f.push_back(f);
                u_r.push_back(r);
                u_v.push_back(v);
              }
            }
          }
          // last_tried_flavor_idx[p][rname] = tried
          if (wl_ok) wl_ok = PyDict_SetItem(lti_dict, rname, tried_o) == 0;
          Py_DECREF(tried_o);
        }
      }
      Py_DECREF(lti_dict);
      Py_DECREF(flavors);
      Py_DECREF(requests);
      Py_DECREF(psa);
    }
    Py_DECREF(totals);
    Py_DECREF(lti);
    Py_DECREF(pod_sets);
    Py_DECREF(usage);
    if (!wl_ok) {
      Py_DECREF(a);
      goto fail;
    }
    if (a_borrowing && PyObject_SetAttr(a, N.borrowing, Py_True) != 0) {
      Py_DECREF(a);
      goto fail;
    }
    {
      size_t m = u_f.size();
      PyObject* l_f = PyList_New(m);
      PyObject* l_r = l_f ? PyList_New(m) : nullptr;
      PyObject* l_v = l_r ? PyList_New(m) : nullptr;
      bool ok_idx = l_v != nullptr;
      for (size_t t = 0; ok_idx && t < m; ++t) {
        PyObject* o_f = PyLong_FromLong(u_f[t]);
        PyObject* o_r = PyLong_FromLong(u_r[t]);
        PyObject* o_v = PyLong_FromLongLong(u_v[t]);
        if (o_f == nullptr || o_r == nullptr || o_v == nullptr) {
          Py_XDECREF(o_f);
          Py_XDECREF(o_r);
          Py_XDECREF(o_v);
          ok_idx = false;
          break;
        }
        PyList_SET_ITEM(l_f, t, o_f);
        PyList_SET_ITEM(l_r, t, o_r);
        PyList_SET_ITEM(l_v, t, o_v);
      }
      PyObject* tup = ok_idx ? PyTuple_Pack(3, l_f, l_r, l_v) : nullptr;
      Py_XDECREF(l_f);
      Py_XDECREF(l_r);
      Py_XDECREF(l_v);
      if (!set_steal(a, N.usage_idx, tup)) {
        Py_DECREF(a);
        goto fail;
      }
    }
    PyList_SET_ITEM(result, w, a);  // steals
  }
  return result;

fail:
  Py_DECREF(result);
  return nullptr;
}

PyMethodDef methods[] = {
    {"decode", decode, METH_VARARGS,
     "Decode solver output tensors into Assignment objects."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_kueue_decode",
    "Native decision decoder for the batched admission solve.", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__kueue_decode(void) {
  N.cluster_queue = PyUnicode_InternFromString("cluster_queue");
  N.allocatable_generation = PyUnicode_InternFromString("allocatable_generation");
  N.cohort = PyUnicode_InternFromString("cohort");
  N.rg_by_resource = PyUnicode_InternFromString("rg_by_resource");
  N.total_requests = PyUnicode_InternFromString("total_requests");
  N.name = PyUnicode_InternFromString("name");
  N.requests = PyUnicode_InternFromString("requests");
  N.count = PyUnicode_InternFromString("count");
  N.pod_sets = PyUnicode_InternFromString("pod_sets");
  N.borrowing = PyUnicode_InternFromString("borrowing");
  N.usage = PyUnicode_InternFromString("usage");
  N.last_state = PyUnicode_InternFromString("last_state");
  N.flavors = PyUnicode_InternFromString("flavors");
  N.reasons = PyUnicode_InternFromString("reasons");
  N.error = PyUnicode_InternFromString("error");
  N.mode = PyUnicode_InternFromString("mode");
  N.mode_memo = PyUnicode_InternFromString("_mode");
  N.msg_memo = PyUnicode_InternFromString("_msg");
  N.resume_sig = PyUnicode_InternFromString("resume_sig");
  N.tried_flavor_idx = PyUnicode_InternFromString("tried_flavor_idx");
  N.borrow = PyUnicode_InternFromString("borrow");
  N.last_tried_flavor_idx = PyUnicode_InternFromString("last_tried_flavor_idx");
  N.cluster_queue_generation =
      PyUnicode_InternFromString("cluster_queue_generation");
  N.cohort_generation = PyUnicode_InternFromString("cohort_generation");
  N.pods = PyUnicode_InternFromString("pods");
  N.usage_idx = PyUnicode_InternFromString("usage_idx");
  N.msg_no_quota = PyUnicode_InternFromString("insufficient unused quota");
  N.msg_no_fit =
      PyUnicode_InternFromString("insufficient quota or no eligible flavor");
  return PyModule_Create(&moduledef);
}
