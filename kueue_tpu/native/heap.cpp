// Keyed binary min-heap with in-place update/delete.
//
// Native counterpart of reference pkg/util/heap/heap.go (the pending-queue
// data structure): items are addressed by a caller-assigned uint64 id and
// ordered by a fixed-width lexicographic int64 key vector, so the hot
// pending-queue operations (push/update/pop at 50k-workload backlogs) run
// without interpreter dispatch. Exposed through a C ABI consumed by
// ctypes (kueue_tpu/utils/native_heap.py).
//
// Build: g++ -O2 -shared -fPIC -o _libkueue_heap.so heap.cpp

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct Heap {
    int key_len;
    // Parallel arrays: ids[i] and keys[i*key_len .. ] describe slot i.
    std::vector<uint64_t> ids;
    std::vector<int64_t> keys;
    std::unordered_map<uint64_t, size_t> index;

    bool less(size_t a, size_t b) const {
        const int64_t* ka = keys.data() + a * key_len;
        const int64_t* kb = keys.data() + b * key_len;
        for (int i = 0; i < key_len; i++) {
            if (ka[i] != kb[i]) return ka[i] < kb[i];
        }
        return false;
    }

    void swap_slots(size_t i, size_t j) {
        std::swap(ids[i], ids[j]);
        for (int k = 0; k < key_len; k++) {
            std::swap(keys[i * key_len + k], keys[j * key_len + k]);
        }
        index[ids[i]] = i;
        index[ids[j]] = j;
    }

    void up(size_t i) {
        while (i > 0) {
            size_t parent = (i - 1) / 2;
            if (!less(i, parent)) break;
            swap_slots(i, parent);
            i = parent;
        }
    }

    bool down(size_t i) {
        size_t n = ids.size(), start = i;
        for (;;) {
            size_t left = 2 * i + 1;
            if (left >= n) break;
            size_t smallest = left, right = left + 1;
            if (right < n && less(right, left)) smallest = right;
            if (!less(smallest, i)) break;
            swap_slots(i, smallest);
            i = smallest;
        }
        return i > start;
    }

    void fix(size_t i) {
        if (!down(i)) up(i);
    }

    void push(uint64_t id, const int64_t* key) {
        size_t i = ids.size();
        ids.push_back(id);
        keys.insert(keys.end(), key, key + key_len);
        index[id] = i;
        up(i);
    }

    // Removes slot i; returns its id.
    uint64_t remove_at(size_t i) {
        uint64_t id = ids[i];
        index.erase(id);
        size_t last = ids.size() - 1;
        if (i != last) {
            swap_slots(i, last);
        }
        ids.pop_back();
        keys.resize(keys.size() - key_len);
        if (i < ids.size()) {
            // After the swap the index entry is stale only for slot i.
            index[ids[i]] = i;
            fix(i);
        }
        return id;
    }
};

}  // namespace

extern "C" {

void* kh_new(int key_len) { return new Heap{key_len}; }

void kh_free(void* h) { delete static_cast<Heap*>(h); }

int64_t kh_len(void* h) {
    return static_cast<int64_t>(static_cast<Heap*>(h)->ids.size());
}

int kh_contains(void* h, uint64_t id) {
    Heap* hp = static_cast<Heap*>(h);
    return hp->index.count(id) ? 1 : 0;
}

// Returns 1 when inserted, 0 when the id was already present (no update).
int kh_push_if_not_present(void* h, uint64_t id, const int64_t* key) {
    Heap* hp = static_cast<Heap*>(h);
    if (hp->index.count(id)) return 0;
    hp->push(id, key);
    return 1;
}

void kh_push_or_update(void* h, uint64_t id, const int64_t* key) {
    Heap* hp = static_cast<Heap*>(h);
    auto it = hp->index.find(id);
    if (it == hp->index.end()) {
        hp->push(id, key);
        return;
    }
    size_t i = it->second;
    std::memcpy(hp->keys.data() + i * hp->key_len, key,
                sizeof(int64_t) * hp->key_len);
    hp->fix(i);
}

// Returns 1 when the id existed and was removed.
int kh_delete(void* h, uint64_t id) {
    Heap* hp = static_cast<Heap*>(h);
    auto it = hp->index.find(id);
    if (it == hp->index.end()) return 0;
    hp->remove_at(it->second);
    return 1;
}

// Returns the popped id, or UINT64_MAX when empty.
uint64_t kh_pop(void* h) {
    Heap* hp = static_cast<Heap*>(h);
    if (hp->ids.empty()) return UINT64_MAX;
    return hp->remove_at(0);
}

// Pops the top of MANY heaps in one call: out[i] receives heap i's popped
// id, or UINT64_MAX when that heap is empty. The scheduler's heads sweep
// pops one item per ClusterQueue per tick — at 1k queues the per-pop
// interpreter/ctypes crossing dominated the sweep, so the whole tick now
// crosses once.
void kh_pop_many(void** heaps, int64_t n, uint64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        Heap* hp = static_cast<Heap*>(heaps[i]);
        out[i] = hp->ids.empty() ? UINT64_MAX : hp->remove_at(0);
    }
}

uint64_t kh_peek(void* h) {
    Heap* hp = static_cast<Heap*>(h);
    if (hp->ids.empty()) return UINT64_MAX;
    return hp->ids[0];
}

// Copies all ids (heap-array order) into out (caller-sized); returns count.
int64_t kh_items(void* h, uint64_t* out, int64_t cap) {
    Heap* hp = static_cast<Heap*>(h);
    int64_t n = static_cast<int64_t>(hp->ids.size());
    if (n > cap) n = cap;
    std::memcpy(out, hp->ids.data(), sizeof(uint64_t) * n);
    return n;
}

}  // extern "C"
