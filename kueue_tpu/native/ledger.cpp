// Native usage-ledger walks for the admission hot path.
//
// The cache and the snapshot mirror account workload usage in nested
// {flavor: {resource: int}} dicts (the FlavorResourceQuantities shape of
// reference pkg/cache/clusterqueue.go:473-508). At north-star scale the
// fused Python walk over a workload's usage triples — update the CQ's own
// usage, the admitted split, and the (non-lending) cohort usage — runs
// thousands of times per tick across assume/forget, the mirror's lockstep
// deltas, and preemption simulation. This extension runs the same walk
// through the CPython dict API: identical semantics (only pairs already
// present in a target dict are tracked), several times faster.
//
// Exposed functions:
//   apply_triples(usage, admitted_or_None, cohort_or_None, triples, sign)
//     -> None; triples = [(flavor:str, resource:str, value:int), ...]
//   lq_apply(reservation, admitted_usage_or_None, triples, sign)
//     -> None; setdefault-style accumulation (missing keys are created,
//     matching Cache._lq_apply).
//
// Arithmetic uses long long with overflow detection; any value that does
// not fit (absurd for milli-quantities, but the API allows arbitrary
// ints) falls back to PyNumber_Add so results stay exact.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

// old + v*sign with exact semantics; returns new reference or nullptr.
PyObject* add_scaled(PyObject* old_val, PyObject* v, long sign) {
  int of1 = 0, of2 = 0;
  long long a = PyLong_AsLongLongAndOverflow(old_val, &of1);
  long long b = PyLong_AsLongLongAndOverflow(v, &of2);
  if (!of1 && !of2 && (a != -1 || !PyErr_Occurred()) &&
      (b != -1 || !PyErr_Occurred())) {
    long long scaled;
    long long sum;
    if (!__builtin_mul_overflow(b, (long long)sign, &scaled) &&
        !__builtin_add_overflow(a, scaled, &sum)) {
      return PyLong_FromLongLong(sum);
    }
  }
  PyErr_Clear();
  // Arbitrary-precision fallback.
  PyObject* s = PyLong_FromLong(sign);
  if (s == nullptr) return nullptr;
  PyObject* scaled = PyNumber_Multiply(v, s);
  Py_DECREF(s);
  if (scaled == nullptr) return nullptr;
  PyObject* out = PyNumber_Add(old_val, scaled);
  Py_DECREF(scaled);
  return out;
}

// Add v*sign to target[flv][res] when both keys exist (tracked pairs
// only — Cache._apply_usage semantics). Returns 0 on success.
int bump_tracked(PyObject* target, PyObject* flv, PyObject* res, PyObject* v,
                 long sign) {
  PyObject* inner = PyDict_GetItemWithError(target, flv);  // borrowed
  if (inner == nullptr) return PyErr_Occurred() ? -1 : 0;
  if (!PyDict_Check(inner)) return 0;
  PyObject* old_val = PyDict_GetItemWithError(inner, res);  // borrowed
  if (old_val == nullptr) return PyErr_Occurred() ? -1 : 0;
  PyObject* out = add_scaled(old_val, v, sign);
  if (out == nullptr) return -1;
  int rc = PyDict_SetItem(inner, res, out);
  Py_DECREF(out);
  return rc;
}

// Add v*sign to target[flv][res], creating missing levels
// (Cache._lq_apply semantics).
int bump_create(PyObject* target, PyObject* flv, PyObject* res, PyObject* v,
                long sign) {
  PyObject* inner = PyDict_GetItemWithError(target, flv);  // borrowed
  if (inner == nullptr) {
    if (PyErr_Occurred()) return -1;
    PyObject* fresh = PyDict_New();
    if (fresh == nullptr || PyDict_SetItem(target, flv, fresh) != 0) {
      Py_XDECREF(fresh);
      return -1;
    }
    inner = fresh;  // still owned by target after SetItem
    Py_DECREF(fresh);
  }
  PyObject* old_val = PyDict_GetItemWithError(inner, res);  // borrowed
  PyObject* out;
  if (old_val == nullptr) {
    if (PyErr_Occurred()) return -1;
    long long b;
    int of = 0;
    b = PyLong_AsLongLongAndOverflow(v, &of);
    if (!of && (b != -1 || !PyErr_Occurred())) {
      long long scaled;
      if (!__builtin_mul_overflow(b, (long long)sign, &scaled))
        out = PyLong_FromLongLong(scaled);
      else
        out = nullptr;
    } else {
      out = nullptr;
    }
    if (out == nullptr) {
      PyErr_Clear();
      PyObject* s = PyLong_FromLong(sign);
      out = s ? PyNumber_Multiply(v, s) : nullptr;
      Py_XDECREF(s);
    }
  } else {
    out = add_scaled(old_val, v, sign);
  }
  if (out == nullptr) return -1;
  int rc = PyDict_SetItem(inner, res, out);
  Py_DECREF(out);
  return rc;
}

// apply_triples(usage, admitted_or_None, cohort_or_None, triples, sign)
PyObject* apply_triples(PyObject*, PyObject* args) {
  PyObject *usage, *admitted, *cohort, *triples;
  int sign;
  if (!PyArg_ParseTuple(args, "OOOOi", &usage, &admitted, &cohort, &triples,
                        &sign))
    return nullptr;
  if (!PyDict_Check(usage) || !PyList_Check(triples)) {
    PyErr_SetString(PyExc_TypeError, "apply_triples(dict, ..., list, int)");
    return nullptr;
  }
  bool has_adm = admitted != Py_None;
  bool has_coh = cohort != Py_None;
  Py_ssize_t n = PyList_GET_SIZE(triples);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(triples, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
      PyErr_SetString(PyExc_TypeError, "triple must be (flv, res, v)");
      return nullptr;
    }
    PyObject* flv = PyTuple_GET_ITEM(t, 0);
    PyObject* res = PyTuple_GET_ITEM(t, 1);
    PyObject* v = PyTuple_GET_ITEM(t, 2);
    if (bump_tracked(usage, flv, res, v, sign) != 0) return nullptr;
    if (has_adm && bump_tracked(admitted, flv, res, v, sign) != 0)
      return nullptr;
    if (has_coh && bump_tracked(cohort, flv, res, v, sign) != 0)
      return nullptr;
  }
  Py_RETURN_NONE;
}

// lq_apply(reservation, admitted_usage_or_None, triples, sign)
PyObject* lq_apply(PyObject*, PyObject* args) {
  PyObject *reservation, *admitted_usage, *triples;
  int sign;
  if (!PyArg_ParseTuple(args, "OOOi", &reservation, &admitted_usage, &triples,
                        &sign))
    return nullptr;
  if (!PyDict_Check(reservation) || !PyList_Check(triples)) {
    PyErr_SetString(PyExc_TypeError, "lq_apply(dict, ..., list, int)");
    return nullptr;
  }
  bool has_adm = admitted_usage != Py_None;
  Py_ssize_t n = PyList_GET_SIZE(triples);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(triples, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
      PyErr_SetString(PyExc_TypeError, "triple must be (flv, res, v)");
      return nullptr;
    }
    PyObject* flv = PyTuple_GET_ITEM(t, 0);
    PyObject* res = PyTuple_GET_ITEM(t, 1);
    PyObject* v = PyTuple_GET_ITEM(t, 2);
    if (bump_create(reservation, flv, res, v, sign) != 0) return nullptr;
    if (has_adm && bump_create(admitted_usage, flv, res, v, sign) != 0)
      return nullptr;
  }
  Py_RETURN_NONE;
}

// flush_mirror(snap_cqs, base, items) -> applied count
//
// The SnapshotMirror.flush_pending loop (snapshot.py) in native form: each
// item is (sign, workload, cq_name, version, alloc_gen, info_or_None)
// exactly as note_admission/note_removal queued it. Per item: resolve the
// snapshot clone by the note-time ClusterQueue name, insert/remove the info
// in the clone's workload map, bump its usage_version, walk the info's
// usage triples into the clone's own usage and (when cohorted) the cohort
// usage — tracked pairs only, identical to _apply_usage with
// admitted=False — and record the cache version in `base`. At north-star
// scale this loop folds ~1.3k completion/admission mutations per tick and
// the interpreter overhead of the Python twin dominated the snapshot
// phase. The caller (flush_pending) only dispatches here when LendingLimit
// is disabled and every addition carries its info; the Python twin remains
// the lending-path / fallback implementation.
PyObject* flush_mirror(PyObject*, PyObject* args) {
  PyObject *snap_cqs, *base, *items;
  if (!PyArg_ParseTuple(args, "OOO", &snap_cqs, &base, &items))
    return nullptr;
  if (!PyDict_Check(snap_cqs) || !PyDict_Check(base) ||
      !PyList_Check(items)) {
    PyErr_SetString(PyExc_TypeError, "flush_mirror(dict, dict, list)");
    return nullptr;
  }
  static PyObject *s_key, *s_workloads,
      *s_usage_version, *s_usage_triples, *s_usage, *s_cohort,
      *s_allocatable_generation, *s_name;
  if (s_key == nullptr) {
    s_key = PyUnicode_InternFromString("key");
    s_workloads = PyUnicode_InternFromString("workloads");
    s_usage_version = PyUnicode_InternFromString("usage_version");
    s_usage_triples = PyUnicode_InternFromString("usage_triples");
    s_usage = PyUnicode_InternFromString("usage");
    s_cohort = PyUnicode_InternFromString("cohort");
    s_allocatable_generation =
        PyUnicode_InternFromString("allocatable_generation");
    s_name = PyUnicode_InternFromString("name");
  }
  long applied = 0;
  Py_ssize_t n = PyList_GET_SIZE(items);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(items, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 6) {
      PyErr_SetString(PyExc_TypeError,
                      "item must be (sign, wl, cq_name, version, gen, info)");
      return nullptr;
    }
    long sign = PyLong_AsLong(PyTuple_GET_ITEM(t, 0));
    if (sign == -1 && PyErr_Occurred()) return nullptr;
    PyObject* wl = PyTuple_GET_ITEM(t, 1);
    PyObject* cq_name = PyTuple_GET_ITEM(t, 2);
    PyObject* version = PyTuple_GET_ITEM(t, 3);
    PyObject* alloc_gen = PyTuple_GET_ITEM(t, 4);
    PyObject* wi = PyTuple_GET_ITEM(t, 5);

    PyObject* cq = PyDict_GetItemWithError(snap_cqs, cq_name);  // borrowed
    if (cq == nullptr) {
      if (PyErr_Occurred()) return nullptr;
      continue;
    }

    PyObject* workloads = PyObject_GetAttr(cq, s_workloads);
    if (workloads == nullptr || !PyDict_Check(workloads)) {
      Py_XDECREF(workloads);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "cq.workloads must be a dict");
      return nullptr;
    }
    PyObject* acting_wi = nullptr;  // owned
    int failed = 0;
    if (sign > 0) {
      PyObject* key = PyObject_GetAttr(wi, s_key);
      failed = key == nullptr ||
               PyDict_SetItem(workloads, key, wi) != 0;
      Py_XDECREF(key);
      acting_wi = wi;
      Py_INCREF(acting_wi);
    } else {
      PyObject* key = PyObject_GetAttr(wl, s_key);
      if (key == nullptr) {
        failed = 1;
      } else {
        acting_wi = PyDict_GetItemWithError(workloads, key);
        if (acting_wi == nullptr) {
          // Not mirrored (already removed) — nothing to apply.
          Py_DECREF(key);
          Py_DECREF(workloads);
          if (PyErr_Occurred()) return nullptr;
          continue;
        }
        Py_INCREF(acting_wi);
        failed = PyDict_DelItem(workloads, key) != 0;
        Py_DECREF(key);
      }
    }
    Py_DECREF(workloads);
    if (failed) {
      Py_XDECREF(acting_wi);
      return nullptr;
    }

    // cq.usage_version += 1
    PyObject* uv = PyObject_GetAttr(cq, s_usage_version);
    if (uv == nullptr) {
      Py_DECREF(acting_wi);
      return nullptr;
    }
    PyObject* one = PyLong_FromLong(1);
    PyObject* uv2 = PyNumber_Add(uv, one);
    Py_DECREF(uv);
    Py_DECREF(one);
    if (uv2 == nullptr || PyObject_SetAttr(cq, s_usage_version, uv2) != 0) {
      Py_XDECREF(uv2);
      Py_DECREF(acting_wi);
      return nullptr;
    }
    Py_DECREF(uv2);

    // Usage walk: clone's own usage + cohort usage (tracked pairs).
    PyObject* triples = PyObject_GetAttr(acting_wi, s_usage_triples);
    Py_DECREF(acting_wi);
    if (triples == nullptr) return nullptr;
    PyObject* usage = PyObject_GetAttr(cq, s_usage);
    PyObject* cohort = PyObject_GetAttr(cq, s_cohort);
    PyObject* cohort_usage = nullptr;
    if (usage != nullptr && cohort != nullptr && cohort != Py_None)
      cohort_usage = PyObject_GetAttr(cohort, s_usage);
    Py_XDECREF(cohort);
    if (usage == nullptr || !PyDict_Check(usage) || !PyList_Check(triples)) {
      Py_XDECREF(usage);
      Py_XDECREF(cohort_usage);
      Py_DECREF(triples);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "usage walk type mismatch");
      return nullptr;
    }
    Py_ssize_t tn = PyList_GET_SIZE(triples);
    for (Py_ssize_t j = 0; j < tn; ++j) {
      PyObject* tr = PyList_GET_ITEM(triples, j);
      if (!PyTuple_Check(tr) || PyTuple_GET_SIZE(tr) != 3) continue;
      PyObject* flv = PyTuple_GET_ITEM(tr, 0);
      PyObject* res = PyTuple_GET_ITEM(tr, 1);
      PyObject* v = PyTuple_GET_ITEM(tr, 2);
      if (bump_tracked(usage, flv, res, v, sign) != 0 ||
          (cohort_usage != nullptr &&
           bump_tracked(cohort_usage, flv, res, v, sign) != 0)) {
        Py_DECREF(usage);
        Py_XDECREF(cohort_usage);
        Py_DECREF(triples);
        return nullptr;
      }
    }
    Py_DECREF(usage);
    Py_XDECREF(cohort_usage);
    Py_DECREF(triples);

    if (sign <= 0 &&
        PyObject_SetAttr(cq, s_allocatable_generation, alloc_gen) != 0)
      return nullptr;

    PyObject* name = PyObject_GetAttr(cq, s_name);
    if (name == nullptr) return nullptr;
    int rc = PyDict_SetItem(base, name, version);
    Py_DECREF(name);
    if (rc != 0) return nullptr;
    ++applied;
  }
  return PyLong_FromLong(applied);
}

// assume_batch(cluster_queues, assumed, local_queues, lq_stats, items,
//              out) -> None
//
// Cache.assume_workloads' per-item walk (cache.py) in native form —
// caller holds the cache lock and has verified every item carries
// (wl, triples!=None, info!=None, admitted!=None); mixed batches stay on
// the Python twin. Per item: duplicate/missing-CQ checks (error strings
// appended exactly like the Python loop), plant the precomputed triples
// on the info, insert into cq.workloads, bump usage_version, fan dirty
// marks to the registered sinks, walk the triples into cq.usage (+ the
// admitted split), apply the LocalQueue stats (reservation/admitted
// usage, keyed admitted set), and record the assumption. At north-star
// scale this commits ~1k admissions/tick and the interpreter overhead of
// the Python twin dominated the flush phase.
PyObject* assume_batch(PyObject*, PyObject* args) {
  PyObject *cluster_queues, *assumed, *local_queues, *lq_stats, *items, *out;
  if (!PyArg_ParseTuple(args, "OOOOOO", &cluster_queues, &assumed,
                        &local_queues, &lq_stats, &items, &out))
    return nullptr;
  if (!PyDict_Check(cluster_queues) || !PyDict_Check(assumed) ||
      !PyDict_Check(local_queues) || !PyDict_Check(lq_stats) ||
      !PyList_Check(items) || !PyList_Check(out)) {
    PyErr_SetString(PyExc_TypeError,
                    "assume_batch(dict, dict, dict, dict, list, list)");
    return nullptr;
  }
  static PyObject *s_admission, *s_key, *s_cluster_queue, *s_workloads,
      *s_usage_version, *s_usage, *s_admitted_usage, *s_dirty_sinks, *s_name,
      *s_namespace, *s_queue_name, *s_usage_triples_priv, *s_reserving,
      *s_admitted, *s_admitted_keys, *s_reservation, *s_admitted_usage_key,
      *s_no_admission;
  if (s_admission == nullptr) {
    s_admission = PyUnicode_InternFromString("admission");
    s_key = PyUnicode_InternFromString("key");
    s_cluster_queue = PyUnicode_InternFromString("cluster_queue");
    s_workloads = PyUnicode_InternFromString("workloads");
    s_usage_version = PyUnicode_InternFromString("usage_version");
    s_usage = PyUnicode_InternFromString("usage");
    s_admitted_usage = PyUnicode_InternFromString("admitted_usage");
    s_dirty_sinks = PyUnicode_InternFromString("_dirty_sinks");
    s_name = PyUnicode_InternFromString("name");
    s_namespace = PyUnicode_InternFromString("namespace");
    s_queue_name = PyUnicode_InternFromString("queue_name");
    s_usage_triples_priv = PyUnicode_InternFromString("_usage_triples");
    s_reserving = PyUnicode_InternFromString("reserving");
    s_admitted = PyUnicode_InternFromString("admitted");
    s_admitted_keys = PyUnicode_InternFromString("admitted_keys");
    s_reservation = PyUnicode_InternFromString("reservation");
    s_admitted_usage_key = PyUnicode_InternFromString("admitted_usage");
    s_no_admission = PyUnicode_InternFromString("workload has no admission");
  }
  Py_ssize_t n = PyList_GET_SIZE(items);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(items, i);
    if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 4) {
      PyErr_SetString(PyExc_TypeError,
                      "item must be (wl, triples, info, admitted)");
      return nullptr;
    }
    PyObject* wl = PyTuple_GET_ITEM(item, 0);
    PyObject* triples = PyTuple_GET_ITEM(item, 1);
    PyObject* info = PyTuple_GET_ITEM(item, 2);
    PyObject* adm_o = PyTuple_GET_ITEM(item, 3);

    PyObject* admission = PyObject_GetAttr(wl, s_admission);
    if (admission == nullptr) return nullptr;
    if (admission == Py_None) {
      Py_DECREF(admission);
      if (PyList_Append(out, s_no_admission) != 0) return nullptr;
      continue;
    }
    PyObject* key = PyObject_GetAttr(wl, s_key);
    if (key == nullptr) {
      Py_DECREF(admission);
      return nullptr;
    }
    int dup = PyDict_Contains(assumed, key);
    if (dup != 0) {
      Py_DECREF(admission);
      if (dup < 0) {
        Py_DECREF(key);
        return nullptr;
      }
      PyObject* msg =
          PyUnicode_FromFormat("workload %U already assumed", key);
      Py_DECREF(key);
      if (msg == nullptr || PyList_Append(out, msg) != 0) {
        Py_XDECREF(msg);
        return nullptr;
      }
      Py_DECREF(msg);
      continue;
    }
    PyObject* cq_name = PyObject_GetAttr(admission, s_cluster_queue);
    Py_DECREF(admission);
    if (cq_name == nullptr) {
      Py_DECREF(key);
      return nullptr;
    }
    PyObject* cq = PyDict_GetItemWithError(cluster_queues, cq_name);
    if (cq == nullptr) {
      if (PyErr_Occurred()) {
        Py_DECREF(key);
        Py_DECREF(cq_name);
        return nullptr;
      }
      PyObject* msg =
          PyUnicode_FromFormat("ClusterQueue %U not found", cq_name);
      Py_DECREF(key);
      Py_DECREF(cq_name);
      if (msg == nullptr || PyList_Append(out, msg) != 0) {
        Py_XDECREF(msg);
        return nullptr;
      }
      Py_DECREF(msg);
      continue;
    }
    // The caller guarantees info.cluster_queue == admission.cluster_queue
    // (assume_workloads only passes the entry's own info); plant the
    // precomputed flattened triples exactly like the Python loop.
    if (PyObject_SetAttr(info, s_usage_triples_priv, triples) != 0) {
      Py_DECREF(key);
      Py_DECREF(cq_name);
      return nullptr;
    }
    int adm = PyObject_IsTrue(adm_o);
    if (adm < 0) {
      Py_DECREF(key);
      Py_DECREF(cq_name);
      return nullptr;
    }

    // cq.add_workload_usage(wi, admitted=adm), inlined:
    // workloads[key] = wi; usage_version += 1; dirty marks; usage walk.
    PyObject* workloads = PyObject_GetAttr(cq, s_workloads);
    int failed = workloads == nullptr || !PyDict_Check(workloads) ||
                 PyDict_SetItem(workloads, key, info) != 0;
    Py_XDECREF(workloads);
    if (!failed) {
      PyObject* uv = PyObject_GetAttr(cq, s_usage_version);
      if (uv != nullptr) {
        PyObject* one = PyLong_FromLong(1);
        PyObject* uv2 = one ? PyNumber_Add(uv, one) : nullptr;
        Py_XDECREF(one);
        failed = uv2 == nullptr ||
                 PyObject_SetAttr(cq, s_usage_version, uv2) != 0;
        Py_XDECREF(uv2);
        Py_DECREF(uv);
      } else {
        failed = 1;
      }
    }
    if (!failed) {
      PyObject* sinks = PyObject_GetAttr(cq, s_dirty_sinks);
      if (sinks == nullptr) {
        failed = 1;
      } else if (sinks != Py_None) {
        PyObject* name = PyObject_GetAttr(cq, s_name);
        if (name == nullptr) {
          failed = 1;
        } else {
          PyObject* it = PyObject_GetIter(sinks);
          if (it == nullptr) {
            failed = 1;
          } else {
            PyObject* sink;
            while (!failed && (sink = PyIter_Next(it)) != nullptr) {
              failed = PySet_Add(sink, name) != 0;
              Py_DECREF(sink);
            }
            if (PyErr_Occurred()) failed = 1;
            Py_DECREF(it);
          }
          Py_DECREF(name);
        }
      }
      Py_XDECREF(sinks);
    }
    if (!failed) {
      // _apply_usage(wi, +1, cohort_too=False, admitted=adm): own usage
      // + admitted split, tracked pairs only (no cohort walk here).
      PyObject* usage = PyObject_GetAttr(cq, s_usage);
      PyObject* adm_usage =
          adm ? PyObject_GetAttr(cq, s_admitted_usage) : nullptr;
      if (usage == nullptr || (adm && adm_usage == nullptr)) {
        failed = 1;
      } else if (PyList_Check(triples)) {
        Py_ssize_t nt = PyList_GET_SIZE(triples);
        for (Py_ssize_t k = 0; !failed && k < nt; ++k) {
          PyObject* t = PyList_GET_ITEM(triples, k);
          if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
            PyErr_SetString(PyExc_TypeError, "triple must be (flv, res, v)");
            failed = 1;
            break;
          }
          PyObject* flv = PyTuple_GET_ITEM(t, 0);
          PyObject* res = PyTuple_GET_ITEM(t, 1);
          PyObject* v = PyTuple_GET_ITEM(t, 2);
          if (bump_tracked(usage, flv, res, v, 1) != 0 ||
              (adm_usage != nullptr &&
               bump_tracked(adm_usage, flv, res, v, 1) != 0))
            failed = 1;
        }
      } else {
        PyErr_SetString(PyExc_TypeError, "triples must be a list");
        failed = 1;
      }
      Py_XDECREF(usage);
      Py_XDECREF(adm_usage);
    }
    if (!failed) {
      // _lq_note(wi, +1, adm): stats keyed "namespace/queue_name",
      // gated on the LocalQueue pointing at this same ClusterQueue.
      PyObject* ns = PyObject_GetAttr(wl, s_namespace);
      PyObject* qn = ns ? PyObject_GetAttr(wl, s_queue_name) : nullptr;
      PyObject* lq_key = qn ? PyUnicode_FromFormat("%U/%U", ns, qn) : nullptr;
      Py_XDECREF(ns);
      Py_XDECREF(qn);
      if (lq_key == nullptr) {
        failed = 1;
      } else {
        PyObject* stats = PyDict_GetItemWithError(lq_stats, lq_key);
        PyObject* lq = stats != nullptr
                           ? PyDict_GetItemWithError(local_queues, lq_key)
                           : nullptr;
        if (PyErr_Occurred()) failed = 1;
        if (!failed && stats != nullptr && lq != nullptr) {
          PyObject* lq_cq = PyObject_GetAttr(lq, s_cluster_queue);
          if (lq_cq == nullptr) {
            failed = 1;
          } else {
            int same = PyObject_RichCompareBool(lq_cq, cq_name, Py_EQ);
            Py_DECREF(lq_cq);
            if (same < 0) failed = 1;
            if (!failed && same == 1) {
              PyObject* resv = PyDict_GetItem(stats, s_reserving);
              PyObject* one = PyLong_FromLong(1);
              PyObject* r2 =
                  (resv && one) ? PyNumber_Add(resv, one) : nullptr;
              failed = r2 == nullptr ||
                       PyDict_SetItem(stats, s_reserving, r2) != 0;
              Py_XDECREF(r2);
              if (!failed && adm) {
                PyObject* keys = PyDict_GetItem(stats, s_admitted_keys);
                failed = keys == nullptr || PySet_Add(keys, key) != 0;
                if (!failed) {
                  PyObject* a = PyDict_GetItem(stats, s_admitted);
                  PyObject* a2 = a ? PyNumber_Add(a, one) : nullptr;
                  failed = a2 == nullptr ||
                           PyDict_SetItem(stats, s_admitted, a2) != 0;
                  Py_XDECREF(a2);
                }
              }
              Py_XDECREF(one);
              if (!failed) {
                PyObject* resd = PyDict_GetItem(stats, s_reservation);
                PyObject* admd =
                    adm ? PyDict_GetItem(stats, s_admitted_usage_key)
                        : nullptr;
                if (resd == nullptr) {
                  failed = 1;
                } else {
                  Py_ssize_t nt = PyList_GET_SIZE(triples);
                  for (Py_ssize_t k = 0; !failed && k < nt; ++k) {
                    PyObject* t = PyList_GET_ITEM(triples, k);
                    PyObject* flv = PyTuple_GET_ITEM(t, 0);
                    PyObject* res = PyTuple_GET_ITEM(t, 1);
                    PyObject* v = PyTuple_GET_ITEM(t, 2);
                    if (bump_create(resd, flv, res, v, 1) != 0 ||
                        (admd != nullptr &&
                         bump_create(admd, flv, res, v, 1) != 0))
                      failed = 1;
                  }
                }
              }
            }
          }
        }
        Py_DECREF(lq_key);
      }
    }
    if (!failed) failed = PyDict_SetItem(assumed, key, cq_name) != 0;
    if (!failed) failed = PyList_Append(out, info) != 0;
    Py_DECREF(key);
    Py_DECREF(cq_name);
    if (failed) {
      // Borrowed-reference misses (a malformed _lq_stats entry) reach
      // here without an exception set; never return NULL bare.
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_KeyError,
                        "LocalQueue stats entry missing a required field");
      return nullptr;
    }
  }
  Py_RETURN_NONE;
}

// RAII int64 buffer view (PyBUF_ND keeps the shape available; PyBUF_FORMAT
// lets the dtype actually be verified — itemsize alone would admit
// float64/uint64 and silently reinterpret their bits).
struct NdBuf {
  Py_buffer view{};
  bool ok = false;
  NdBuf(PyObject* o, bool writable) {
    if (PyObject_GetBuffer(o, &view,
                           PyBUF_ND | PyBUF_FORMAT |
                               (writable ? PyBUF_WRITABLE : 0)) == 0) {
      const char* f = view.format;
      if (view.itemsize == 8 && f != nullptr &&
          (f[0] == 'q' || f[0] == 'l') && f[1] == '\0') {
        ok = true;
      } else {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_TypeError, "expected an int64 array");
      }
    }
  }
  ~NdBuf() {
    if (ok) PyBuffer_Release(&view);
  }
  const long long* data() const { return (const long long*)view.buf; }
  long long* wdata() const { return (long long*)view.buf; }
};

// hier_gate_fold(t, blim, lend, paths, nominal, usage, cq_lend,
//                ci, fis, ris, vals, do_gate, do_fold) -> bool
//
// Fused HierCycleState per-entry operation reading the solver's dense
// int64 tensors directly (no per-item Python scalar indexing):
//   gate  — the admission-cycle feasibility walk: each item's delta is
//           clamped through the ClusterQueue's own lending limit
//           (min(lend_cq, t_old) - min(lend_cq, t_old - val)), then
//           propagated up `paths[ci]` checking every ancestor balance
//           against its borrowing limit. Returns False on the first
//           violated node WITHOUT mutating anything.
//   fold  — the reservation charge: the raw value lands at the direct
//           cohort node (deliberately NOT through the CQ clamp — the
//           cycle's cohortsUsage semantics, see core/hierarchy.py) and
//           propagates up through each node's lending clamp, mutating t.
// With both flags set the fold only runs when the gate passes — the
// scheduler's FIT-entry sequence (gate, then reserve) in ONE call.
//
// t: flat [K2*F*R] writable; blim/lend: flat [K2*F*R]; paths: [C,D]
// (raw node ids, -1 padded); nominal/usage/cq_lend: [C,F,R]. All int64.
PyObject* hier_gate_fold(PyObject*, PyObject* args) {
  PyObject *t_o, *blim_o, *lend_o, *paths_o, *nom_o, *use_o, *cql_o;
  PyObject *fis_o, *ris_o, *vals_o;
  int ci, do_gate, do_fold;
  if (!PyArg_ParseTuple(args, "OOOOOOOiOOOpp", &t_o, &blim_o, &lend_o,
                        &paths_o, &nom_o, &use_o, &cql_o, &ci, &fis_o,
                        &ris_o, &vals_o, &do_gate, &do_fold))
    return nullptr;
  NdBuf t(t_o, true), blim(blim_o, false), lend(lend_o, false),
      paths(paths_o, false), nom(nom_o, false), use(use_o, false),
      cql(cql_o, false);
  if (!t.ok || !blim.ok || !lend.ok || !paths.ok || !nom.ok || !use.ok ||
      !cql.ok)
    return nullptr;
  if (nom.view.ndim != 3 || paths.view.ndim != 2) {
    PyErr_SetString(PyExc_TypeError,
                    "hier_gate_fold: nominal must be [C,F,R], paths [C,D]");
    return nullptr;
  }
  const Py_ssize_t R = nom.view.shape[2];
  const Py_ssize_t FR = nom.view.shape[1] * R;
  const Py_ssize_t D = paths.view.shape[1];
  const long long* path = paths.data() + (Py_ssize_t)ci * D;
  PyObject* fis = PySequence_Fast(fis_o, "fis must be a sequence");
  PyObject* ris = fis ? PySequence_Fast(ris_o, "ris must be a sequence")
                      : nullptr;
  PyObject* vals = ris ? PySequence_Fast(vals_o, "vals must be a sequence")
                       : nullptr;
  if (vals == nullptr) {
    Py_XDECREF(fis);
    Py_XDECREF(ris);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fis);
  if (PySequence_Fast_GET_SIZE(ris) != n ||
      PySequence_Fast_GET_SIZE(vals) != n) {
    PyErr_SetString(PyExc_ValueError, "fis/ris/vals length mismatch");
    n = -1;
  }
  const long long* td = t.data();
  long long* tw = t.wdata();
  const long long* blimd = blim.data();
  const long long* lendd = lend.data();
  const long long* nomd = nom.data();
  const long long* used = use.data();
  const long long* cqld = cql.data();
  bool fail = n < 0;
  bool blocked = false;
  for (int phase = 0; !fail && !blocked && phase < 2; ++phase) {
    if (phase == 0 ? !do_gate : (!do_fold)) continue;
    for (Py_ssize_t i = 0; !fail && i < n; ++i) {
      long long fi = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fis, i));
      long long ri = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(ris, i));
      long long val = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(vals, i));
      if (PyErr_Occurred()) {
        fail = true;
        break;
      }
      const Py_ssize_t off = (Py_ssize_t)(fi * R + ri);
      long long delta;
      if (phase == 0) {
        const Py_ssize_t base = (Py_ssize_t)ci * FR + off;
        const long long t_old = nomd[base] - used[base];
        const long long lcq = cqld[base];
        delta = (lcq < t_old ? lcq : t_old) -
                (lcq < t_old - val ? lcq : t_old - val);
      } else {
        delta = val;
      }
      for (Py_ssize_t d = 0; d < D; ++d) {
        const long long node = path[d];
        if (node < 0 || (phase == 1 && delta == 0)) break;
        const Py_ssize_t idx = (Py_ssize_t)node * FR + off;
        const long long tv = td[idx];
        const long long tn = tv - delta;
        if (phase == 0) {
          if (tn < -blimd[idx]) {
            blocked = true;
            break;
          }
        } else {
          tw[idx] = tn;
        }
        const long long l = lendd[idx];
        delta = (l < tv ? l : tv) - (l < tn ? l : tn);
      }
      if (blocked) break;
    }
  }
  Py_DECREF(fis);
  Py_DECREF(ris);
  Py_DECREF(vals);
  if (fail) return nullptr;
  if (blocked) Py_RETURN_FALSE;
  Py_RETURN_TRUE;
}

PyMethodDef methods[] = {
    {"apply_triples", apply_triples, METH_VARARGS,
     "Fused tracked-pair usage walk (cache/_apply_usage semantics)."},
    {"lq_apply", lq_apply, METH_VARARGS,
     "Setdefault-style LocalQueue stats walk (Cache._lq_apply semantics)."},
    {"flush_mirror", flush_mirror, METH_VARARGS,
     "SnapshotMirror.flush_pending loop (lockstep add/remove walk)."},
    {"hier_gate_fold", hier_gate_fold, METH_VARARGS,
     "Fused HierCycleState gate+fold on dense int64 tensors."},
    {"assume_batch", assume_batch, METH_VARARGS,
     "Cache.assume_workloads commit loop (caller holds the cache lock)."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_kueue_ledger",
                         "Native usage-ledger walks.", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__kueue_ledger(void) {
  return PyModule_Create(&moduledef);
}
