// Native usage-ledger walks for the admission hot path.
//
// The cache and the snapshot mirror account workload usage in nested
// {flavor: {resource: int}} dicts (the FlavorResourceQuantities shape of
// reference pkg/cache/clusterqueue.go:473-508). At north-star scale the
// fused Python walk over a workload's usage triples — update the CQ's own
// usage, the admitted split, and the (non-lending) cohort usage — runs
// thousands of times per tick across assume/forget, the mirror's lockstep
// deltas, and preemption simulation. This extension runs the same walk
// through the CPython dict API: identical semantics (only pairs already
// present in a target dict are tracked), several times faster.
//
// Exposed functions:
//   apply_triples(usage, admitted_or_None, cohort_or_None, triples, sign)
//     -> None; triples = [(flavor:str, resource:str, value:int), ...]
//   lq_apply(reservation, admitted_usage_or_None, triples, sign)
//     -> None; setdefault-style accumulation (missing keys are created,
//     matching Cache._lq_apply).
//
// Arithmetic uses long long with overflow detection; any value that does
// not fit (absurd for milli-quantities, but the API allows arbitrary
// ints) falls back to PyNumber_Add so results stay exact.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

// old + v*sign with exact semantics; returns new reference or nullptr.
PyObject* add_scaled(PyObject* old_val, PyObject* v, long sign) {
  int of1 = 0, of2 = 0;
  long long a = PyLong_AsLongLongAndOverflow(old_val, &of1);
  long long b = PyLong_AsLongLongAndOverflow(v, &of2);
  if (!of1 && !of2 && (a != -1 || !PyErr_Occurred()) &&
      (b != -1 || !PyErr_Occurred())) {
    long long scaled;
    long long sum;
    if (!__builtin_mul_overflow(b, (long long)sign, &scaled) &&
        !__builtin_add_overflow(a, scaled, &sum)) {
      return PyLong_FromLongLong(sum);
    }
  }
  PyErr_Clear();
  // Arbitrary-precision fallback.
  PyObject* s = PyLong_FromLong(sign);
  if (s == nullptr) return nullptr;
  PyObject* scaled = PyNumber_Multiply(v, s);
  Py_DECREF(s);
  if (scaled == nullptr) return nullptr;
  PyObject* out = PyNumber_Add(old_val, scaled);
  Py_DECREF(scaled);
  return out;
}

// Add v*sign to target[flv][res] when both keys exist (tracked pairs
// only — Cache._apply_usage semantics). Returns 0 on success.
int bump_tracked(PyObject* target, PyObject* flv, PyObject* res, PyObject* v,
                 long sign) {
  PyObject* inner = PyDict_GetItemWithError(target, flv);  // borrowed
  if (inner == nullptr) return PyErr_Occurred() ? -1 : 0;
  if (!PyDict_Check(inner)) return 0;
  PyObject* old_val = PyDict_GetItemWithError(inner, res);  // borrowed
  if (old_val == nullptr) return PyErr_Occurred() ? -1 : 0;
  PyObject* out = add_scaled(old_val, v, sign);
  if (out == nullptr) return -1;
  int rc = PyDict_SetItem(inner, res, out);
  Py_DECREF(out);
  return rc;
}

// Add v*sign to target[flv][res], creating missing levels
// (Cache._lq_apply semantics).
int bump_create(PyObject* target, PyObject* flv, PyObject* res, PyObject* v,
                long sign) {
  PyObject* inner = PyDict_GetItemWithError(target, flv);  // borrowed
  if (inner == nullptr) {
    if (PyErr_Occurred()) return -1;
    PyObject* fresh = PyDict_New();
    if (fresh == nullptr || PyDict_SetItem(target, flv, fresh) != 0) {
      Py_XDECREF(fresh);
      return -1;
    }
    inner = fresh;  // still owned by target after SetItem
    Py_DECREF(fresh);
  }
  PyObject* old_val = PyDict_GetItemWithError(inner, res);  // borrowed
  PyObject* out;
  if (old_val == nullptr) {
    if (PyErr_Occurred()) return -1;
    long long b;
    int of = 0;
    b = PyLong_AsLongLongAndOverflow(v, &of);
    if (!of && (b != -1 || !PyErr_Occurred())) {
      long long scaled;
      if (!__builtin_mul_overflow(b, (long long)sign, &scaled))
        out = PyLong_FromLongLong(scaled);
      else
        out = nullptr;
    } else {
      out = nullptr;
    }
    if (out == nullptr) {
      PyErr_Clear();
      PyObject* s = PyLong_FromLong(sign);
      out = s ? PyNumber_Multiply(v, s) : nullptr;
      Py_XDECREF(s);
    }
  } else {
    out = add_scaled(old_val, v, sign);
  }
  if (out == nullptr) return -1;
  int rc = PyDict_SetItem(inner, res, out);
  Py_DECREF(out);
  return rc;
}

// apply_triples(usage, admitted_or_None, cohort_or_None, triples, sign)
PyObject* apply_triples(PyObject*, PyObject* args) {
  PyObject *usage, *admitted, *cohort, *triples;
  int sign;
  if (!PyArg_ParseTuple(args, "OOOOi", &usage, &admitted, &cohort, &triples,
                        &sign))
    return nullptr;
  if (!PyDict_Check(usage) || !PyList_Check(triples)) {
    PyErr_SetString(PyExc_TypeError, "apply_triples(dict, ..., list, int)");
    return nullptr;
  }
  bool has_adm = admitted != Py_None;
  bool has_coh = cohort != Py_None;
  Py_ssize_t n = PyList_GET_SIZE(triples);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(triples, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
      PyErr_SetString(PyExc_TypeError, "triple must be (flv, res, v)");
      return nullptr;
    }
    PyObject* flv = PyTuple_GET_ITEM(t, 0);
    PyObject* res = PyTuple_GET_ITEM(t, 1);
    PyObject* v = PyTuple_GET_ITEM(t, 2);
    if (bump_tracked(usage, flv, res, v, sign) != 0) return nullptr;
    if (has_adm && bump_tracked(admitted, flv, res, v, sign) != 0)
      return nullptr;
    if (has_coh && bump_tracked(cohort, flv, res, v, sign) != 0)
      return nullptr;
  }
  Py_RETURN_NONE;
}

// lq_apply(reservation, admitted_usage_or_None, triples, sign)
PyObject* lq_apply(PyObject*, PyObject* args) {
  PyObject *reservation, *admitted_usage, *triples;
  int sign;
  if (!PyArg_ParseTuple(args, "OOOi", &reservation, &admitted_usage, &triples,
                        &sign))
    return nullptr;
  if (!PyDict_Check(reservation) || !PyList_Check(triples)) {
    PyErr_SetString(PyExc_TypeError, "lq_apply(dict, ..., list, int)");
    return nullptr;
  }
  bool has_adm = admitted_usage != Py_None;
  Py_ssize_t n = PyList_GET_SIZE(triples);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(triples, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
      PyErr_SetString(PyExc_TypeError, "triple must be (flv, res, v)");
      return nullptr;
    }
    PyObject* flv = PyTuple_GET_ITEM(t, 0);
    PyObject* res = PyTuple_GET_ITEM(t, 1);
    PyObject* v = PyTuple_GET_ITEM(t, 2);
    if (bump_create(reservation, flv, res, v, sign) != 0) return nullptr;
    if (has_adm && bump_create(admitted_usage, flv, res, v, sign) != 0)
      return nullptr;
  }
  Py_RETURN_NONE;
}

// flush_mirror(snap_cqs, base, items) -> applied count
//
// The SnapshotMirror.flush_pending loop (snapshot.py) in native form: each
// item is (sign, workload, cq_name, version, alloc_gen, info_or_None)
// exactly as note_admission/note_removal queued it. Per item: resolve the
// snapshot clone by the note-time ClusterQueue name, insert/remove the info
// in the clone's workload map, bump its usage_version, walk the info's
// usage triples into the clone's own usage and (when cohorted) the cohort
// usage — tracked pairs only, identical to _apply_usage with
// admitted=False — and record the cache version in `base`. At north-star
// scale this loop folds ~1.3k completion/admission mutations per tick and
// the interpreter overhead of the Python twin dominated the snapshot
// phase. The caller (flush_pending) only dispatches here when LendingLimit
// is disabled and every addition carries its info; the Python twin remains
// the lending-path / fallback implementation.
PyObject* flush_mirror(PyObject*, PyObject* args) {
  PyObject *snap_cqs, *base, *items;
  if (!PyArg_ParseTuple(args, "OOO", &snap_cqs, &base, &items))
    return nullptr;
  if (!PyDict_Check(snap_cqs) || !PyDict_Check(base) ||
      !PyList_Check(items)) {
    PyErr_SetString(PyExc_TypeError, "flush_mirror(dict, dict, list)");
    return nullptr;
  }
  static PyObject *s_key, *s_workloads,
      *s_usage_version, *s_usage_triples, *s_usage, *s_cohort,
      *s_allocatable_generation, *s_name;
  if (s_key == nullptr) {
    s_key = PyUnicode_InternFromString("key");
    s_workloads = PyUnicode_InternFromString("workloads");
    s_usage_version = PyUnicode_InternFromString("usage_version");
    s_usage_triples = PyUnicode_InternFromString("usage_triples");
    s_usage = PyUnicode_InternFromString("usage");
    s_cohort = PyUnicode_InternFromString("cohort");
    s_allocatable_generation =
        PyUnicode_InternFromString("allocatable_generation");
    s_name = PyUnicode_InternFromString("name");
  }
  long applied = 0;
  Py_ssize_t n = PyList_GET_SIZE(items);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(items, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 6) {
      PyErr_SetString(PyExc_TypeError,
                      "item must be (sign, wl, cq_name, version, gen, info)");
      return nullptr;
    }
    long sign = PyLong_AsLong(PyTuple_GET_ITEM(t, 0));
    if (sign == -1 && PyErr_Occurred()) return nullptr;
    PyObject* wl = PyTuple_GET_ITEM(t, 1);
    PyObject* cq_name = PyTuple_GET_ITEM(t, 2);
    PyObject* version = PyTuple_GET_ITEM(t, 3);
    PyObject* alloc_gen = PyTuple_GET_ITEM(t, 4);
    PyObject* wi = PyTuple_GET_ITEM(t, 5);

    PyObject* cq = PyDict_GetItemWithError(snap_cqs, cq_name);  // borrowed
    if (cq == nullptr) {
      if (PyErr_Occurred()) return nullptr;
      continue;
    }

    PyObject* workloads = PyObject_GetAttr(cq, s_workloads);
    if (workloads == nullptr || !PyDict_Check(workloads)) {
      Py_XDECREF(workloads);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "cq.workloads must be a dict");
      return nullptr;
    }
    PyObject* acting_wi = nullptr;  // owned
    int failed = 0;
    if (sign > 0) {
      PyObject* key = PyObject_GetAttr(wi, s_key);
      failed = key == nullptr ||
               PyDict_SetItem(workloads, key, wi) != 0;
      Py_XDECREF(key);
      acting_wi = wi;
      Py_INCREF(acting_wi);
    } else {
      PyObject* key = PyObject_GetAttr(wl, s_key);
      if (key == nullptr) {
        failed = 1;
      } else {
        acting_wi = PyDict_GetItemWithError(workloads, key);
        if (acting_wi == nullptr) {
          // Not mirrored (already removed) — nothing to apply.
          Py_DECREF(key);
          Py_DECREF(workloads);
          if (PyErr_Occurred()) return nullptr;
          continue;
        }
        Py_INCREF(acting_wi);
        failed = PyDict_DelItem(workloads, key) != 0;
        Py_DECREF(key);
      }
    }
    Py_DECREF(workloads);
    if (failed) {
      Py_XDECREF(acting_wi);
      return nullptr;
    }

    // cq.usage_version += 1
    PyObject* uv = PyObject_GetAttr(cq, s_usage_version);
    if (uv == nullptr) {
      Py_DECREF(acting_wi);
      return nullptr;
    }
    PyObject* one = PyLong_FromLong(1);
    PyObject* uv2 = PyNumber_Add(uv, one);
    Py_DECREF(uv);
    Py_DECREF(one);
    if (uv2 == nullptr || PyObject_SetAttr(cq, s_usage_version, uv2) != 0) {
      Py_XDECREF(uv2);
      Py_DECREF(acting_wi);
      return nullptr;
    }
    Py_DECREF(uv2);

    // Usage walk: clone's own usage + cohort usage (tracked pairs).
    PyObject* triples = PyObject_GetAttr(acting_wi, s_usage_triples);
    Py_DECREF(acting_wi);
    if (triples == nullptr) return nullptr;
    PyObject* usage = PyObject_GetAttr(cq, s_usage);
    PyObject* cohort = PyObject_GetAttr(cq, s_cohort);
    PyObject* cohort_usage = nullptr;
    if (usage != nullptr && cohort != nullptr && cohort != Py_None)
      cohort_usage = PyObject_GetAttr(cohort, s_usage);
    Py_XDECREF(cohort);
    if (usage == nullptr || !PyDict_Check(usage) || !PyList_Check(triples)) {
      Py_XDECREF(usage);
      Py_XDECREF(cohort_usage);
      Py_DECREF(triples);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "usage walk type mismatch");
      return nullptr;
    }
    Py_ssize_t tn = PyList_GET_SIZE(triples);
    for (Py_ssize_t j = 0; j < tn; ++j) {
      PyObject* tr = PyList_GET_ITEM(triples, j);
      if (!PyTuple_Check(tr) || PyTuple_GET_SIZE(tr) != 3) continue;
      PyObject* flv = PyTuple_GET_ITEM(tr, 0);
      PyObject* res = PyTuple_GET_ITEM(tr, 1);
      PyObject* v = PyTuple_GET_ITEM(tr, 2);
      if (bump_tracked(usage, flv, res, v, sign) != 0 ||
          (cohort_usage != nullptr &&
           bump_tracked(cohort_usage, flv, res, v, sign) != 0)) {
        Py_DECREF(usage);
        Py_XDECREF(cohort_usage);
        Py_DECREF(triples);
        return nullptr;
      }
    }
    Py_DECREF(usage);
    Py_XDECREF(cohort_usage);
    Py_DECREF(triples);

    if (sign <= 0 &&
        PyObject_SetAttr(cq, s_allocatable_generation, alloc_gen) != 0)
      return nullptr;

    PyObject* name = PyObject_GetAttr(cq, s_name);
    if (name == nullptr) return nullptr;
    int rc = PyDict_SetItem(base, name, version);
    Py_DECREF(name);
    if (rc != 0) return nullptr;
    ++applied;
  }
  return PyLong_FromLong(applied);
}

// hier_entry(t, blim, lend, path, pairs, fold) -> bool
//
// The HierCycleState per-entry ancestor walk (ops/hier_cycle.py
// fits/fold) in native form. `t`/`blim`/`lend` are the state's flat
// Python-int lists indexed node*FR+offset; `path` is the entry's
// ancestor node list PRE-MULTIPLIED by FR (-FR-padded sentinels stay
// negative); `pairs` is [(offset, delta)] where offset = fi*R + ri and
// delta is the leaf-level delta (the CQ lending clamp applied
// host-side for checks; the raw reserve value for folds). With fold=0
// this checks every balance against the borrowing limit and mutates
// nothing; with fold=1 it charges the delta at each node and writes the
// new balances back. All arithmetic is long long — values are bounded
// by the NO_LIMIT sentinel (2^62).
PyObject* hier_entry(PyObject*, PyObject* args) {
  PyObject *t_l, *blim_l, *lend_l, *path, *pairs;
  int fold;
  if (!PyArg_ParseTuple(args, "OOOOOi", &t_l, &blim_l, &lend_l, &path,
                        &pairs, &fold))
    return nullptr;
  if (!PyList_Check(t_l) || !PyList_Check(blim_l) || !PyList_Check(lend_l) ||
      !PyList_Check(path) || !PyList_Check(pairs)) {
    PyErr_SetString(PyExc_TypeError, "hier_entry(list x5, int)");
    return nullptr;
  }
  Py_ssize_t depth = PyList_GET_SIZE(path);
  Py_ssize_t np_ = PyList_GET_SIZE(pairs);
  for (Py_ssize_t p = 0; p < np_; ++p) {
    PyObject* pr = PyList_GET_ITEM(pairs, p);
    if (!PyTuple_Check(pr) || PyTuple_GET_SIZE(pr) != 2) {
      PyErr_SetString(PyExc_TypeError, "pair must be (offset, delta)");
      return nullptr;
    }
    long long off = PyLong_AsLongLong(PyTuple_GET_ITEM(pr, 0));
    long long delta = PyLong_AsLongLong(PyTuple_GET_ITEM(pr, 1));
    if (PyErr_Occurred()) return nullptr;
    for (Py_ssize_t d = 0; d < depth; ++d) {
      // `path` holds node*FR (pre-multiplied by the caller), so the flat
      // index is just +offset (= fi*R + ri).
      long long node = PyLong_AsLongLong(PyList_GET_ITEM(path, d));
      if (PyErr_Occurred()) return nullptr;
      if (node < 0 || (fold && delta == 0)) break;
      Py_ssize_t idx = (Py_ssize_t)(node + off);
      long long t = PyLong_AsLongLong(PyList_GET_ITEM(t_l, idx));
      if (PyErr_Occurred()) return nullptr;
      long long t_new = t - delta;
      if (!fold) {
        long long blim = PyLong_AsLongLong(PyList_GET_ITEM(blim_l, idx));
        if (PyErr_Occurred()) return nullptr;
        if (t_new < -blim) Py_RETURN_FALSE;
      } else {
        PyObject* nv = PyLong_FromLongLong(t_new);
        if (nv == nullptr) return nullptr;
        if (PyList_SetItem(t_l, idx, nv) != 0) return nullptr;  // steals nv
      }
      long long lend = PyLong_AsLongLong(PyList_GET_ITEM(lend_l, idx));
      if (PyErr_Occurred()) return nullptr;
      long long c_old = lend < t ? lend : t;
      long long c_new = lend < t_new ? lend : t_new;
      delta = c_old - c_new;
    }
  }
  Py_RETURN_TRUE;
}

PyMethodDef methods[] = {
    {"apply_triples", apply_triples, METH_VARARGS,
     "Fused tracked-pair usage walk (cache/_apply_usage semantics)."},
    {"lq_apply", lq_apply, METH_VARARGS,
     "Setdefault-style LocalQueue stats walk (Cache._lq_apply semantics)."},
    {"flush_mirror", flush_mirror, METH_VARARGS,
     "SnapshotMirror.flush_pending loop (lockstep add/remove walk)."},
    {"hier_entry", hier_entry, METH_VARARGS,
     "HierCycleState per-entry ancestor walk (check or fold)."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_kueue_ledger",
                         "Native usage-ledger walks.", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__kueue_ledger(void) {
  return PyModule_Create(&moduledef);
}
