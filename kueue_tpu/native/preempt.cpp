// Batched minimalPreemptions victim scan (native engine).
//
// Semantics mirror the host referee scheduler/preemption._minimal_preemptions
// (itself golden against reference pkg/scheduler/preemption/preemption.go:
// 172-231 minimalPreemptions + :352-389 workloadFits) and the jittable
// device scan ops/preemption_scan._scan_core. The tick's independent victim
// searches arrive as dense batch tensors (ops/preemption_batch builds them
// from the ClusterQueue encoding and the lockstep usage tensor); this runs
// the sequential remove-until-fits / add-back refinement per problem at
// native speed. A remote-attached accelerator loses this race on link
// round-trips and small-int64 sequential work — the scan is runtime, not
// compute, so it belongs in C++ (the jax/pallas engines remain available
// and decision-equivalent for locally-attached devices).
//
// Layout (row-major):
//   usage0/nominal/guaranteed      [B][Y][FR] int64
//   wl_req/blim/requestable        [B][FR]    int64
//   cand_use                       [B][N][FR] int64
//   cand_y/cand_prio               [B][N]     int32
//   threshold                      [B]        int32
//   q_def                          [B][Y][FR] uint8
//   wl_req_mask/blim_def/res_mask  [B][FR]    uint8
//   cand_valid                     [B][N]     uint8
//   has_cohort/allow_b0/has_threshold [B]     uint8
// Outputs: victim [B][N] uint8, fits [B] uint8.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Problem {
    int64_t Y, FR, N;
    const int64_t *usage0, *nominal, *guaranteed;
    const int64_t *wl_req, *blim, *requestable;
    const int64_t *cand_use;
    const int32_t *cand_y, *cand_prio;
    const uint8_t *q_def, *wl_req_mask, *blim_def, *res_mask, *cand_valid;
    bool has_cohort, lending;
    int32_t threshold;
    bool has_threshold;
};

// workloadFits (preemption.go:352-389) over the dense pair grid.
static bool fits(const Problem& p, const std::vector<int64_t>& U,
                 bool allow_b) {
    const int64_t FR = p.FR;
    const uint8_t* t_def = p.q_def;  // row 0 = target
    // Own-CQ cap: nominal, or nominal+borrowingLimit when borrowing.
    const bool use_nominal = !p.has_cohort || !allow_b;
    for (int64_t f = 0; f < FR; f++) {
        if (!t_def[f] || !p.wl_req_mask[f]) continue;
        const int64_t own = U[f] + p.wl_req[f];
        if (use_nominal) {
            if (own > p.nominal[f]) return false;
        } else if (p.blim_def[f]) {
            // Subtraction form: nominal carries the BIG 2^62 sentinel where
            // undefined and user quotas reach 2^60+, so nominal + blim can
            // pass INT64_MAX (signed overflow, UB). own >= 0 and blim >= 0
            // keep own - blim in range. Mirrors the XLA scan's TRC02 fix.
            if (own - p.blim[f] > p.nominal[f]) return false;
        }
    }
    if (!p.has_cohort) return true;
    for (int64_t f = 0; f < FR; f++) {
        if (!t_def[f] || !p.wl_req_mask[f]) continue;
        int64_t above = 0;
        for (int64_t y = 0; y < p.Y; y++) {
            const int64_t d = U[y * FR + f] - p.guaranteed[y * FR + f];
            if (d > 0) above += d;
        }
        int64_t cohort_used = above;
        if (p.lending) {
            const int64_t u0 = U[f];
            const int64_t g0 = p.guaranteed[f];
            cohort_used += (u0 < g0 ? u0 : g0);
        }
        if (cohort_used + p.wl_req[f] > p.requestable[f]) return false;
    }
    return true;
}

static void solve_one(const Problem& p, uint8_t* victim, uint8_t* fits_out) {
    const int64_t FR = p.FR, N = p.N;
    std::vector<int64_t> U(p.usage0, p.usage0 + p.Y * FR);
    std::vector<uint8_t> taken(N, 0);
    bool allow_b = *fits_out;  // caller stashes allow_b0 here
    bool done = false;
    int64_t stop_idx = -1;

    for (int64_t i = 0; i < N && !done; i++) {
        if (!p.cand_valid[i]) continue;
        const int32_t y = p.cand_y[i];
        const bool is_target = (y == 0);
        if (!is_target) {
            // Skip candidates whose CQ stopped borrowing (the dynamic
            // re-check inside the loop, preemption.go:188-192).
            bool borrowing = false;
            for (int64_t f = 0; f < FR && !borrowing; f++) {
                if (p.res_mask[f] && p.q_def[y * FR + f] &&
                    U[y * FR + f] > p.nominal[y * FR + f])
                    borrowing = true;
            }
            if (!borrowing) continue;
            if (p.has_threshold && p.cand_prio[i] >= p.threshold)
                allow_b = false;
        }
        for (int64_t f = 0; f < FR; f++)
            U[y * FR + f] -= p.cand_use[i * FR + f];
        taken[i] = 1;
        if (fits(p, U, allow_b)) {
            done = true;
            stop_idx = i;
        }
    }

    if (!done) {
        *fits_out = 0;
        std::memset(victim, 0, N);
        return;
    }

    // Add-back refinement, reverse order, last-removed never re-added
    // (preemption.go:214-224).
    std::memset(victim, 0, N);
    for (int64_t i = N - 1; i >= 0; i--) {
        if (!taken[i] || i > stop_idx) continue;
        if (i == stop_idx) {
            victim[i] = 1;
            continue;
        }
        for (int64_t f = 0; f < FR; f++)
            U[p.cand_y[i] * FR + f] += p.cand_use[i * FR + f];
        if (!fits(p, U, allow_b)) {
            for (int64_t f = 0; f < FR; f++)
                U[p.cand_y[i] * FR + f] -= p.cand_use[i * FR + f];
            victim[i] = 1;
        }
    }
    *fits_out = 1;
}

}  // namespace

extern "C" void kueue_minimal_preemptions_batch(
    int64_t B, int64_t Y, int64_t FR, int64_t N,
    const int64_t* usage0, const int64_t* nominal, const int64_t* guaranteed,
    const int64_t* wl_req, const int64_t* blim, const int64_t* requestable,
    const int64_t* cand_use,
    const int32_t* cand_y, const int32_t* cand_prio, const int32_t* threshold,
    const uint8_t* q_def, const uint8_t* wl_req_mask, const uint8_t* blim_def,
    const uint8_t* res_mask, const uint8_t* cand_valid,
    const uint8_t* has_cohort, const uint8_t* allow_b0,
    const uint8_t* has_threshold, uint8_t lending,
    uint8_t* victim_out, uint8_t* fits_out) {
    for (int64_t b = 0; b < B; b++) {
        Problem p;
        p.Y = Y; p.FR = FR; p.N = N;
        p.usage0 = usage0 + b * Y * FR;
        p.nominal = nominal + b * Y * FR;
        p.guaranteed = guaranteed + b * Y * FR;
        p.wl_req = wl_req + b * FR;
        p.blim = blim + b * FR;
        p.requestable = requestable + b * FR;
        p.cand_use = cand_use + b * N * FR;
        p.cand_y = cand_y + b * N;
        p.cand_prio = cand_prio + b * N;
        p.q_def = q_def + b * Y * FR;
        p.wl_req_mask = wl_req_mask + b * FR;
        p.blim_def = blim_def + b * FR;
        p.res_mask = res_mask + b * FR;
        p.cand_valid = cand_valid + b * N;
        p.has_cohort = has_cohort[b];
        p.lending = lending;
        p.threshold = threshold[b];
        p.has_threshold = has_threshold[b];
        fits_out[b] = allow_b0[b];  // in/out: carries allow_b0 in
        solve_one(p, victim_out + b * N, fits_out + b);
    }
}
