"""Device kernels for the scheduler's hot ops.

  preemption_scan    minimalPreemptions as a device scan (JAX int64 path)
  preemption_pallas  the same scan as a hand-written Pallas TPU kernel

Quota math is exact integer arithmetic; enable x64 before any kernel is
traced (same switch as kueue_tpu.models).
"""

import jax

jax.config.update("jax_enable_x64", True)
