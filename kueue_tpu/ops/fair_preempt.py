"""Vectorized KEP-1714 fair-preemption victim search.

The host referee (`scheduler.preemption._fair_preemptions_host`) picks
victims round by round, re-deriving `dominant_resource_share` per
ClusterQueue per while-iteration — and, for the
LessThanOrEqualToFinalShare strategy, once per CANDIDATE per iteration —
as Python dict walks over the snapshot, with a full `order` re-sort each
round. At the fair-bench shape (1k CQs in one KEP-79 tree) that loop was
the last pre-PR-5 tax on the tick (BENCH_r04 fair p99 156ms vs the 69ms
northstar).

This module runs the SAME algorithm on precomputed tensors:

  * every candidate's committed usage row comes from the `AdmittedArena`
    in one fancy-index gather per candidate set (falling back to a
    one-time triples walk when a row is missing);
  * share-without-victim for the FinalShare strategy is one broadcast
    subtract + max-over-resources per (dirty) ClusterQueue, cached until
    that CQ's usage moves;
  * each strategy scan is a masked argmax over the per-CQ share vector
    (first-occurrence ties == the host's stable sort), with an
    incremental share/borrow/fits-state update per removed victim;
  * `workloadFits` runs vectorized over the preemptor's request pairs —
    flat cohorts against an incrementally-maintained lending-aware pool,
    hierarchical trees against locally-held KEP-79 node balances (the
    same T aggregation as ops/hier_cycle, updated per removal through
    the lending clamps).

Decision identity: the search consumes and mutates ONLY local copies (the
snapshot is never touched), and the host referee stays the oracle —
`KUEUE_TPU_NO_DEVICE_FAIR=1` restores it everywhere, and the randomized
churn goldens (tests/test_fair_device.py) pin the A/B byte-identical
across every registered engine. `KUEUE_TPU_DEBUG_FAIR=1` additionally
runs both paths per search and asserts equal victim sequences.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.solver.schema import NO_LIMIT


class FairPreemptContext:
    """Per-encoding constants for the vectorized fair victim search.

    Built once per CQ-encoding generation
    (BatchSolver.fair_preempt_context); `usage` (the lockstep [C,F,R]
    tensor) and `arena` (the AdmittedArena) are live references refreshed
    per call.
    """

    def __init__(self, enc, structural):
        self.enc = enc
        # fair_structural's (cap, weight, cohorted); the cohorted mask
        # is FairShareState's concern — the search scopes by candidate
        # queues, never by the mask.
        self.cap, self.weight, _ = structural
        C, F, R = enc.nominal.shape
        self.F, self.R = F, R
        self.blim_def = enc.configured & (enc.borrow_limit != NO_LIMIT)
        self.cohort_requestable = enc.cohort_requestable()   # [K,F,R]
        perm = np.argsort(enc.cohort_id, kind="stable")
        sorted_ids = enc.cohort_id[perm]
        starts = np.searchsorted(sorted_ids, np.arange(enc.num_cohorts + 1))
        self.members_by_k = [perm[starts[k]:starts[k + 1]]
                             for k in range(enc.num_cohorts)]
        # Live per-call refs.
        self.usage: Optional[np.ndarray] = None
        self.arena = None


def _frq_tensor(frq: Dict[str, Dict[str, int]], enc, F: int, R: int,
                ) -> np.ndarray:
    out = np.zeros((F, R), dtype=np.int64)
    f_index = enc.flavor_index
    r_index = enc.resource_index
    for fname, resources in frq.items():
        fi = f_index.get(fname)
        if fi is None:
            continue
        for rname, v in resources.items():
            ri = r_index.get(rname)
            if ri is not None:
                out[fi, ri] += v
    return out


def _cand_rows(ctx: FairPreemptContext, cands: Sequence[WorkloadInfo],
               ci: int) -> np.ndarray:
    """[n,F,R] committed usage rows for one CQ's candidates: the
    AdmittedArena gather, or (rows missing — e.g. arena off) a one-time
    triples walk with the same configured-pair filter the cache applies."""
    arena = ctx.arena
    rows = arena.rows_for(cands) if arena is not None else None
    if rows is not None:
        return arena.use_fr[rows].reshape(len(cands), ctx.F, ctx.R)
    F, R = ctx.F, ctx.R
    enc = ctx.enc
    conf = enc.configured[ci]
    f_index = enc.flavor_index
    r_index = enc.resource_index
    out = np.zeros((len(cands), F, R), dtype=np.int64)
    for i, c in enumerate(cands):
        row = out[i]
        for fname, rname, v in c.usage_triples:
            fi = f_index.get(fname)
            if fi is None:
                continue
            ri = r_index.get(rname)
            if ri is not None and conf[fi, ri]:
                row[fi, ri] += v
    return out


class _FairSearch:
    """One fair victim search's mutable local state (nothing shared is
    ever written)."""

    def __init__(self, ctx: FairPreemptContext, ci0: int,
                 cis: np.ndarray, y0: int,
                 wl_req_t: np.ndarray, res_mask: np.ndarray):
        enc = ctx.enc
        self.ctx = ctx
        self.cis = cis
        self.y0 = y0
        usage = ctx.usage
        self.U = usage[cis].copy()                    # [Y,F,R]
        self.nom = enc.nominal[cis]
        self.guar = enc.guaranteed[cis]
        self.conf = enc.configured[cis]
        self.res_mask = res_mask
        self.wl_fi, self.wl_ri = np.nonzero(wl_req_t)
        self.wl_val = wl_req_t[self.wl_fi, self.wl_ri]
        self.cap = ctx.cap[cis]                       # [Y,R]
        self.weight = ctx.weight[cis]
        from kueue_tpu.models.fair_share import weighted_shares_np
        self._shares_np = weighted_shares_np
        above = np.maximum(self.U - self.nom, 0).sum(axis=1)
        self.share = weighted_shares_np(above, self.cap, self.weight)
        self.borrow = ((self.U > self.nom) & res_mask
                       & self.conf).any(axis=(1, 2))
        self._sx: Optional[float] = None
        # Hierarchical vs flat fits machinery for the preemptor's tree.
        h = enc.hier
        self.hier = h is not None and bool(h.cq_hier[ci0])
        if self.hier:
            self.h = h
            # Local KEP-79 node balances (the ops/hier_cycle T
            # aggregation, against the search-start usage).
            t_cq = enc.nominal - usage
            K2 = h.node_own_nominal.shape[0]
            seg = np.where(h.cq_node >= 0, h.cq_node, K2)
            contrib = np.minimum(h.cq_lend, t_cq)
            m = np.zeros((K2 + 1,) + t_cq.shape[1:], dtype=np.int64)
            np.add.at(m, seg, contrib)
            t_node = h.node_own_nominal + m[:K2]
            for nodes, parents in h.levels:
                np.add.at(t_node, parents,
                          np.minimum(h.node_lend[nodes], t_node[nodes]))
            self.t3 = t_node
        else:
            k0 = enc.cohort_id[ci0]
            members = ctx.members_by_k[k0]
            self.pool = np.maximum(
                usage[members] - enc.guaranteed[members], 0
            ).sum(axis=0)                                     # [F,R]
            self.requestable = (ctx.cohort_requestable[k0]
                                + enc.guaranteed[ci0])        # [F,R]
        self.blim = enc.borrow_limit[cis[y0]]
        self.blim_def = ctx.blim_def[cis[y0]]

    # -- shares ------------------------------------------------------------

    def share_x(self) -> float:
        """The preemptor's prospective share (with the incoming workload
        admitted); cached until an own-CQ victim moves its usage."""
        sx = self._sx
        if sx is None:
            u = self.U[self.y0].copy()
            u[self.wl_fi, self.wl_ri] += self.wl_val
            above = np.maximum(u - self.nom[self.y0], 0).sum(
                axis=0)[None]                                  # [1,R]
            sx = self._sx = float(self._shares_np(
                above, self.cap[self.y0][None],
                self.weight[self.y0:self.y0 + 1])[0])
        return sx

    def _refresh_y(self, y: int) -> None:
        above = np.maximum(self.U[y] - self.nom[y], 0).sum(axis=0)[None]
        self.share[y] = self._shares_np(
            above, self.cap[y][None], self.weight[y:y + 1])[0]
        self.borrow[y] = bool(((self.U[y] > self.nom[y]) & self.res_mask
                               & self.conf[y]).any())

    # -- workloadFits (preemption.go:352-389) ------------------------------

    def fits(self) -> bool:
        fi, ri, val = self.wl_fi, self.wl_ri, self.wl_val
        if not len(fi):
            return True
        u = self.U[self.y0][fi, ri]
        nom = self.nom[self.y0][fi, ri]
        bdef = self.blim_def[fi, ri]
        if np.any(bdef & (u + val > nom + self.blim[fi, ri])):
            return False
        if self.hier:
            return self._fits_hier(fi, ri, val)
        pool = self.pool[fi, ri]
        g = self.guar[self.y0][fi, ri]
        used = pool + np.minimum(u, g)
        return not np.any(used + val > self.requestable[fi, ri])

    def _fits_hier(self, fi, ri, val) -> bool:
        """hierarchical_lack == 0 for every request pair, against the
        local balances (one D-step walk, vectorized over pairs)."""
        h = self.h
        ci0 = self.cis[self.y0]
        t_old = self.nom[self.y0][fi, ri] - self.U[self.y0][fi, ri]
        lend_cq = h.cq_lend[ci0][fi, ri]
        delta = np.minimum(lend_cq, t_old) \
            - np.minimum(lend_cq, t_old - val)
        path = h.cq_path[ci0]
        for node in path:
            if node < 0:
                break
            t_n = self.t3[node, fi, ri]
            t_new = t_n - delta
            if np.any(t_new < -h.node_blim[node, fi, ri]):
                return False
            lend = h.node_lend[node, fi, ri]
            delta = np.minimum(lend, t_n) - np.minimum(lend, t_new)
        return True

    # -- incremental victim apply ------------------------------------------

    def apply(self, y: int, row: np.ndarray, sign: int) -> None:
        """Remove (sign=-1) or add back (sign=+1) one victim's usage row
        from ClusterQueue `y`, updating shares / borrowing / fits state
        incrementally (the snapshot.remove_workload twin on local
        tensors)."""
        u_old = self.U[y].copy()
        self.U[y] += sign * row
        self._refresh_y(y)
        if y == self.y0:
            self._sx = None
        if self.hier:
            fi, ri = np.nonzero(row)
            if len(fi):
                h = self.h
                ciy = self.cis[y]
                nom = self.nom[y][fi, ri]
                t_before_cq = nom - u_old[fi, ri]
                t_after_cq = nom - self.U[y][fi, ri]
                lend_cq = h.cq_lend[ciy][fi, ri]
                delta = np.minimum(lend_cq, t_after_cq) \
                    - np.minimum(lend_cq, t_before_cq)
                for node in h.cq_path[ciy]:
                    if node < 0:
                        break
                    t_before = self.t3[node, fi, ri]
                    t_after = t_before + delta
                    self.t3[node, fi, ri] = t_after
                    lend = h.node_lend[node, fi, ri]
                    delta = np.minimum(lend, t_after) \
                        - np.minimum(lend, t_before)
        else:
            g = self.guar[y]
            self.pool += np.maximum(self.U[y] - g, 0) \
                - np.maximum(u_old - g, 0)


def fair_targets(ctx: FairPreemptContext, cq, wl_req,
                 per_cq: Dict[str, List[WorkloadInfo]], res_per_flv,
                 strategies) -> Optional[List[WorkloadInfo]]:
    """The vectorized `_fair_preemptions` loop. Returns the victim list
    (same order as the host referee), or None when the search cannot be
    expressed against the current encoding (caller falls back to the
    host oracle)."""
    from kueue_tpu.api.types import FairSharingStrategy

    enc = ctx.enc
    if ctx.usage is None:
        return None
    cq_index = enc.cq_index
    ci0 = cq_index.get(cq.name)
    if ci0 is None:
        return None
    qn = list(per_cq)
    nq = len(qn)
    cis_list = []
    for name in qn:
        ci = cq_index.get(name)
        if ci is None:
            return None
        cis_list.append(ci)
    # Scope = the candidate queues plus (when it holds no candidates of
    # its own) the preemptor, whose usage the fits/share_x state reads.
    if cq.name in per_cq:
        y0 = qn.index(cq.name)
    else:
        y0 = nq
        cis_list.append(ci0)
    cis = np.asarray(cis_list, dtype=np.int64)

    F, R = ctx.F, ctx.R
    wl_req_t = _frq_tensor(wl_req, enc, F, R)
    wl_req_t = np.where(enc.configured[ci0], wl_req_t, 0)
    res_mask = np.zeros((F, R), dtype=bool)
    f_index = enc.flavor_index
    r_index = enc.resource_index
    for fname, resources in res_per_flv.items():
        fi = f_index.get(fname)
        if fi is None:
            continue
        for rname in resources:
            ri = r_index.get(rname)
            if ri is not None:
                res_mask[fi, ri] = True

    # Flat candidate layout: all queues' candidates concatenated in
    # per_cq insertion order, each queue's block pre-sorted by the host's
    # candidate ordering. Validity masks replace the host's list pops.
    cands_flat: List[WorkloadInfo] = []
    cand_y_parts = []
    use_parts = []
    seg = np.zeros(nq + 1, dtype=np.int64)
    for y, name in enumerate(qn):
        cands = per_cq[name]
        seg[y + 1] = seg[y] + len(cands)
        cands_flat.extend(cands)
        cand_y_parts.append(np.full(len(cands), y, dtype=np.int64))
        use_parts.append(_cand_rows(ctx, cands, cis_list[y]))
    N = len(cands_flat)
    cand_y = (np.concatenate(cand_y_parts) if N
              else np.zeros(0, dtype=np.int64))
    cand_use = (np.concatenate(use_parts) if N
                else np.zeros((0, F, R), dtype=np.int64))
    valid = np.ones(N, dtype=bool)

    st = _FairSearch(ctx, ci0, cis, y0, wl_req_t, res_mask)

    # Share-without-victim cache (FinalShare strategy): one broadcast
    # subtract + max-over-resources per queue, refreshed only when that
    # queue's usage moved.
    swo = np.zeros(N, dtype=np.float64)
    swo_dirty = np.ones(nq, dtype=bool)

    def refresh_swo(active_y: np.ndarray) -> None:
        for y in np.nonzero(swo_dirty & active_y)[0]:
            a, b = seg[y], seg[y + 1]
            above = np.maximum(
                st.U[y][None] - cand_use[a:b] - st.nom[y][None], 0
            ).sum(axis=1)                                     # [n,R]
            swo[a:b] = st._shares_np(
                above, np.broadcast_to(st.cap[y], (b - a, R)),
                np.full(b - a, st.weight[y]))
            swo_dirty[y] = False

    final = FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE
    own_y = y0 if y0 < nq else -1

    def pick(strategy, sx: float):
        # Every per_cq segment is non-empty by construction (the host
        # builder only records queues with candidates), so reduceat's
        # empty-slice quirk cannot fire.
        has_valid = np.add.reduceat(valid, seg[:-1]) > 0 if N \
            else np.zeros(nq, dtype=bool)
        if not has_valid.any():
            return None
        if strategy == final:
            active = has_valid & st.borrow[:nq]
            refresh_swo(active)
            ok = valid & (swo >= sx)
            ok_y = np.zeros(nq, dtype=bool)
            np.logical_or.at(ok_y, cand_y[ok], True)
            elig = active & ok_y
        else:
            ok = valid
            elig = has_valid & st.borrow[:nq] & (st.share[:nq] > sx)
        if own_y >= 0 and has_valid[own_y]:
            elig = elig.copy()
            elig[own_y] = True
        if not elig.any():
            return None
        score = np.where(elig, st.share[:nq], -1.0)
        y = int(np.argmax(score))     # first occurrence == stable-sort tie
        a, b = seg[y], seg[y + 1]
        zmask = valid[a:b] if y == own_y else (ok[a:b] & valid[a:b])
        return y, int(a + np.argmax(zmask))

    targets: List[int] = []
    fits = False
    while True:
        if st.fits():
            fits = True
            break
        sx = st.share_x()
        picked = None
        for strategy in strategies:
            picked = pick(strategy, sx)
            if picked is not None:
                break
        if picked is None:
            break
        y, z = picked
        valid[z] = False
        st.apply(y, cand_use[z], -1)
        swo_dirty[y] = True
        targets.append(z)

    if not fits:
        return []

    # Add-back minimization, exactly the host's reverse swap-pop walk.
    i = len(targets) - 2
    while i >= 0:
        z = targets[i]
        y = int(cand_y[z])
        st.apply(y, cand_use[z], 1)
        if st.fits():
            targets[i] = targets[-1]
            targets.pop()
        else:
            st.apply(y, cand_use[z], -1)
        i -= 1
    return [cands_flat[z] for z in targets]
