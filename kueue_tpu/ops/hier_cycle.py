"""Per-cycle hierarchical-cohort bookkeeping on the dense encoding.

The admission cycle's same-tick reservation gate for KEP-79 trees
(scheduler.go:204-275 cohortsUsage, generalized to trees) was previously a
per-entry `fits_in_hierarchy(..., extra=cycle_usage)` walk — a full-subtree
recomputation per entry that is O(tree) in dict ops and quadratic per tick
at north-star scale (1k ClusterQueues solved 9+ seconds/tick).

`HierCycleState` replaces it with the device kernel's formulation
(models/flavor_fit.py aggregate_t / hier_ok) run host-side on the solver's
dense tensors: one vectorized bottom-up T aggregation per cycle, then
O(depth) integer walks per entry for both the feasibility check and the
reservation fold. Semantics are pinned to the dict referee
(core/hierarchy.py) by a randomized equivalence test.

Only valid while the solver encoding matches the snapshot the cycle runs
against (BatchSolver.encoding_matches) — the scheduler falls back to the
dict walk otherwise.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from kueue_tpu.utils import native_ledger

_ledger = native_ledger.load()
_HIER_ENTRY = getattr(_ledger, "hier_entry", None)


class HierCycleState:
    """T balances of every cohort node, updated as the cycle reserves.

    Mirrors core/hierarchy.py exactly:

      T(node) = own_nominal
              + sum over member CQs  of min(cq_lend, nominal - usage)
              + sum over child nodes of min(node_lend, T(child))

    minus the cycle's same-tick reservations, each charged at the
    admitting ClusterQueue's direct cohort node and propagated upward
    through the lending clamps (subtree_t's `extra` semantics).
    """

    __slots__ = ("enc", "h", "t", "_blim", "_lend", "_paths",
                 "_nominal", "_usage", "_cq_lend", "_t_np", "folds")

    def __init__(self, enc, usage: np.ndarray):
        """`enc`: the solver CQEncoding (with .hier); `usage`: the
        lockstep [C,F,R] usage tensor (UsageEncoder.usage)."""
        h = enc.hier
        K2 = h.node_own_nominal.shape[0]
        t_cq = enc.nominal - usage                        # [C,F,R]
        seg = np.where(h.cq_node >= 0, h.cq_node, K2)
        contrib = np.minimum(h.cq_lend, t_cq)
        m = np.zeros((K2 + 1,) + t_cq.shape[1:], dtype=np.int64)
        np.add.at(m, seg, contrib)
        t_node = h.node_own_nominal + m[:K2]
        for nodes, parents in h.levels:
            np.add.at(t_node, parents,
                      np.minimum(h.node_lend[nodes], t_node[nodes]))
        self.enc = enc
        self.h = h
        # Node-side tensors as flat Python lists: the per-entry walks read
        # a handful of scalars each, and list indexing is ~7x cheaper than
        # numpy scalar indexing. The flattening is O(nodes x F x R) once
        # per cycle — small next to one entry's former full-tree walk.
        _, F, R = t_cq.shape
        self.t = t_node.ravel().tolist()
        # Dense copy for the vectorized fold-free batch check (fits_many);
        # diverges from the list once folds run, hence the folds guard.
        self._t_np = t_node
        self._blim = h.node_blim.ravel().tolist()
        self._lend = h.node_lend.ravel().tolist()
        # Paths pre-multiplied by F*R: the flat index of (node, fi, ri)
        # is path[d] + fi*R + ri (the C walk's contract; sentinels stay
        # negative).
        self._paths = (h.cq_path.astype(np.int64) * (F * R)).tolist()
        self._nominal = enc.nominal
        self._usage = usage
        self._cq_lend = h.cq_lend
        self.folds = 0

    # -- per-entry operations (plain-int walks, O(depth x pairs)) ----------

    def fits(self, ci: int, items: Sequence[Tuple[int, int, int]]) -> bool:
        """True when adding `items` ([(flavor_idx, resource_idx, val)]) to
        ClusterQueue `ci` keeps every ancestor balance within its
        borrowing limit — `hierarchical_lack(...) == 0` for each pair,
        against the snapshot state minus this cycle's folds."""
        R = self._nominal.shape[2]
        if _HIER_ENTRY is not None:
            pairs = []
            for fi, ri, val in items:
                t_old = int(self._nominal[ci, fi, ri]) \
                    - int(self._usage[ci, fi, ri])
                lend_cq = int(self._cq_lend[ci, fi, ri])
                pairs.append((fi * R + ri,
                              min(lend_cq, t_old)
                              - min(lend_cq, t_old - int(val))))
            return _HIER_ENTRY(self.t, self._blim, self._lend,
                               self._paths[ci], pairs, 0)
        t_l = self.t
        blim_l = self._blim
        lend_l = self._lend
        path = self._paths[ci]
        for fi, ri, val in items:
            off = fi * R + ri
            t_old = int(self._nominal[ci, fi, ri]) \
                - int(self._usage[ci, fi, ri])
            lend_cq = int(self._cq_lend[ci, fi, ri])
            delta = min(lend_cq, t_old) - min(lend_cq, t_old - int(val))
            for node in path:
                if node < 0:
                    break
                j = node + off
                t = t_l[j]
                t_new = t - delta
                if t_new < -blim_l[j]:
                    return False
                lend = lend_l[j]
                delta = min(lend, t) - min(lend, t_new)
        return True

    def fits_many(self, cis, fis, ris, vals) -> np.ndarray:
        """Vectorized `fits` over independent (cq, flavor, resource, val)
        rows — the staleness-revalidation batch. Only valid on a
        FOLD-FREE state (the dense copy does not track folds); mirrors
        the device kernel's hier_ok walk (models/flavor_fit.py)."""
        if self.folds:
            raise ValueError("fits_many requires a fold-free state")
        h = self.h
        t = self._t_np
        ci = np.asarray(cis)
        fi = np.asarray(fis)
        ri = np.asarray(ris)
        val = np.asarray(vals, dtype=np.int64)
        t_old = self._nominal[ci, fi, ri] - self._usage[ci, fi, ri]
        lend_cq = h.cq_lend[ci, fi, ri]
        delta = np.minimum(lend_cq, t_old) - np.minimum(lend_cq, t_old - val)
        ok = np.ones(ci.shape[0], dtype=bool)
        paths = h.cq_path[ci]                               # [n, D]
        for d in range(paths.shape[1]):
            node = paths[:, d]
            valid = node >= 0
            ns = np.maximum(node, 0)
            t_n = t[ns, fi, ri]
            t_new = t_n - delta
            ok &= np.where(valid, t_new >= -h.node_blim[ns, fi, ri], True)
            lend = h.node_lend[ns, fi, ri]
            delta = np.where(
                valid,
                np.minimum(lend, t_n) - np.minimum(lend, t_new), delta)
        return ok

    def fold(self, ci: int, items: Sequence[Tuple[int, int, int]]) -> None:
        """Reserve `items` at ClusterQueue `ci`'s direct cohort node and
        propagate the clamped delta up the ancestor chain (the cycle's
        cohortsUsage fold, subtree_t `extra` semantics)."""
        R = self._nominal.shape[2]
        self.folds += 1
        if _HIER_ENTRY is not None:
            _HIER_ENTRY(self.t, self._blim, self._lend, self._paths[ci],
                        [(fi * R + ri, int(val)) for fi, ri, val in items],
                        1)
            return
        t_l = self.t
        lend_l = self._lend
        path = self._paths[ci]
        for fi, ri, val in items:
            off = fi * R + ri
            delta = int(val)
            for node in path:
                if node < 0 or delta == 0:
                    break
                j = node + off
                t = t_l[j]
                t_new = t - delta
                t_l[j] = t_new
                lend = lend_l[j]
                delta = min(lend, t) - min(lend, t_new)

    # -- coordinate helpers -------------------------------------------------

    def coords(self, frq) -> List[Tuple[int, int, int]]:
        """{flavor: {resource: val}} -> [(fi, ri, val)]; raises KeyError
        for names outside the encoding (callers fall back to the dict
        walk)."""
        enc = self.enc
        out: List[Tuple[int, int, int]] = []
        for fname, resources in frq.items():
            fi = enc.flavor_index[fname]
            for rname, val in resources.items():
                out.append((fi, enc.resource_index[rname], val))
        return out
