"""Per-cycle hierarchical-cohort bookkeeping on the dense encoding.

The admission cycle's same-tick reservation gate for KEP-79 trees
(scheduler.go:204-275 cohortsUsage, generalized to trees) was previously a
per-entry `fits_in_hierarchy(..., extra=cycle_usage)` walk — a full-subtree
recomputation per entry that is O(tree) in dict ops and quadratic per tick
at north-star scale (1k ClusterQueues solved 9+ seconds/tick).

`HierCycleState` replaces it with the device kernel's formulation
(models/flavor_fit.py aggregate_t / hier_ok) run host-side on the solver's
dense tensors: one vectorized bottom-up T aggregation per cycle, then
O(depth) integer walks per entry for both the feasibility check and the
reservation fold. The walks run in ONE native call per entry
(native/ledger.cpp hier_gate_fold) reading the int64 tensors directly —
the scheduler's FIT sequence (gate, then reserve) is a single fused call.
Semantics are pinned to the dict referee (core/hierarchy.py) by a
randomized equivalence test.

Only valid while the solver encoding matches the snapshot the cycle runs
against (BatchSolver.encoding_matches) — the scheduler falls back to the
dict walk otherwise.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from kueue_tpu.utils import native_ledger

_ledger = native_ledger.load()
_GATE_FOLD = getattr(_ledger, "hier_gate_fold", None)


class HierCycleState:
    """T balances of every cohort node, updated as the cycle reserves.

    Mirrors core/hierarchy.py exactly:

      T(node) = own_nominal
              + sum over member CQs  of min(cq_lend, nominal - usage)
              + sum over child nodes of min(node_lend, T(child))

    minus the cycle's same-tick reservations, each charged at the
    admitting ClusterQueue's direct cohort node and propagated upward
    through the lending clamps (subtree_t's `extra` semantics).
    """

    __slots__ = ("enc", "h", "t", "_t3", "_blim", "_lend", "_paths",
                 "_nominal", "_usage", "_cq_lend", "folds")

    def __init__(self, enc, usage: np.ndarray):
        """`enc`: the solver CQEncoding (with .hier); `usage`: the
        lockstep [C,F,R] usage tensor (UsageEncoder.usage)."""
        h = enc.hier
        K2 = h.node_own_nominal.shape[0]
        t_cq = enc.nominal - usage                        # [C,F,R]
        seg = np.where(h.cq_node >= 0, h.cq_node, K2)
        contrib = np.minimum(h.cq_lend, t_cq)
        m = np.zeros((K2 + 1,) + t_cq.shape[1:], dtype=np.int64)
        np.add.at(m, seg, contrib)
        t_node = h.node_own_nominal + m[:K2]
        for nodes, parents in h.levels:
            np.add.at(t_node, parents,
                      np.minimum(h.node_lend[nodes], t_node[nodes]))
        self.enc = enc
        self.h = h
        # Balances stay a contiguous int64 tensor: `t` is the flat view
        # the native walk indexes (node*F*R + fi*R + ri), `_t3` the same
        # memory shaped [K2,F,R] for the vectorized fold-free batch check
        # (fits_many).
        t_node = np.ascontiguousarray(t_node)
        self._t3 = t_node
        self.t = t_node.reshape(-1)
        self._blim = h.node_blim.reshape(-1)
        self._lend = h.node_lend.reshape(-1)
        # Raw ancestor node ids as int64 (the native call's dtype),
        # cached per encoding — cq_path itself is i32.
        paths = getattr(h, "_paths64", None)
        if paths is None:
            paths = np.ascontiguousarray(h.cq_path, dtype=np.int64)
            h._paths64 = paths
        self._paths = paths
        self._nominal = enc.nominal
        self._usage = usage
        self._cq_lend = h.cq_lend
        self.folds = 0

    # -- per-entry operations (one native call, O(depth x pairs)) ----------

    def gate_fold(self, ci: int, fis: Sequence[int], ris: Sequence[int],
                  vals: Sequence[int], do_gate: bool = True,
                  do_fold: bool = True) -> bool:
        """Fused admission-cycle step for one entry: feasibility walk
        (each pair's delta clamped through the CQ's own lending limit,
        checked against every ancestor's borrowing limit), then — only
        when the gate passes — the reservation fold (raw values charged
        at the direct cohort node, propagated through the node lending
        clamps). Returns False when gated; mutates nothing in that case."""
        if _GATE_FOLD is not None:
            ok = _GATE_FOLD(self.t, self._blim, self._lend, self._paths,
                            self._nominal, self._usage, self._cq_lend,
                            ci, fis, ris, vals, do_gate, do_fold)
        else:
            ok = not do_gate or self._fits_py(ci, fis, ris, vals)
            if ok and do_fold:
                self._fold_py(ci, fis, ris, vals)
        if ok and do_fold:
            self.folds += 1
        return ok

    def fits(self, ci: int, items: Sequence[Tuple[int, int, int]]) -> bool:
        """True when adding `items` ([(flavor_idx, resource_idx, val)]) to
        ClusterQueue `ci` keeps every ancestor balance within its
        borrowing limit — `hierarchical_lack(...) == 0` for each pair,
        against the snapshot state minus this cycle's folds."""
        if not items:
            return True
        fis, ris, vals = zip(*items)
        return self.gate_fold(ci, fis, ris, vals, do_gate=True,
                              do_fold=False)

    def fold(self, ci: int, items: Sequence[Tuple[int, int, int]]) -> None:
        """Reserve `items` at ClusterQueue `ci`'s direct cohort node and
        propagate the clamped delta up the ancestor chain (the cycle's
        cohortsUsage fold, subtree_t `extra` semantics)."""
        if not items:
            self.folds += 1
            return
        fis, ris, vals = zip(*items)
        self.gate_fold(ci, fis, ris, vals, do_gate=False, do_fold=True)

    # -- pure-Python fallback walks (no native toolchain) -------------------

    def _fits_py(self, ci, fis, ris, vals) -> bool:
        R = self._nominal.shape[2]
        FR = self._nominal.shape[1] * R
        t_l = self.t
        blim_l = self._blim
        lend_l = self._lend
        path = self._paths[ci]
        for fi, ri, val in zip(fis, ris, vals):
            off = fi * R + ri
            t_old = int(self._nominal[ci, fi, ri]) \
                - int(self._usage[ci, fi, ri])
            lend_cq = int(self._cq_lend[ci, fi, ri])
            delta = min(lend_cq, t_old) - min(lend_cq, t_old - int(val))
            for node in path:
                if node < 0:
                    break
                j = int(node) * FR + off
                t = int(t_l[j])
                t_new = t - delta
                if t_new < -int(blim_l[j]):
                    return False
                lend = int(lend_l[j])
                delta = min(lend, t) - min(lend, t_new)
        return True

    def _fold_py(self, ci, fis, ris, vals) -> None:
        R = self._nominal.shape[2]
        FR = self._nominal.shape[1] * R
        t_l = self.t
        lend_l = self._lend
        path = self._paths[ci]
        for fi, ri, val in zip(fis, ris, vals):
            off = fi * R + ri
            delta = int(val)
            for node in path:
                if node < 0 or delta == 0:
                    break
                j = int(node) * FR + off
                t = int(t_l[j])
                t_new = t - delta
                t_l[j] = t_new
                lend = int(lend_l[j])
                delta = min(lend, t) - min(lend, t_new)

    def fits_many(self, cis, fis, ris, vals) -> np.ndarray:
        """Vectorized `fits` over independent (cq, flavor, resource, val)
        rows — the staleness-revalidation batch. Only valid on a
        FOLD-FREE state (the dense copy does not track folds); mirrors
        the device kernel's hier_ok walk (models/flavor_fit.py)."""
        if self.folds:
            raise ValueError("fits_many requires a fold-free state")
        h = self.h
        t = self._t3
        ci = np.asarray(cis)
        fi = np.asarray(fis)
        ri = np.asarray(ris)
        val = np.asarray(vals, dtype=np.int64)
        t_old = self._nominal[ci, fi, ri] - self._usage[ci, fi, ri]
        lend_cq = h.cq_lend[ci, fi, ri]
        delta = np.minimum(lend_cq, t_old) - np.minimum(lend_cq, t_old - val)
        ok = np.ones(ci.shape[0], dtype=bool)
        paths = h.cq_path[ci]                               # [n, D]
        for d in range(paths.shape[1]):
            node = paths[:, d]
            valid = node >= 0
            ns = np.maximum(node, 0)
            t_n = t[ns, fi, ri]
            t_new = t_n - delta
            ok &= np.where(valid, t_new >= -h.node_blim[ns, fi, ri], True)
            lend = h.node_lend[ns, fi, ri]
            delta = np.where(
                valid,
                np.minimum(lend, t_n) - np.minimum(lend, t_new), delta)
        return ok

    # -- coordinate helpers -------------------------------------------------

    def coords(self, frq) -> List[Tuple[int, int, int]]:
        """{flavor: {resource: val}} -> [(fi, ri, val)]; raises KeyError
        for names outside the encoding (callers fall back to the dict
        walk)."""
        enc = self.enc
        out: List[Tuple[int, int, int]] = []
        for fname, resources in frq.items():
            fi = enc.flavor_index[fname]
            for rname, val in resources.items():
                out.append((fi, enc.resource_index[rname], val))
        return out
