"""One device dispatch for a whole tick's preemption-victim searches.

The per-problem device scan (ops/preemption_scan) is decision-equivalent to
the host `minimalPreemptions` referee, but a preemption-heavy tick runs
hundreds of independent searches — one dispatch each would drown in
host<->device round trips (the link, not the FLOPs, is the bottleneck on
remote-attached TPUs). This module batches every search of a tick into ONE
engine call — the C++ batch scan (native/preempt.cpp) by default, or one
packed XLA dispatch (`_packed_batch_kernel`, vmap of _scan_core) for the
jax/pallas backends:

  * the FR axis is the GLOBAL (flavor x resource) grid of the tick's
    ClusterQueue encoding (solver/schema.CQEncoding) — uniform across
    problems by construction, no per-problem pair vocabulary;
  * the member axis Y is padded to the largest cohort in the batch
    (padding rows carry zero usage and BIG nominals, so they neither
    borrow nor constrain);
  * the candidate axis N is padded to a power-of-two bucket with an
    explicit validity mask (a padded step must not trigger the
    fits-after-removal check — see _scan_core).

Problem tensors are sliced straight out of the encoding and the lockstep
usage tensor (solver/schema.UsageEncoder) instead of walking snapshot
dicts, so the encode is vectorized numpy per problem.

reference: pkg/scheduler/preemption/preemption.go:172-231 (semantics),
pkg/util/parallelize (the reference's 8-way intra-process analog).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import kueue_tpu.ops  # noqa: F401  (enables x64 before tracing)
import jax
import jax.numpy as jnp

from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.ops.preemption_scan import BIG, _scan_core
from kueue_tpu.solver.schema import NO_LIMIT


@dataclass
class PlannedSearch:
    """One minimalPreemptions invocation, planned host-side.

    `candidates` are already policy-filtered and ordered
    (candidatesOrdering); `allow_borrowing`/`threshold` carry the
    borrowWithinCohort round parameters."""

    target_ci: int
    has_cohort: bool
    candidates: List[WorkloadInfo]
    cand_cis: List[int]
    allow_borrowing: bool
    threshold: Optional[int]


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


_NATIVE = None


def _native_lib():
    """The C++ batch engine (native/preempt.cpp), or None when the
    toolchain is unavailable."""
    global _NATIVE
    if _NATIVE is None:
        from kueue_tpu.utils import native_build
        path = native_build.build("preempt.cpp", "_libkueue_preempt.so")
        if path is None:
            _NATIVE = False
        else:
            import ctypes
            lib = ctypes.CDLL(path)
            lib.kueue_minimal_preemptions_batch.restype = None
            _NATIVE = lib
    return _NATIVE or None


class BatchContext:
    """Per-encoding constants reused across ticks (invalidated with the
    encoding itself)."""

    def __init__(self, enc, lending: bool):
        self.enc = enc
        self.lending = lending
        C, F, R = enc.nominal.shape
        self.FR = F * R
        self.F, self.R = F, R
        conf = enc.configured.reshape(C, self.FR)
        self.q_def = conf
        self.nominal = np.where(conf, enc.nominal.reshape(C, self.FR), BIG)
        self.guaranteed = enc.guaranteed.reshape(C, self.FR)
        blim_flat = enc.borrow_limit.reshape(C, self.FR)
        self.blim = blim_flat
        self.blim_def = conf & (blim_flat != NO_LIMIT)
        # requestable cohort quota per (target, pair): lendable pool of the
        # cohort + the target's own guaranteed (clusterqueue.go:583-600).
        self.cohort_requestable = enc.cohort_requestable().reshape(
            enc.num_cohorts, self.FR)
        # cohort members (target-first rotation happens per problem).
        perm = np.argsort(enc.cohort_id, kind="stable")
        sorted_ids = enc.cohort_id[perm]
        starts = np.searchsorted(sorted_ids, np.arange(enc.num_cohorts + 1))
        self.members_by_k = [perm[starts[k]:starts[k + 1]]
                             for k in range(enc.num_cohorts)]
        # Optional AdmittedArena (solver/schema): pooled committed-usage
        # rows keyed by workload, refreshed by BatchSolver per call. When
        # set, run_batch gathers candidate usage with one fancy-index
        # read per search instead of one usage_triples walk per
        # candidate (the rows carry the same configured-pair filter the
        # walk applies).
        self.admitted_arena = None
        # Optional cohort mesh (parallel/mesh.CohortMesh) + its
        # ShardAssignment, refreshed by BatchSolver per call: a victim
        # search reads only its target's cohort (members + candidates),
        # so the packed-XLA batch shards over the same cohort-hash mesh
        # as the flavor-fit solve — per-shard compacted search blocks,
        # no collectives. The native C++ engine ignores these (it has no
        # device to shard over).
        self.cohort_mesh = None
        self.shard_assignment = None

    def pair_index(self, fname: str, rname: str) -> Optional[int]:
        fi = self.enc.flavor_index.get(fname)
        ri = self.enc.resource_index.get(rname)
        if fi is None or ri is None:
            return None
        return fi * self.R + ri


@functools.partial(jax.jit, static_argnames=("shapes", "lending"))
def _packed_batch_kernel(buf, *, shapes, lending):
    """Unpack the byte buffer (device-side bitcasts; host and TPU are both
    little-endian) and run the vmapped victim scan."""
    B, Y, FR, N = shapes
    n64 = (3 * B * Y * FR + 3 * B * FR + B * N * FR) * 8
    n32 = (2 * B * N + B) * 4
    i64 = jax.lax.bitcast_convert_type(buf[:n64].reshape(-1, 8), jnp.int64)
    i32 = jax.lax.bitcast_convert_type(
        buf[n64:n64 + n32].reshape(-1, 4), jnp.int32)
    u8 = buf[n64 + n32:]

    off = 0

    def take64(n, shape):
        nonlocal off
        out = i64[off:off + n].reshape(shape)
        off += n
        return out

    usage0 = take64(B * Y * FR, (B, Y, FR))
    nominal = take64(B * Y * FR, (B, Y, FR))
    guaranteed = take64(B * Y * FR, (B, Y, FR))
    wl_req = take64(B * FR, (B, FR))
    blim = take64(B * FR, (B, FR))
    requestable = take64(B * FR, (B, FR))
    cand_use = take64(B * N * FR, (B, N, FR))

    cand_y = i32[:B * N].reshape(B, N)
    cand_prio = i32[B * N:2 * B * N].reshape(B, N)
    threshold = i32[2 * B * N:].reshape(B)

    off8 = 0

    def take8(n, shape):
        nonlocal off8
        out = u8[off8:off8 + n].reshape(shape).astype(bool)
        off8 += n
        return out

    q_def = take8(B * Y * FR, (B, Y, FR))
    wl_req_mask = take8(B * FR, (B, FR))
    blim_def = take8(B * FR, (B, FR))
    res_mask = take8(B * FR, (B, FR))
    cand_valid = take8(B * N, (B, N))
    has_cohort = take8(B, (B,))
    allow_b0 = take8(B, (B,))
    has_threshold = take8(B, (B,))

    lending_b = jnp.full(B, lending)
    return jax.vmap(_scan_core)(
        usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
        blim, blim_def, requestable, res_mask,
        cand_y, cand_use, cand_prio, cand_valid,
        has_cohort, lending_b, allow_b0, has_threshold, threshold)


_SHARDED_SCAN_CACHE: Dict[Tuple, object] = {}


def _sharded_scan_program(cmesh, lending: bool):
    """The cohort-sharded packed victim scan: shard_map over the search
    axis (each device runs the vmapped `_scan_core` on its shard's
    compacted block). Cached per (mesh, lending) — shapes re-trace under
    the jit like the single-device kernel."""
    key = (id(cmesh.mesh), cmesh.n_shards, lending)
    program = _SHARDED_SCAN_CACHE.get(key)
    if program is not None:
        return program
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kueue_tpu.parallel.mesh import SHARD_AXIS

    sharded = P(SHARD_AXIS)

    def run(usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
            blim, blim_def, requestable, res_mask,
            cand_y, cand_use, cand_prio, cand_valid,
            has_cohort, allow_b0, has_threshold, threshold):
        lending_b = jnp.full(usage0.shape[0], lending)
        return jax.vmap(_scan_core)(
            usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
            blim, blim_def, requestable, res_mask,
            cand_y, cand_use, cand_prio, cand_valid,
            has_cohort, lending_b, allow_b0, has_threshold, threshold)

    program = jax.jit(shard_map(
        run, mesh=cmesh.mesh, in_specs=(sharded,) * 18,
        out_specs=sharded, check_rep=False))
    _SHARDED_SCAN_CACHE[key] = program
    return program


def run_batch(ctx: BatchContext, usage: np.ndarray,
              searches: Sequence[PlannedSearch],
              wl_reqs: Sequence[Dict[str, Dict[str, int]]],
              res_per_flvs: Sequence[Dict[str, set]],
              backend: str = "native",
              ) -> List[Optional[List[WorkloadInfo]]]:
    """Solve every planned search in one engine call.

    `usage` is the CURRENT [C,F,R] lockstep usage tensor. Returns one
    victim list per search ([] = search failed / nothing to preempt).

    `backend`: "native" = the C++ engine (the default — the victim scan is
    sequential small-integer runtime work, which a remote-attached
    accelerator loses on link round trips); "jax"/"pallas" = one packed
    XLA dispatch for the whole batch.
    """
    B_real = len(searches)
    if B_real == 0:
        return []
    if backend == "native" and _native_lib() is None:
        backend = "jax"
    FR = ctx.FR
    U2 = usage.reshape(-1, FR)
    enc = ctx.enc

    Ymax = 1
    Nmax = 1
    member_rows: List[np.ndarray] = []
    for s in searches:
        if s.has_cohort:
            members = ctx.members_by_k[enc.cohort_id[s.target_ci]]
            # Target first (row 0 is the target by kernel contract).
            rows = np.concatenate((
                [s.target_ci], members[members != s.target_ci]))
        else:
            rows = np.asarray([s.target_ci])
        member_rows.append(rows)
        Ymax = max(Ymax, len(rows))
        Nmax = max(Nmax, len(s.candidates))
    B = B_real
    if backend != "native":
        # XLA recompiles per distinct (B, Y, FR, N): bucket every axis to
        # a power of two so steady-state ticks reuse the compiled kernel.
        Nmax = _pow2(Nmax)
        Ymax = _pow2(Ymax)
        B = _pow2(B_real)

    usage0 = np.zeros((B, Ymax, FR), dtype=np.int64)
    nominal = np.full((B, Ymax, FR), BIG, dtype=np.int64)
    q_def = np.zeros((B, Ymax, FR), dtype=bool)
    guaranteed = np.zeros((B, Ymax, FR), dtype=np.int64)
    wl_req = np.zeros((B, FR), dtype=np.int64)
    wl_req_mask = np.zeros((B, FR), dtype=bool)
    blim = np.full((B, FR), BIG, dtype=np.int64)
    blim_def = np.zeros((B, FR), dtype=bool)
    requestable = np.zeros((B, FR), dtype=np.int64)
    res_mask = np.zeros((B, FR), dtype=bool)
    cand_y = np.zeros((B, Nmax), dtype=np.int32)
    cand_use = np.zeros((B, Nmax, FR), dtype=np.int64)
    cand_prio = np.zeros((B, Nmax), dtype=np.int32)
    cand_valid = np.zeros((B, Nmax), dtype=bool)
    has_cohort = np.zeros(B, dtype=bool)
    allow_b0 = np.zeros(B, dtype=bool)
    has_threshold = np.zeros(B, dtype=bool)
    threshold = np.zeros(B, dtype=np.int32)

    for b, s in enumerate(searches):
        rows = member_rows[b]
        Y = len(rows)
        usage0[b, :Y] = U2[rows]
        nominal[b, :Y] = ctx.nominal[rows]
        q_def[b, :Y] = ctx.q_def[rows]
        guaranteed[b, :Y] = ctx.guaranteed[rows]
        for fname, resources in wl_reqs[b].items():
            for rname, v in resources.items():
                fi = ctx.pair_index(fname, rname)
                if fi is not None:
                    wl_req[b, fi] = v
                    wl_req_mask[b, fi] = True
        blim[b] = ctx.blim[s.target_ci]
        blim_def[b] = ctx.blim_def[s.target_ci]
        if s.has_cohort:
            requestable[b] = (
                ctx.cohort_requestable[enc.cohort_id[s.target_ci]]
                + ctx.guaranteed[s.target_ci])
        for fname, resources in res_per_flvs[b].items():
            for rname in resources:
                fi = ctx.pair_index(fname, rname)
                if fi is not None:
                    res_mask[b, fi] = True
        pos = {ci: y for y, ci in enumerate(rows.tolist())}
        N = len(s.candidates)
        arena = ctx.admitted_arena
        arows = arena.rows_for(s.candidates) if arena is not None else None
        if arows is not None:
            # Admitted-arena fast path: every candidate's committed
            # (configured-pair filtered) usage row in ONE gather.
            cand_use[b, :N] = arena.use_fr[arows]
            cand_y[b, :N] = [pos[cci] for cci in s.cand_cis]
            cand_prio[b, :N] = [c.obj.priority for c in s.candidates]
            cand_valid[b, :N] = True
        else:
            for i, (cand, cci) in enumerate(zip(s.candidates,
                                                s.cand_cis)):
                cand_y[b, i] = pos[cci]
                conf_row = ctx.q_def[cci]
                for fname, rname, v in cand.usage_triples:
                    fi = ctx.pair_index(fname, rname)
                    # Only pairs the candidate's own CQ tracks count
                    # (clusterqueue.go:473-485).
                    if fi is not None and conf_row[fi]:
                        cand_use[b, i, fi] += v
                cand_prio[b, i] = cand.obj.priority
                cand_valid[b, i] = True
        has_cohort[b] = s.has_cohort
        allow_b0[b] = s.allow_borrowing
        has_threshold[b] = s.threshold is not None
        threshold[b] = s.threshold if s.threshold is not None else 0

    if backend == "native":
        import ctypes

        lib = _native_lib()
        victim = np.zeros((B, Nmax), dtype=np.uint8)
        fits = np.zeros(B, dtype=np.uint8)
        c = np.ascontiguousarray

        def p64(a):
            return c(a).ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

        def p32(a):
            return c(a).ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def p8(a):
            return c(a).ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

        lib.kueue_minimal_preemptions_batch(
            ctypes.c_int64(B), ctypes.c_int64(Ymax), ctypes.c_int64(FR),
            ctypes.c_int64(Nmax),
            p64(usage0), p64(nominal), p64(guaranteed),
            p64(wl_req), p64(blim), p64(requestable), p64(cand_use),
            p32(cand_y), p32(cand_prio), p32(threshold),
            p8(q_def.view(np.uint8)), p8(wl_req_mask.view(np.uint8)),
            p8(blim_def.view(np.uint8)), p8(res_mask.view(np.uint8)),
            p8(cand_valid.view(np.uint8)),
            p8(has_cohort.view(np.uint8)), p8(allow_b0.view(np.uint8)),
            p8(has_threshold.view(np.uint8)),
            ctypes.c_uint8(1 if ctx.lending else 0),
            p8(victim), p8(fits))
        victim = victim.astype(bool)
        out_native: List[Optional[List[WorkloadInfo]]] = []
        for b, s in enumerate(searches):
            if not fits[b]:
                out_native.append([])
                continue
            mask = victim[b]
            out_native.append(
                [cand for i, cand in enumerate(s.candidates) if mask[i]])
        return out_native

    cmesh = ctx.cohort_mesh
    if cmesh is not None and ctx.shard_assignment is not None \
            and cmesh.n_shards > 1 and B_real >= cmesh.n_shards:
        # Cohort-sharded dispatch: searches grouped by their target's
        # shard into per-shard compacted blocks — the SAME plan the
        # flavor-fit solve uses (parallel/mesh.plan_shards), with the
        # search's target CQ as the row — results mapped back to search
        # order.
        from kueue_tpu.parallel.mesh import plan_shards
        target_cis = np.fromiter((s.target_ci for s in searches),
                                 dtype=np.int32, count=B_real)
        rows, _counts, Bs = plan_shards(ctx.shard_assignment, target_cis,
                                        B_real, min_bucket=1)
        SB = cmesh.n_shards * Bs

        def scat(a):
            out = np.zeros((SB,) + a.shape[1:], dtype=a.dtype)
            out[rows] = a[:B_real]
            return out

        program = _sharded_scan_program(cmesh, ctx.lending)
        victim, fits = program(*(jnp.asarray(scat(a)) for a in (
            usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
            blim, blim_def, requestable, res_mask,
            cand_y, cand_use, cand_prio, cand_valid,
            has_cohort, allow_b0, has_threshold, threshold)))
        victim, fits = jax.device_get((victim, fits))
        victim = victim[rows]
        fits = fits[rows]
        out_sharded: List[Optional[List[WorkloadInfo]]] = []
        for b, s in enumerate(searches):
            if not fits[b]:
                out_sharded.append([])
                continue
            mask = victim[b]
            out_sharded.append(
                [c for i, c in enumerate(s.candidates) if mask[i]])
        return out_sharded

    # ONE host->device transfer: every section packed into a byte buffer
    # and bitcast apart on device — per-array transfers are round trips on
    # remote-attached TPUs and would dominate the search (the same
    # discipline as models/flavor_fit.pack_dynamic).
    buf = np.concatenate([
        usage0.ravel().view(np.uint8),
        nominal.ravel().view(np.uint8),
        guaranteed.ravel().view(np.uint8),
        wl_req.ravel().view(np.uint8),
        blim.ravel().view(np.uint8),
        requestable.ravel().view(np.uint8),
        cand_use.ravel().view(np.uint8),
        cand_y.ravel().view(np.uint8),
        cand_prio.ravel().view(np.uint8),
        threshold.ravel().view(np.uint8),
        q_def.ravel().view(np.uint8),
        wl_req_mask.ravel().view(np.uint8),
        blim_def.ravel().view(np.uint8),
        res_mask.ravel().view(np.uint8),
        cand_valid.ravel().view(np.uint8),
        has_cohort.view(np.uint8),
        allow_b0.view(np.uint8),
        has_threshold.view(np.uint8),
    ])
    victim, fits = _packed_batch_kernel(
        jnp.asarray(buf), shapes=(B, Ymax, FR, Nmax), lending=ctx.lending)
    victim, fits = jax.device_get((victim, fits))
    victim = victim[:B_real]
    fits = fits[:B_real]

    out: List[Optional[List[WorkloadInfo]]] = []
    for b, s in enumerate(searches):
        if not fits[b]:
            out.append([])
            continue
        mask = victim[b]
        out.append([c for i, c in enumerate(s.candidates) if mask[i]])
    return out
