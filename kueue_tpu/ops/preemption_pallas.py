"""Pallas TPU kernel for the minimalPreemptions scan.

Same decision semantics as ops/preemption_scan.scan_kernel (itself golden
against reference preemption.go:172-231), hand-scheduled for the TPU:

  * layout: the (flavor, resource) axis is the 128-lane dimension, cohort
    members are sublanes — one [Ypad, 128] int32 tile holds the whole
    mutable usage state in VMEM for the entire scan; the feasibility check
    is a handful of VPU reductions over that tile.
  * grid = (2N,): steps 0..N-1 are the remove phase, steps N..2N-1 walk the
    same candidates in reverse for the add-back phase; scan state (usage
    tile, taken flags) lives in VMEM scratch, control flags in SMEM — both
    persist across sequential TPU grid steps.
  * candidate metadata (member index, priority) rides scalar prefetch
    (PrefetchScalarGridSpec) so the per-step dynamic row update is an SMEM
    scalar index into the usage tile.

Quota values are rescaled host-side to int32: each (flavor, resource)
column is divided by the gcd of every value in that column, which preserves
all per-column comparisons and sums exactly. Columns that still exceed
int32 after scaling fall back to the int64 XLA scan.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import numpy as np

import kueue_tpu.ops  # noqa: F401  (x64 switch)
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kueue_tpu.ops import preemption_scan as ps

LANES = 128
SUBLANES = 8
I32_SENTINEL = np.int32(2**30)  # "no limit" after rescale


def _pad_axis(a: np.ndarray, axis: int, to: int) -> np.ndarray:
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, to - a.shape[axis])
    return np.pad(a, pad)


def _rescale_int32(p: ps.Problem, bound: int = 2**30):
    """Per-column gcd rescale to int32; returns None when impossible.

    `bound` is the acceptance ceiling for REAL (non-sentinel) values.
    The kernel sums up to `ypad` usage rows plus the lending credit and
    the workload request into one int32 (`cohort_used + wl_req`), so the
    caller passes (2^31 - 1) // (ypad + 2) — any rescaled value at or
    above that could wrap int32 inside `fits_now` on contract-valid
    inputs (the static TRC02 interval analysis proves the bound tight)."""
    FR = p.usage0.shape[1]
    cols = []
    for c in range(FR):
        vals = [int(v) for v in p.usage0[:, c]] + \
               [int(v) for v in p.nominal[:, c] if v < ps.BIG] + \
               [int(v) for v in p.guaranteed[:, c]] + \
               [int(p.wl_req[c])] + \
               ([int(p.blim[c])] if p.blim_def[c] else []) + \
               [int(p.requestable[c])] + \
               [int(v) for v in p.cand_use[:, c]]
        g = 0
        for v in vals:
            g = math.gcd(g, abs(v))
        cols.append(g if g > 0 else 1)
    g = np.asarray(cols, dtype=np.int64)

    def scale(a, sentinel_mask=None):
        out = a // g
        # Range-check only the REAL entries: the sentinel itself is 2^30,
        # so checking after masking rejected every problem carrying an
        # undefined quota/limit — which made the Pallas path unreachable
        # dead code (every call fell back to the XLA scan).
        real = out if sentinel_mask is None else out[~sentinel_mask]
        if real.max(initial=0) >= bound:
            return None
        if sentinel_mask is not None:
            out = np.where(sentinel_mask, I32_SENTINEL, out)
        return out.astype(np.int32)

    usage0 = scale(p.usage0)
    nominal = scale(p.nominal, sentinel_mask=~p.q_def | (p.nominal >= ps.BIG))
    guaranteed = scale(p.guaranteed)
    wl_req = scale(p.wl_req)
    blim = scale(p.blim, sentinel_mask=~p.blim_def)
    requestable = scale(p.requestable)
    cand_use = scale(p.cand_use)
    parts = (usage0, nominal, guaranteed, wl_req, blim, requestable, cand_use)
    if any(x is None for x in parts):
        return None
    return parts


def _kernel(cand_y, cand_prio, scalars,          # scalar-prefetch (SMEM)
            usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
            blim, blim_def, requestable, res_mask, cand_use,   # VMEM in
            victim_out, fits_out,                               # VMEM out
            U, taken, flags):                                   # scratch
    n = scalars[0]
    has_cohort = scalars[1]
    lending = scalars[2]
    allow_b0 = scalars[3]
    has_threshold = scalars[4]
    threshold = scalars[5]

    s = pl.program_id(0)
    phase2 = s >= n
    i = jnp.where(phase2, 2 * n - 1 - s, s)

    # flags: 0=allow_b, 1=done, 2=stop_idx, 3=fits_any
    @pl.when(s == 0)
    def _init():
        U[:, :] = usage0[:, :]
        # Literal writes must be int32: under x64 a bare Python int traces
        # as (weak) int64, and the SMEM ref discharge rejects the mixed
        # dtypes.
        flags[0] = allow_b0
        flags[1] = jnp.int32(0)
        flags[2] = n
        flags[3] = jnp.int32(0)

    y = cand_y[i]
    prio = cand_prio[i]
    is_target = y == 0

    def fits_now(allow_b):
        check = (q_def[0, :] != 0) & (wl_req_mask[0, :] != 0)
        own = U[0, :] + wl_req[0, :]
        nominal_cap = jnp.where(check, own <= nominal[0, :], True).all()
        # Subtraction form: nominal and blim both carry the I32_SENTINEL
        # 2^30 where undefined, and 2^30 + 2^30 wraps int32 — same hazard
        # (and same fix) as the int64 scan's TRC02 finding.
        blim_cap = jnp.where(
            check & (blim_def[0, :] != 0),
            own - blim[0, :] <= nominal[0, :], True).all()
        use_nominal = jnp.logical_or(has_cohort == 0, allow_b == 0)
        own_ok = jnp.where(use_nominal, nominal_cap, blim_cap)
        above = jnp.maximum(U[:, :] - guaranteed[:, :], 0).sum(axis=0)
        cohort_used = above + jnp.where(
            lending != 0, jnp.minimum(U[0, :], guaranteed[0, :]), 0)
        cohort_ok = jnp.where(
            check, cohort_used + wl_req[0, :] <= requestable[0, :],
            True).all()
        return own_ok & jnp.logical_or(has_cohort == 0, cohort_ok)

    # Dynamic row select/update as one-hot masked ops over the (<=8-row)
    # member axis: a traced-int32 pl.ds start mixes with literal int64
    # starts in x64 interpret mode, and a full-array VPU select is at
    # least as fast at these shapes on real hardware anyway.
    ypad = U.shape[0]
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (ypad, LANES), 0)
    sel = row_ids == y                                      # [ypad,128]

    def row_of(arr):
        # dtype pinned: under x64 an int32 sum would promote to int64 and
        # poison every downstream ref write.
        return jnp.where(sel, arr[:, :], 0).sum(
            axis=0, keepdims=True, dtype=jnp.int32)

    row = row_of(U)                                         # [1,128]
    nom_row = row_of(nominal)
    qd_row = row_of(q_def)
    use_row = cand_use[:, :]                                # block [1,128]

    @pl.when(jnp.logical_not(phase2))
    def _remove():
        borrowing = ((res_mask[0:1, :] != 0) & (qd_row != 0)
                     & (row > nom_row)).any()
        skip = jnp.logical_and(jnp.logical_not(is_target),
                               jnp.logical_not(borrowing))
        done = flags[1] != 0
        act = jnp.logical_and(jnp.logical_not(skip), jnp.logical_not(done))
        flip = (act & jnp.logical_not(is_target) & (has_threshold != 0)
                & (prio >= threshold))
        flags[0] = jnp.where(flip, 0, flags[0])
        # In contract, removed usage never exceeds the row's current
        # usage, so the floor is a no-op — it pins U to [0, usage0] for
        # the interval analysis instead of drifting one candidate-range
        # lower per grid step.
        new_row = jnp.maximum(row - jnp.where(act, use_row, 0), 0)
        U[:, :] = jnp.where(sel, new_row, U[:, :])
        taken[i] = act.astype(jnp.int32)
        # Host semantics: fits is only checked right after an actual removal.
        fits = fits_now(flags[0]) & act
        first_fit = fits & (flags[3] == 0)
        flags[2] = jnp.where(first_fit, i, flags[2])
        flags[3] = jnp.where(first_fit, 1, flags[3])
        flags[1] = jnp.where(fits, 1, flags[1])
        victim_out[:, :] = jnp.zeros((1, LANES), jnp.int32)

    @pl.when(phase2)
    def _addback():
        fits_any = flags[3] != 0
        stop_idx = flags[2]
        removed = (taken[i] != 0) & (i <= stop_idx) & fits_any
        tentative = removed & (i != stop_idx)
        row_now = row_of(U)
        # Adding back only ever restores usage removed in phase 1, so U
        # stays within [0, usage0] in contract — the ceiling/floor are
        # no-ops that keep the interval analysis from widening U by one
        # candidate range per grid step.
        row_try = jnp.minimum(row_now + jnp.where(tentative, use_row, 0),
                              row_of(usage0))
        U[:, :] = jnp.where(sel, row_try, U[:, :])
        fits = fits_now(flags[0])
        keep_added = tentative & fits
        # Roll back the tentative add when the preemptor no longer fits.
        rollback = tentative & jnp.logical_not(keep_added)
        U[:, :] = jnp.where(sel,
                            jnp.maximum(
                                row_try - jnp.where(rollback, use_row, 0),
                                0),
                            U[:, :])
        victim = removed & jnp.logical_not(keep_added)
        victim_out[:, :] = jnp.full((1, LANES), 1, jnp.int32) \
            * victim.astype(jnp.int32)
        fits_out[:, :] = jnp.full((1, LANES), 1, jnp.int32) \
            * fits_any.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n", "ypad", "interpret"))
def _pallas_call(cand_y, cand_prio, scalars,
                 usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
                 blim, blim_def, requestable, res_mask, cand_use,
                 *, n: int, ypad: int, interpret: bool):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(2 * n,),
        in_specs=[
            pl.BlockSpec((ypad, LANES), lambda s, *_: (0, 0)),   # usage0
            pl.BlockSpec((ypad, LANES), lambda s, *_: (0, 0)),   # nominal
            pl.BlockSpec((ypad, LANES), lambda s, *_: (0, 0)),   # q_def
            pl.BlockSpec((ypad, LANES), lambda s, *_: (0, 0)),   # guaranteed
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # wl_req
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # wl_req_mask
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # blim
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # blim_def
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # requestable
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),      # res_mask
            # candidate i's usage row; forward then reverse walk
            pl.BlockSpec(
                (1, LANES),
                lambda s, *_: (jnp.where(s < n, s, 2 * n - 1 - s), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, LANES),
                         lambda s, *_: (jnp.where(s < n, s, 2 * n - 1 - s), 0)),
            pl.BlockSpec((1, LANES), lambda s, *_: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((ypad, LANES), jnp.int32),   # U
            pltpu.SMEM((n,), jnp.int32),            # taken
            pltpu.SMEM((4,), jnp.int32),            # flags
        ],
    )
    victim, fits = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n, LANES), jnp.int32),
            jax.ShapeDtypeStruct((1, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(cand_y, cand_prio, scalars,
      usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
      blim, blim_def, requestable, res_mask, cand_use)
    return victim[:, 0], fits[0, 0]


def scan_kernel_pallas(p: ps.Problem,
                       interpret: bool | None = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Run the Pallas kernel on a Problem; falls back to the int64 XLA scan
    when the int32 rescale is impossible."""
    Y = p.usage0.shape[0]
    ypad = max(SUBLANES, ((Y + SUBLANES - 1) // SUBLANES) * SUBLANES)
    # fits_now folds ypad usage rows + the lending credit + wl_req into
    # one int32 sum; values must leave that much headroom or the kernel
    # can wrap where the int64 referee does not.
    scaled = _rescale_int32(p, bound=(2**31 - 1) // (ypad + 2))
    if scaled is None:
        victim, fits = ps.scan_kernel(
            jnp.asarray(p.usage0), jnp.asarray(p.nominal),
            jnp.asarray(p.q_def), jnp.asarray(p.guaranteed),
            jnp.asarray(p.wl_req), jnp.asarray(p.wl_req_mask),
            jnp.asarray(p.blim), jnp.asarray(p.blim_def),
            jnp.asarray(p.requestable), jnp.asarray(p.res_mask),
            jnp.asarray(p.cand_y), jnp.asarray(p.cand_use),
            jnp.asarray(p.cand_prio),
            jnp.asarray(p.has_cohort), jnp.asarray(p.lending),
            jnp.asarray(p.allow_borrowing),
            jnp.asarray(p.threshold is not None),
            jnp.asarray(p.threshold or 0, dtype=jnp.int32))
        return np.asarray(victim), np.asarray(fits)

    usage0, nominal, guaranteed, wl_req, blim, requestable, cand_use = scaled
    FR = usage0.shape[1]
    N = cand_use.shape[0]
    if FR > LANES:
        raise ValueError(f"FR={FR} exceeds one lane tile")

    def pad2(a, rows):
        return _pad_axis(_pad_axis(np.atleast_2d(a), 1, LANES), 0, rows)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scalars = np.asarray(
        [N, int(p.has_cohort), int(p.lending), int(p.allow_borrowing),
         int(p.threshold is not None), int(p.threshold or 0)],
        dtype=np.int32)
    victim, fits = _pallas_call(
        np.asarray(p.cand_y, dtype=np.int32),
        np.asarray(p.cand_prio, dtype=np.int32), scalars,
        pad2(usage0, ypad),
        # Padded rows must never look borrowing or over-quota: keep their
        # nominal at the sentinel and usage at zero.
        pad2(np.where(p.q_def, nominal, I32_SENTINEL), ypad),
        pad2(p.q_def.astype(np.int32), ypad),
        pad2(guaranteed, ypad),
        pad2(wl_req, 1), pad2(p.wl_req_mask.astype(np.int32), 1),
        pad2(np.where(p.blim_def, blim, I32_SENTINEL), 1),
        pad2(p.blim_def.astype(np.int32), 1),
        pad2(requestable, 1), pad2(p.res_mask.astype(np.int32), 1),
        _pad_axis(cand_use, 1, LANES),
        n=N, ypad=ypad, interpret=bool(interpret))
    return np.asarray(victim), np.asarray(fits)
