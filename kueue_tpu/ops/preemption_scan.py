"""minimalPreemptions as a device scan.

Counterpart of the greedy victim search in reference
pkg/scheduler/preemption/preemption.go:172-231 (`minimalPreemptions`) +
workloadFits (:352-389), reformulated for the accelerator:

  remove phase   a `lax.scan` over the ordered candidates; the carry is the
                 per-cohort-member usage tensor [Y, FR] plus the
                 allow-borrowing and done flags. Each step applies the
                 dynamic skip rule (cross-CQ candidates are skipped once
                 their CQ stops borrowing), the borrowWithinCohort
                 threshold flip, subtracts the candidate's usage, and
                 re-evaluates `workloadFits` — all masks and reductions,
                 no data-dependent branching.
  add-back phase a reverse `lax.scan` over the same candidates that re-adds
                 each taken victim and keeps it admitted when the preemptor
                 still fits (preemption.go:214-224).

The host wrapper `minimal_preemptions_device` is a drop-in for the
sequential `scheduler.preemption._minimal_preemptions` (bit-equal decisions;
see tests/test_preemption_scan.py's randomized equivalence harness).

Integer semantics are exact (int64). The Pallas TPU version of the same
scan lives in kueue_tpu.ops.preemption_pallas.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import kueue_tpu.ops  # noqa: F401  (enables x64 before tracing)
import jax
import jax.numpy as jnp

from kueue_tpu import features
from kueue_tpu.core.cache import CachedClusterQueue
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo

BIG = np.int64(2**62)


@dataclass
class Problem:
    """One minimalPreemptions instance, densely encoded.

    Axes: Y = cohort members (target ClusterQueue first), FR = the union of
    (flavor, resource) pairs any member's quota covers, N = ordered
    candidates.
    """

    members: List[str]
    fr_pairs: List[Tuple[str, str]]
    usage0: np.ndarray        # [Y, FR] int64
    nominal: np.ndarray       # [Y, FR] int64 (BIG where quota undefined)
    q_def: np.ndarray         # [Y, FR] bool: quota defined
    guaranteed: np.ndarray    # [Y, FR] int64
    wl_req: np.ndarray        # [FR] int64
    wl_req_mask: np.ndarray   # [FR] bool: pair requested by the preemptor
    blim: np.ndarray          # [FR] int64: target borrowingLimit (BIG if none)
    blim_def: np.ndarray      # [FR] bool
    requestable: np.ndarray   # [FR] int64: target requestable cohort quota
    res_mask: np.ndarray      # [FR] bool: resources requiring preemption
    cand_y: np.ndarray        # [N] int32: candidate's member index
    cand_use: np.ndarray      # [N, FR] int64
    cand_prio: np.ndarray     # [N] int32
    has_cohort: bool
    lending: bool
    allow_borrowing: bool
    threshold: Optional[int]


def encode_problem(cq: CachedClusterQueue, snapshot: Snapshot,
                   wl_req: Dict[str, Dict[str, int]],
                   res_per_flv: Dict[str, set],
                   candidates: Sequence[WorkloadInfo],
                   allow_borrowing: bool,
                   threshold: Optional[int]) -> Problem:
    """Tensorize one victim search against the tick snapshot."""
    members = [cq]
    if cq.cohort is not None:
        # Name order: the identity-hashed set iterates in memory-layout
        # order, and the member/pair tensor layout should not vary
        # between runs of the same cluster state.
        members += [m for m in cq.cohort.sorted_members() if m is not cq]
    member_idx = {m.name: i for i, m in enumerate(members)}

    pairs: List[Tuple[str, str]] = []
    pair_idx: Dict[Tuple[str, str], int] = {}
    for m in members:
        for fname, resources in m.usage.items():
            for rname in resources:
                key = (fname, rname)
                if key not in pair_idx:
                    pair_idx[key] = len(pairs)
                    pairs.append(key)
    Y, FR, N = len(members), len(pairs), len(candidates)

    usage0 = np.zeros((Y, FR), dtype=np.int64)
    nominal = np.full((Y, FR), BIG, dtype=np.int64)
    q_def = np.zeros((Y, FR), dtype=bool)
    guaranteed = np.zeros((Y, FR), dtype=np.int64)
    lending = features.enabled(features.LENDING_LIMIT)
    for yi, m in enumerate(members):
        for fname, resources in m.usage.items():
            for rname, used in resources.items():
                usage0[yi, pair_idx[(fname, rname)]] = used
        for rg in m.resource_groups:
            for fq in rg.flavors:
                for rname, quota in fq.resources:
                    fi = pair_idx.get((fq.name, rname))
                    if fi is None:
                        continue
                    nominal[yi, fi] = quota.nominal
                    q_def[yi, fi] = True
        if lending:
            for fname, resources in m.guaranteed_quota.items():
                for rname, g in resources.items():
                    fi = pair_idx.get((fname, rname))
                    if fi is not None:
                        guaranteed[yi, fi] = g

    wl_req_arr = np.zeros(FR, dtype=np.int64)
    wl_req_mask = np.zeros(FR, dtype=bool)
    for fname, resources in wl_req.items():
        for rname, v in resources.items():
            fi = pair_idx.get((fname, rname))
            if fi is not None:
                wl_req_arr[fi] = v
                wl_req_mask[fi] = True

    blim = np.full(FR, BIG, dtype=np.int64)
    blim_def = np.zeros(FR, dtype=bool)
    requestable = np.zeros(FR, dtype=np.int64)
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            for rname, quota in fq.resources:
                fi = pair_idx.get((fq.name, rname))
                if fi is None:
                    continue
                if quota.borrowing_limit is not None:
                    blim[fi] = quota.borrowing_limit
                    blim_def[fi] = True
                if cq.cohort is not None:
                    requestable[fi] = cq.requestable_cohort_quota(
                        fq.name, rname)

    res_mask = np.zeros(FR, dtype=bool)
    for fname, resources in res_per_flv.items():
        for rname in resources:
            fi = pair_idx.get((fname, rname))
            if fi is not None:
                res_mask[fi] = True

    cand_y = np.zeros(N, dtype=np.int32)
    cand_use = np.zeros((N, FR), dtype=np.int64)
    cand_prio = np.zeros(N, dtype=np.int32)
    for i, cand in enumerate(candidates):
        cand_y[i] = member_idx[cand.cluster_queue]
        # Only pairs the candidate's own CQ tracks count (_update_usage,
        # clusterqueue.go:473-485).
        tracked = snapshot.cluster_queues[cand.cluster_queue].usage
        for fname, resources in cand.usage().items():
            if fname not in tracked:
                continue
            for rname, v in resources.items():
                if rname not in tracked[fname]:
                    continue
                cand_use[i, pair_idx[(fname, rname)]] = v
        cand_prio[i] = cand.obj.priority

    return Problem(
        members=[m.name for m in members], fr_pairs=pairs,
        usage0=usage0, nominal=nominal, q_def=q_def, guaranteed=guaranteed,
        wl_req=wl_req_arr, wl_req_mask=wl_req_mask,
        blim=blim, blim_def=blim_def, requestable=requestable,
        res_mask=res_mask, cand_y=cand_y, cand_use=cand_use,
        cand_prio=cand_prio,
        has_cohort=cq.cohort is not None, lending=lending,
        allow_borrowing=allow_borrowing, threshold=threshold)


# ---------------------------------------------------------------------------
# The scan (jittable)
# ---------------------------------------------------------------------------


def _fits(U, wl_req, wl_req_mask, t_def, nominal0, blim, blim_def,
          guaranteed, requestable, has_cohort, lending, allow_b):
    """workloadFits (preemption.go:352-389) as masked reductions.

    `U` is [Y, FR]; row 0 is the target ClusterQueue.
    """
    check = t_def & wl_req_mask                       # [FR]
    own = U[0] + wl_req
    nominal_cap = jnp.where(check, own <= nominal0, True)
    # `own <= nominal0 + blim` via subtraction: both operands can carry the
    # BIG/NO_LIMIT 2^62 sentinel (and user quotas in canonical units reach
    # 2^60+), so the sum can pass 2^63 and wrap — flipping the verdict
    # against the host referee's exact arithmetic. `own - blim` stays in
    # range (own >= 0, blim >= 0). Proven safe by kueueverify TRC02.
    blim_cap = jnp.where(check & blim_def, own - blim <= nominal0, True)
    use_nominal = jnp.logical_or(~has_cohort, ~allow_b)
    own_ok = jnp.where(use_nominal, nominal_cap.all(), blim_cap.all())

    above = jnp.maximum(U - guaranteed, 0).sum(axis=0)      # [FR]
    cohort_used = above + jnp.where(lending, jnp.minimum(U[0], guaranteed[0]), 0)
    cohort_ok = jnp.where(check, cohort_used + wl_req <= requestable, True).all()
    return own_ok & jnp.logical_or(~has_cohort, cohort_ok)


def _scan_core(usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
               blim, blim_def, requestable, res_mask,
               cand_y, cand_use, cand_prio, cand_valid,
               has_cohort, lending, allow_b0, has_threshold, threshold):
    """Remove-until-fits + reverse add-back; returns (victim[N], fits).

    `cand_valid` masks padding rows when problems are batched to a common
    candidate count: a padded step must neither remove usage nor trigger a
    fits check (the host checks fits only after an actual removal)."""
    t_def = q_def[0]
    fits_fn = functools.partial(
        _fits, wl_req=wl_req, wl_req_mask=wl_req_mask, t_def=t_def,
        nominal0=nominal[0], blim=blim, blim_def=blim_def,
        guaranteed=guaranteed, requestable=requestable,
        has_cohort=has_cohort, lending=lending)

    def remove_step(carry, xs):
        U, allow_b, done = carry
        y, use, prio, valid = xs
        is_target = y == 0
        row = U[y]
        borrowing = (res_mask & q_def[y] & (row > nominal[y])).any()
        skip = (~is_target) & ~borrowing
        act = (~skip) & (~done) & valid
        allow_b = jnp.where(
            act & (~is_target) & has_threshold & (prio >= threshold),
            False, allow_b)
        U = U.at[y].add(jnp.where(act, -use, 0))
        # The host checks fits only after an actual removal
        # (skipped candidates fall through with `continue`).
        fits = fits_fn(U, allow_b=allow_b) & act
        done_after = done | fits
        return (U, allow_b, done_after), (act, done_after)

    carry0 = (usage0, allow_b0, jnp.asarray(False))
    (U_end, allow_b_end, fits_any), (taken, done_seq) = jax.lax.scan(
        remove_step, carry0, (cand_y, cand_use, cand_prio, cand_valid))

    # Victims = taken candidates up to and including the stop index.
    N = cand_y.shape[0]
    stop_idx = jnp.where(fits_any,
                         jnp.argmax(done_seq),
                         N)  # first True
    in_prefix = jnp.arange(N) <= stop_idx
    removed = taken & in_prefix

    def addback_step(carry, xs):
        U, victim_count = carry
        i, y, use = xs
        # The last removed candidate is never re-added
        # (preemption.go:214 starts at len(targets)-2).
        is_last = i == stop_idx
        tentative = removed[i] & (~is_last)
        U_try = U.at[y].add(jnp.where(tentative, use, 0))
        fits = fits_fn(U_try, allow_b=allow_b_end)
        keep_added = tentative & fits
        U = jnp.where(keep_added, U_try, U)
        victim = removed[i] & ~keep_added
        return (U, victim_count + victim), victim

    idx_rev = jnp.arange(N - 1, -1, -1)
    (_, n_victims), victim_rev = jax.lax.scan(
        addback_step, (U_end, jnp.asarray(0)),
        (idx_rev, cand_y[idx_rev], cand_use[idx_rev]))
    victim = victim_rev[::-1]
    victim = jnp.where(fits_any, victim, False)
    return victim, fits_any


@jax.jit
def scan_kernel(usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
                blim, blim_def, requestable, res_mask,
                cand_y, cand_use, cand_prio,
                has_cohort, lending, allow_b0, has_threshold, threshold):
    """Single-problem entry (all candidates valid)."""
    return _scan_core(
        usage0, nominal, q_def, guaranteed, wl_req, wl_req_mask,
        blim, blim_def, requestable, res_mask,
        cand_y, cand_use, cand_prio,
        jnp.ones(cand_y.shape[0], dtype=bool),
        has_cohort, lending, allow_b0, has_threshold, threshold)


def minimal_preemptions_device(
        wl_req: Dict[str, Dict[str, int]],
        cq: CachedClusterQueue, snapshot: Snapshot,
        res_per_flv: Dict[str, set],
        candidates: Sequence[WorkloadInfo],
        allow_borrowing: bool,
        allow_borrowing_below_priority: Optional[int],
        backend: str = "jax") -> List[WorkloadInfo]:
    """Drop-in for scheduler.preemption._minimal_preemptions, solved on the
    device. Does not mutate the snapshot (the host version restores it)."""
    if not candidates:
        return []
    p = encode_problem(cq, snapshot, wl_req, res_per_flv, candidates,
                       allow_borrowing, allow_borrowing_below_priority)
    if backend == "pallas":
        from kueue_tpu.ops.preemption_pallas import scan_kernel_pallas
        victim, fits = scan_kernel_pallas(p)
    else:
        victim, fits = scan_kernel(
            jnp.asarray(p.usage0), jnp.asarray(p.nominal),
            jnp.asarray(p.q_def), jnp.asarray(p.guaranteed),
            jnp.asarray(p.wl_req), jnp.asarray(p.wl_req_mask),
            jnp.asarray(p.blim), jnp.asarray(p.blim_def),
            jnp.asarray(p.requestable), jnp.asarray(p.res_mask),
            jnp.asarray(p.cand_y), jnp.asarray(p.cand_use),
            jnp.asarray(p.cand_prio),
            jnp.asarray(p.has_cohort), jnp.asarray(p.lending),
            jnp.asarray(p.allow_borrowing),
            jnp.asarray(p.threshold is not None),
            jnp.asarray(p.threshold if p.threshold is not None else 0,
                        dtype=jnp.int32))
    if not bool(fits):
        return []
    mask = np.asarray(victim)
    return [c for i, c in enumerate(candidates) if mask[i]]
