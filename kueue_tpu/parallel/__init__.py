"""Device-mesh sharding of the admission solve."""

from kueue_tpu.parallel.mesh import make_mesh, sharded_flavor_fit
