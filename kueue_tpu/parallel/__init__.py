"""Device-mesh sharding of the admission solve."""

from kueue_tpu.parallel.mesh import (
    CohortMesh,
    ShardAssignment,
    assign_shards,
    cohort_sharded_solve,
    make_mesh,
    sharded_flavor_fit,
)
