"""Multi-chip sharding of the admission solve.

This is the ICI-scaling story of the framework (the analog of the reference's
intra-process parallelize.Until + multi-replica deployment, mapped onto a TPU
device mesh):

  * ClusterQueue usage state is sharded across devices on the CQ axis; cohort
    aggregates (requestable/lending pools and above-guaranteed usage,
    snapshot.go:160-201) are computed with on-device `segment_sum` + `psum`
    collectives, and the full usage view is rebuilt with a tiled
    `all_gather` -- all riding ICI.
  * The pending-workload batch is data-parallel over the same mesh axis:
    each device solves its workload shard against the replicated snapshot
    (valid because heads are independent within a tick;
    scheduler.go:317-351).

All shapes are padded host-side to multiples of the mesh size, and the
compiled sharded program is cached per (mesh, shape) so steady-state ticks
re-dispatch without re-tracing.
"""

from __future__ import annotations

import functools
import zlib
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu import features
from kueue_tpu.models.flavor_fit import solve_core

AXIS = "wl"
SHARD_AXIS = "shard"

_PROGRAM_CACHE: Dict[Tuple, "jax.stages.Wrapped"] = {}


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            # Fail loudly: silently running on fewer chips than configured
            # would leave the operator believing N-way sharding is active.
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devices)} device(s) are visible")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def _pad_axis(x: np.ndarray, axis: int, multiple: int) -> np.ndarray:
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return np.pad(x, pad)


def _build_program(mesh: Mesh, C: int, K: int, num_slots: int,
                   fungibility_enabled: bool, has_hier: bool):
    sharded = P(AXIS)
    repl = P()

    # The hierarchical cohort-forest tensors (KEP-79) are replicated: they
    # are node/CQ-indexed statics, and solve_core's per-node T aggregation
    # runs on the all_gather-rebuilt full usage view, so every device
    # computes identical tree balances. P() broadcasts over the pytree.
    in_specs = (sharded, sharded, sharded, sharded,   # usage/guar/lend/cohort_id (C axis)
                repl, repl, repl, repl,               # nominal/blim/guar_full/cohort_id_full
                repl, repl, repl, repl, repl, repl,   # group/slot/nf/policies
                sharded, sharded, sharded, sharded, sharded, sharded, sharded)
    if has_hier:
        in_specs = in_specs + (repl,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs,
        out_specs=sharded,
        check_rep=False)
    def run(usage_shard, guar_shard, lend_shard, cid_shard,
            nominal, borrow_limit, guaranteed, cohort_id_full,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_pol, preempt_pol,
            wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
            hier=None):
        # --- cohort aggregation over the sharded CQ axis (ICI psum) ---
        # The closure captures below (K, C, num_slots, fungibility_enabled)
        # are safe: every captured value is part of the _PROGRAM_CACHE key,
        # so a different value builds (and caches) a fresh program instead
        # of silently retracing this one.
        above = jnp.maximum(usage_shard - guar_shard, 0)
        part_cu = jax.ops.segment_sum(
            above, cid_shard, num_segments=K + 1)  # kueuelint: disable=RET02
        cohort_usage = jax.lax.psum(part_cu, AXIS)[:K]
        part_cr = jax.ops.segment_sum(lend_shard, cid_shard, num_segments=K + 1)
        cohort_requestable = jax.lax.psum(part_cr, AXIS)[:K]
        # Rebuild the full usage view for the workload-side gathers AND the
        # hierarchy aggregation (per-node T balances need every leaf).
        usage_full = jax.lax.all_gather(usage_shard, AXIS, axis=0, tiled=True)

        return solve_core(
            nominal, borrow_limit, guaranteed,
            usage_full[:C],  # kueuelint: disable=RET02
            cohort_requestable, cohort_usage, cohort_id_full,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_pol, preempt_pol,
            wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
            num_slots=num_slots,  # kueuelint: disable=RET02
            fungibility_enabled=fungibility_enabled,  # kueuelint: disable=RET02
            hier=hier)

    return jax.jit(run)


def sharded_flavor_fit(enc, usage_tensors, wt, mesh: Mesh) -> Dict[str, np.ndarray]:
    """Run the batched flavor-fit solve sharded over `mesh`.

    CQ usage aggregation happens on-device (psum over the mesh axis); the
    workload axis is data-parallel. Returns the same outputs as
    `models.flavor_fit.solve_flavor_fit`, truncated to the input sizes.
    """
    n_dev = mesh.devices.size
    C = enc.nominal.shape[0]
    W = wt.wl_cq.shape[0]
    K = enc.num_cohorts
    fungible = features.enabled(features.FLAVOR_FUNGIBILITY)
    h = enc.hier
    hier_shape = None if h is None else (
        h.node_own_nominal.shape, h.cq_path.shape,
        tuple(len(n) for n, _ in h.levels))

    key = (id(mesh), n_dev, C, K, W, enc.num_slots, fungible,
           wt.req.shape, wt.elig.shape, hier_shape)
    program = _PROGRAM_CACHE.get(key)
    if program is None:
        program = _build_program(mesh, C, K, enc.num_slots, fungible,
                                 h is not None)
        _PROGRAM_CACHE[key] = program

    # Pad the sharded axes to multiples of the mesh size.
    usage = _pad_axis(usage_tensors.usage, 0, n_dev)
    guaranteed_p = _pad_axis(enc.guaranteed, 0, n_dev)
    lendable_p = _pad_axis(enc.lendable, 0, n_dev)
    # Padding CQs land in a dead cohort slot (K) that no real CQ reads.
    cohort_id_p = _pad_axis(enc.cohort_id, 0, n_dev)
    cohort_id_p[C:] = K

    args = (
        jnp.asarray(usage), jnp.asarray(guaranteed_p), jnp.asarray(lendable_p),
        jnp.asarray(cohort_id_p),
        jnp.asarray(enc.nominal), jnp.asarray(enc.borrow_limit),
        jnp.asarray(enc.guaranteed), jnp.asarray(enc.cohort_id),
        jnp.asarray(enc.group_of_resource), jnp.asarray(enc.slot_flavor),
        jnp.asarray(enc.num_flavors),
        jnp.asarray(enc.bwc_enabled), jnp.asarray(enc.borrow_policy_is_borrow),
        jnp.asarray(enc.preempt_policy_is_preempt),
        jnp.asarray(_pad_axis(wt.wl_cq, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.req, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.has_req, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.podset_valid, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.podset_unsat, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.elig, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.resume_slot, 0, n_dev)),
    )
    if h is not None:
        # KEP-79 forest, replicated across the mesh (same tensors the
        # single-device packed kernel consumes via device_static).
        args = args + ((
            jnp.asarray(h.node_own_nominal), jnp.asarray(h.node_blim),
            jnp.asarray(h.node_lend), jnp.asarray(h.cq_node),
            jnp.asarray(h.cq_lend), jnp.asarray(h.cq_hier),
            jnp.asarray(h.cq_path),
            tuple((jnp.asarray(n), jnp.asarray(p)) for n, p in h.levels)),)
    out = program(*args)
    return {k: np.asarray(v)[:W] if v.ndim >= 1 else np.asarray(v)
            for k, v in out.items()}


# ---------------------------------------------------------------------------
# Cohort-sharded solve: shard_map over a cohort-hash device mesh
# ---------------------------------------------------------------------------
#
# The production scale-out seam (ROADMAP item 1): the admission problem
# partitions cleanly by cohort — a workload's fit reads only its own
# ClusterQueue's row and its cohort's member rows, never another cohort's
# — so hashing cohorts onto a device mesh makes the whole batched solve
# embarrassingly parallel: each shard solves its own cohorts' workloads as
# a compacted, per-shard-padded block, with NO collectives at all (the
# `wl`-axis mesh above needed psum/all_gather because it split cohorts
# mid-aggregate; the cohort hash never does). The only cross-shard step
# left is the host-side lending-clamp reconcile of the admission cycle
# (scheduler._admission_cycle phase B), which is O(deferred entries), not
# O(backlog).
#
# Hierarchical cohort forests (KEP-79) hash by DIRECT cohort name, so one
# tree's subtrees may land on different shards. That is deliberate: the
# tree is the one structure whose quota math spans cohorts, and the
# two-phase admit cycle (optimistic per-shard solve, then a global clamp
# pass that revokes over-borrowed admissions) is exactly Aryl's
# cluster-level capacity-loaning loop mapped onto the mesh. `split_roots`
# names the trees that need it.


def _crc_shard(name: str, n_shards: int) -> int:
    """Stable cohort-name hash (process-independent: two scheduler
    replicas must agree on the shard of every cohort)."""
    return zlib.crc32(name.encode("utf-8")) % n_shards


@dataclass(frozen=True)
class ShardAssignment:
    """Cohort-hash shard assignment for one CQ-encoding generation."""

    n_shards: int
    shard_of_cohort: np.ndarray        # [K] i32
    shard_of_cq: np.ndarray            # [C] i32
    # Hierarchical cohort roots whose member CQs span >1 shard: the only
    # structures whose admission bookkeeping crosses shards, hence the
    # only entries the admit cycle routes through the reconcile pass.
    split_roots: FrozenSet[str]


def assign_shards(enc, n_shards: int) -> ShardAssignment:
    """Hash the encoding's cohorts onto `n_shards` shards.

    Flat cohorts (including the `__solo__/` singletons of cohort-less
    ClusterQueues) are self-contained — every CQ a workload's fit can
    read lives on its own shard. Hierarchical trees hash by direct
    cohort, so subtrees may split; the roots that do are reported in
    `split_roots` for the admit cycle's two-phase reconcile."""
    shard_of_cohort = np.fromiter(
        (_crc_shard(name, n_shards) for name in enc.cohort_names),
        dtype=np.int32, count=len(enc.cohort_names))
    shard_of_cq = shard_of_cohort[enc.cohort_id]
    split: set = set()
    h = enc.hier
    if h is not None and n_shards > 1:
        root_shards: Dict[int, set] = {}
        for ci in np.nonzero(h.cq_hier)[0]:
            path = h.cq_path[ci]
            valid = path[path >= 0]
            if not len(valid):
                continue
            root = int(valid[-1])
            root_shards.setdefault(root, set()).add(int(shard_of_cq[ci]))
        for root, shards in root_shards.items():
            if len(shards) > 1:
                split.add(h.node_names[root])
    return ShardAssignment(
        n_shards=n_shards, shard_of_cohort=shard_of_cohort,
        shard_of_cq=shard_of_cq, split_roots=frozenset(split))


class CohortMesh:
    """An n-shard device mesh partitioned by cohort hash.

    Owns the jax Mesh plus the per-encoding shard-assignment cache; the
    solver asks `assignment(enc)` once per encoding generation and the
    scheduler reads the same object's `split_roots` for the two-phase
    admit cycle."""

    def __init__(self, n_shards: Optional[int] = None,
                 devices: Optional[list] = None):
        if devices is None:
            devices = jax.devices()
        if n_shards is None:
            n_shards = len(devices)
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        if n_shards > len(devices):
            # Fail loudly, like make_mesh: silently running on fewer
            # chips than configured would misreport the sharding factor.
            raise ValueError(
                f"requested a {n_shards}-shard cohort mesh but only "
                f"{len(devices)} device(s) are visible")
        self.n_shards = n_shards
        self.mesh = Mesh(np.array(devices[:n_shards]), (SHARD_AXIS,))
        # enc identity -> (enc, ShardAssignment). The encoding ref is
        # HELD in the value: cached entries keep their encodings alive,
        # so an id() can never be recycled onto a different live
        # encoding and return a stale assignment (identity re-checked on
        # hit regardless).
        self._assignments: Dict[int, tuple] = {}

    def assignment(self, enc) -> ShardAssignment:
        hit = self._assignments.get(id(enc))
        if hit is not None and hit[0] is enc:
            return hit[1]
        if len(self._assignments) > 8:
            self._assignments.clear()
        a = assign_shards(enc, self.n_shards)
        self._assignments[id(enc)] = (enc, a)
        return a


def shard_solve_body(
    nominal, borrow_limit, guaranteed, lendable, cohort_id,
    group_of_resource, slot_flavor, num_flavors,
    bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
    hier, usage,
    wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
    hetero=None,
    *, num_slots: int, num_cohorts: int, fungibility_enabled: bool,
):
    """One shard's solve: the exact per-shard program `shard_map` runs on
    each device — cohort aggregation from the broadcast usage view, then
    `solve_core` over the shard's compacted workload block. Kept as a
    standalone traceable function so kueueverify lowers it like every
    other registered kernel (TRC01-04), and so the TRC03-across-shard-
    counts test can pin that the per-shard jaxpr depends only on the
    padded bucket, never on the shard count (the one-compile-per-bucket
    contract, per shard).

    Identical arithmetic to `_solve_kernel_packed`'s aggregation: the
    sharded outputs are bitwise equal to the single-device kernel's on
    the same rows."""
    above = jnp.maximum(usage - guaranteed, 0)
    cohort_usage = jax.ops.segment_sum(
        above, cohort_id, num_segments=num_cohorts)
    cohort_requestable = jax.ops.segment_sum(
        lendable, cohort_id, num_segments=num_cohorts)
    return solve_core(
        nominal, borrow_limit, guaranteed, usage,
        cohort_requestable, cohort_usage, cohort_id,
        group_of_resource, slot_flavor, num_flavors,
        bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
        wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
        num_slots=num_slots, fungibility_enabled=fungibility_enabled,
        hier=hier, hetero=hetero)


def _build_cohort_program(cmesh: CohortMesh, num_slots: int,
                          num_cohorts: int, fungibility_enabled: bool,
                          has_hier: bool, has_hetero: bool = False):
    repl = P()
    sharded = P(SHARD_AXIS)
    # CQ statics + usage broadcast (each shard READS only its own
    # cohorts' rows — the gathers are wl_cq-indexed — but the tensor is
    # replicated so the layout matches the single-device kernel exactly);
    # the 7 workload tensors — plus the per-shard hetero score/profile
    # views in hetero mode — are block-sharded on the leading axis.
    n_wl = 7 + (2 if has_hetero else 0)
    in_specs = (repl,) * 11 + ((repl,) if has_hier else ()) + (repl,) \
        + (sharded,) * n_wl

    def run(nominal, borrow_limit, guaranteed, lendable, cohort_id,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_policy_is_borrow, preempt_policy_is_preempt,
            *rest):
        if has_hier:
            hier, usage = rest[0], rest[1]
            wl = rest[2:]
        else:
            hier, usage = None, rest[0]
            wl = rest[1:]
        hetero = None
        if has_hetero:
            # The trailing two block-sharded tensors are this shard's
            # score-matrix view and profiled mask (each shard reads only
            # its own rows — the per-shard matrix view).
            hetero = (wl[-2], wl[-1])
            wl = wl[:-2]
        # Closure captures (num_slots/num_cohorts/fungibility) are safe:
        # every captured value is part of the _PROGRAM_CACHE key, so a
        # different value builds a fresh program instead of retracing.
        return shard_solve_body(
            nominal, borrow_limit, guaranteed, lendable, cohort_id,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_policy_is_borrow,
            preempt_policy_is_preempt, hier, usage, *wl, hetero,
            num_slots=num_slots, num_cohorts=num_cohorts,
            fungibility_enabled=fungibility_enabled)

    run = shard_map(run, mesh=cmesh.mesh, in_specs=in_specs,
                    out_specs=sharded, check_rep=False)
    return jax.jit(run)


def plan_shards(assignment: ShardAssignment, wl_cq: np.ndarray, n: int,
                min_bucket: int = 8):
    """Per-shard compaction plan for a batch of `n` workloads.

    Returns (dest, counts, Ws): `dest[i]` is row i's slot in the stacked
    `[n_shards * Ws]` layout (shard-major, compacted within shard in
    batch order — decision order inside a shard is preserved), `counts`
    the per-shard real row counts, `Ws` the shared per-shard padded
    bucket (pow2 of the largest shard's count — the per-shard twin of
    the W-axis bucketing, so steady ticks reuse one compiled program)."""
    from kueue_tpu.solver.schema import _pad_pow2

    shards = assignment.shard_of_cq[wl_cq[:n]]
    counts = np.bincount(shards, minlength=assignment.n_shards)
    Ws = _pad_pow2(int(counts.max()) if n else 1, floor=min_bucket)
    # Rank within shard, preserving batch order: stable argsort by shard
    # then positions within each shard run.
    order = np.argsort(shards, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n) - np.repeat(
        np.concatenate(([0], np.cumsum(counts)[:-1])), counts)
    dest = shards.astype(np.int64) * Ws + rank
    return dest, counts, Ws


def _cohort_program_key(cmesh: CohortMesh, enc, Ws: int, P_: int,
                        fungible: bool, has_hetero: bool = False):
    h = enc.hier
    hier_shape = None if h is None else (
        h.node_own_nominal.shape, h.cq_path.shape,
        tuple(len(n) for n, _ in h.levels))
    C, F, R = enc.nominal.shape
    return ("cohort-shard", id(cmesh.mesh), cmesh.n_shards, Ws, P_, R,
            enc.num_groups, enc.num_slots, C, F, enc.num_cohorts,
            fungible, hier_shape, has_hetero)


def _cohort_program(cmesh: CohortMesh, enc, Ws: int, P_: int,
                    fungible: bool, has_hetero: bool = False):
    key = _cohort_program_key(cmesh, enc, Ws, P_, fungible, has_hetero)
    program = _PROGRAM_CACHE.get(key)
    if program is None:
        program = _build_cohort_program(
            cmesh, enc.num_slots, enc.num_cohorts, fungible,
            enc.hier is not None, has_hetero)
        _PROGRAM_CACHE[key] = program
    return program


def _static_args(enc) -> tuple:
    base = tuple(jnp.asarray(x) for x in (
        enc.nominal, enc.borrow_limit, enc.guaranteed, enc.lendable,
        enc.cohort_id, enc.group_of_resource, enc.slot_flavor,
        enc.num_flavors, enc.bwc_enabled, enc.borrow_policy_is_borrow,
        enc.preempt_policy_is_preempt))
    h = enc.hier
    if h is None:
        return base
    return base + ((
        jnp.asarray(h.node_own_nominal), jnp.asarray(h.node_blim),
        jnp.asarray(h.node_lend), jnp.asarray(h.cq_node),
        jnp.asarray(h.cq_lend), jnp.asarray(h.cq_hier),
        jnp.asarray(h.cq_path),
        tuple((jnp.asarray(n), jnp.asarray(p)) for n, p in h.levels)),)


def cohort_sharded_solve(enc, usage_tensors, wt, cmesh: CohortMesh,
                         hetero=None,
                         ) -> Tuple[Dict[str, np.ndarray], dict]:
    """Run the batched flavor-fit solve cohort-sharded over `cmesh`.

    Each shard solves its own cohorts' workloads as one compacted
    `[Ws, ...]` block (per-shard padded bucket); no collectives cross
    shards. Returns `(outputs, stats)` where outputs are in the batch's
    ORIGINAL row order truncated to the real row count (decision order is
    untouched — downstream decode/CSR consume them exactly like the
    single-device kernel's), and stats carries the per-shard head counts
    and the padded bucket for the bench's imbalance metrics."""
    assignment = cmesh.assignment(enc)
    n = wt.num_real
    dest, counts, Ws = plan_shards(assignment, wt.wl_cq, n)
    S = assignment.n_shards
    WsS = S * Ws
    P_ = wt.req.shape[1]
    R = wt.req.shape[2]
    G = wt.resume_slot.shape[2]

    wl_cq = np.zeros(WsS, dtype=np.int32)
    req = np.zeros((WsS, P_, R), dtype=np.int64)
    has_req = np.zeros((WsS, P_, R), dtype=bool)
    podset_valid = np.zeros((WsS, P_), dtype=bool)
    podset_unsat = np.zeros((WsS, P_), dtype=bool)
    elig = np.zeros((WsS,) + wt.elig.shape[1:], dtype=bool)
    resume_slot = np.zeros((WsS, P_, G), dtype=np.int32)
    if n:
        wl_cq[dest] = wt.wl_cq[:n]
        req[dest] = wt.req[:n]
        has_req[dest] = wt.has_req[:n]
        podset_valid[dest] = wt.podset_valid[:n]
        podset_unsat[dest] = wt.podset_unsat[:n]
        elig[dest] = wt.elig[:n]
        resume_slot[dest] = wt.resume_slot[:n]

    fungible = features.enabled(features.FLAVOR_FUNGIBILITY)
    program = _cohort_program(cmesh, enc, Ws, P_, fungible,
                              hetero is not None)
    args = _static_args(enc) + (
        jnp.asarray(usage_tensors.usage),
        jnp.asarray(wl_cq), jnp.asarray(req), jnp.asarray(has_req),
        jnp.asarray(podset_valid), jnp.asarray(podset_unsat),
        jnp.asarray(elig), jnp.asarray(resume_slot))
    if hetero is not None:
        # Per-shard score-matrix views: the [W,F] scores and profiled
        # mask compact through the SAME dest plan as the workload
        # tensors, so each shard's block carries exactly its own rows.
        h_score, h_prof = hetero
        F_ = h_score.shape[1]
        score_s = np.zeros((WsS, F_), dtype=np.int64)
        prof_s = np.zeros(WsS, dtype=bool)
        if n:
            score_s[dest] = h_score[:n]
            prof_s[dest] = h_prof[:n]
        args = args + (jnp.asarray(score_s), jnp.asarray(prof_s))
    out = program(*args)
    out = jax.device_get(out)
    stats = {"shard_heads": counts, "shard_bucket": Ws,
             "n_shards": S}
    if n:
        out = {k: np.asarray(v)[dest] for k, v in out.items()}
    else:
        out = {k: np.asarray(v)[:0] for k, v in out.items()}
    return out, stats


def prewarm_cohort_program(enc, cmesh: CohortMesh, Ws: int, P_: int,
                           fungible: bool, hetero: bool = False) -> None:
    """Compile the cohort-sharded program for one per-shard bucket NOW
    (all-zeros inputs; compilation depends only on shapes/dtypes) — the
    sharded twin of BatchSolver._prewarm_one, called from the idle
    window so a per-shard bucket rotation never compiles in-tick."""
    S = cmesh.n_shards
    WsS = S * Ws
    R = len(enc.resource_names)
    G = enc.num_groups
    S_slots = enc.num_slots
    program = _cohort_program(cmesh, enc, Ws, P_, fungible, hetero)
    args = _static_args(enc) + (
        jnp.zeros(enc.nominal.shape, dtype=jnp.int64),
        jnp.zeros(WsS, dtype=jnp.int32),
        jnp.zeros((WsS, P_, R), dtype=jnp.int64),
        jnp.zeros((WsS, P_, R), dtype=bool),
        jnp.zeros((WsS, P_), dtype=bool),
        jnp.zeros((WsS, P_), dtype=bool),
        jnp.zeros((WsS, P_, G, S_slots), dtype=bool),
        jnp.zeros((WsS, P_, G), dtype=jnp.int32))
    if hetero:
        F_ = enc.nominal.shape[1]
        args = args + (jnp.zeros((WsS, F_), dtype=jnp.int64),
                       jnp.zeros(WsS, dtype=bool))
    jax.block_until_ready(program(*args))


# -- cohort-sharded fair shares (KEP-1714 over the cohort mesh) -------------


def _share_program(cmesh: CohortMesh):
    """Per-shard weighted-DRF share pass: shard_map over the CQ axis with
    ZERO collectives — a ClusterQueue's share reads only its own usage
    row and its structural capacity row (the cohort denominators are
    baked into `cap` per CQ), so any partition of the CQ axis is valid
    and each device scores its block independently."""
    key = ("fair-share", id(cmesh.mesh), cmesh.n_shards)
    program = _PROGRAM_CACHE.get(key)
    if program is not None:
        return program
    from kueue_tpu.models.fair_share import _weighted_shares_xp

    sharded = P(SHARD_AXIS)

    def run(nominal, usage, cap, weight):
        above = jnp.maximum(usage - nominal, 0).sum(axis=1)    # [c,R]
        # The SAME arithmetic function as the numpy referee twin and the
        # bulk kernel — the bitwise-identity contract is structural, not
        # a hand-synced copy.
        return _weighted_shares_xp(jnp, above, cap, weight)[0]

    program = jax.jit(shard_map(
        run, mesh=cmesh.mesh, in_specs=(sharded,) * 4,
        out_specs=sharded, check_rep=False))
    _PROGRAM_CACHE[key] = program
    return program


def sharded_fair_shares(cmesh: CohortMesh, nominal: np.ndarray,
                        usage: np.ndarray, cap: np.ndarray,
                        weight: np.ndarray) -> np.ndarray:
    """[C] weighted share values over the cohort mesh, bitwise-identical
    to the host arithmetic (models/fair_share.weighted_shares_np): the
    integer ratio and the float64 division are the same IEEE ops on
    every backend. Rows are padded to a shard multiple with zero
    usage/cap (share 0) and truncated on return."""
    C = nominal.shape[0]
    S = cmesh.n_shards
    pad = (-C) % S
    if pad:
        nominal = np.concatenate(
            [nominal, np.zeros((pad,) + nominal.shape[1:], nominal.dtype)])
        usage = np.concatenate(
            [usage, np.zeros((pad,) + usage.shape[1:], usage.dtype)])
        cap = np.concatenate(
            [cap, np.zeros((pad,) + cap.shape[1:], cap.dtype)])
        weight = np.concatenate([weight, np.zeros(pad, weight.dtype)])
    program = _share_program(cmesh)
    out = jax.device_get(program(
        jnp.asarray(nominal), jnp.asarray(usage),
        jnp.asarray(cap), jnp.asarray(weight)))
    return np.asarray(out[:C])
