"""Multi-chip sharding of the admission solve.

This is the ICI-scaling story of the framework (the analog of the reference's
intra-process parallelize.Until + multi-replica deployment, mapped onto a TPU
device mesh):

  * ClusterQueue usage state is sharded across devices on the CQ axis; cohort
    aggregates (requestable/lending pools and above-guaranteed usage,
    snapshot.go:160-201) are computed with on-device `segment_sum` + `psum`
    collectives, and the full usage view is rebuilt with a tiled
    `all_gather` -- all riding ICI.
  * The pending-workload batch is data-parallel over the same mesh axis:
    each device solves its workload shard against the replicated snapshot
    (valid because heads are independent within a tick;
    scheduler.go:317-351).

All shapes are padded host-side to multiples of the mesh size, and the
compiled sharded program is cached per (mesh, shape) so steady-state ticks
re-dispatch without re-tracing.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu import features
from kueue_tpu.models.flavor_fit import solve_core

AXIS = "wl"

_PROGRAM_CACHE: Dict[Tuple, "jax.stages.Wrapped"] = {}


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            # Fail loudly: silently running on fewer chips than configured
            # would leave the operator believing N-way sharding is active.
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devices)} device(s) are visible")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def _pad_axis(x: np.ndarray, axis: int, multiple: int) -> np.ndarray:
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return np.pad(x, pad)


def _build_program(mesh: Mesh, C: int, K: int, num_slots: int,
                   fungibility_enabled: bool, has_hier: bool):
    sharded = P(AXIS)
    repl = P()

    # The hierarchical cohort-forest tensors (KEP-79) are replicated: they
    # are node/CQ-indexed statics, and solve_core's per-node T aggregation
    # runs on the all_gather-rebuilt full usage view, so every device
    # computes identical tree balances. P() broadcasts over the pytree.
    in_specs = (sharded, sharded, sharded, sharded,   # usage/guar/lend/cohort_id (C axis)
                repl, repl, repl, repl,               # nominal/blim/guar_full/cohort_id_full
                repl, repl, repl, repl, repl, repl,   # group/slot/nf/policies
                sharded, sharded, sharded, sharded, sharded, sharded, sharded)
    if has_hier:
        in_specs = in_specs + (repl,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs,
        out_specs=sharded,
        check_rep=False)
    def run(usage_shard, guar_shard, lend_shard, cid_shard,
            nominal, borrow_limit, guaranteed, cohort_id_full,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_pol, preempt_pol,
            wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
            hier=None):
        # --- cohort aggregation over the sharded CQ axis (ICI psum) ---
        # The closure captures below (K, C, num_slots, fungibility_enabled)
        # are safe: every captured value is part of the _PROGRAM_CACHE key,
        # so a different value builds (and caches) a fresh program instead
        # of silently retracing this one.
        above = jnp.maximum(usage_shard - guar_shard, 0)
        part_cu = jax.ops.segment_sum(
            above, cid_shard, num_segments=K + 1)  # kueuelint: disable=RET02
        cohort_usage = jax.lax.psum(part_cu, AXIS)[:K]
        part_cr = jax.ops.segment_sum(lend_shard, cid_shard, num_segments=K + 1)
        cohort_requestable = jax.lax.psum(part_cr, AXIS)[:K]
        # Rebuild the full usage view for the workload-side gathers AND the
        # hierarchy aggregation (per-node T balances need every leaf).
        usage_full = jax.lax.all_gather(usage_shard, AXIS, axis=0, tiled=True)

        return solve_core(
            nominal, borrow_limit, guaranteed,
            usage_full[:C],  # kueuelint: disable=RET02
            cohort_requestable, cohort_usage, cohort_id_full,
            group_of_resource, slot_flavor, num_flavors,
            bwc_enabled, borrow_pol, preempt_pol,
            wl_cq, req, has_req, podset_valid, podset_unsat, elig, resume_slot,
            num_slots=num_slots,  # kueuelint: disable=RET02
            fungibility_enabled=fungibility_enabled,  # kueuelint: disable=RET02
            hier=hier)

    return jax.jit(run)


def sharded_flavor_fit(enc, usage_tensors, wt, mesh: Mesh) -> Dict[str, np.ndarray]:
    """Run the batched flavor-fit solve sharded over `mesh`.

    CQ usage aggregation happens on-device (psum over the mesh axis); the
    workload axis is data-parallel. Returns the same outputs as
    `models.flavor_fit.solve_flavor_fit`, truncated to the input sizes.
    """
    n_dev = mesh.devices.size
    C = enc.nominal.shape[0]
    W = wt.wl_cq.shape[0]
    K = enc.num_cohorts
    fungible = features.enabled(features.FLAVOR_FUNGIBILITY)
    h = enc.hier
    hier_shape = None if h is None else (
        h.node_own_nominal.shape, h.cq_path.shape,
        tuple(len(n) for n, _ in h.levels))

    key = (id(mesh), n_dev, C, K, W, enc.num_slots, fungible,
           wt.req.shape, wt.elig.shape, hier_shape)
    program = _PROGRAM_CACHE.get(key)
    if program is None:
        program = _build_program(mesh, C, K, enc.num_slots, fungible,
                                 h is not None)
        _PROGRAM_CACHE[key] = program

    # Pad the sharded axes to multiples of the mesh size.
    usage = _pad_axis(usage_tensors.usage, 0, n_dev)
    guaranteed_p = _pad_axis(enc.guaranteed, 0, n_dev)
    lendable_p = _pad_axis(enc.lendable, 0, n_dev)
    # Padding CQs land in a dead cohort slot (K) that no real CQ reads.
    cohort_id_p = _pad_axis(enc.cohort_id, 0, n_dev)
    cohort_id_p[C:] = K

    args = (
        jnp.asarray(usage), jnp.asarray(guaranteed_p), jnp.asarray(lendable_p),
        jnp.asarray(cohort_id_p),
        jnp.asarray(enc.nominal), jnp.asarray(enc.borrow_limit),
        jnp.asarray(enc.guaranteed), jnp.asarray(enc.cohort_id),
        jnp.asarray(enc.group_of_resource), jnp.asarray(enc.slot_flavor),
        jnp.asarray(enc.num_flavors),
        jnp.asarray(enc.bwc_enabled), jnp.asarray(enc.borrow_policy_is_borrow),
        jnp.asarray(enc.preempt_policy_is_preempt),
        jnp.asarray(_pad_axis(wt.wl_cq, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.req, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.has_req, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.podset_valid, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.podset_unsat, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.elig, 0, n_dev)),
        jnp.asarray(_pad_axis(wt.resume_slot, 0, n_dev)),
    )
    if h is not None:
        # KEP-79 forest, replicated across the mesh (same tensors the
        # single-device packed kernel consumes via device_static).
        args = args + ((
            jnp.asarray(h.node_own_nominal), jnp.asarray(h.node_blim),
            jnp.asarray(h.node_lend), jnp.asarray(h.cq_node),
            jnp.asarray(h.cq_lend), jnp.asarray(h.cq_hier),
            jnp.asarray(h.cq_path),
            tuple((jnp.asarray(n), jnp.asarray(p)) for n, p in h.levels)),)
    out = program(*args)
    return {k: np.asarray(v)[:W] if v.ndim >= 1 else np.asarray(v)
            for k, v in out.items()}
