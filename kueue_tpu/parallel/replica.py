"""Multi-process replica scheduling: shard-group partitioning + the
cross-replica reconcile commit protocol.

PR 7 sharded the *device solve* over a cohort-hash mesh; this module
shards the *scheduler itself*: one replica process per shard group, each
owning the full vertical slice for its cohorts (queue manager, cache,
arenas, nominate cache, BatchSolver), fed by a partitioned Store watch
stream. The partition key is exactly the PR 7 hash — crc32 of the direct
cohort name (cohort-less ClusterQueues hash by their ``__solo__/<cq>``
singleton) — so every flat cohort is replica-complete and all of its
quota math stays in one process.

Hierarchical KEP-79 trees hash by DIRECT cohort, so one tree's subtrees
may land on different replicas (``GroupMap.split_roots``). Those roots
are the ONLY cross-replica traffic: each replica's admission cycle runs
phase A shard-local exactly as before, and phase B becomes a real commit
protocol — replicas ship their split-root candidate admissions (usage
triples + the packed sort key, the PR 6/7 wire shape) plus their local
members' pre-cycle usage to the lease-holding :class:`Coordinator`,
which replays every candidate in GLOBAL cycle order against the merged
lending-clamp state (the same `fits_in_hierarchy` arithmetic the
single-process phase B uses) and returns per-entry commit/revoke
verdicts BEFORE any replica flushes. The optimistic-local-pass /
global-revoke loop is Aryl's cross-partition capacity-loaning reconcile
(PAPERS.md), layered on a two-level resource-offer split in the Mesos
allocation spirit: replicas claim locally, the coordinator arbitrates
only what genuinely spans partitions.

Known, deliberate divergences from the single-process scheduler (all
outside the pinned golden scenarios, documented in README):

  * preemption victim search inside a SPLIT root sees only the owning
    replica's subtree members (candidates never cross processes);
  * fair-sharing share denominators of a split tree are subtree-local;
  * the PodsReady block-admission gate is evaluated per replica.

Everything else — flat cohorts, same-replica trees, ordering, lending
clamps — is decision-identical by construction and pinned by
tests/test_replica.py's churn goldens at replicas {1, 2, 4}.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Callable, Dict, FrozenSet, List, Optional, Protocol

from kueue_tpu.core.cache import CachedClusterQueue, Cohort, frq_add
from kueue_tpu.core.hierarchy import fits_in_hierarchy
from kueue_tpu.transport.watchdog import BarrierStallError

SOLO_PREFIX = "__solo__/"


def group_of(name: str, n_groups: int) -> int:
    """Stable shard-group hash — the PR 7 cohort hash
    (`parallel.mesh._crc_shard`): process-independent, so every replica
    and the coordinator agree on the group of every cohort."""
    return zlib.crc32(name.encode("utf-8")) % n_groups


def group_key(cq_name: str, cohort: Optional[str]) -> str:
    """The hash key of a ClusterQueue: its direct cohort, or its
    ``__solo__/<name>`` singleton when cohort-less (schema.py naming)."""
    return cohort if cohort else SOLO_PREFIX + cq_name


class GroupMap:
    """Shard-group assignment + split-root tracking for one deployment.

    Placement is FIRST-SEEN: a ClusterQueue keeps the group its original
    cohort hashed to even if its cohort later changes — correctness does
    not depend on placement (a mis-placed member simply makes its root
    split, which routes its quota math through the commit protocol);
    placement only decides which process pays the work.
    """

    def __init__(self, n_groups: int):
        self.n_groups = n_groups
        self.cq_group: Dict[str, int] = {}       # cq -> placed group
        self.cq_cohort: Dict[str, str] = {}      # cq -> direct cohort ("")
        self.lq_cq: Dict[str, str] = {}          # "ns/lq" -> cq name
        self.cohort_parent: Dict[str, str] = {}  # cohort -> parent ("")
        self.split_roots: FrozenSet[str] = frozenset()

    def root_of(self, cohort: str) -> str:
        seen = set()
        node = cohort
        while self.cohort_parent.get(node):
            if node in seen:
                return cohort  # cycle: the snapshot deactivates these
            seen.add(node)
            node = self.cohort_parent[node]
        return node

    def place_cq(self, name: str, cohort: Optional[str]) -> int:
        g = self.cq_group.get(name)
        if g is None:
            g = group_of(group_key(name, cohort), self.n_groups)
            self.cq_group[name] = g
        self.cq_cohort[name] = cohort or ""
        return g

    def note_cohort(self, name: str, parent: Optional[str]) -> None:
        self.cohort_parent[name] = parent or ""

    def drop_cohort(self, name: str) -> None:
        self.cohort_parent.pop(name, None)

    def drop_cq(self, name: str) -> None:
        self.cq_group.pop(name, None)
        self.cq_cohort.pop(name, None)

    def place_lq(self, key: str, cq: str) -> Optional[int]:
        self.lq_cq[key] = cq
        return self.cq_group.get(cq)

    def recompute_split(self) -> FrozenSet[str]:
        """Roots whose member ClusterQueues live on more than one group.
        Flat cohorts can only split after a live cohort move (first-seen
        placement); KEP-79 trees split whenever their direct cohorts
        hash apart — exactly `mesh.ShardAssignment.split_roots`."""
        by_root: Dict[str, set] = {}
        for cq, g in self.cq_group.items():
            cohort = self.cq_cohort.get(cq)
            if not cohort:
                continue  # __solo__ singletons are their own root/group
            by_root.setdefault(self.root_of(cohort), set()).add(g)
        self.split_roots = frozenset(
            r for r, gs in by_root.items() if len(gs) > 1)
        return self.split_roots


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------
#
# One reconcile ROUND per replica per tick (submitted even when empty —
# the barrier is the protocol's ordering guarantee, and a replica's
# shipped usage feeds OTHER replicas' candidate gating):
#
#   {"replica": int, "tick": int,
#    "usage": {cq_name: {flavor: {resource: int}}},   # split-root members
#    "candidates": [candidate, ...]}                  # local cycle order
#
# candidate = {"i": submission index, "key": workload key, "cq": name,
#              "mode": solver mode (FIT/PREEMPT), "usage": frq dict,
#              "borrow": bool, "sort": entry sort-key components,
#              "pos": cycle position, "has_targets": bool,
#              "opt_ok": shard-local optimistic verdict (FIT only)}
#
# The verdict reply is a per-replica list of bools aligned with the
# submission order. Candidate usage is the admission's (flavor,
# resource, value) coordinates — the same triples the PR 6 CSR commit
# flattens — and `sort` is the packed entry ordering key, so the
# coordinator replays in exactly the single-process cycle order.


class ReplicaChannel(Protocol):
    """Transport seam between a replica and its runtime: loopback queue
    pairs in-process, a multiprocessing pipe across processes."""

    def send(self, msg) -> None: ...

    def recv(self): ...


class ReplicaContext:
    """Scheduler-side handle for the commit protocol.

    The owning runtime wires `submit` (blocking round-trip to the
    coordinator) and `usage_provider` (split-root member usage from the
    live cache, for rounds submitted outside an admission cycle); the
    scheduler reads `split_roots` to decide deferral and calls
    `reconcile` exactly once per cycle."""

    def __init__(self, submit: Callable[[dict], List[bool]],
                 usage_provider: Optional[Callable[[], dict]] = None):
        self._submit = submit
        self.usage_provider = usage_provider
        self.split_roots: FrozenSet[str] = frozenset()
        self.tick_submitted = False
        self.rtt_samples: List[float] = []
        self.rounds = 0
        # False when the owning runtime feeds the coordinator from a
        # pre-tick usage exchange instead (the ghost-member design):
        # rounds then ship no usage — the exchange is authoritative, and
        # a replica must never ship its (one-exchange-stale) ghost view
        # of a member another replica owns.
        self.ship_usage = True
        # Degraded safe mode (the coordinator is unreachable and no
        # re-election succeeded): reconcile goes SHARD-LOCAL — every
        # split-root candidate parks (all-False verdicts, no channel
        # traffic), flat-cohort admission continues untouched because
        # it never needed the coordinator's arithmetic in the first
        # place. `on_stall` (set by the owning worker) is consulted
        # when a live round misses the barrier deadline: returning True
        # flips the context into degraded mode instead of raising.
        self.degraded = False
        self.parked = 0
        self.on_stall: Optional[Callable[[], bool]] = None

    def reconcile(self, candidates: List[dict],
                  usage: Dict[str, dict]) -> List[bool]:
        from kueue_tpu.tracing import trace_now

        self.tick_submitted = True
        if self.degraded:
            self.parked += len(candidates)
            return [False] * len(candidates)
        self.rounds += 1
        t0 = trace_now()
        try:
            verdicts = self._submit({"candidates": candidates,
                                     "usage": usage})
        except BarrierStallError:
            if self.on_stall is not None and self.on_stall():
                # The worker confirmed the coordinator is presumed dead:
                # park this round's candidates and finish the cycle in
                # degraded mode rather than unwinding mid-admission.
                self.degraded = True
                self.parked += len(candidates)
                return [False] * len(candidates)
            raise
        self.rtt_samples.append(trace_now() - t0)
        return verdicts

    def flush_tick(self) -> None:
        """Submit the tick's round if the scheduler never did (no heads,
        quiescent replay, empty cycle): the coordinator barrier needs one
        round per live replica per tick."""
        if self.tick_submitted:
            self.tick_submitted = False
            return
        usage = (self.usage_provider()
                 if self.usage_provider and self.ship_usage else {})
        self.reconcile([], usage)
        self.tick_submitted = False

    def drain_rtt(self) -> List[float]:
        out, self.rtt_samples = self.rtt_samples, []
        return out


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class Coordinator:
    """The lease-holding reconcile authority for split cohort roots.

    Holds the admin SPECS (flavors, cohort specs, ClusterQueues) routed
    through the runtime, rebuilds a minimal cached view of the split
    trees on structure changes, and replays each barrier's candidates in
    global cycle order against the merged lending-clamp state — the
    exact `fits_in_hierarchy` arithmetic (plus the skip-preemption /
    common-resource gates) the single-process phase B applies, so a
    replica-split deployment admits the same set a single process would.

    Per-round state is rebuilt from the replicas' shipped absolute usage,
    which makes the coordinator restart-safe by construction; committed
    verdicts are journaled (`coordinator.jsonl`) when a state dir is
    configured, so a takeover can audit-replay every cross-replica
    decision."""

    def __init__(self, journal_path: Optional[str] = None,
                 epoch: int = 0):
        self.journal_path = journal_path
        # Barrier-round epoch: which coordinator INCARNATION arbitrated.
        # Sourced from the lease's transition count, bumped at every
        # takeover; journal entries carry (epoch, round) so an audit
        # attributes every verdict to exactly one incarnation, and a
        # re-run of an interrupted round is visible as the same round
        # number under a higher epoch.
        self.epoch = epoch
        self._journal_file = None
        self._lock = threading.Lock()
        self._flavors: Dict[str, object] = {}
        self._cohort_specs: Dict[str, object] = {}
        self._cq_specs: Dict[str, object] = {}
        self._split: FrozenSet[str] = frozenset()
        self._dirty = True
        self._cqs: Dict[str, CachedClusterQueue] = {}
        self.rounds = 0
        self.revocations = 0
        self.commits = 0
        # Takeover replay (recover()): journaled verdicts of the round
        # the previous incarnation arbitrated but may not have answered
        # — consumed by the next run_round so the resumed barrier gets
        # the SAME verdicts it would have gotten.
        self._replay: Optional[Dict[tuple, bool]] = None
        self.replayed_verdicts = 0

    def evidence(self) -> dict:
        """Commit-protocol counters as one JSON-ready block — the fuzz
        lattice driver attaches this to every replica drive's report so
        a divergence can be triaged against what the coordinator
        actually arbitrated (rounds run, split-root commits, revocations,
        verdicts replayed at fail-over)."""
        return {
            "rounds": self.rounds,
            "commits": self.commits,
            "revocations": self.revocations,
            "replayed_verdicts": self.replayed_verdicts,
            "epoch": self.epoch,
        }

    # -- admin state --------------------------------------------------------

    def note_flavor(self, rf, deleted: bool = False) -> None:
        with self._lock:
            if deleted:
                self._flavors.pop(rf if isinstance(rf, str) else rf.name,
                                  None)
            else:
                self._flavors[rf.name] = rf
            self._dirty = True

    def note_cohort(self, spec, deleted: bool = False) -> None:
        with self._lock:
            if deleted:
                self._cohort_specs.pop(
                    spec if isinstance(spec, str) else spec.name, None)
            else:
                self._cohort_specs[spec.name] = spec
            self._dirty = True

    def note_cluster_queue(self, spec, deleted: bool = False) -> None:
        with self._lock:
            if deleted:
                self._cq_specs.pop(
                    spec if isinstance(spec, str) else spec.name, None)
            else:
                self._cq_specs[spec.name] = spec
            self._dirty = True

    def set_split(self, split_roots: FrozenSet[str]) -> None:
        with self._lock:
            if split_roots != self._split:
                self._split = frozenset(split_roots)
                self._dirty = True

    def _root_of(self, cohort: str) -> str:
        seen = set()
        node = cohort
        while True:
            spec = self._cohort_specs.get(node)
            parent = spec.parent if spec is not None else ""
            if not parent or node in seen:
                return node
            seen.add(node)
            node = parent

    def _rebuild(self) -> None:
        """Materialize the split trees: Cohort nodes linked per the specs
        (the snapshot's tree-building shape) with a CachedClusterQueue
        per member — usage dicts are overwritten per round. Caller
        holds _lock."""
        self._cqs = {}
        nodes: Dict[str, Cohort] = {}

        def get_node(name: str) -> Cohort:
            node = nodes.get(name)
            if node is None:
                node = nodes[name] = Cohort(
                    name, spec=self._cohort_specs.get(name))
            return node

        member_cqs = [
            spec for spec in self._cq_specs.values()
            if spec.cohort and self._root_of(spec.cohort) in self._split]
        needed = set()
        for spec in member_cqs:
            node = spec.cohort
            while node and node not in needed:
                needed.add(node)
                cspec = self._cohort_specs.get(node)
                node = cspec.parent if cspec is not None else ""
        # EVERY node of a split tree participates in the balance math,
        # not just member-ancestor chains: a spec-only cohort (e.g. a
        # lending pool with quota but no ClusterQueues) contributes
        # lendable capacity its siblings borrow through.
        for name in list(self._cohort_specs):
            if self._root_of(name) in self._split:
                node = name
                while node and node not in needed:
                    needed.add(node)
                    cspec = self._cohort_specs.get(node)
                    node = cspec.parent if cspec is not None else ""
        # Sorted walk: `needed` accumulates in ancestor-chain discovery
        # order (a set), but `parent.children` ordering feeds the
        # balance walk — keep it a function of the names, not of set
        # iteration order.
        for name in sorted(needed):
            get_node(name)
        for name in sorted(needed):
            node = nodes[name]
            if node.spec is not None and node.spec.parent:
                parent = get_node(node.spec.parent)
                node.parent = parent
                parent.children.append(node)
        for spec in member_cqs:
            cq = CachedClusterQueue(spec, self._flavors)
            cohort = nodes[spec.cohort]
            cohort.members.add(cq)
            cq.cohort = cohort
            self._cqs[spec.name] = cq
        for node in nodes.values():
            node.invalidate_memos()
        self._dirty = False

    # -- takeover ------------------------------------------------------------

    def recover(self, in_flight: bool = False) -> int:
        """Rebuild this (newly elected) incarnation's round state from
        the journal: the round counter resumes where the previous
        incarnation stopped, and — when a round was IN FLIGHT at the
        takeover (arbitrated + journaled, but the verdicts may never
        have reached the replicas) — its journaled verdicts are loaded
        for replay, so re-running the round resumes the barrier with
        bit-identical answers instead of re-deciding (or stalling).
        Returns the number of verdicts staged for replay."""
        if not self.journal_path or not os.path.exists(self.journal_path):
            return 0
        last = None
        with self._lock:
            # One-shot takeover path: the journal read MUST complete
            # before any round touches the tree, and nothing else runs
            # yet in this incarnation — blocking here is the point.
            with open(self.journal_path, "r",  # kueuelint: disable=LOCK01
                      encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entry = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn final line: never acknowledged
                    last = entry
            if last is None:
                return 0
            last_round = int(last.get("round", 0))
            if not in_flight:
                self.rounds = last_round
                return 0
            # The interrupted round re-runs under this epoch: rewind the
            # counter so it keeps its number, and stage its verdicts.
            self.rounds = max(0, last_round - 1)
            self._replay = {
                (v.get("replica"), v.get("i"), v.get("key")): bool(v["ok"])
                for v in last.get("verdicts", ())
                if "i" in v}
            return len(self._replay)

    # -- the round ----------------------------------------------------------

    def run_round(self, rounds: List[dict],
                  usage: Optional[Dict[str, dict]] = None,
                  ) -> Dict[int, List[bool]]:
        """Arbitrate one barrier: merge the shipped usage (per-round, or
        the runtime's authoritative pre-tick exchange via `usage`), sort
        every candidate by its entry ordering key (the single-process
        cycle order — ties broken by cycle position, then workload key
        across replicas), and gate each against
        the merged tree state with the same-cycle reservations folded in.
        Returns per-replica verdict lists in submission order."""
        with self._lock:
            if self._dirty:
                self._rebuild()
            merged = dict(usage or {})
            for r in rounds:
                merged.update(r.get("usage", {}))
            for cq_name, cq_usage in merged.items():
                cq = self._cqs.get(cq_name)
                if cq is not None:
                    cq.usage = {f: dict(res)
                                for f, res in cq_usage.items()}
            ordered = []
            for r in rounds:
                for c in r.get("candidates", ()):
                    ordered.append((tuple(c["sort"]), c["key"],
                                    r["replica"], c))
            # Cycle position FIRST among equal sort keys: the single-
            # process cycle replays deferred entries in original cycle
            # order, and cycle_pos is exactly that order — with one
            # replica this reproduces it bit for bit even when two heads
            # tie on the whole sort key (same priority + timestamp); the
            # workload key only disambiguates true cross-replica ties.
            ordered.sort(key=lambda t: (t[0], t[3].get("pos", 0), t[1]))
            verdicts = {r["replica"]: [False] * len(r.get("candidates", ()))
                        for r in rounds}
            cycle_usage: Dict[str, dict] = {}
            root_usage: Dict[str, dict] = {}
            skip: set = set()
            from kueue_tpu.scheduler.scheduler import (
                _has_common_flavor_resources, preempt_reserve)
            from kueue_tpu.solver.modes import FIT, PREEMPT

            replay, self._replay = self._replay, None
            committed = 0
            for _, _, rid, c in ordered:
                journaled = (replay.get((rid, c["i"], c["key"]))
                             if replay is not None else None)
                cq = self._cqs.get(c["cq"])
                if cq is None or cq.cohort is None:
                    # A candidate for a root the coordinator does not
                    # model (spec lag): commit — the owning replica's
                    # local pass already validated it, and refusing here
                    # would wedge the workload until the specs arrive.
                    verdicts[rid][c["i"]] = True
                    continue
                mode = c["mode"]
                usage = c["usage"]
                root = cq.cohort.root_name
                if journaled is not None:
                    # Takeover replay: the previous incarnation already
                    # arbitrated this candidate; honor its journaled
                    # verdict — but still fold committed reserves so any
                    # non-replayed candidate later in the order gates
                    # against the same cycle state it would have.
                    blocked = not journaled
                    self.replayed_verdicts += 1
                else:
                    blocked = False
                    if mode == PREEMPT and root in skip:
                        blocked = _has_common_flavor_resources(
                            root_usage.get(root), usage)
                    if not blocked and mode == FIT:
                        blocked = not fits_in_hierarchy(
                            cq, usage, extra=cycle_usage)
                if not blocked:
                    reserve = usage if mode != PREEMPT else \
                        preempt_reserve(usage, c["borrow"], cq)
                    frq_add(cycle_usage.setdefault(cq.cohort.name, {}),
                            reserve)
                    frq_add(root_usage.setdefault(root, {}), reserve)
                    if mode == FIT or c.get("has_targets"):
                        skip.add(root)
                    committed += 1
                verdicts[rid][c["i"]] = not blocked
            self.rounds += 1
            self.commits += committed
            self.revocations += sum(
                1 for _, _, rid, c in ordered
                if c.get("opt_ok") and not verdicts[rid][c["i"]])
            if ordered and self.journal_path is not None:
                self._journal(ordered, verdicts)
            return verdicts

    def _journal(self, ordered, verdicts) -> None:
        """Append the round's verdicts (reconcile decisions are durable
        like every other admission input: a takeover can audit-replay
        exactly which cross-replica admissions were committed). Caller
        holds _lock."""
        if self._journal_file is None:
            os.makedirs(os.path.dirname(self.journal_path) or ".",
                        exist_ok=True)
            self._journal_file = open(
                self.journal_path, "a", encoding="utf-8")
        entry = {
            "round": self.rounds,
            "epoch": self.epoch,
            "verdicts": [
                {"key": c["key"], "cq": c["cq"], "replica": rid,
                 "i": c["i"], "ok": verdicts[rid][c["i"]]}
                for _, _, rid, c in ordered],
        }
        self._journal_file.write(json.dumps(entry, separators=(",", ":"))
                                 + "\n")
        self._journal_file.flush()

    def close(self) -> None:
        with self._lock:
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None
