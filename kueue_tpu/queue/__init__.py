"""Pending-workload state: per-ClusterQueue FIFO heaps and the queue manager."""

from kueue_tpu.queue.manager import (
    Manager,
    RequeueReason,
    PendingClusterQueue,
)
