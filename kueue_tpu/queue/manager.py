"""Queue manager: pending workloads per ClusterQueue.

Counterpart of reference pkg/queue/: a keyed heap per ClusterQueue ordered by
(priority desc, queue-order timestamp asc) (cluster_queue_strict_fifo.go:53-66),
an `inadmissible` parking lot with the popCycle/queueInadmissibleCycle race
guard (cluster_queue_impl.go:40-63,177-229), StrictFIFO vs BestEffortFIFO
requeue policies, requeue backoff (RequeueState.requeue_at), cohort-wide
inadmissible flushes, and the blocking `heads()` used by the scheduler tick
(manager.go:470-508).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, Dict, List, Mapping, Optional

from kueue_tpu import knobs
from kueue_tpu.api.types import (
    CONDITION_EVICTED,
    CONDITION_FINISHED,
    CONDITION_QUOTA_RESERVED,
    EVICTED_BY_PODS_READY_TIMEOUT,
    ClusterQueue,
    LocalQueue,
    QueueingStrategy,
    Workload,
)
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.tracing import TRACER
from kueue_tpu.utils.heap import KeyedHeap


class RequeueReason:
    GENERIC = ""
    NAMESPACE_MISMATCH = "NamespaceMismatch"
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    PENDING_PREEMPTION = "PendingPreemption"


# Dirty-cohort routing key prefix for cohort-less ClusterQueues (each is
# its own admission domain — the solver's __solo__ singleton idiom).
SOLO_COHORT = "__cq__/"


def _evicted_by_pods_ready_timeout(wl: Workload) -> bool:
    c = wl.find_condition(CONDITION_EVICTED)
    return c is not None and c.status and c.reason == EVICTED_BY_PODS_READY_TIMEOUT


class PendingClusterQueue:
    """Per-CQ pending heap + inadmissible parking lot
    (reference: clusterQueueBase, cluster_queue_impl.go:40-63)."""

    def __init__(self, spec: ClusterQueue, ordering: WorkloadOrdering,
                 clock: Callable[[], float] = _time.time):
        self.name = spec.name
        self.strategy = spec.queueing_strategy
        self.cohort = spec.cohort
        self.namespace_selector = spec.namespace_selector
        self.active = True
        self._ordering = ordering
        self._clock = clock
        self.heap = self._make_heap()
        self.inadmissible: Dict[str, WorkloadInfo] = {}
        # Admission-relevant state at park time; the runtime shares Workload
        # objects, so change detection must compare against a snapshot, not
        # the (same) object.
        self._parked_fingerprint: Dict[str, tuple] = {}
        # popCycle / queueInadmissibleCycle race guard
        # (cluster_queue_impl.go:49-57).
        self.pop_cycle = 0
        self.queue_inadmissible_cycle = -1
        # Earliest pods-ready requeue_at among parked workloads, or +inf
        # when none; None = recompute lazily (backoff_deadline). Lets the
        # per-tick flush_expired_backoffs sweep skip a parked-but-not-due
        # ClusterQueue in O(1) instead of walking its whole parking lot.
        self._backoff_deadline: Optional[float] = float("inf")

    def _less(self, a: WorkloadInfo, b: WorkloadInfo) -> bool:
        """Priority desc, then queue-order timestamp asc
        (cluster_queue_strict_fifo.go:53-66)."""
        pa, pb = a.obj.priority, b.obj.priority
        if pa != pb:
            return pa > pb
        ta = self._ordering.queue_order_time(a.obj)
        tb = self._ordering.queue_order_time(b.obj)
        return not tb < ta

    def _make_heap(self):
        """Native C++ heap when the toolchain built it (utils/native_heap,
        the counterpart of the reference's Go heap running outside the
        interpreter); pure-Python fallback otherwise."""
        if knobs.raw("KUEUE_TPU_NATIVE_HEAP") != "0":
            from kueue_tpu.utils import native_heap
            if native_heap.native_available():
                return native_heap.NativeKeyedHeap(
                    key_fn=lambda wi: wi.key,
                    sort_key_fn=lambda wi: (
                        -wi.obj.priority,
                        int(self._ordering.queue_order_time(wi.obj) * 1e9)),
                    key_len=2)
        return KeyedHeap(key_fn=lambda wi: wi.key, less=self._less)

    def update(self, spec: ClusterQueue) -> None:
        self.cohort = spec.cohort
        self.strategy = spec.queueing_strategy
        self.namespace_selector = spec.namespace_selector

    # -- backoff (cluster_queue_impl.go:139-150) ----------------------------

    def _backoff_expired(self, wi: WorkloadInfo) -> bool:
        rs = wi.obj.requeue_state
        if rs is None or rs.requeue_at is None:
            return True
        if not _evicted_by_pods_ready_timeout(wi.obj):
            return True
        return self._clock() >= rs.requeue_at

    # -- mutations ----------------------------------------------------------

    @staticmethod
    def _fingerprint(wi: WorkloadInfo) -> tuple:
        evicted = wi.obj.find_condition(CONDITION_EVICTED)
        return (
            [(ps.name, ps.count, ps.min_count, tuple(sorted(ps.requests.items())),
              ps.node_selector, ps.affinity_terms, ps.tolerations)
             for ps in wi.obj.pod_sets],
            dict(wi.obj.reclaimable_pods),
            (evicted.status, evicted.reason, evicted.last_transition_time)
            if evicted else None,
        )

    def backoff_deadline(self) -> float:
        """Earliest clock at which the flush sweep could move something
        out of this parking lot (+inf when nothing is clock-gated). A
        parked workload with a requeue_at whose eviction is NOT
        PodsReadyTimeout has an already-expired backoff
        (`_backoff_expired` ignores the timestamp then) and the sweep
        moves it on the next tick — it contributes "due now", exactly
        like the pre-deadline sweep treated it."""
        d = self._backoff_deadline
        if d is None:
            d = float("inf")
            for wi in self.inadmissible.values():
                rs = wi.obj.requeue_state
                if rs is None or rs.requeue_at is None:
                    continue
                if _evicted_by_pods_ready_timeout(wi.obj):
                    d = min(d, rs.requeue_at)
                else:
                    d = 0.0
                    break
            self._backoff_deadline = d
        return d

    def _park(self, key: str, wi: WorkloadInfo) -> None:
        self.inadmissible[key] = wi
        self._parked_fingerprint[key] = self._fingerprint(wi)
        rs = wi.obj.requeue_state
        if rs is not None and rs.requeue_at is not None \
                and self._backoff_deadline is not None:
            due = rs.requeue_at \
                if _evicted_by_pods_ready_timeout(wi.obj) else 0.0
            self._backoff_deadline = min(self._backoff_deadline, due)

    def _unpark(self, key: str) -> Optional[WorkloadInfo]:
        self._parked_fingerprint.pop(key, None)
        out = self.inadmissible.pop(key, None)
        if out is not None:
            # The removed entry may have carried the minimum deadline;
            # recompute lazily on the next sweep that needs it.
            self._backoff_deadline = None
        return out

    def push_or_update(self, wi: WorkloadInfo) -> None:
        key = wi.key
        if key in self.inadmissible:
            # Keep parked if nothing admission-relevant changed
            # (cluster_queue_impl.go:113-131).
            if self._parked_fingerprint.get(key) == self._fingerprint(wi):
                self.inadmissible[key] = wi
                # requeue_state is outside the fingerprint; the update
                # may have moved this entry's backoff deadline.
                self._backoff_deadline = None
                return
            self._unpark(key)
        if self.heap.get_by_key(key) is None and not self._backoff_expired(wi):
            self._park(key, wi)
            return
        self.heap.push_or_update(wi)

    def delete(self, wl: Workload) -> None:
        key = wl.key
        self._unpark(key)
        self.heap.delete(key)

    def requeue_if_not_present(self, wi: WorkloadInfo, reason: str) -> bool:
        """cluster_queue_impl.go:177-203 + per-strategy immediate rules."""
        if self.strategy == QueueingStrategy.STRICT_FIFO:
            immediate = reason != RequeueReason.NAMESPACE_MISMATCH
        else:
            immediate = reason in (RequeueReason.FAILED_AFTER_NOMINATION,
                                   RequeueReason.PENDING_PREEMPTION)
        key = wi.key
        if self._backoff_expired(wi) and (
                immediate or self.queue_inadmissible_cycle >= self.pop_cycle
                or (wi.last_assignment is not None
                    and wi.last_assignment.pending_flavors())):
            parked = self._unpark(key)
            if parked is not None:
                wi = parked
            return self.heap.push_if_not_present(wi)

        if key in self.inadmissible or self.heap.get_by_key(key) is not None:
            return False
        self._park(key, wi)
        return True

    def queue_inadmissible_workloads(
            self, ns_labels: Callable[[str], Optional[Mapping[str, str]]]) -> bool:
        """Move parked workloads back to the heap (cluster_queue_impl.go:205-229)."""
        self.queue_inadmissible_cycle = self.pop_cycle
        if not self.inadmissible:
            return False
        moved = False
        for key, wi in list(self.inadmissible.items()):
            labels = ns_labels(wi.obj.namespace)
            if labels is not None and self.namespace_selector.matches(labels) \
                    and self._backoff_expired(wi):
                self._unpark(key)
                moved = self.heap.push_if_not_present(wi) or moved
        return moved

    def pop(self) -> Optional[WorkloadInfo]:
        self.pop_cycle += 1
        return self.heap.pop()

    # -- stats --------------------------------------------------------------

    @property
    def pending_active(self) -> int:
        return len(self.heap)

    @property
    def pending_inadmissible(self) -> int:
        return len(self.inadmissible)

    @property
    def pending(self) -> int:
        return self.pending_active + self.pending_inadmissible


class Manager:
    """reference: pkg/queue/manager.go:63-79."""

    def __init__(self, ordering: Optional[WorkloadOrdering] = None,
                 namespace_lister: Optional[Callable[[str], Optional[Mapping[str, str]]]] = None,
                 clock: Callable[[], float] = _time.time):
        self._cond = threading.Condition()
        self.ordering = ordering or WorkloadOrdering()
        self.cluster_queues: Dict[str, PendingClusterQueue] = {}
        # cohort name -> member queues; keeps cohort flushes O(members)
        # instead of a full scan over every ClusterQueue (quota releases
        # flush a cohort per finish/evict — manager.go:424-447).
        self._cohort_members: Dict[str, Dict[str, PendingClusterQueue]] = {}
        self.local_queues: Dict[str, LocalQueue] = {}
        self._ns_lister = namespace_lister or (lambda name: {})
        self._clock = clock
        self._stopped = False
        # Pending-workload event sinks (the solver's incremental tensor
        # arena): note_pending_workload on every add/update entering a
        # queue, forget_pending_workload on delete. Requeues of an
        # unchanged info fire nothing — the subscriber's row stays valid.
        self._workload_sinks: List = []
        # Batched heads sweep: the native heaps' top pops ride ONE C call
        # per tick (utils/native_heap.PopGroup). The plan (CQ order +
        # handle buffer) is cached and keyed on the ClusterQueue-set
        # version, so steady-state sweeps never rebuild it.
        self._cq_version = 0
        self._pop_plan = None
        self._pop_plan_version = -1
        # Dirty-cohort event routing (the micro-tick fast path's feed):
        # {cohort name | SOLO_COHORT+cq: triggering event} recorded on
        # every admission-relevant arrival (submit, quota-release flush,
        # backoff expiry) and drained by Scheduler.microtick — or folded
        # into the next full heads sweep, which pops every queue anyway.
        # Bounded by the cohort+CQ population; requeues of losing heads
        # deliberately record NOTHING (a NoFit requeue re-dirtying its
        # cohort would spin micro-ticks forever on an unchanged input).
        self._dirty_cohorts: Dict[str, str] = {}

    # -- pending-workload events (solver arena subscription) -----------------

    def register_workload_sink(self, sink) -> None:
        """Subscribe to pending-workload dirty events. `sink` implements
        note_pending_workload(info) and forget_pending_workload(uid);
        both are called under the manager lock (keep them O(row))."""
        with self._cond:
            if sink not in self._workload_sinks:
                self._workload_sinks.append(sink)

    def unregister_workload_sink(self, sink) -> None:
        with self._cond:
            if sink in self._workload_sinks:
                self._workload_sinks.remove(sink)

    def _note_sinks(self, wi: WorkloadInfo) -> None:
        for sink in self._workload_sinks:
            sink.note_pending_workload(wi)

    def _forget_sinks(self, wl: Workload) -> None:
        for sink in self._workload_sinks:
            sink.forget_pending_workload(wl.uid)

    # -- dirty-cohort events (the micro-tick fast path) ----------------------

    def _mark_dirty(self, cq: PendingClusterQueue, event: str) -> None:
        """Record an admission-relevant event against the CQ's cohort
        (callers hold the manager lock). Latest event wins — the mark is
        a routing key, the event string only explains the trigger."""
        self._dirty_cohorts[cq.cohort or SOLO_COHORT + cq.name] = event

    def has_dirty_cohorts(self) -> bool:
        return bool(self._dirty_cohorts)

    def remark_dirty(self, key: str, event: str) -> None:
        """Put a drained dirty-cohort key back (micro-tick CQ-budget
        overflow: the full tick, or a later micro-tick, handles it)."""
        with self._cond:
            self._dirty_cohorts.setdefault(key, event)

    def mark_dirty_cq(self, name: str, event: str) -> None:
        """Externally re-mark one ClusterQueue's cohort dirty (the
        micro-tick's round-cap handback: pending heads remain that a
        later micro-tick should continue draining)."""
        with self._cond:
            cq = self.cluster_queues.get(name)
            if cq is not None:
                self._mark_dirty(cq, event)

    def drain_dirty_cohorts(self) -> Dict[str, str]:
        """Take (and clear) the dirty-cohort marks accumulated since the
        last drain: {cohort | SOLO_COHORT+cq: triggering event}."""
        with self._cond:
            if not self._dirty_cohorts:
                return {}
            out, self._dirty_cohorts = self._dirty_cohorts, {}
            return out

    def cohort_member_names(self, key: str) -> List[str]:
        """The ClusterQueues a dirty-cohort key routes to: the cohort's
        member queues, or the solo CQ itself."""
        with self._cond:
            if key.startswith(SOLO_COHORT):
                name = key[len(SOLO_COHORT):]
                return [name] if name in self.cluster_queues else []
            return sorted(self._cohort_members.get(key, {}))

    def pop_heads_for(self, cq_names) -> List[WorkloadInfo]:
        """Pop one head from each NAMED ClusterQueue (the micro-tick's
        focused twin of the full `heads` sweep — same pop semantics,
        including the popCycle advance, so the popCycle /
        queueInadmissibleCycle race guard keeps counting)."""
        out: List[WorkloadInfo] = []
        with self._cond:
            for name in cq_names:
                cq = self.cluster_queues.get(name)
                if cq is None or not cq.active:
                    continue
                wi = cq.pop()
                if wi is not None:
                    out.append(wi)
        return out

    def restore_heads(self, infos) -> None:
        """Push popped-but-undecided heads back onto their heaps (the
        eager-encode abandon path: a predispatched tick was invalidated
        before its completion ran, and nothing about the heads changed
        — they re-enter exactly as they were popped)."""
        with self._cond:
            restored = False
            for wi in infos:
                cq = self.cluster_queues.get(wi.cluster_queue)
                if cq is not None:
                    restored = cq.heap.push_if_not_present(wi) or restored
            if restored:
                self._cond.notify_all()

    def pending_infos(self) -> List[WorkloadInfo]:
        """Every pending WorkloadInfo (heaps + parking lots) — the
        solver arena's backlog supplier for full rebuilds."""
        with self._cond:
            out: List[WorkloadInfo] = []
            for cq in self.cluster_queues.values():
                out.extend(cq.heap.items())
                out.extend(cq.inadmissible.values())
            return out

    # -- cluster queues ------------------------------------------------------

    def add_cluster_queue(self, spec: ClusterQueue,
                          pending: List[Workload] = ()) -> None:
        with self._cond:
            if spec.name in self.cluster_queues:
                raise ValueError(f"queue {spec.name} already exists")
            cq = PendingClusterQueue(spec, self.ordering, self._clock)
            self.cluster_queues[spec.name] = cq
            self._cq_version += 1
            if cq.cohort:
                self._cohort_members.setdefault(cq.cohort, {})[cq.name] = cq
            # Re-adopt pending workloads that arrived before the CQ
            # (manager.go:121-134).
            for wl in pending:
                lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
                if lq is not None and lq.cluster_queue == spec.name \
                        and not wl.has_quota_reservation and not wl.is_finished \
                        and wl.active:
                    wi = WorkloadInfo(wl, cluster_queue=spec.name)
                    cq.push_or_update(wi)
                    self._note_sinks(wi)
                    self._mark_dirty(cq, f"submit {wl.name}")
            self._cond.notify_all()

    def update_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._cond:
            cq = self.cluster_queues[spec.name]
            old_cohort = cq.cohort
            cq.update(spec)
            if cq.cohort != old_cohort:
                self._drop_cohort_member(old_cohort, cq.name)
                if cq.cohort:
                    self._cohort_members.setdefault(cq.cohort, {})[cq.name] = cq
            # Any spec update (quota raise, namespace selector, stop
            # policy) may make parked workloads admissible: requeue the
            # whole cohort's inadmissible set (manager.go
            # UpdateClusterQueue with specUpdated=true).
            # KUEUE_TPU_FUZZ_MUTATION=no-requeue-on-cq-update reverts to
            # the pre-PR-9 bug (requeue only on cohort CHANGE, so a
            # plain quota raise leaves NoFit workloads parked forever) —
            # an oracle-mutation drill: the fuzz corpus meta-test proves
            # the checked-in PR 9 reproducer goes red under it. Inert
            # unless the env gate is set; never set it in production.
            from kueue_tpu import knobs as _knobs
            if _knobs.raw("KUEUE_TPU_FUZZ_MUTATION") == \
                    "no-requeue-on-cq-update":
                if cq.cohort != old_cohort:
                    self._queue_cohort_inadmissible(cq.cohort, fallback=cq)
            else:
                self._queue_cohort_inadmissible(cq.cohort, fallback=cq)
            self._cond.notify_all()

    def delete_cluster_queue(self, name: str) -> None:
        with self._cond:
            cq = self.cluster_queues.pop(name, None)
            if cq is not None:
                self._cq_version += 1
                self._drop_cohort_member(cq.cohort, name)

    def _drop_cohort_member(self, cohort: str, name: str) -> None:
        members = self._cohort_members.get(cohort or "")
        if members is not None:
            members.pop(name, None)
            if not members:
                del self._cohort_members[cohort]

    # -- local queues --------------------------------------------------------

    def add_local_queue(self, lq: LocalQueue, pending: List[Workload] = ()) -> None:
        with self._cond:
            self.local_queues[lq.key] = lq
            cq = self.cluster_queues.get(lq.cluster_queue)
            if cq is not None:
                for wl in pending:
                    if wl.namespace == lq.namespace and wl.queue_name == lq.name \
                            and not wl.has_quota_reservation and not wl.is_finished \
                            and wl.active:
                        wi = WorkloadInfo(wl, cluster_queue=cq.name)
                        cq.push_or_update(wi)
                        self._note_sinks(wi)
                        self._mark_dirty(cq, f"submit {wl.name}")
                self._cond.notify_all()

    def delete_local_queue(self, lq: LocalQueue) -> None:
        with self._cond:
            self.local_queues.pop(lq.key, None)

    # -- workloads -----------------------------------------------------------

    def cluster_queue_for(self, wl: Workload) -> Optional[str]:
        lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        return lq.cluster_queue if lq else None

    def add_or_update_workload(self, wl: Workload) -> bool:
        with self._cond:
            cq_name = self.cluster_queue_for(wl)
            if cq_name is None:
                return False
            cq = self.cluster_queues.get(cq_name)
            if cq is None:
                return False
            wi = WorkloadInfo(wl, cluster_queue=cq_name)
            cq.push_or_update(wi)
            self._note_sinks(wi)
            self._mark_dirty(cq, f"submit {wl.name}")
            self._cond.notify_all()
            return True

    def add_or_update_workloads(self, wls) -> int:
        """Bulk submit under ONE lock acquisition with one wakeup and one
        dirty mark per distinct cohort — the micro-tick storm guard: the
        serve loop polls dirty cohorts at 20ms granularity, so a 10k-burst
        arriving as per-workload marks would re-trigger micro-tick after
        micro-tick mid-burst. Returns the routed count (unroutable
        workloads skip silently, exactly like add_or_update_workload
        returning False)."""
        added = 0
        with TRACER.lock(self._cond, "queue.lock_wait.submit_batch"):
            dirty: Dict[str, PendingClusterQueue] = {}
            for wl in wls:
                cq_name = self.cluster_queue_for(wl)
                if cq_name is None:
                    continue
                cq = self.cluster_queues.get(cq_name)
                if cq is None:
                    continue
                wi = WorkloadInfo(wl, cluster_queue=cq_name)
                cq.push_or_update(wi)
                self._note_sinks(wi)
                dirty[cq.cohort or SOLO_COHORT + cq.name] = cq
                added += 1
            for cq in dirty.values():
                self._mark_dirty(cq, f"submit-batch x{added}")
            if added:
                self._cond.notify_all()
        return added

    def delete_workload(self, wl: Workload) -> None:
        with self._cond:
            cq_name = self.cluster_queue_for(wl)
            if cq_name:
                cq = self.cluster_queues.get(cq_name)
                if cq is not None:
                    cq.delete(wl)
            self._forget_sinks(wl)

    def requeue_workload(self, wi: WorkloadInfo, reason: str) -> bool:
        """manager.go RequeueWorkload; caller must pass a still-pending info."""
        return self.requeue_workloads([(wi, reason)]) == 1

    def requeue_workloads(self, items) -> int:
        """Bulk requeue ([(info, reason)]) under one lock with one wakeup —
        the scheduler's post-cycle sweep returns a few hundred losers per
        tick at scale. The per-entry admission-state reads go through ONE
        condition-map fetch per workload (the sweep previously re-walked
        the same conditions through three property lookups each — the
        per-entry re-lookup behind the requeue-phase regression the
        BENCH_r05 northstar config exposed)."""
        added = 0
        # tracer.lock: when tracing is enabled the queue lock's
        # acquisition wait becomes a span (contention with API-server
        # mutators is otherwise invisible inside the requeue phase);
        # disabled it IS the plain `with self._cond:`.
        with TRACER.lock(self._cond, "queue.lock_wait.requeue"):
            cqs = self.cluster_queues
            for wi, reason in items:
                wl = wi.obj
                cmap = wl._cond_map()
                c = cmap.get(CONDITION_QUOTA_RESERVED)
                if c is not None and c.status:
                    continue
                c = cmap.get(CONDITION_FINISHED)
                if (c is not None and c.status) or not wl.active:
                    continue
                cq = cqs.get(wi.cluster_queue)
                if cq is None:
                    continue
                if cq.requeue_if_not_present(wi, reason):
                    added += 1
            if added:
                self._cond.notify_all()
        return added

    # -- inadmissible flushes ------------------------------------------------

    def queue_associated_inadmissible_workloads(self, wl: Workload) -> None:
        """After a workload releases quota, flush its CQ's cohort
        (manager.go:424-447)."""
        with self._cond:
            cq_name = self.cluster_queue_for(wl)
            if cq_name is None and wl.admission is not None:
                cq_name = wl.admission.cluster_queue
            cq = self.cluster_queues.get(cq_name or "")
            if cq is None:
                return
            self._queue_cohort_inadmissible(cq.cohort, fallback=cq)

    def flush_expired_backoffs(self) -> bool:
        """Move parked workloads whose requeue backoff has expired back to
        their heaps (the reference does this with per-workload RequeueAfter
        timers, workload_controller.go:352-356). Returns whether anything
        moved — the eager-encode path invalidates a predispatched tick on
        True (a clock-gated head became poppable after the predispatch
        popped its sweep)."""
        with self._cond:
            moved = False
            now = self._clock()
            for cq in self.cluster_queues.values():
                if not cq.inadmissible:
                    # The common steady-state CQ parks nothing; skip the
                    # per-CQ list materialization (this sweep runs at the
                    # top of EVERY tick over every ClusterQueue).
                    continue
                if cq.backoff_deadline() > now:
                    # Parked, but no backoff is due yet: nothing in this
                    # lot can move (generic parks wait for a quota
                    # release flush, not the clock) — O(1) instead of a
                    # whole-lot walk per tick.
                    continue
                cq_moved = False
                for key, wi in list(cq.inadmissible.items()):
                    rs = wi.obj.requeue_state
                    if rs is not None and rs.requeue_at is not None \
                            and cq._backoff_expired(wi):
                        cq._unpark(key)
                        cq_moved = cq.heap.push_if_not_present(wi) \
                            or cq_moved
                if cq_moved:
                    self._mark_dirty(cq, "backoff-expired")
                    moved = True
            if moved:
                self._cond.notify_all()
            return moved

    def queue_inadmissible_workloads(self, cq_names) -> None:
        with self._cond:
            queued = False
            cohorts = set()
            for name in cq_names:
                cq = self.cluster_queues.get(name)
                if cq is None:
                    continue
                if cq.cohort:
                    cohorts.add(cq.cohort)
                elif cq.queue_inadmissible_workloads(self._ns_lister):
                    self._mark_dirty(cq, "quota-release")
                    queued = True
            for cohort in cohorts:
                queued = self._flush_cohort(cohort) or queued
            if queued:
                self._cond.notify_all()

    def _queue_cohort_inadmissible(self, cohort: str,
                                   fallback: Optional[PendingClusterQueue] = None) -> None:
        if cohort:
            if self._flush_cohort(cohort):
                self._cond.notify_all()
        elif fallback is not None:
            if fallback.queue_inadmissible_workloads(self._ns_lister):
                self._mark_dirty(fallback, "quota-release")
                self._cond.notify_all()

    def _flush_cohort(self, cohort: str) -> bool:
        queued = False
        for cq in self._cohort_members.get(cohort, {}).values():
            if cq.queue_inadmissible_workloads(self._ns_lister):
                self._mark_dirty(cq, "quota-release")
                queued = True
        return queued

    # -- heads ---------------------------------------------------------------

    def heads(self, timeout: Optional[float] = None) -> List[WorkloadInfo]:
        """Block until at least one CQ has a head, then pop one head per CQ
        (manager.go:470-508)."""
        deadline = None if timeout is None else self._clock() + timeout
        with TRACER.lock(self._cond, "queue.lock_wait.heads"):
            while not self._stopped:
                out = self._heads_locked()
                if out:
                    return out
                remaining = None
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return []
                self._cond.wait(remaining)
            return []

    def _build_pop_plan(self) -> None:
        """(Re)build the batched heads-sweep plan: the active CQs in
        dict order (the entry sort is stable, so sweep order is part of
        the decision contract) with every native heap grouped into one
        PopGroup. `PendingClusterQueue.active` is write-once True today;
        a future deactivation path must bump `_cq_version`."""
        from kueue_tpu.utils import native_heap as nh
        plan = []                       # (cq, index into group | -1)
        native: List[PendingClusterQueue] = []
        batched = nh.pop_many_available()
        for cq in self.cluster_queues.values():
            if not cq.active:
                continue
            if batched and isinstance(cq.heap, nh.NativeKeyedHeap):
                plan.append((cq, len(native)))
                native.append(cq)
            else:
                plan.append((cq, -1))
        group = nh.PopGroup([cq.heap for cq in native]) if native else None
        self._pop_plan = (plan, group)
        self._pop_plan_version = self._cq_version

    def _heads_locked(self) -> List[WorkloadInfo]:
        if self._pop_plan_version != self._cq_version:
            self._build_pop_plan()
        # The full sweep pops every queue: standing dirty-cohort marks
        # are consumed by this tick (anything it could not pop — parked
        # workloads — a micro-tick could not pop either).
        self._dirty_cohorts.clear()
        plan, group = self._pop_plan
        popped = group.pop_each() if group is not None else None
        out: List[WorkloadInfo] = []
        for cq, gi in plan:
            # pop() semantics inlined: the popCycle advances for every
            # active CQ per sweep, empty or not (the popCycle /
            # queueInadmissibleCycle race guard counts sweeps).
            cq.pop_cycle += 1
            wi = popped[gi] if gi >= 0 else cq.heap.pop()
            if wi is not None:
                out.append(wi)
        return out

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    # -- stats ---------------------------------------------------------------

    def pending(self, cq_name: str) -> int:
        with self._cond:
            cq = self.cluster_queues.get(cq_name)
            return cq.pending if cq else 0

    def pending_in_local_queue(self, namespace: str, name: str) -> int:
        """Pending count scoped to one LocalQueue (the LQ status's
        pendingWorkloads, localqueue_controller.go status sync)."""
        with self._cond:
            lq = self.local_queues.get(f"{namespace}/{name}")
            if lq is None:
                return 0
            cq = self.cluster_queues.get(lq.cluster_queue)
            if cq is None:
                return 0
            return sum(
                1
                for wi in list(cq.heap.items()) + list(cq.inadmissible.values())
                if wi.obj.namespace == namespace
                and wi.obj.queue_name == name)
