"""Scheduling tick orchestration (counterpart of reference pkg/scheduler/)."""

from kueue_tpu.scheduler.preemption import get_targets
from kueue_tpu.scheduler.scheduler import Scheduler, SchedulerMetrics
