"""Preemption-victim search.

Counterpart of reference pkg/scheduler/preemption/preemption.go: candidate
collection (findCandidates :256-303), deterministic candidate ordering
(candidatesOrdering :397-424), and the greedy remove-until-fits /
add-back-minimal heuristic (minimalPreemptions :172-231), simulated on the
tick snapshot.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from kueue_tpu import features
from kueue_tpu import knobs
from kueue_tpu.api.types import (
    BorrowWithinCohortPolicy,
    CONDITION_EVICTED,
    FairSharingStrategy,
    PreemptionPolicy,
)
from kueue_tpu.core.cache import CachedClusterQueue, FlavorResourceQuantities
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.solver.fair_share import dominant_resource_share
from kueue_tpu.solver.modes import PREEMPT
from kueue_tpu.solver.referee import Assignment

ResourcesPerFlavor = Dict[str, Set[str]]

DEFAULT_FAIR_STRATEGIES = (
    FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
    FairSharingStrategy.LESS_THAN_INITIAL_SHARE,
)


def _plan_rounds(wi: WorkloadInfo, cq: CachedClusterQueue,
                 candidates: List[WorkloadInfo]):
    """The policy decision of get_targets: which minimalPreemptions rounds
    to run. Returns (round1, round2) as (candidates, allow_borrowing,
    threshold) tuples; round2 is the retry when round1 finds nothing
    (preemption.go:96-117)."""
    same_queue = [c for c in candidates if c.cluster_queue == wi.cluster_queue]

    if len(same_queue) == len(candidates):
        # No cross-queue candidates: preempt within the CQ, borrowing allowed.
        return (candidates, True, None), None

    bwc = cq.preemption.borrow_within_cohort
    if bwc is not None and bwc.policy != BorrowWithinCohortPolicy.NEVER:
        threshold = wi.priority
        if bwc.max_priority_threshold is not None \
                and bwc.max_priority_threshold < threshold:
            threshold = bwc.max_priority_threshold + 1
        return (candidates, True, threshold), None

    return (candidates, False, None), (same_queue, True, None)


def get_targets(wi: WorkloadInfo, assignment: Assignment, snapshot: Snapshot,
                ordering: WorkloadOrdering, now: float,
                fair_strategies=DEFAULT_FAIR_STRATEGIES,
                engine: Optional[str] = None,
                fair_ctx=None,
                key_memo: Optional[dict] = None) -> List[WorkloadInfo]:
    """Workloads to evict so `wi` fits (preemption.go:81-126).

    With the FairSharing gate on and the CQ in a cohort, victim selection is
    share-based (KEP-1714) instead of the classic priority/reclaim rules;
    `fair_ctx` (BatchSolver.fair_preempt_context) routes that search
    through the vectorized tensors (ops/fair_preempt), with the
    sequential dict walk as the referee oracle.

    `engine` selects the minimalPreemptions implementation: None = the
    sequential host referee; "jax" / "pallas" = the device scan
    (ops/preemption_scan, ops/preemption_pallas — decision-equivalent).
    Hierarchical trees always run the host referee: its workloadFits is the
    only implementation of the KEP-79 ancestor walk.

    `key_memo` shares `_candidate_sort_key`'s per-candidate parts across
    every search of a tick (get_targets_batch owns one) — cohort mates
    are re-sorted by every searching entry.
    """
    res_per_flv = _resources_requiring_preemption(assignment)
    cq = snapshot.cluster_queues[wi.cluster_queue]

    if features.enabled(features.FAIR_SHARING) and cq.cohort is not None:
        return _fair_preemptions(wi, assignment, snapshot, res_per_flv,
                                 ordering, now, fair_strategies,
                                 fair_ctx=fair_ctx, key_memo=key_memo)

    if cq.cohort is not None and cq.cohort.is_hierarchical():
        engine = None
    # getattr: native-decoded Assignments bypass __init__, so the slot may
    # be unset on topology-free ticks.
    hint = getattr(assignment, "topology_hint", None)
    if hint is not None:
        # Topology-steered victim selection runs the host referee: the
        # candidate reorder below is the whole mechanism.
        engine = None

    def minimal(cands, allow_borrowing, threshold):
        if engine in ("jax", "pallas"):
            from kueue_tpu.ops.preemption_scan import \
                minimal_preemptions_device
            wl_req = _total_requests_for_assignment(wi, assignment)
            return minimal_preemptions_device(
                wl_req, cq, snapshot, res_per_flv, cands, allow_borrowing,
                threshold, backend=engine)
        return _minimal_preemptions(wi, assignment, snapshot, res_per_flv,
                                    cands, allow_borrowing, threshold)

    candidates = _find_candidates(wi, ordering, cq, res_per_flv)
    if not candidates:
        return []
    candidates.sort(key=lambda c: _candidate_sort_key(c, cq.name, now,
                                                      key_memo))
    if hint is not None:
        candidates = _topology_prefer(candidates, hint, snapshot)

    round1, round2 = _plan_rounds(wi, cq, candidates)
    targets = minimal(*round1)
    if not targets and round2 is not None:
        targets = minimal(*round2)
    return targets


def get_targets_batch(items, snapshot: Snapshot, ordering: WorkloadOrdering,
                      now: float, fair_strategies, ctx, usage,
                      backend: str = "native", fair_ctx=None,
                      ) -> List[List[WorkloadInfo]]:
    """Victim search for every PREEMPT-mode entry of a tick in (at most)
    two batched engine calls (ops/preemption_batch).

    `items` is a sequence of (WorkloadInfo, Assignment); `ctx`/`usage` come
    from BatchSolver.preemption_context(). Entries the device kernel cannot
    express (fair sharing, hierarchical trees, CQs outside the encoding)
    fall back to the host path, preserving decision equivalence.
    """
    from kueue_tpu.ops.preemption_batch import PlannedSearch, run_batch

    enc = ctx.enc
    results: List[Optional[List[WorkloadInfo]]] = [None] * len(items)
    searches: List[PlannedSearch] = []
    search_meta = []   # (item_idx, wl_req, res_per_flv, round2 | None)
    fair = features.enabled(features.FAIR_SHARING)
    key_memo: dict = {}

    for idx, (wi, assignment) in enumerate(items):
        res_per_flv = _resources_requiring_preemption(assignment)
        cq = snapshot.cluster_queues[wi.cluster_queue]
        hier = cq.cohort is not None and cq.cohort.is_hierarchical()
        ci = enc.cq_index.get(wi.cluster_queue)
        if (fair and cq.cohort is not None) or hier or ci is None \
                or getattr(assignment, "topology_hint", None) is not None:
            results[idx] = get_targets(wi, assignment, snapshot, ordering,
                                       now, fair_strategies, engine=None,
                                       fair_ctx=fair_ctx, key_memo=key_memo)
            continue
        candidates = _find_candidates(wi, ordering, cq, res_per_flv)
        if not candidates:
            results[idx] = []
            continue
        candidates.sort(key=lambda c: _candidate_sort_key(c, cq.name, now,
                                                          key_memo))
        round1, round2 = _plan_rounds(wi, cq, candidates)
        cands, allow_b, thr = round1
        wl_req = _total_requests_for_assignment(wi, assignment)
        searches.append(PlannedSearch(
            target_ci=ci, has_cohort=cq.cohort is not None,
            candidates=cands,
            cand_cis=[enc.cq_index[c.cluster_queue] for c in cands],
            allow_borrowing=allow_b, threshold=thr))
        search_meta.append((idx, wl_req, res_per_flv, round2))

    if searches:
        out1 = run_batch(ctx, usage, searches,
                         [m[1] for m in search_meta],
                         [m[2] for m in search_meta], backend=backend)
        retry_searches: List[PlannedSearch] = []
        retry_meta = []
        for (idx, wl_req, res_per_flv, round2), targets in zip(
                search_meta, out1):
            if targets or round2 is None:
                results[idx] = targets
                continue
            cands, allow_b, thr = round2
            if not cands:
                results[idx] = []
                continue
            wi = items[idx][0]
            ci = enc.cq_index[wi.cluster_queue]
            retry_searches.append(PlannedSearch(
                target_ci=ci,
                has_cohort=snapshot.cluster_queues[
                    wi.cluster_queue].cohort is not None,
                candidates=cands,
                cand_cis=[enc.cq_index[c.cluster_queue] for c in cands],
                allow_borrowing=allow_b, threshold=thr))
            retry_meta.append((idx, wl_req, res_per_flv))
        if retry_searches:
            out2 = run_batch(ctx, usage, retry_searches,
                             [m[1] for m in retry_meta],
                             [m[2] for m in retry_meta], backend=backend)
            for (idx, _, _), targets in zip(retry_meta, out2):
                results[idx] = targets

    return results


def _resources_requiring_preemption(assignment: Assignment) -> ResourcesPerFlavor:
    out: ResourcesPerFlavor = {}
    for ps in assignment.pod_sets:
        for res, fa in ps.flavors.items():
            if fa.mode != PREEMPT:
                continue
            out.setdefault(fa.name, set()).add(res)
    return out


def _find_candidates(wi: WorkloadInfo, ordering: WorkloadOrdering,
                     cq: CachedClusterQueue,
                     res_per_flv: ResourcesPerFlavor) -> List[WorkloadInfo]:
    candidates: List[WorkloadInfo] = []
    wl_priority = wi.priority

    if cq.preemption.within_cluster_queue != PreemptionPolicy.NEVER:
        consider_same_prio = (cq.preemption.within_cluster_queue
                              == PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY)
        preemptor_ts = ordering.queue_order_time(wi.obj)
        for cand in cq.workloads.values():
            cand_priority = cand.obj.priority
            if cand_priority > wl_priority:
                continue
            if cand_priority == wl_priority and not (
                    consider_same_prio
                    and preemptor_ts < ordering.queue_order_time(cand.obj)):
                continue
            if not _uses_resources(cand, res_per_flv):
                continue
            candidates.append(cand)

    if cq.cohort is not None \
            and cq.preemption.reclaim_within_cohort != PreemptionPolicy.NEVER:
        only_lower_prio = cq.preemption.reclaim_within_cohort != PreemptionPolicy.ANY
        # Reclaim acts across the whole cohort structure — for hierarchical
        # trees (KEP-79) that is every ClusterQueue under the root.
        for cohort_cq in cq.cohort.root().tree_cluster_queues():
            if cohort_cq is cq or not _cq_is_borrowing(cohort_cq, res_per_flv):
                continue
            for cand in cohort_cq.workloads.values():
                if only_lower_prio and cand.obj.priority >= wl_priority:
                    continue
                if not _uses_resources(cand, res_per_flv):
                    continue
                candidates.append(cand)
    return candidates


def _cq_is_borrowing(cq: CachedClusterQueue,
                     res_per_flv: ResourcesPerFlavor) -> bool:
    if cq.cohort is None:
        return False
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            if fq.name not in res_per_flv:
                continue
            fusage = cq.usage.get(fq.name)
            if not fusage:
                continue
            quotas = fq.resources_dict
            for rname in res_per_flv[fq.name]:
                quota = quotas.get(rname)
                if quota is not None and fusage.get(rname, 0) > quota.nominal:
                    return True
    return False


def _uses_resources(wi: WorkloadInfo, res_per_flv: ResourcesPerFlavor) -> bool:
    for flv, res, _ in wi.usage_triples:
        rs = res_per_flv.get(flv)
        if rs is not None and res in rs:
            return True
    return False


def _candidate_sort_key(c: WorkloadInfo, cq_name: str, now: float,
                        memo: Optional[dict] = None):
    """Evicted first, other-CQ first, lowest priority, newest admission,
    UID tiebreak (preemption.go:397-424).

    `memo` caches the search-independent parts per candidate: cohort mates
    are re-sorted by every searching entry of a tick, and the condition
    lookups dominate the sort otherwise."""
    parts = memo.get(id(c)) if memo is not None else None
    if parts is None:
        parts = (
            not c.obj.condition_true(CONDITION_EVICTED),
            c.obj.priority,
            -c.obj.quota_reserved_time(now),
            c.obj.uid,
        )
        if memo is not None:
            memo[id(c)] = parts
    return (parts[0], c.cluster_queue == cq_name) + parts[1:]


def _topology_prefer(candidates: List[WorkloadInfo], hint,
                     snapshot: Snapshot) -> List[WorkloadInfo]:
    """Fragmentation-reducing victim preference (topology-aware
    scheduling): when the preemptor needs one contiguous domain at
    `hint`'s level, stably move the candidates occupying the most
    promising domain — the one where (current free + slots the candidates
    would release) is largest — to the front, so minimalPreemptions'
    greedy remove-until-fits empties ONE domain instead of nibbling
    slots across many. A pure reorder: the victim-set legality rules
    (priority, borrowing, policies) are untouched, and without a hint the
    ordering is byte-identical to the reference's."""
    flavor, level_name, _count = hint
    topo = getattr(snapshot, "topology", None)
    rf = snapshot.resource_flavors.get(flavor)
    spec = rf.topology if rf is not None else None
    if topo is None or spec is None:
        return candidates
    lvl = spec.level_index(level_name)
    if lvl is None:
        return candidates
    free = spec.domain_free(topo.get(flavor, ()), lvl)
    freed: Dict[tuple, int] = {}
    cand_domain = []
    for c in candidates:
        dom = None
        adm = c.obj.admission
        if adm is not None:
            # EVERY placed podset contributes to the freed totals (a
            # multi-podset victim can release slots in several domains);
            # the candidate groups under its first placed podset's domain
            # (a workload is evicted whole, so it needs one group).
            for psa in adm.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is not None and ta.flavor == flavor \
                        and len(ta.domain) > lvl:
                    d = ta.domain[:lvl + 1]
                    freed[d] = freed.get(d, 0) \
                        + sum(n for _, n in ta.counts)
                    if dom is None:
                        dom = d
        cand_domain.append(dom)
    if not freed:
        return candidates
    best = min(freed, key=lambda d: (-(free.get(d, 0) + freed[d]), d))
    in_best = [c for c, d in zip(candidates, cand_domain) if d == best]
    rest = [c for c, d in zip(candidates, cand_domain) if d != best]
    return in_best + rest


def _total_requests_for_assignment(wi: WorkloadInfo,
                                   assignment: Assignment) -> FlavorResourceQuantities:
    # Use the assignment's own request totals: unlike wi.total_requests they
    # include the synthetic "pods" resource when the CQ accounts for it.
    usage: FlavorResourceQuantities = {}
    for ps in assignment.pod_sets:
        for res, q in ps.requests.items():
            flv = ps.flavors[res].name
            usage.setdefault(flv, {})
            usage[flv][res] = usage[flv].get(res, 0) + q
    return usage


def _minimal_preemptions(wi: WorkloadInfo, assignment: Assignment,
                         snapshot: Snapshot, res_per_flv: ResourcesPerFlavor,
                         candidates: List[WorkloadInfo], allow_borrowing: bool,
                         allow_borrowing_below_priority: Optional[int],
                         ) -> List[WorkloadInfo]:
    """Greedy remove-until-fits then add-back refinement (preemption.go:172-231)."""
    wl_req = _total_requests_for_assignment(wi, assignment)
    cq = snapshot.cluster_queues[wi.cluster_queue]

    targets: List[WorkloadInfo] = []
    fits = False
    for cand in candidates:
        cand_cq = snapshot.cluster_queues[cand.cluster_queue]
        if cq is not cand_cq and not _cq_is_borrowing(cand_cq, res_per_flv):
            continue
        if cq is not cand_cq and allow_borrowing_below_priority is not None \
                and cand.obj.priority >= allow_borrowing_below_priority:
            # Once a candidate at/above the threshold is targeted, the
            # preemptor may no longer borrow (preemption.go:184-198).
            allow_borrowing = False
        snapshot.remove_workload(cand)
        targets.append(cand)
        if _workload_fits(wl_req, cq, allow_borrowing):
            fits = True
            break

    if not fits:
        for t in targets:
            snapshot.add_workload(t)
        return []

    # Add candidates back (reverse order) while the workload still fits.
    i = len(targets) - 2
    while i >= 0:
        snapshot.add_workload(targets[i])
        if _workload_fits(wl_req, cq, allow_borrowing):
            targets[i] = targets[-1]
            targets.pop()
        else:
            snapshot.remove_workload(targets[i])
        i -= 1

    # Restore the snapshot.
    for t in targets:
        snapshot.add_workload(t)
    return targets


def _negated_usage(wi: WorkloadInfo) -> FlavorResourceQuantities:
    return {f: {r: -v for r, v in res.items()}
            for f, res in wi.usage().items()}


def _fair_candidate_queues(wi: WorkloadInfo, cq: CachedClusterQueue,
                           res_per_flv: ResourcesPerFlavor,
                           ordering: WorkloadOrdering, now: float,
                           key_memo: Optional[dict] = None,
                           ) -> Dict[str, List[WorkloadInfo]]:
    """Per-CQ candidate queues, best victim first — shared by the host
    referee and the vectorized search. Cross-CQ candidates still honor
    the preemptor's reclaimWithinCohort contract: Never forbids any
    cross-queue eviction, LowerPriority restricts victims by priority
    (fair-share rules replace only the share comparison, not the
    admin-facing policy). `key_memo` is the tick-level sort-key memo
    (get_targets_batch): cohort mates are re-sorted by every searching
    entry, and within one search each candidate is keyed exactly once."""
    per_cq: Dict[str, List[WorkloadInfo]] = {}
    own = _find_candidates(wi, ordering, cq, res_per_flv)
    own = [c for c in own if c.cluster_queue == cq.name]
    if own:
        own.sort(key=lambda c: _candidate_sort_key(c, cq.name, now,
                                                   key_memo))
        per_cq[cq.name] = own
    reclaim = cq.preemption.reclaim_within_cohort
    if reclaim != PreemptionPolicy.NEVER:
        only_lower = reclaim != PreemptionPolicy.ANY
        for member in cq.cohort.root().tree_cluster_queues():
            if member is cq:
                continue
            cands = [c for c in member.workloads.values()
                     if _uses_resources(c, res_per_flv)
                     and not (only_lower and c.obj.priority >= wi.priority)]
            if cands:
                cands.sort(key=lambda c: _candidate_sort_key(c, cq.name, now,
                                                             key_memo))
                per_cq[member.name] = cands
    return per_cq


def _fair_preemptions(wi: WorkloadInfo, assignment: Assignment,
                      snapshot: Snapshot, res_per_flv: ResourcesPerFlavor,
                      ordering: WorkloadOrdering, now: float,
                      strategies, fair_ctx=None,
                      key_memo: Optional[dict] = None) -> List[WorkloadInfo]:
    """Share-based victim search (KEP-1714): the vectorized tensor search
    (ops/fair_preempt) when a solver context covers this search, the
    sequential dict-walk referee otherwise. KUEUE_TPU_NO_DEVICE_FAIR=1
    forces the referee; KUEUE_TPU_DEBUG_FAIR=1 runs both and asserts
    identical victim sequences."""
    cq = snapshot.cluster_queues[wi.cluster_queue]
    wl_req = _total_requests_for_assignment(wi, assignment)
    per_cq = _fair_candidate_queues(wi, cq, res_per_flv, ordering, now,
                                    key_memo)
    if not per_cq:
        # No eligible candidates (policies Never, or nothing borrowing
        # uses the contended resources): both searches end victimless —
        # the referee's first round finds no `best` and the vectorized
        # search has no rows — so skip building either. This is the
        # common shape of a steady state whose heads re-pop as Preempt
        # mode every tick.
        return []

    # The kill switch lives with the producers: both fair_ctx sources
    # (BatchSolver.fair_preempt_context, Scheduler._fair_ctx) return
    # None under KUEUE_TPU_NO_DEVICE_FAIR=1.
    if fair_ctx is not None:
        from kueue_tpu.ops.fair_preempt import fair_targets
        debug = knobs.flag("KUEUE_TPU_DEBUG_FAIR")
        vec_per_cq = {n: list(c) for n, c in per_cq.items()} if debug \
            else per_cq
        out = fair_targets(fair_ctx, cq, wl_req, vec_per_cq, res_per_flv,
                           strategies)
        if out is not None:
            if debug:
                oracle = _fair_preemptions_host(
                    cq, wl_req, per_cq, snapshot, res_per_flv, strategies)
                if [t.obj.uid for t in out] != \
                        [t.obj.uid for t in oracle]:
                    raise AssertionError(
                        "fair_preempt drift: vectorized victims "
                        f"{[t.obj.name for t in out]} != referee "
                        f"{[t.obj.name for t in oracle]} for "
                        f"{wi.obj.name}")
            return out
    return _fair_preemptions_host(cq, wl_req, per_cq, snapshot,
                                  res_per_flv, strategies)


def _fair_preemptions_host(cq: CachedClusterQueue,
                           wl_req: FlavorResourceQuantities,
                           per_cq: Dict[str, List[WorkloadInfo]],
                           snapshot: Snapshot,
                           res_per_flv: ResourcesPerFlavor,
                           strategies) -> List[WorkloadInfo]:
    """The sequential share-based referee (KEP-1714 "Preemption
    algorithm") — the oracle the vectorized search is pinned against.

    Round by round, pick the next victim from the cohort member with the
    highest share value, admitting it only if the configured strategy holds:
      * LessThanOrEqualToFinalShare (S2-a): after removing the victim, the
        offender's share is still >= the preemptor's share with the incoming
        workload admitted.
      * LessThanInitialShare (S2-b): the offender's current share strictly
        exceeds the preemptor's prospective share.
    Own-CQ victims follow the classic WithinClusterQueue policy. Ends with
    the same add-back minimization as the classic path.

    NOTE: `per_cq` lists are consumed (popped) by the search.
    """
    targets: List[WorkloadInfo] = []
    fits = False
    while True:
        if _workload_fits(wl_req, cq, True):
            fits = True
            break
        # The referee oracle intentionally keeps the per-iteration dict
        # walks the vectorized search (ops/fair_preempt) replaces — the
        # two are pinned identical by the churn goldens.
        share_x, _ = dominant_resource_share(cq, wl_req)  # kueuelint: disable=PERF01
        order = sorted(
            (name for name, cands in per_cq.items() if cands),
            key=lambda n: -dominant_resource_share(  # kueuelint: disable=PERF01
                snapshot.cluster_queues[n])[0])
        best = None
        for strategy in strategies:
            for y_name in order:
                y = snapshot.cluster_queues[y_name]
                cands = per_cq[y_name]
                if y is cq:
                    # Preempting our own workload always improves our share.
                    best = (y_name, 0)
                    break
                if not _cq_is_borrowing(y, res_per_flv):
                    continue
                # Scan the CQ's sorted candidates for the first that
                # satisfies the strategy (KEP-1714: "checking which of them
                # matches"), not just the head.
                for zi, z in enumerate(cands):
                    if strategy == FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE:
                        share_y_wo, _ = dominant_resource_share(  # kueuelint: disable=PERF01
                            y, _negated_usage(z))
                        ok = share_y_wo >= share_x
                    else:
                        share_y, _ = dominant_resource_share(y)  # kueuelint: disable=PERF01
                        ok = share_y > share_x
                    if ok:
                        best = (y_name, zi)
                        break
                if best is not None:
                    break
            if best is not None:
                break
        if best is None:
            break
        y_name, zi = best
        z = per_cq[y_name].pop(zi)
        snapshot.remove_workload(z)
        targets.append(z)

    if not fits:
        for t in targets:
            snapshot.add_workload(t)
        return []

    # Add-back minimization, as in the classic path (preemption.go:214-224).
    i = len(targets) - 2
    while i >= 0:
        snapshot.add_workload(targets[i])
        if _workload_fits(wl_req, cq, True):
            targets[i] = targets[-1]
            targets.pop()
        else:
            snapshot.remove_workload(targets[i])
        i -= 1
    for t in targets:
        snapshot.add_workload(t)
    return targets


def _workload_fits(wl_req: FlavorResourceQuantities, cq: CachedClusterQueue,
                   allow_borrowing: bool) -> bool:
    """preemption.go:352-389."""
    hierarchical = cq.cohort is not None and cq.cohort.is_hierarchical()
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            flv_req = wl_req.get(fq.name)
            if flv_req is None:
                continue
            cq_usage = cq.usage.get(fq.name, {})
            quotas = fq.resources_dict
            for rname, req in flv_req.items():
                quota = quotas.get(rname)
                if quota is None:
                    continue
                if cq.cohort is None or not allow_borrowing:
                    if cq_usage.get(rname, 0) + req > quota.nominal:
                        return False
                elif quota.borrowing_limit is not None:
                    if cq_usage.get(rname, 0) + req > quota.nominal + quota.borrowing_limit:
                        return False
                if hierarchical:
                    from kueue_tpu.core.hierarchy import hierarchical_lack
                    if hierarchical_lack(cq, fq.name, rname, req) > 0:
                        return False
                elif cq.cohort is not None:
                    cohort_used = cq.used_cohort_quota(fq.name, rname)
                    requestable = cq.requestable_cohort_quota(fq.name, rname)
                    if cohort_used + req > requestable:
                        return False
    return True
