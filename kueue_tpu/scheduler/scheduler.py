"""The scheduling tick.

Counterpart of reference pkg/scheduler/scheduler.go:174-288: pop queue heads,
snapshot the cache, nominate (flavor assignment + preemption targets), order
entries (borrowing < priority < FIFO), admit at most one borrowing workload
per cohort per cycle, issue preemptions, and requeue losers.

The flavor-assignment step is pluggable: by default every head is solved
sequentially with the referee (`kueue_tpu.solver.referee`); when a
`batch_solver` is supplied (see `kueue_tpu.models.flavor_fit.BatchSolver`)
all heads are solved in one batched JAX program on the accelerator, and only
preemption-target search runs host-side on the snapshot.
"""

from __future__ import annotations

import time as _time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from kueue_tpu import features
from kueue_tpu import knobs
from kueue_tpu.api.types import (
    Admission,
    Condition,
    PodSetAssignment,
    Workload,
)
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.tracing import TRACER, ExplainStore, build_record
from kueue_tpu.core.cache import (
    Cache,
    CachedClusterQueue,
    FlavorResourceQuantities,
    frq_add,
)
from kueue_tpu.core.hierarchy import fits_in_hierarchy
from kueue_tpu.core.snapshot import Snapshot, SnapshotMirror
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.queue.manager import Manager, RequeueReason
from kueue_tpu.scheduler import preemption as preemption_mod
from kueue_tpu.solver import fair_share, podset_reducer
from kueue_tpu.utils import parallelize
from kueue_tpu.solver.modes import FIT, NO_FIT, PREEMPT
from kueue_tpu.solver.referee import Assignment, assign_flavors

# Entry statuses (scheduler.go:289-300).
NOT_NOMINATED = ""
NOMINATED = "nominated"
SKIPPED = "skipped"
ASSUMED = "assumed"


@dataclass(slots=True)
class Entry:
    info: WorkloadInfo
    assignment: Optional[Assignment] = None
    status: str = NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: str = RequeueReason.GENERIC
    # None = victim search deferred to the admission cycle (batch mode):
    # the cycle issues at most one preemption round per cohort root per
    # cycle, so most PREEMPT entries never need their victim set, and the
    # snapshot is frozen between nominate and the cycle so a deferred
    # search returns exactly what an eager one would.
    preemption_targets: Optional[List[WorkloadInfo]] = field(
        default_factory=list)
    # ClusterQueue share value at nomination time (KEP-1714 fair sharing).
    share: float = 0.0
    # Batched staleness re-validation verdict (None = not validated; the
    # admission cycle falls back to the per-entry referee walk).
    reval_ok: Optional[bool] = None
    # Row of this entry in the batched solve it was decoded from (-1 when
    # the assignment was referee-built or replaced since): the admission
    # cycle reads the solve's CSR usage coordinates by this row instead
    # of walking the assignment's Python dicts/lists.
    solve_row: int = -1
    # Position in the admission cycle's decision order: entries deferred
    # to the cross-shard reconcile pass re-merge into the flush and
    # preemption-issue sequences at their original position, so the
    # two-phase cycle commits in exactly the single-phase order.
    cycle_pos: int = 0
    # Hetero solve mode (kueue_tpu/hetero): set when this entry's chosen
    # flavor differs from the first-fit twin — (flavor, first_fit_flavor,
    # throughput, score, score_rank, podset_idx), surfaced through the
    # explain records so `?explain=true` answers "why flavor B".
    hetero: Optional[tuple] = None


@dataclass
class TickInFlight:
    """A dispatched-but-not-completed scheduling tick (pipelined mode).

    Holds the popped heads (as prepped entries), the solver's in-flight
    device handle, and the snapshot the solve was encoded against. The
    completion phase (`Scheduler.schedule_finish`) fetches the solve,
    searches preemption targets, runs the admission cycle with staleness
    re-validation, and requeues losers."""

    start: float
    entries: List[Entry]
    solvable: List[Entry]
    handle: Optional[dict]
    snapshot: Snapshot
    dispatched_at: float = 0.0
    # Dirty-cohort micro-tick (event-driven fast path): {cq name:
    # triggering dirty event} when this tick solves ONLY the cohorts
    # dirtied since the last full tick; None for a full tick. Drives
    # the "admitted: micro-tick" explain reason, the micro metrics, and
    # the cycle's no-replica-round guard.
    micro: Optional[Dict[str, str]] = None


@dataclass
class SchedulerMetrics:
    admission_attempts: int = 0
    admitted: int = 0
    preempted: int = 0
    skipped: int = 0
    inadmissible: int = 0
    last_tick_seconds: float = 0.0
    # Two-phase (cohort-sharded) admit cycle: entries the optimistic
    # per-shard pass admitted but the global lending-clamp reconcile
    # revoked before flush. Always 0 single-phase (shards=1).
    reconcile_revocations: int = 0
    # Quiescent-tick fast path: ticks whose admit/sort/requeue
    # bookkeeping replayed the previous tick's (provably identical)
    # outcome instead of recomputing it.
    quiescent_ticks: int = 0
    # Event-driven fast path: dirty-cohort micro-ticks run between full
    # ticks, and the workloads they admitted.
    microticks: int = 0
    micro_admitted: int = 0


class Scheduler:
    def __init__(self, queues: Manager, cache: Cache,
                 apply_admission: Optional[Callable[[Workload], bool]] = None,
                 apply_preemption: Optional[Callable[[Workload, str], None]] = None,
                 namespace_lister: Optional[Callable[[str], Optional[dict]]] = None,
                 batch_solver=None,
                 ordering: Optional[WorkloadOrdering] = None,
                 pods_ready_gate: Optional[Callable[[], bool]] = None,
                 fair_strategies=preemption_mod.DEFAULT_FAIR_STRATEGIES,
                 workload_validator: Optional[
                     Callable[[Workload], List[str]]] = None,
                 preemption_engine: Optional[str] = None,
                 clock: Callable[[], float] = _time.time):
        self.queues = queues
        self.cache = cache
        self.apply_admission = apply_admission or (lambda wl: True)
        self.apply_preemption = apply_preemption or (lambda wl, msg: None)
        self._ns_lister = namespace_lister or (lambda name: {})
        self.batch_solver = batch_solver
        # Incremental workload arena plumbing: the solver subscribes to
        # the queue manager's pending-workload events (add/update/delete
        # keep rows fresh between ticks) and uses it as the backlog
        # supplier for full arena rebuilds.
        if batch_solver is not None:
            bind = getattr(batch_solver, "bind_queues", None)
            if bind is not None:
                bind(queues)
            # Admitted-set arena plumbing: the solver subscribes to the
            # cache's assume/add/forget/delete events so committed usage
            # stays arena-resident (preemption candidate rows, mirror
            # flush) across ticks.
            bind_cache = getattr(batch_solver, "bind_cache", None)
            if bind_cache is not None:
                bind_cache(cache)
        self.ordering = ordering or WorkloadOrdering()
        # waitForPodsReady.blockAdmission (KEP-349): admission is withheld
        # while the gate reports not-ready. The reference blocks the loop on
        # a condvar (cache.go:118-173); this synchronous runtime skips the
        # cycle's admissions and requeues instead.
        self.pods_ready_gate = pods_ready_gate
        # Per-workload admissibility gate run at nomination time — the
        # reference validates resource limits and the namespace LimitRange
        # summary here (scheduler.go:330-340 validateResources/
        # validateLimitRange); returns reasons, empty == admissible.
        self.workload_validator = workload_validator or (lambda wl: [])
        self.fair_strategies = tuple(fair_strategies)
        # minimalPreemptions engine: None = host referee, "jax"/"pallas" =
        # device scan (ops/preemption_scan).
        self.preemption_engine = preemption_engine
        self.clock = clock
        self.metrics = SchedulerMetrics()
        # Admission explainability: one compact decision record per
        # scheduling attempt per workload, bounded (tracing/explain.py),
        # surfaced via the visibility API (?explain=true) and the Dumper.
        self.explain = ExplainStore()
        # Incremental tick snapshot: re-clones only ClusterQueues whose
        # usage moved outside the scheduler's own assume/forget lockstep
        # (replaces the reference's per-tick deep copy, snapshot.go:95-129).
        self._mirror = SnapshotMirror(cache)
        if batch_solver is not None:
            view = getattr(batch_solver, "admitted_view", None)
            if view is not None:
                # Mirror flush fast path: touched ClusterQueues read
                # their usage (and clamped cohort deltas) straight from
                # the admitted arena instead of walking pending items.
                self._mirror.bind_admitted_view(view)
        # Topology-aware stage (kueue_tpu/topology), built lazily from the
        # snapshot's flavor set and keyed on its structure version; stays
        # None on topology-free clusters (the provable no-op).
        self._topo_key = None
        self._topo_stage = None
        # CSR admission commit: "1" forces it, "0" forces the classic
        # walk, unset = on exactly when the native bulk-assume is not
        # built (cache.native_assume_available — the C++ walk wins when
        # present, the aggregation wins over the Python fallback).
        knob = knobs.raw("KUEUE_TPU_CSR_ASSUME")
        from kueue_tpu.core import cache as cache_mod
        self._csr_assume = knob == "1" or (
            knob != "0" and not cache_mod.native_assume_available())
        # Quiescent-tick fast path (BENCH_r06: a steady tick with ZERO
        # work still paid ~29ms requeue + ~29ms admit + ~8ms sort of
        # bookkeeping): when every head replays its fingerprint-cached
        # verdict, nothing mutated the cache since the last finish, and
        # the previous cycle provably did nothing, this tick's sort
        # order / admit cycle / loser condition-writes are replayed
        # instead of recomputed. KUEUE_TPU_NO_QUIET_TICK=1 kills it (the
        # goldens drive both paths).
        self._quiet_enabled = not knobs.flag("KUEUE_TPU_NO_QUIET_TICK")
        # Ring of recent fully-cached tick signatures keyed by the entry
        # uid sequence (pipelined ticks cycle head sets with period ~=
        # depth, so "the identical tick" is usually depth ticks back, not
        # one): each entry pins the Assignment refs + messages it was
        # recorded with (identity compares can't alias recycled objects),
        # the sorted order, the cache mutation count at its finish, and
        # whether its cycle provably did nothing.
        self._quiet_ring: "OrderedDict[tuple, dict]" = OrderedDict()
        # (selector ref, ns-labels ref) -> verdict per (cq, namespace):
        # the namespace-selector match in _prep_entries is pure in the
        # two held objects, and both are replaced (never mutated) on
        # change, so identity-keyed memoization is exact.
        self._ns_match_memo: Dict[tuple, tuple] = {}
        # Per-tick fair-sharing state (KEP-1714): the solver's
        # incremental share state (set by _resolve; None with fair off,
        # no solver, or KUEUE_TPU_NO_DEVICE_FAIR=1) and the count of
        # ClusterQueues the bulk share tensors did not cover this tick.
        self._tick_fair_state = None
        self._fair_bulk_miss = 0
        # Multi-process replica mode (parallel/replica.py): when the
        # owning runtime wires a ReplicaContext here, entries whose
        # cohort root spans replica shard groups are deferred to the
        # cross-replica commit protocol instead of the in-process
        # reconcile — the coordinator replays them in global cycle order
        # and returns commit/revoke verdicts before the flush.
        self.replica_ctx = None
        self._cycle_replica_candidates = 0
        self._replica_member_memo = None

    def close(self) -> None:
        """Release cache/queue subscriptions. Call when retiring this
        scheduler while its cache lives on (e.g. config-reload
        replacement) — the mirror's dirty sink and the solver's queue
        subscription would otherwise stay registered forever."""
        self._mirror.detach()
        if self.batch_solver is not None:
            unbind = getattr(self.batch_solver, "unbind_queues", None)
            if unbind is not None:
                unbind()
            unbind_cache = getattr(self.batch_solver, "unbind_cache", None)
            if unbind_cache is not None:
                unbind_cache()

    def prewarm(self, head_counts: Sequence[int], podsets: int = 1) -> None:
        """Warmup hook: compile the batched solve for the given head-count
        buckets NOW (off the measured path), so no XLA compile lands
        inside a scheduling tick. The solver also auto-prewarms neighbor
        buckets when the live head count drifts toward a rotation
        (BatchSolver._maybe_prewarm); this hook covers startup and
        operator-known arrival shapes."""
        bs = self.batch_solver
        warm = getattr(bs, "warmup", None)
        if warm is not None:
            warm(self._mirror.refresh(), head_counts, podsets)

    def prewarm_idle(self) -> int:
        """Drain queued neighbor-bucket compiles in the idle window
        between ticks (BatchSolver.prewarm_idle, plus the topology fit
        kernel's item buckets); returns how many shapes were compiled.
        The serve loop and the bench's churn slot call this so a bucket
        rotation never compiles inside a measured tick."""
        fn = getattr(self.batch_solver, "prewarm_idle", None)
        done = fn() if fn is not None else 0
        if self._topo_stage is not None and self.batch_solver is not None:
            done += self._topo_stage.prewarm_idle()
        return done

    # -- one tick -----------------------------------------------------------

    def schedule(self, timeout: Optional[float] = 0.0) -> int:
        """Run one scheduling cycle synchronously; returns admissions.

        Phase timings (snapshot / nominate incl. the device solve / admit /
        requeue) land in the kueue_tick_phase_seconds histogram — the
        TPU-build observability addition SURVEY §5 calls for on top of the
        reference's whole-tick histogram (metrics.go:70-79)."""
        tick = self.schedule_async(timeout=timeout)
        if tick is None:
            return 0
        return self.schedule_finish(tick)

    def schedule_async(self, timeout: Optional[float] = 0.0,
                       ) -> Optional[TickInFlight]:
        """Dispatch phase of a tick: pop heads, refresh the snapshot, gate
        entries, and launch the batched device solve without blocking on
        it. With pipeline depth N, up to N ticks run dispatch-overlapped:
        tick i+1's solve crosses the interconnect while tick i's admission
        cycle runs host-side — the production version of the depth-k
        pipeline the round-1 bench only simulated."""
        heads = self.queues.heads(timeout=timeout)
        if not heads:
            return None
        return self._dispatch(heads)

    def _dispatch(self, heads: Sequence[WorkloadInfo],
                  snapshot: Optional[Snapshot] = None,
                  micro: Optional[Dict[str, str]] = None,
                  ) -> Optional[TickInFlight]:
        """The tick pipeline's first two stages over already-popped
        heads: INGEST (snapshot refresh + entry gating) and ENCODE
        (arena gather + device dispatch, which returns without blocking
        — the solve itself runs on the device lane while later host
        stages of OLDER ticks execute). Shared by the full tick
        (`schedule_async`) and the dirty-cohort micro-tick."""
        start = self.clock()
        with TRACER.phase("tick.stage.ingest"):
            if snapshot is None:
                with TRACER.phase("snapshot"):
                    snapshot = self._mirror.refresh()
            entries, solvable = self._prep_entries(heads, snapshot)
        handle = None
        if self.batch_solver is not None and solvable:
            with TRACER.phase("tick.stage.encode"):
                handle = self.batch_solver.solve_async(
                    [e.info for e in solvable], snapshot)
        return TickInFlight(start=start, entries=entries, solvable=solvable,
                            handle=handle, snapshot=snapshot,
                            dispatched_at=self._mirror.mutation_count,
                            micro=micro)

    def schedule_finish(self, tick: TickInFlight) -> int:
        """Completion phase: collect the solve, search preemption targets,
        order entries, run the admission cycle (with staleness
        re-validation when the snapshot moved since dispatch), requeue."""
        # Later finishes must see earlier finishes' admissions: apply any
        # queued lockstep mutations before validating against the snapshot.
        self._mirror.flush_pending()
        stale = self._mirror.mutation_count != tick.dispatched_at
        snapshot = tick.snapshot
        entries = tick.entries
        with TRACER.phase("nominate") as nsp:
            self._resolve(tick)
            if tick.handle is not None and (
                    tick.handle.get("handle") is not None
                    or tick.handle.get("out") is not None):
                # The device-solve stage's span: dispatch -> fetch, on
                # its own Perfetto lane (DEVICE_LANE) — in pipelined
                # mode it visibly overlaps the NEXT tick's host-side
                # ingest/encode stage spans.
                from kueue_tpu.tracing import DEVICE_LANE, trace_now
                t0 = tick.handle.get("dispatched")
                if t0 is not None:
                    TRACER.record_span(
                        "tick.stage.solve", t0, trace_now(),
                        lane=DEVICE_LANE,
                        attrs={"micro": tick.micro is not None})
            if features.enabled(features.FAIR_SHARING):
                # How many ClusterQueues fell off the bulk share tensors
                # onto the per-CQ dict walk (0 in a normal tick).
                nsp.set("fair.bulk_miss", self._fair_bulk_miss)
            if tick.handle is not None:
                cached = tick.handle.get("cached")
                if cached is not None:
                    # Nominate-cache evidence: how many heads replayed a
                    # fingerprint-unchanged verdict vs solved fresh.
                    nsp.set("heads_cached", len(cached))
                    nsp.set("heads_total", len(tick.handle["workloads"]))
            # Quiescent tick: every head replayed its cached verdict AND
            # an earlier fully-cached tick had the exact same inputs
            # (same uid sequence, same Assignment objects, same pre-cycle
            # messages, no cache mutation since its finish) — so the
            # sort order is that tick's order, and (when that tick's
            # cycle took no externally-visible action beyond
            # deterministic skips) the admit cycle's outcome too.
            quiet_entry = None if stale \
                else self._quiescent_match(tick, entries)
            pre_uids = None
            sort_order = None
            pre_assign = None
            pre_msgs = None
            with TRACER.phase("nominate.sort"):
                if quiet_entry is not None:
                    order = quiet_entry["order"]
                    entries[:] = [entries[i] for i in order]
                else:
                    pre_uids = tuple(e.info.obj.uid for e in entries)
                    for pos, e in enumerate(entries):
                        e.cycle_pos = pos
                    self._sort_entries(entries)
                    # sort_order[j] = pre-sort index of sorted slot j;
                    # snapshot the cycle INPUTS (the cycle mutates
                    # messages) in pre-sort order for the ring record.
                    n_e = len(entries)
                    sort_order = [e.cycle_pos for e in entries]
                    pre_assign = [None] * n_e
                    pre_msgs = [""] * n_e
                    for j, e in enumerate(entries):
                        pre_assign[sort_order[j]] = e.assignment
                        pre_msgs[sort_order[j]] = e.inadmissible_msg
        skip_cycle = quiet_entry is not None \
            and quiet_entry["outcomes"] is not None
        with TRACER.phase("admit") as sp:
            if skip_cycle:
                # The recorded cycle ran to completion on identical
                # inputs and did nothing but deterministic bookkeeping
                # (no admission, no preemption issued): replay its
                # per-entry outcomes instead of recomputing them.
                admitted = 0
                for e, (st, msg, reason, cleared) in zip(
                        entries, quiet_entry["outcomes"]):
                    if st == SKIPPED:
                        e.status = st
                        e.inadmissible_msg = msg
                        e.requeue_reason = reason
                        if cleared:
                            e.info.last_assignment = None
                self.metrics.skipped += quiet_entry["skipped_delta"]
                self.metrics.reconcile_revocations += \
                    quiet_entry["revoked_delta"]
                self.metrics.quiescent_ticks += 1
                sp.set("quiescent", True)
            else:
                usage_csr = tick.handle.get("usage_csr") \
                    if tick.handle is not None else None
                preempted_before = self.metrics.preempted
                skipped_before = self.metrics.skipped
                revoked_before = self.metrics.reconcile_revocations
                admitted = self._admission_cycle(entries, snapshot,
                                                 revalidate=stale,
                                                 usage_csr=usage_csr,
                                                 micro=tick.micro is not None)
                # Replayable = nothing escaped the tick: no admission
                # assumed, no preemption issued — only NOT_NOMINATED
                # losers and deterministic SKIPPED bookkeeping. A cycle
                # that shipped candidates to the cross-replica
                # coordinator is never replayable: its outcome depends on
                # OTHER replicas' state, which no local signature pins.
                replayable = (
                    admitted == 0
                    and self.metrics.preempted == preempted_before
                    and self._cycle_replica_candidates == 0
                    and all(e.status in (NOT_NOMINATED, SKIPPED)
                            for e in entries))
                self._quiescent_record(
                    tick, entries, quiet_entry, replayable,
                    pre_uids, sort_order, pre_assign, pre_msgs,
                    self.metrics.skipped - skipped_before,
                    self.metrics.reconcile_revocations - revoked_before)
            sp.set("admitted", admitted)
            sp.set("entries", len(entries))
        with TRACER.phase("requeue"):
            self._requeue_sweep([e for e in entries if e.status != ASSUMED],
                                quiescent=skip_cycle)
        st = self._tick_fair_state
        if st is not None:
            # Post-commit publication refresh: fold the cycle's usage
            # movement into the share state NOW (dirty cohorts only; one
            # generation compare when nothing committed), so the
            # off-thread metrics scrape (fair_shares_last) serves
            # end-of-tick shares even when the system then drains and no
            # later nominate refreshes. Decision paths are untouched —
            # the next nominate's refresh is idempotent on the same
            # usage tensors.
            with TRACER.phase("fair.publish"):
                st.refresh()
        self.metrics.admission_attempts += 1
        self.metrics.last_tick_seconds = self.clock() - tick.start
        self._record_decisions(entries, quiescent=skip_cycle,
                               micro=tick.micro)
        result = "success" if admitted else "inadmissible"
        REGISTRY.admission_attempts_total.inc(result)
        REGISTRY.admission_attempt_duration_seconds.observe(
            result, value=self.metrics.last_tick_seconds)
        if tick.micro is not None:
            self.metrics.microticks += 1
            self.metrics.micro_admitted += admitted
            REGISTRY.microticks_total.inc()
            REGISTRY.microtick_latency_seconds.observe(
                value=max(0.0, self.metrics.last_tick_seconds))
        return admitted

    # How many distinct recent tick signatures the quiescent ring
    # remembers. The steady state is periodic, not fixed: head sets
    # cycle with period ~= pipeline depth, and each NoFit head's
    # resume-protocol verdict cycles with period <= 4 — the joint
    # signature repeats every lcm of those (measured 24 at depth 4;
    # bounded by ~12 x depth). 128 covers depth 8 with headroom; one
    # entry is three lists of per-head refs, so the ring is a few MB at
    # 1k heads, pinned only while quiescence holds.
    QUIET_RING_MAX = 128

    def _fair_share_term(self) -> int:
        """The quiescent-signature share term: the incremental share
        state's version (bumped exactly when any share value changed),
        -1 when fair sharing runs on the dict-walk fallback, 0 with the
        gate off."""
        if not features.enabled(features.FAIR_SHARING):
            return 0
        st = self._tick_fair_state
        return st.version if st is not None else -1

    def _hetero_term(self) -> int:
        """The quiescent-signature hetero term: the solver's score-matrix
        version while the hetero mode is actively overriding, 0 otherwise
        (an inactive hetero tick decides exactly like the default mode,
        so the 0 key aliases it safely) — a hetero steady state replays
        sort/admit/requeue AND dispatches zero solves."""
        fn = getattr(self.batch_solver, "hetero_signature_term", None)
        return fn() if fn is not None else 0

    def _quiescent_match(self, tick: TickInFlight,
                         entries: List[Entry]) -> Optional[dict]:
        """The recorded ring entry whose inputs provably equal this
        tick's, or None. Requires: every solvable head replayed a
        fingerprint-cached verdict, a ring entry exists for this exact
        uid sequence, nothing mutated the cache since that entry's
        finish, and the per-entry Assignment objects (identity — the
        refs are pinned by the ring) and messages match."""
        if not self._quiet_enabled:
            return None
        if self.pods_ready_gate is not None:
            # The gate reads state outside the cache (pod readiness); a
            # mutation-count check cannot prove it unchanged.
            return None
        handle = tick.handle
        if handle is None:
            return None
        cached = handle.get("cached")
        if cached is None or len(cached) != len(handle["workloads"]):
            return None  # at least one head solved fresh
        # The resume protocol cycles each head through a short ring of
        # cached verdicts, so one uid sequence recurs with several
        # distinct Assignment combinations — the verdict identities are
        # part of the key. (ids are safe IN the key: a hit's entry pins
        # its refs alive, so its recorded ids cannot have been recycled.)
        # The sort-relevant feature gates ride along: they can flip
        # without a cache mutation, and the recorded order bakes them in.
        # So does the fair-share state VERSION (the share term of the
        # signature): shares are a pure function of cache usage — which
        # the mutation stamp already pins — but the explicit term keeps
        # the fair sort order provably identical even if the share
        # machinery ever gained another input.
        key = (tuple(e.info.obj.uid for e in entries),
               tuple(id(e.assignment) for e in entries),
               features.enabled(features.FAIR_SHARING),
               features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT),
               self._fair_share_term(),
               self._hetero_term())
        ent = self._quiet_ring.get(key)
        if ent is None or ent["mut"] != self._mirror.mutation_count:
            return None
        assignments = ent["assignments"]
        msgs = ent["msgs"]
        for i, e in enumerate(entries):
            if e.assignment is not assignments[i] \
                    or e.inadmissible_msg != msgs[i]:
                return None
        self._quiet_ring.move_to_end(key)
        return ent

    def _quiescent_record(self, tick: TickInFlight, entries: List[Entry],
                          quiet_entry: Optional[dict], replayable: bool,
                          pre_uids: Optional[tuple],
                          sort_order: Optional[list],
                          pre_assign: Optional[list],
                          pre_msgs: Optional[list],
                          skipped_delta: int, revoked_delta: int) -> None:
        """Record (or refresh) this finish's signature after a real
        cycle ran: the pre-cycle INPUTS (uid sequence, Assignment refs,
        messages — what the match compares) plus, when the cycle was
        replayable, its per-entry OUTCOMES in sorted order (what the
        replay applies). A matched entry whose cycle had to run anyway
        just refreshes its outcome and mutation stamp."""
        if not self._quiet_enabled:
            return
        mut = self._mirror.mutation_count
        outcomes = None
        if replayable:
            outcomes = [(e.status, e.inadmissible_msg, e.requeue_reason,
                         e.info.last_assignment is None) for e in entries]
        if quiet_entry is not None:
            quiet_entry["outcomes"] = outcomes
            quiet_entry["skipped_delta"] = skipped_delta
            quiet_entry["revoked_delta"] = revoked_delta
            quiet_entry["mut"] = mut
            return
        handle = tick.handle
        if handle is None or pre_uids is None or sort_order is None:
            return
        cached = handle.get("cached")
        if cached is None or len(cached) != len(handle["workloads"]):
            return  # only fully-cached ticks can ever match
        ring = self._quiet_ring
        ring[(pre_uids, tuple(id(a) for a in pre_assign),
              features.enabled(features.FAIR_SHARING),
              features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT),
              self._fair_share_term(),
              self._hetero_term())] = {
            "assignments": pre_assign,
            "msgs": pre_msgs,
            "order": sort_order,
            "outcomes": outcomes,
            "skipped_delta": skipped_delta,
            "revoked_delta": revoked_delta,
            "mut": mut,
        }
        while len(ring) > self.QUIET_RING_MAX:
            ring.popitem(last=False)

    def _record_decisions(self, entries: List[Entry],
                          quiescent: bool = False,
                          micro: Optional[Dict[str, str]] = None) -> None:
        """Append this attempt's decision record per workload (admission
        explainability). Runs after the requeue sweep so each record
        carries the final outcome + Pending message of the attempt.

        On a quiescent tick (the admit cycle replayed the previous
        provably-identical outcome) each workload's LAST record is
        collapsed in place — its tick/time stamps advance and a repeat
        counter bumps — instead of rebuilding an identical flavor-trail
        record per head per tick.

        Micro-tick admissions (`micro` = {cq: triggering dirty event})
        record the outcome reason "admitted: micro-tick (<event>)", so
        `?explain=true` distinguishes the event-driven fast path from
        full-tick decisions — and names the dirty event that woke it."""
        from kueue_tpu.tracing import explain as explain_mod

        seq = self.metrics.admission_attempts
        now = self.clock()
        if quiescent:
            self.explain.record_repeats(
                [e.info.key for e in entries], seq, now)
            return
        items = []
        for e in entries:
            if e.status == ASSUMED:
                outcome = explain_mod.ADMITTED
            elif e.status == SKIPPED:
                outcome = explain_mod.SKIPPED
            elif e.preemption_targets:
                outcome = explain_mod.PREEMPTING
            else:
                outcome = explain_mod.INADMISSIBLE
            rec = build_record(e, seq, now, outcome)
            if micro is not None and e.status == ASSUMED:
                event = micro.get(e.info.cluster_queue, "dirty cohort")
                # Layout index 4 is the reason field (an admitted
                # entry's inadmissible_msg is empty otherwise).
                rec = rec[:4] + (f"admitted: micro-tick ({event})",) \
                    + rec[5:]
            items.append((e.info.key, rec))
        self.explain.record_bulk(items)

    # -- dirty-cohort micro-tick (event-driven fast path) --------------------

    @staticmethod
    def microtick_enabled() -> bool:
        """The micro-tick kill switch, read live so identity drives can
        flip KUEUE_TPU_NO_MICROTICK per run."""
        return not knobs.flag("KUEUE_TPU_NO_MICROTICK")

    def microtick(self) -> int:
        """Solve ONLY the cohorts dirtied since the last tick — the
        event-driven admission path between full ticks.

        Flat cohorts are solve-independent by construction (the
        CohortMesh shards over exactly this property), so a micro-tick
        pops just the dirty cohorts' heads and runs the normal
        dispatch/finish pipeline over them: the nominate-cache
        fingerprints replay unchanged heads, the admission cycle runs
        the same quota arithmetic against the refreshed mirror, and any
        in-flight pipelined full tick re-validates against the mirror
        mutations this commit makes (the standing optimistic-concurrency
        contract). Hierarchical trees, shard-split and replica-split
        roots always defer to the next full tick — their quota math
        needs merged state a focused pass does not hold.

        Intentional reorder vs the sequential tick is pinned by
        linearizability-style invariants instead of byte identity: no
        quota oversubscribed (same milli-unit cycle gates), no admitted
        workload revoked without a journaled verdict (micro-ticks never
        ship replica rounds, so nothing arbitrates them remotely), and
        FIFO preserved within each ClusterQueue (heads pop in heap
        order, exactly like the full sweep). KUEUE_TPU_NO_MICROTICK=1
        makes this a no-op — decisions then match the barrier-paced
        trail byte for byte."""
        if not self.microtick_enabled():
            return 0
        queues = self.queues
        if not queues.has_dirty_cohorts():
            return 0
        dirty = queues.drain_dirty_cohorts()
        if not dirty:
            return 0
        with TRACER.tick("microtick"):
            with TRACER.phase("microtick.route") as rsp:
                snapshot = self._mirror.refresh()
                split = frozenset()
                if self.batch_solver is not None:
                    sv_fn = getattr(self.batch_solver, "shard_view", None)
                    sv = sv_fn(snapshot) if sv_fn is not None else None
                    if sv is not None:
                        split = sv[0].split_roots
                rctx = self.replica_ctx
                rsplit = rctx.split_roots if rctx is not None \
                    else frozenset()
                events: Dict[str, str] = {}
                deferred = 0
                overflow = 0
                # Submit events first: the micro-tick is a LATENCY
                # path. A mass quota-release storm (hundreds of cohorts
                # flushed by a completion wave) is throughput work the
                # full tick's batched sweep does better — cohorts past
                # the CQ budget are re-marked and handed back to it.
                ordered = sorted(
                    dirty.items(),
                    key=lambda kv: (0 if kv[1].startswith("submit")
                                    else 1, kv[0]))
                for key, event in ordered:
                    members = queues.cohort_member_names(key)
                    eligible = bool(members)
                    for name in members:
                        cq = snapshot.cluster_queues.get(name)
                        if cq is None:
                            continue
                        cohort = cq.cohort
                        if cohort is not None and (
                                cohort.is_hierarchical()
                                or cohort.root_name in split
                                or cohort.root_name in rsplit):
                            eligible = False
                            break
                    if not eligible:
                        deferred += 1
                        continue
                    if events and len(events) + len(members) \
                            > self.MICROTICK_MAX_CQS:
                        overflow += 1
                        queues.remark_dirty(key, event)
                        continue
                    for name in members:
                        events[name] = event
                rsp.set("dirty", len(dirty))
                rsp.set("deferred", deferred)
                rsp.set("overflow", overflow)
                rsp.set("cqs", len(events))
            if not events:
                return 0
            # Drain loop: one head pops per CQ per round (the sweep
            # semantics), so a burst deeper than one per queue needs
            # several rounds — keep going while admissions flow, up to
            # a bound that keeps a single micro-tick from starving the
            # caller. An early stop with pending left re-marks the
            # cohorts dirty so the NEXT micro-tick continues instead of
            # waiting for a fresh event.
            total = 0
            names = sorted(events)
            for _round in range(self.MICROTICK_MAX_ROUNDS):
                heads = queues.pop_heads_for(names)
                if not heads:
                    return total
                tick = self._dispatch(heads, snapshot=snapshot,
                                      micro=events)
                admitted = self.schedule_finish(tick)
                total += admitted
                if not admitted:
                    return total
                # The finish may have moved the mirror; later rounds
                # must gate against the refreshed view.
                snapshot = self._mirror.refresh()
            for name in names:
                if self.queues.pending(name):
                    self.queues.mark_dirty_cq(
                        name, "micro-tick round cap")
            return total

    # One micro-tick drains at most this many rounds before handing the
    # rest back (as fresh dirty marks) — bounds the caller's stall while
    # a deep burst drains.
    MICROTICK_MAX_ROUNDS = 16
    # ... and touches at most this many ClusterQueues: past the budget a
    # dirty cohort is re-marked for the full tick (whose batched sweep
    # is the right tool for completion-wave storms). One cohort whose
    # member count alone exceeds the budget still runs whole — cohorts
    # are the atomic admission domain.
    MICROTICK_MAX_CQS = 64

    # -- nomination (scheduler.go:317-351) ----------------------------------

    def _prep_entries(self, heads: Sequence[WorkloadInfo],
                      snapshot: Snapshot):
        entries: List[Entry] = []
        solvable: List[Entry] = []
        already = self.cache.assumed_or_admitted_bulk(
            [wi.obj for wi in heads])
        cqs_by_name = snapshot.cluster_queues
        inactive = snapshot.inactive_cluster_queues
        ns_lister = self._ns_lister
        validator = self.workload_validator
        # One namespace-labels fetch per namespace per tick (heads at
        # scale share a handful of namespaces, and the lister may cross
        # into informer/runtime state).
        ns_cache: Dict[str, Optional[dict]] = {}
        for wi, skip in zip(heads, already):
            if skip:
                continue
            e = Entry(info=wi)
            cq = cqs_by_name.get(wi.cluster_queue)
            if wi.obj.admission_check_states \
                    and _has_retry_or_rejected_checks(wi.obj):
                e.inadmissible_msg = "The workload has failed admission checks"
            elif wi.cluster_queue in inactive:
                e.inadmissible_msg = f"ClusterQueue {wi.cluster_queue} is inactive"
            elif cq is None:
                e.inadmissible_msg = f"ClusterQueue {wi.cluster_queue} not found"
            else:
                namespace = wi.obj.namespace
                try:
                    ns = ns_cache[namespace]
                except KeyError:
                    ns = ns_cache[namespace] = ns_lister(namespace)
                if ns is None:
                    e.inadmissible_msg = "Could not obtain workload namespace"
                elif not self._ns_matches(cq, namespace, ns):
                    e.inadmissible_msg = \
                        "Workload namespace doesn't match ClusterQueue selector"
                    e.requeue_reason = RequeueReason.NAMESPACE_MISMATCH
                else:
                    reasons = validator(wi.obj)
                    if reasons:
                        e.inadmissible_msg = "; ".join(reasons)
                    else:
                        solvable.append(e)
            entries.append(e)
        return entries, solvable

    def _ns_matches(self, cq: CachedClusterQueue, namespace: str,
                    ns: dict) -> bool:
        """Memoized namespace-selector match: one real `matches` per
        (ClusterQueue, namespace) per selector/labels GENERATION instead
        of one per head per tick (the quiescent-tick profile's single
        largest _prep_entries cost at 1k CQs). Both memo keys are
        compared by identity with the refs held — the selector is a
        frozen dataclass replaced on CQ update, and the runtime replaces
        the labels dict on namespace update — so a stale hit is
        impossible."""
        memo = self._ns_match_memo
        key = (cq.name, namespace)
        hit = memo.get(key)
        sel = cq.namespace_selector
        if hit is not None and hit[0] is sel and hit[1] is ns:
            return hit[2]
        verdict = sel.matches(ns)
        if len(memo) > 100_000:
            memo.clear()
        memo[key] = (sel, ns, verdict)
        return verdict

    def _topology_stage(self, snapshot: Snapshot):
        """The topology-aware placement stage for this snapshot, or None
        when no flavor declares a topology (or the gate is off)."""
        if snapshot.topology is None \
                or not features.enabled(features.TOPOLOGY_AWARE_SCHEDULING):
            return None
        if self._topo_key != snapshot.structure_version:
            from kueue_tpu.topology import (
                TopologyStage, build_topology_encoding)
            enc = build_topology_encoding(snapshot.resource_flavors)
            self._topo_stage = TopologyStage(enc) if enc is not None else None
            self._topo_key = snapshot.structure_version
        return self._topo_stage

    def _topology_pair(self, snapshot: Snapshot):
        """(stage, leaf-occupancy view) for the referee path, or None."""
        stage = self._topology_stage(snapshot)
        if stage is None:
            return None
        return stage, snapshot.topology

    def _resolve(self, tick: TickInFlight) -> None:
        """Flavor-assign all nominable entries: collect the batched device
        solve when one is in flight, else run the sequential referee."""
        entries = tick.solvable
        snapshot = tick.snapshot
        solve_rows = None
        if tick.handle is not None:
            assignments = self.batch_solver.collect(tick.handle)
            # Entry index -> row in the (miss-only) solve batch; None
            # when the nominate cache is off (identity mapping then).
            solve_rows = tick.handle.get("solve_rows")
            topo_stage = self._topology_stage(snapshot)
            if topo_stage is not None:
                # Topology stage over the whole batch: one vectorized
                # best-fit-level search on the device path (the referee
                # path runs its host twin inside assign_flavors).
                topo_stage.apply([e.info for e in entries], assignments,
                                 snapshot.topology, use_device=True)
        else:
            assignments = None
        fair = features.enabled(features.FAIR_SHARING)
        shares: Dict[str, float] = {}
        fair_state = None
        fair_cq_index = None
        if fair:
            # The incremental share state: shares replayed across ticks
            # (memoized on the per-cohort usage-VALUE generations) with
            # only dirty cohorts' members recomputed — instead of a dict
            # DRF walk per ClusterQueue, or even a full [C,F,R] pass,
            # per tick (KEP-1714 at 1k-CQ scale). Falls back to the
            # per-CQ referee when the solver has no matching encoding
            # or KUEUE_TPU_NO_DEVICE_FAIR=1.
            with TRACER.phase("nominate.fair"):
                fs_fn = getattr(self.batch_solver, "fair_share_state",
                                None)
                fair_state = fs_fn(snapshot) if fs_fn is not None else None
            if fair_state is not None:
                fair_cq_index = fair_state.enc.cq_index
                if knobs.flag("KUEUE_TPU_DEBUG_FAIR"):
                    fair_state.verify(snapshot)
        self._tick_fair_state = fair_state
        self._fair_bulk_miss = 0

        def share_of(cq_name: str) -> float:
            if fair_cq_index is not None:
                ci = fair_cq_index.get(cq_name)
                if ci is not None:
                    return fair_state.share_of_ci(ci)
            s = shares.get(cq_name)
            if s is None:
                cq = snapshot.cluster_queues.get(cq_name)
                if cq is None:
                    # A CQ outside the snapshot entirely (inactive or
                    # deleted — only non-solvable entries get here):
                    # share 0 by definition, not an encoding gap.
                    s = shares[cq_name] = 0.0
                else:
                    # Bulk miss: a ClusterQueue outside the solver's
                    # share tensors (no encoding, rotation in flight, or
                    # the kill switch) pays the dict DRF walk — counted
                    # and surfaced as the nominate span's
                    # `fair.bulk_miss` attribute.
                    self._fair_bulk_miss += 1
                    s = shares[cq_name] = \
                        fair_share.dominant_resource_share(cq)[0]
            return s
        # Batched device victim search: all PREEMPT-mode entries of the
        # tick solved in at most two dispatches instead of one per entry
        # (preemption.go runs these sequentially per head; the searches
        # are independent against the frozen snapshot, so batching is
        # decision-preserving).
        partial_feature = features.enabled(features.PARTIAL_ADMISSION)
        # Only partial-admission-eligible PREEMPT entries need their victim
        # set at nomination time (the reducer's decision depends on it);
        # everyone else's search defers to the admission cycle.
        pre_pairs = [] if assignments is None else [
            (i, entries[i].info, a) for i, a in enumerate(assignments)
            if a.representative_mode == PREEMPT
            and partial_feature
            and entries[i].info.obj.can_be_partially_admitted()]
        batch_targets = self._batched_targets(pre_pairs, snapshot)
        partial_pending: List[Entry] = []
        for i, e in enumerate(entries):
            full = assignments[i] if assignments is not None else None
            if full is not None and full.representative_mode == FIT:
                # Batched-solve FIT fast path: nothing to search, no
                # message to build (a FIT assignment has no reasons).
                e.assignment = full
                e.solve_row = i if solve_rows is None else int(solve_rows[i])
                e.preemption_targets = []
                e.inadmissible_msg = ""
                e.info.last_assignment = full.last_state
                continue
            if (full is not None and full.representative_mode == PREEMPT
                    and i not in batch_targets):
                assignment, targets = full, None   # deferred victim search
            else:
                assignment, targets = self._get_assignment(
                    e.info, snapshot, full,
                    precomputed_targets=batch_targets.get(i),
                    allow_partial=assignments is None)
            e.assignment = assignment
            e.preemption_targets = targets
            needs_partial = (assignments is not None and not targets
                             and assignment.representative_mode != FIT
                             and partial_feature
                             and e.info.obj.can_be_partially_admitted())
            e.inadmissible_msg = assignment.message()
            if needs_partial:
                # Defer the resume-state update: the reducer's probes must
                # resume from the PREVIOUS attempt's flavor state, exactly
                # like the sequential path whose probes run before the
                # caller overwrites last_assignment.
                partial_pending.append(e)
            else:
                e.info.last_assignment = assignment.last_state
        if fair:
            # ALL entries are sorted, not just the solvable ones — key
            # every entry (incl. failed-checks / inactive-CQ / namespace
            # mismatches) by its ClusterQueue's actual share, so the
            # packed rank sort, the float-share fallback, and the tuple
            # referee (_entry_sort_key) order identically.
            for e in tick.entries:
                e.share = share_of(e.info.cluster_queue)
        hov = tick.handle.get("hetero_overrides") \
            if tick.handle is not None else None
        if hov is not None:
            # Hetero solve mode: annotate the entries whose chosen flavor
            # beat the first-fit twin, so the explain records (and the
            # span) answer "why flavor B" — present only when a hetero
            # solve actually dispatched, so the default mode's trace is
            # untouched.
            with TRACER.phase("nominate.hetero") as hsp:
                if hov:
                    row_to_entry: Dict[int, int] = {}
                    if solve_rows is None:
                        for i in range(len(entries)):
                            row_to_entry[i] = i
                    else:
                        for i, r in enumerate(solve_rows):
                            if r >= 0:
                                row_to_entry[int(r)] = i
                    for row, info in hov.items():
                        i = row_to_entry.get(row)
                        if i is not None:
                            entries[i].hetero = info
                hsp.set("overrides", len(hov))
                hsp.set("version", getattr(self.batch_solver,
                                           "hetero_version", 0))
        if partial_pending:
            self._batch_partial_admission(partial_pending, snapshot)

    def _fair_ctx(self, snapshot: Snapshot):
        """The solver's vectorized fair-preemption context for this
        snapshot (ops/fair_preempt), or None — fair sharing off, no
        batch solver, stale encoding, or the device-fair kill switch;
        get_targets then runs the host fair referee."""
        if not features.enabled(features.FAIR_SHARING) \
                or self.batch_solver is None:
            return None
        fn = getattr(self.batch_solver, "fair_preempt_context", None)
        return fn(snapshot) if fn is not None else None

    def _get_assignment(self, wi: WorkloadInfo, snap: Snapshot,
                        precomputed: Optional[Assignment],
                        precomputed_targets: Optional[List[WorkloadInfo]] = None,
                        allow_partial: bool = True):
        """scheduler.go getAssignments (:390-429). With `allow_partial`
        False the caller runs partial admission itself (the batched
        device rounds of _batch_partial_admission)."""
        cq = snap.cluster_queues[wi.cluster_queue]
        full = precomputed if precomputed is not None else \
            assign_flavors(wi, cq, snap.resource_flavors,
                           topology=self._topology_pair(snap))
        mode = full.representative_mode
        if mode == FIT:
            return full, []
        targets: List[WorkloadInfo] = []
        if mode == PREEMPT:
            targets = precomputed_targets if precomputed_targets is not None \
                else preemption_mod.get_targets(
                    wi, full, snap, self.ordering, self.clock(),
                    fair_strategies=self.fair_strategies,
                    engine=self.preemption_engine,
                    fair_ctx=self._fair_ctx(snap))
        if not allow_partial \
                or not features.enabled(features.PARTIAL_ADMISSION) or targets:
            return full, targets
        if wi.obj.can_be_partially_admitted():
            def fits(counts):
                assignment = assign_flavors(
                    wi, cq, snap.resource_flavors, counts,
                    topology=self._topology_pair(snap))
                if assignment.representative_mode == FIT:
                    return (assignment, []), True
                t = preemption_mod.get_targets(
                    wi, assignment, snap, self.ordering, self.clock(),
                    fair_strategies=self.fair_strategies,
                    engine=self.preemption_engine,
                    fair_ctx=self._fair_ctx(snap))
                if t:
                    return (assignment, t), True
                return None, False

            result, found = podset_reducer.search(wi.obj.pod_sets, fits)
            if found:
                return result
        return full, []

    def _batched_targets(self, pairs, snapshot: Snapshot,
                         ) -> Dict[int, List[WorkloadInfo]]:
        """Victim search for PREEMPT-mode (key, info, assignment) pairs in
        one batched engine call when the configured engine supports it,
        else one per-entry host/engine search each. Returns {key: targets}
        for every pair."""
        if not pairs:
            return {}
        ctx_usage = None
        if self.preemption_engine in ("native", "jax", "pallas"):
            ctx_fn = getattr(self.batch_solver, "preemption_context", None)
            ctx_usage = ctx_fn(snapshot) if ctx_fn is not None else None
        if ctx_usage is not None:
            targets_list = preemption_mod.get_targets_batch(
                [(wi, a) for _, wi, a in pairs],
                snapshot, self.ordering, self.clock(),
                self.fair_strategies, *ctx_usage,
                backend=self.preemption_engine,
                fair_ctx=self._fair_ctx(snapshot))
            return {key: t for (key, _, _), t in zip(pairs, targets_list)}
        fair_ctx = self._fair_ctx(snapshot)
        return {key: preemption_mod.get_targets(
                    wi, a, snapshot, self.ordering, self.clock(),
                    fair_strategies=self.fair_strategies,
                    engine=self.preemption_engine, fair_ctx=fair_ctx)
                for key, wi, a in pairs}

    def _batch_partial_admission(self, entries: List[Entry],
                                 snapshot: Snapshot) -> None:
        """Partial admission in batch mode: every searching workload's
        binary search (podset_reducer.SearchState — the same stepper the
        sequential reducer runs) advances in LOCKSTEP rounds, each round
        solving all active probes as ONE batched device dispatch instead
        of one referee run per probe per workload (podset_reducer.go:86
        via scheduler.go:410-427). Preemption probes batch through the
        same victim-search engine as the main path."""
        searches: List[tuple] = []
        for e in entries:
            state = podset_reducer.SearchState(e.info.obj.pod_sets)
            if state.searchable():
                searches.append((e, state))

        while True:
            active = [(e, s) for e, s in searches if s.active()]
            if not active:
                break
            probes = [s.probe() for _, s in active]
            assignments = self.batch_solver.solve_with_counts(
                [e.info for e, _ in active], snapshot, probes)
            topo_stage = self._topology_stage(snapshot)
            if topo_stage is not None:
                topo_stage.apply([e.info for e, _ in active], assignments,
                                 snapshot.topology, use_device=True)
            # Non-Fit probes need victim sets to count as fitting — the
            # reducer's fits() tries preemption on ANY non-Fit probe
            # (even a NoFit-representative truncated assignment can carry
            # Preempt podsets whose victims free enough quota).
            targets_by_idx = self._batched_targets(
                [(i, active[i][0].info, a) for i, a in enumerate(assignments)
                 if a.representative_mode != FIT], snapshot)
            for i, (e, s) in enumerate(active):
                a = assignments[i]
                targets = targets_by_idx.get(i, [])
                ok = a.representative_mode == FIT or bool(targets)
                s.advance((a, targets) if ok else None, ok)

        for e, s in searches:
            result, found = s.result()
            if found and result is not None:
                assignment, targets = result
                e.assignment = assignment
                e.preemption_targets = targets
                e.inadmissible_msg = assignment.message()
        # The deferred resume-state update (the sequential path applies it
        # after the reducer returns, whether or not a reduction was found).
        for e in entries:
            e.info.last_assignment = e.assignment.last_state

    # -- ordering (scheduler.go:564-588) ------------------------------------

    def _entry_sort_key(self, e: Entry):
        borrows = e.assignment.borrowing if e.assignment is not None else False
        key = [borrows]
        if features.enabled(features.FAIR_SHARING):
            # Lowest current share admits first (KEP-1714).
            key.append(e.share)
        if features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT):
            key.append(-e.info.obj.priority)
        key.append(self.ordering.queue_order_time(e.info.obj))
        return tuple(key)

    @staticmethod
    def _hier_fits(state, cq: CachedClusterQueue, assignment,
                   cycle_usage: Dict[str, FlavorResourceQuantities]) -> bool:
        """Hierarchical cycle gate through the dense state; falls back to
        the dict walk (the dicts fold every reservation the state folds,
        so both give the same verdict) for coordinates outside the
        encoding."""
        ci = state.enc.cq_index.get(cq.name)
        if ci is not None:
            idx = assignment.usage_idx
            if idx is not None:
                return state.fits(ci, list(zip(*idx)))
            try:
                return state.fits(ci, state.coords(assignment.usage))
            except KeyError:
                pass
        return fits_in_hierarchy(cq, assignment.usage, extra=cycle_usage)

    def _sort_entries(self, entries: List[Entry]) -> None:
        """entryOrdering sort. Large ticks go through a stable lexsort over
        per-component key arrays — same ordering as sorting on
        `_entry_sort_key` tuples (both sorts are stable, components are
        compared in the same significance order), without a thousand tuple
        allocations and log-depth tuple comparisons on the hot path.

        The queue-order timestamps come from the memoized
        `queue_order_time` (they only move on Evicted transitions), and
        the adjacent integer components — borrowing (most significant),
        the fair-share RANK (the share kernel's dense order-preserving
        quantization of the weighted share, when FairSharing is on and
        the solver's share state covers every entry), and negated
        priority — are PACKED into one int64 key (borrow in bit 62,
        rank in bits 34..61, priority far below 2^33), so BOTH configs
        sort with two argsort passes instead of four `np.fromiter`
        generator walks plus three passes."""
        n = len(entries)
        if n < 64:
            entries.sort(key=self._entry_sort_key)
            return
        import numpy as np
        qot = self.ordering.queue_order_time
        # np.lexsort keys run least-significant first.
        keys = [np.array([qot(e.info.obj) for e in entries],
                         dtype=np.float64)]
        prio_on = features.enabled(features.PRIORITY_SORTING_WITHIN_COHORT)
        fair = features.enabled(features.FAIR_SHARING)
        borrow = np.array(
            [e.assignment is not None and e.assignment.borrowing
             for e in entries], dtype=np.int64)
        ranks = self._fair_ranks(entries) if fair else None
        if fair and ranks is None:
            # No share state covering every entry (kill switch / stale
            # encoding / out-of-encoding CQ): the float share stays its
            # own lexsort key between priority and borrowing.
            if prio_on:
                keys.append(np.array([-e.info.obj.priority for e in entries],
                                     dtype=np.int64))
            keys.append(np.array([e.share for e in entries],
                                 dtype=np.float64))
            keys.append(borrow)
        else:
            packed = borrow << 62
            if ranks is not None:
                # Dense ranks order exactly as the float shares (equal
                # shares share a rank), so the packed key sorts
                # identically to the separate share component.
                packed += ranks << 34
            if prio_on:
                packed += np.array([-e.info.obj.priority for e in entries],
                                   dtype=np.int64)
            keys.append(packed)
        order = np.lexsort(keys)
        entries[:] = [entries[i] for i in order.tolist()]

    def _fair_ranks(self, entries: List[Entry]):
        """[n] int64 share ranks for the packed fair sort key, or None
        when the tick's share state does not cover every entry's
        ClusterQueue (the caller falls back to float-share lexsort)."""
        st = self._tick_fair_state
        if st is None:
            return None
        import numpy as np
        cq_index = st.enc.cq_index
        rank = st.rank
        memo: Dict[str, int] = {}
        out = np.empty(len(entries), dtype=np.int64)
        for i, e in enumerate(entries):
            name = e.info.cluster_queue
            r = memo.get(name)
            if r is None:
                ci = cq_index.get(name)
                if ci is None:
                    return None
                r = memo[name] = int(rank[ci])
            out[i] = r
        return out

    # -- admission cycle (scheduler.go:204-275) ------------------------------

    def _admission_cycle(self, entries: List[Entry], snapshot: Snapshot,
                         revalidate: bool = False,
                         usage_csr=None, micro: bool = False) -> int:
        cycle_cohorts_usage: Dict[str, FlavorResourceQuantities] = {}
        # Root-merged view of the same reservations: the preempt skip gate
        # compares against the whole tree's cycle usage (for flat cohorts
        # node == root and the two dicts coincide).
        cycle_root_usage: Dict[str, FlavorResourceQuantities] = {}
        cycle_cohorts_skip_preemption: Set[str] = set()
        # Hoisted once per cycle for the fused cohort gate (the per-pair
        # helpers each re-read the gate otherwise).
        lending = features.enabled(features.LENDING_LIMIT)
        # Hierarchical-cohort cycle bookkeeping on the solver's dense
        # tensors (ops/hier_cycle): O(depth) per entry instead of a
        # full-subtree dict walk per entry. Lazily created on the first
        # hierarchical entry; None falls back to fits_in_hierarchy.
        hier_box: List = [None, False]   # [state, tried]

        def ensure_hier_state():
            if not hier_box[1]:
                hier_box[1] = True
                fn = getattr(self.batch_solver, "hier_cycle_state", None)
                if fn is not None:
                    hier_box[0] = fn(snapshot)
            return hier_box[0]

        # While the dense tree state is alive, hierarchical reservations
        # defer their dict bookkeeping to a flat log — the dicts are only
        # read by fallback paths (state death, out-of-encoding gates, the
        # preempt common-resource check), so the common all-FIT cycle
        # skips ~2 dict walks per admission. Materialization replays the
        # log once and switches back to eager mode; flat cohorts (disjoint
        # key space) stay eager throughout.
        hier_lazy = [True]
        hier_fold_log: List[tuple] = []

        def materialize_cycle_dicts():
            if hier_lazy[0]:
                hier_lazy[0] = False
                for node_name, root_n, reserve_ in hier_fold_log:
                    frq_add(cycle_cohorts_usage.setdefault(node_name, {}),
                            reserve_)
                    frq_add(cycle_root_usage.setdefault(root_n, {}),
                            reserve_)
                hier_fold_log.clear()
        preempting: List = []
        pending_assumes: List = []
        # Topology admission bookkeeping: the cycle's own leaf-occupancy
        # copy (built from the LIVE ledger, so pipelined staleness is
        # covered), charged per admission so two admissions in one cycle
        # cannot pack into the same free slots.
        topo_stage = self._topology_stage(snapshot)
        topo_cycle = None
        # Deferred victim searches, pre-batched for the entries most likely
        # to reach the issue branch — the first TWO PREEMPT entries per
        # cohort root (and every cohortless one) in cycle order: a FIT
        # admission earlier in the root often blocks the first preempting
        # entry on common resources, letting the next root-mate reach the
        # branch. The snapshot is frozen for the whole cycle, so
        # pre-computing is decision-identical to computing at the branch;
        # deeper stragglers still fall back to the lazy per-entry search.
        per_root_count: Dict[str, int] = {}
        prebatch: List[Entry] = []
        for e in entries:
            if e.assignment is None or e.preemption_targets is not None \
                    or e.assignment.representative_mode != PREEMPT:
                continue
            cq = snapshot.cluster_queues.get(e.info.cluster_queue)
            if cq is None:
                continue
            if cq.cohort is None:
                prebatch.append(e)
            else:
                root = cq.cohort.root_name
                seen = per_root_count.get(root, 0)
                if seen < 2:
                    per_root_count[root] = seen + 1
                    prebatch.append(e)
        if prebatch:
            pre_targets = self._batched_targets(
                [(id(e), e.info, e.assignment) for e in prebatch], snapshot)
            for e in prebatch:
                e.preemption_targets = pre_targets.get(id(e))
        # Batched staleness re-validation: one vectorized pass over all
        # in-doubt FIT entries against the solver's lockstep usage tensor
        # (falls back to the per-entry referee walk when unavailable).
        if revalidate and self.batch_solver is not None:
            with TRACER.phase("admit.reval"):
                fit_entries = [
                    e for e in entries
                    if e.assignment is not None
                    and e.assignment.representative_mode == FIT]
                if fit_entries:
                    reval = getattr(self.batch_solver, "revalidate_fits", None)
                    coords = None
                    if usage_csr is not None and all(
                            e.solve_row >= 0 for e in fit_entries):
                        # Every in-doubt FIT came from this solve: gather
                        # their usage coordinates from the decode's CSR in
                        # one vectorized slice concat — no per-entry walk.
                        from kueue_tpu.solver.schema import csr_gather
                        import numpy as np
                        coords = csr_gather(usage_csr, np.fromiter(
                            (e.solve_row for e in fit_entries), np.int64,
                            count=len(fit_entries)))
                    # Build the tree state once; the revalidation uses it
                    # fold-free and the admission loop below reuses it.
                    mask = reval([(e.info.cluster_queue, e.assignment)
                                  for e in fit_entries], snapshot=snapshot,
                                 hier_state=ensure_hier_state(),
                                 coords=coords) \
                        if reval is not None else None
                    if mask is not None:
                        for e, ok in zip(fit_entries, mask):
                            e.reval_ok = bool(ok)
        # Two-phase (cohort-sharded) cycle: entries whose cohort root
        # spans shards (hierarchical trees split by the cohort hash) are
        # DEFERRED to the reconcile pass — phase A never folds or gates
        # them, so its bookkeeping is exactly the per-shard-local state a
        # sharded deployment would hold, and phase B replays the deferred
        # entries in original cycle order against the exact merged state
        # (revoking what the optimistic per-shard view over-admitted).
        # Cohort-disjointness makes this decision-identical: a deferred
        # entry's quota math only reads its own (deferred) root's state.
        sv = None
        if self.batch_solver is not None:
            sv_fn = getattr(self.batch_solver, "shard_view", None)
            if sv_fn is not None:
                sv = sv_fn(snapshot)
        split_roots = sv[0].split_roots if sv is not None else None
        deferred: List = []
        # Cross-REPLICA deferral (multi-process mode): roots whose member
        # ClusterQueues live on other replica processes. Checked before
        # the mesh deferral — a root that is both replica-split and
        # device-shard-split belongs to the commit protocol (the local
        # reconcile cannot see the remote members at all).
        rctx = self.replica_ctx
        replica_roots = rctx.split_roots if rctx is not None else None
        deferred_replica: List = []
        self._cycle_replica_candidates = 0

        def _cycle_one(e: Entry, cq: CachedClusterQueue, mode: int) -> None:
            nonlocal topo_cycle
            if cq.cohort is not None:
                # Cycle bookkeeping: this cycle's reservations are not in
                # the snapshot yet, so track them on the side and re-check
                # fit against them (scheduler.go:204-275 cohortsUsage).
                # For hierarchical trees (KEP-79) usage is recorded at the
                # admitting CQ's own cohort node and charged through the
                # tree's lending clamps, so an admission in one subtree
                # only defers siblings where a shared ancestor's capacity
                # is genuinely consumed — not root-wide. The skip guard
                # keys on the root (root() is self when flat).
                hier = cq.cohort.is_hierarchical()
                root_name = cq.cohort.root_name
                # A pending preemption invalidates later preemption
                # calculations only where this cycle actually reserved
                # common flavor-resources (scheduler.go:218-222).
                blocked = False
                if mode == PREEMPT \
                        and root_name in cycle_cohorts_skip_preemption:
                    if hier:
                        materialize_cycle_dicts()
                    blocked = _has_common_flavor_resources(
                        cycle_root_usage.get(root_name),
                        e.assignment.usage)
                fused_folded = False
                if not blocked and mode == FIT:
                    if hier:
                        hier_state = ensure_hier_state()
                        if hier_state is not None:
                            idx = e.assignment.usage_idx
                            ci = hier_state.enc.cq_index.get(cq.name)
                            if idx is not None and ci is not None:
                                # Fused gate+reserve: ONE native ancestor
                                # walk checks feasibility and, only when
                                # it passes, charges the reservation —
                                # the FIT entry's whole tree interaction.
                                blocked = not hier_state.gate_fold(
                                    ci, idx[0], idx[1], idx[2],
                                    do_gate=bool(hier_state.folds),
                                    do_fold=True)
                                fused_folded = not blocked
                            elif hier_state.folds:
                                materialize_cycle_dicts()
                                blocked = not self._hier_fits(
                                    hier_state, cq, e.assignment,
                                    cycle_cohorts_usage)
                        elif cycle_cohorts_usage and not fits_in_hierarchy(
                                cq, e.assignment.usage,
                                extra=cycle_cohorts_usage):
                            blocked = True
                    else:
                        node = cycle_cohorts_usage.get(root_name)
                        if node:
                            # Fused common-pair + capacity walk — same
                            # verdict as _has_common_flavor_resources +
                            # _common_usage_sum + fit_in_cohort in one
                            # pass over the assignment's pairs.
                            common, ok = cq.fit_in_cohort_fused(
                                node, e.assignment.usage, lending)
                            blocked = common and not ok
                if blocked:
                    e.status = SKIPPED
                    e.inadmissible_msg = \
                        "other workloads in the cohort were prioritized"
                    # Do not skip flavors on the retry (scheduler.go:225-229).
                    e.info.last_assignment = None
                    self.metrics.skipped += 1
                    return
                reserve = e.assignment.usage if mode != PREEMPT \
                    else _resources_to_reserve(e, cq)
                if hier:
                    # The first hierarchical entry may be a fold (not a
                    # FIT gate): the state must exist before the fold or
                    # later gates would miss this reservation.
                    hier_state = ensure_hier_state()
                    folded = fused_folded and hier_state is not None
                    if hier_state is not None and not folded:
                        ci = hier_state.enc.cq_index.get(cq.name)
                        idx = e.assignment.usage_idx \
                            if reserve is e.assignment.usage else None
                        try:
                            if ci is None:
                                coords = None
                            elif idx is not None:
                                # Non-preempting reserve == the assignment
                                # usage: reuse its decoded integer
                                # coordinates, no name->index dict walk.
                                coords = list(zip(*idx))
                            else:
                                coords = hier_state.coords(reserve)
                        except KeyError:
                            coords = None
                        if coords is None:
                            # Unknown CQ/flavor/resource: the dicts below
                            # hold every reservation, so the dict walk
                            # takes over for the rest of the cycle.
                            hier_box[0] = None
                            materialize_cycle_dicts()
                        else:
                            hier_state.fold(ci, coords)
                            folded = True
                    if folded and hier_lazy[0]:
                        hier_fold_log.append(
                            (cq.cohort.name, root_name, reserve))
                    else:
                        frq_add(cycle_cohorts_usage.setdefault(
                            cq.cohort.name, {}), reserve)
                        frq_add(cycle_root_usage.setdefault(root_name, {}),
                                reserve)
                else:
                    # Flat cohort: node == root; share ONE dict so the
                    # reservation folds once and both views read it.
                    node = cycle_cohorts_usage.get(root_name)
                    if node is None:
                        node = cycle_cohorts_usage[root_name] = {}
                        cycle_root_usage[root_name] = node
                    frq_add(node, reserve)
            if mode == FIT and self.pods_ready_gate is not None \
                    and not self.pods_ready_gate():
                # Admission blocked until all admitted workloads are ready
                # (scheduler.go:256-266). Preemptions still proceed while
                # blocked, matching the reference's loop order (the preempt
                # branch above runs before the PodsReady wait).
                e.status = SKIPPED
                e.inadmissible_msg = ("Waiting for all admitted workloads to "
                                      "be in the PodsReady condition")
                return
            if mode != FIT:
                if e.preemption_targets is None:
                    # Deferred victim search (see Entry.preemption_targets):
                    # runs only for the one entry per cohort root that
                    # reaches this branch. The evictions themselves apply
                    # AFTER the cycle (see below), so a deferred search
                    # sees exactly the pre-cycle eviction state an eager
                    # (reference-timed, pre-cycle) search saw.
                    e.preemption_targets = preemption_mod.get_targets(
                        e.info, e.assignment, snapshot, self.ordering,
                        self.clock(), fair_strategies=self.fair_strategies,
                        engine=self.preemption_engine,
                        fair_ctx=self._fair_ctx(snapshot))
                if e.preemption_targets:
                    # Next attempt should try all flavors (scheduler.go:240).
                    e.info.last_assignment = None
                    preempting.append((e, cq))
                    count = len(e.preemption_targets)
                    self.metrics.preempted += count
                    e.inadmissible_msg += \
                        f". Pending the preemption of {count} workload(s)"
                    e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                    if cq.cohort is not None:
                        cycle_cohorts_skip_preemption.add(cq.cohort.root_name)
                return
            topo_assignments = None
            if topo_stage is not None \
                    and getattr(e.assignment, "topology", None):
                if topo_cycle is None:
                    from kueue_tpu.topology import TopologyCycle
                    topo_cycle = TopologyCycle(self.cache.topology)
                topo_assignments, ok = self._charge_topology(
                    topo_stage, topo_cycle, e.assignment)
                if not ok:
                    # A domain that fit at solve time was consumed (by an
                    # earlier admission this cycle, or — pipelined — by a
                    # tick that finished since dispatch). Never place a
                    # required podset across domains: requeue and re-solve
                    # against fresh occupancy next tick.
                    e.status = SKIPPED
                    e.inadmissible_msg = ("topology domain no longer fits; "
                                          "other workloads were prioritized")
                    e.info.last_assignment = None
                    self.metrics.skipped += 1
                    return
            e.status = NOMINATED
            self._admit(e, cq, pending_assumes,
                        topo_assignments=topo_assignments)
            if cq.cohort is not None:
                cycle_cohorts_skip_preemption.add(cq.cohort.root_name)

        def _commit_replica(e: Entry, cq: CachedClusterQueue,
                            mode: int) -> None:
            """Apply a coordinator-COMMITTED verdict: _cycle_one without
            the local cohort gating/bookkeeping — the merged-tree gate
            already ran (and folded) at the coordinator, in global cycle
            order, before any replica flushed."""
            nonlocal topo_cycle
            if mode != FIT:
                if e.preemption_targets:
                    e.info.last_assignment = None
                    preempting.append((e, cq))
                    count = len(e.preemption_targets)
                    self.metrics.preempted += count
                    e.inadmissible_msg += \
                        f". Pending the preemption of {count} workload(s)"
                    e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                return
            if self.pods_ready_gate is not None \
                    and not self.pods_ready_gate():
                e.status = SKIPPED
                e.inadmissible_msg = (
                    "Waiting for all admitted workloads to be in the "
                    "PodsReady condition")
                return
            topo_assignments = None
            if topo_stage is not None \
                    and getattr(e.assignment, "topology", None):
                if topo_cycle is None:
                    from kueue_tpu.topology import TopologyCycle
                    topo_cycle = TopologyCycle(self.cache.topology)
                topo_assignments, ok = self._charge_topology(
                    topo_stage, topo_cycle, e.assignment)
                if not ok:
                    e.status = SKIPPED
                    e.inadmissible_msg = (
                        "topology domain no longer fits; other workloads "
                        "were prioritized")
                    e.info.last_assignment = None
                    self.metrics.skipped += 1
                    return
            e.status = NOMINATED
            self._admit(e, cq, pending_assumes,
                        topo_assignments=topo_assignments)

        # -- phase A: the optimistic pass -------------------------------
        for pos, e in enumerate(entries):
            e.cycle_pos = pos
            if e.assignment is None:
                continue
            mode = e.assignment.representative_mode
            if mode == NO_FIT:
                continue
            cq = snapshot.cluster_queues[e.info.cluster_queue]
            if revalidate and mode == FIT:
                verdict = e.reval_ok
                if verdict is None:
                    verdict = _assignment_still_fits(e.assignment, cq)
                if not verdict:
                    # Pipelined staleness: the solve ran against usage from
                    # dispatch time and another in-flight tick's admissions
                    # landed since. Never overadmit — requeue and re-solve
                    # with fresh usage next tick (optimistic concurrency, the
                    # assume/forget discipline of cache.go:498-546 applied to
                    # the solve itself).
                    e.status = SKIPPED
                    e.inadmissible_msg = ("admission solve became stale; "
                                          "re-solving with fresh usage")
                    e.info.last_assignment = None
                    self.metrics.skipped += 1
                    continue
            if replica_roots and cq.cohort is not None \
                    and cq.cohort.root_name in replica_roots:
                deferred_replica.append((e, cq, mode))
                continue
            if split_roots and cq.cohort is not None \
                    and cq.cohort.root_name in split_roots:
                deferred.append((e, cq, mode))
                continue
            _cycle_one(e, cq, mode)

        # -- phase B: cross-replica commit protocol ---------------------
        if rctx is not None and not micro:
            # Micro-ticks NEVER ship a reconcile round: their
            # eligibility gate keeps replica-split roots out (so
            # deferred_replica is empty by construction), and the
            # coordinator barrier counts exactly one round per replica
            # per FULL tick — an extra mid-window round would desync it.
            self._cycle_replica_candidates = len(deferred_replica)
            self._replica_reconcile(deferred_replica, snapshot,
                                    _commit_replica)
        # -- phase B: cross-shard borrow reconciliation -----------------
        if deferred:
            self._reconcile_deferred(deferred, sv, snapshot, _cycle_one)
        if deferred or deferred_replica:
            # Deferred entries re-merge into the commit sequences at
            # their original cycle position.
            pending_assumes.sort(key=lambda item: item[0].cycle_pos)
            preempting.sort(key=lambda item: item[0].cycle_pos)
        with TRACER.phase("tick.stage.flush"):
            with TRACER.phase("admit.flush"):
                admitted = self._flush_assumes(pending_assumes, snapshot,
                                               usage_csr=usage_csr)
            for e, cq in preempting:
                self._issue_preemptions(e, cq)
        return admitted

    def _reconcile_deferred(self, deferred, sv, snapshot: Snapshot,
                            cycle_one) -> int:
        """Phase B of the two-phase (cohort-sharded) admission cycle.

        Replays the entries of shard-SPLIT cohort roots in original
        decision order against the exact merged cycle state (`cycle_one`
        — the same gating/fold/admit logic phase A ran for everyone
        else), while a per-shard optimistic twin state records what each
        shard would have admitted seeing only its own folds. The delta —
        optimistic pass, exact fail — is a revocation: the admission a
        shard-local cycle would have committed and the global
        lending-clamp pass takes back (Aryl's cluster-level loaning
        reconcile, mapped onto KEP-79 trees)."""
        assignment, cq_index = sv
        state_fn = getattr(self.batch_solver, "hier_cycle_state",
                           lambda s: None)
        opt_states: Dict[int, object] = {}
        revoked = 0
        with TRACER.phase("admit.reconcile") as rsp:
            for e, cq, mode in deferred:
                opt_ok = None
                if mode == FIT:
                    ci = cq_index.get(cq.name)
                    idx = e.assignment.usage_idx \
                        if e.assignment is not None else None
                    if ci is not None and idx is not None:
                        shard = int(assignment.shard_of_cq[ci])
                        st = opt_states.get(shard)
                        if st is None:
                            st = state_fn(snapshot)
                            opt_states[shard] = st
                        if st is not None:
                            # The shard-local optimistic gate+fold: sees
                            # only this shard's earlier reservations.
                            opt_ok = st.gate_fold(
                                ci, idx[0], idx[1], idx[2],
                                do_gate=bool(st.folds), do_fold=True)
                cycle_one(e, cq, mode)
                if opt_ok and e.status == SKIPPED \
                        and e.inadmissible_msg.startswith(
                            "other workloads in the cohort"):
                    revoked += 1
            rsp.set("deferred", len(deferred))
            rsp.set("revoked", revoked)
        self.metrics.reconcile_revocations += revoked
        return revoked

    def _replica_reconcile(self, deferred, snapshot: Snapshot,
                           commit) -> None:
        """Phase B across PROCESSES (parallel/replica.py): ship this
        replica's split-root candidates (usage triples, packed sort key,
        cycle position) plus its local members' pre-cycle usage to the
        lease-holding coordinator, which replays every replica's
        candidates in global cycle order against the merged lending-clamp
        state and answers commit/revoke per entry — the in-process
        `_reconcile_deferred` promoted to a real commit protocol (Aryl's
        optimistic-local-pass / global-revoke loaning loop between
        scheduler replicas). Always submits, even with zero candidates:
        the coordinator barrier orders the round, and this replica's
        shipped usage feeds the OTHER replicas' gating."""
        rctx = self.replica_ctx
        # Victim searches for deferred PREEMPT entries run against the
        # frozen snapshot BEFORE submission (pre-computing is decision-
        # identical — the prebatch argument), because the coordinator's
        # skip-preemption bookkeeping needs to know whether each
        # preempting candidate actually found victims. Candidates are
        # subtree-local: a split root's victims never cross processes.
        need = [(id(e), e.info, e.assignment) for e, _cq, m in deferred
                if m == PREEMPT and e.preemption_targets is None]
        if need:
            got = self._batched_targets(need, snapshot)
            for e, _cq, m in deferred:
                if m == PREEMPT and e.preemption_targets is None:
                    e.preemption_targets = got.get(id(e), [])
        opt_usage: Dict[str, FlavorResourceQuantities] = {}
        cands: List[dict] = []
        for e, cq, mode in deferred:
            usage = e.assignment.usage
            opt_ok = False
            if mode == FIT:
                # The shard-local optimistic twin: this replica's subtree
                # view only (the per-shard HierCycleState analog of
                # _reconcile_deferred) — optimistic pass + coordinator
                # revoke is exactly one counted revocation.
                opt_ok = fits_in_hierarchy(cq, usage, extra=opt_usage)
                if opt_ok:
                    frq_add(opt_usage.setdefault(cq.cohort.name, {}),
                            usage)
            cands.append({
                "i": len(cands), "key": e.info.key, "cq": cq.name,
                "mode": mode, "usage": usage,
                "borrow": bool(e.assignment.borrowing),
                "sort": list(self._entry_sort_key(e)),
                "pos": e.cycle_pos,
                "has_targets": bool(e.preemption_targets),
                "opt_ok": opt_ok,
            })
        with TRACER.phase("admit.reconcile.rtt") as sp:
            usage = self._replica_usage(snapshot) if rctx.ship_usage else {}
            verdicts = rctx.reconcile(cands, usage)
            sp.set("deferred", len(deferred))
            sp.set("round", rctx.rounds)
        revoked = 0
        # Degraded safe mode parks split-root entries with an explain
        # reason that says so (the coordinator's merged arithmetic is
        # unavailable, not lost to a priority race) and counts no
        # revocations — nothing was arbitrated.
        parked = bool(getattr(rctx, "degraded", False))
        deny_msg = ("parked: degraded mode (coordinator unreachable); "
                    "split-root admission awaits the rejoin reconcile"
                    if parked else
                    "other workloads in the cohort were prioritized")
        for (e, cq, mode), cand, ok in zip(deferred, cands, verdicts):
            if ok:
                commit(e, cq, mode)
            else:
                e.status = SKIPPED
                e.inadmissible_msg = deny_msg
                e.info.last_assignment = None
                self.metrics.skipped += 1
                if cand["opt_ok"] and not parked:
                    revoked += 1
        self.metrics.reconcile_revocations += revoked

    def _replica_usage(self, snapshot: Snapshot) -> Dict[str, dict]:
        """This replica's split-root members' PRE-CYCLE usage (snapshot
        copies, flavor -> resource -> value). The coordinator reassembles
        the merged lending-clamp state from every replica's shipped view
        each round, so it never holds usage a live replica did not just
        vouch for (and a coordinator restart loses nothing)."""
        rctx = self.replica_ctx
        key = (snapshot.structure_version, rctx.split_roots)
        memo = self._replica_member_memo
        if memo is None or memo[0] != key:
            names = [
                cq.name for cq in snapshot.cluster_queues.values()
                if cq.cohort is not None
                and cq.cohort.root_name in rctx.split_roots]
            memo = self._replica_member_memo = (key, names)
        cqs = snapshot.cluster_queues
        return {
            name: {f: dict(res) for f, res in cqs[name].usage.items()}
            for name in memo[1] if name in cqs}

    @staticmethod
    def _charge_topology(stage, topo_cycle, assignment):
        """Re-validate and charge every topology candidate of a FIT entry
        against the cycle occupancy. All-or-nothing: a failing podset
        rolls back the earlier podsets' charges (flavor arrays are tiny,
        so a per-entry backup of the touched flavors is cheap). Returns
        (per-podset TopologyAssignment list, ok)."""
        cands = assignment.topology
        touched = {c.flavor for c in cands if c is not None}
        backup = {f: topo_cycle.used[f].copy()
                  for f in touched if f in topo_cycle.used}
        created = touched - set(backup)
        out = []
        for p, psa in enumerate(assignment.pod_sets):
            cand = cands[p] if p < len(cands) else None
            if cand is None:
                out.append(None)
                continue
            ta, ok = stage.charge(topo_cycle.used, cand, psa.name)
            if not ok:
                for f, arr in backup.items():
                    topo_cycle.used[f] = arr
                for f in created:
                    topo_cycle.used.pop(f, None)
                return None, False
            out.append(ta)
        return out, True

    def _issue_preemptions(self, e: Entry, cq: CachedClusterQueue) -> None:
        """IssuePreemptions (preemption.go:129-156): evictions applied with
        bounded fan-out — the apply callback may cross a network boundary.
        Runs after the admission cycle so deferred victim searches never
        observe this cycle's own evictions (the reference picks every
        target before its cycle starts)."""
        targets = [t for t in e.preemption_targets if not t.obj.is_evicted]

        def evict(target: WorkloadInfo) -> None:
            origin = "ClusterQueue" if cq.name == target.cluster_queue \
                else "cohort"
            self.apply_preemption(
                target.obj,
                f"Preempted to accommodate a higher priority Workload ({origin})")

        err = parallelize.for_each(targets, evict)
        if err is not None:
            raise err

    def _admit(self, e: Entry, cq: CachedClusterQueue, pending: list,
               topo_assignments: Optional[list] = None) -> bool:
        """scheduler.go admit (:493-541), split for the batched commit:
        the per-entry phase reserves on the workload object (admission +
        conditions) and runs the apply callback; the cache/mirror/solver
        accounting is deferred to ONE bulk commit at cycle end
        (_flush_assumes) — sound because nothing in-cycle reads the cache
        (fit math runs on the frozen snapshot plus cycle_cohorts_usage)."""
        wl = e.info.obj
        psas = []
        # Plant the admission usage flattening only when it matches what
        # WorkloadInfo._compute_totals would derive: no reclaim scaling
        # AND no partial-admission count reduction (the cache accounts
        # SPEC-count totals scaled back up, workload.go:230-234 — the
        # reduced assignment usage would under-count held quota). The
        # single-podset common case compares counts directly instead of
        # building a name map.
        spec_sets = wl.pod_sets
        single = len(spec_sets) == 1
        spec_counts = None if single else {ps.name: ps.count
                                           for ps in spec_sets}
        triples: Optional[list] = [] if not wl.reclaimable_pods else None
        for pi, ps in enumerate(e.assignment.pod_sets):
            flavors = {r: fa.name for r, fa in ps.flavors.items()}
            # ps.requests is freshly built per solve and never mutated
            # after decode — alias it instead of copying (readers that
            # need a private dict copy on their side, workload.py:194).
            requests = ps.requests
            psas.append(PodSetAssignment(
                name=ps.name, flavors=flavors,
                resource_usage=requests, count=ps.count,
                topology_assignment=(topo_assignments[pi]
                                     if topo_assignments is not None
                                     and pi < len(topo_assignments)
                                     else None)))
            if triples is not None:
                spec_count = spec_sets[0].count if single \
                    else spec_counts.get(ps.name, ps.count)
                if ps.count != spec_count:
                    triples = None
                    continue
                for r, q in requests.items():
                    flv = flavors.get(r)
                    if flv is not None:
                        triples.append((flv, r, q))
        admission = Admission(cluster_queue=e.info.cluster_queue,
                              pod_set_assignments=psas)
        # One condition-map read covers every lookup below; in-place
        # Condition updates keep it valid, appends invalidate it by length
        # (set_condition semantics, unrolled — this runs per admission).
        cmap = wl._cond_map()
        # Wait time runs from creation, or from the eviction being recovered
        # from (scheduler.go:516-520); capture before clearing Evicted.
        wait_started = wl.creation_time
        evicted_cond = cmap.get("Evicted")
        was_evicted = evicted_cond is not None and evicted_cond.status
        if was_evicted:
            wait_started = evicted_cond.last_transition_time
        wl.admission = admission
        now = self.clock()
        _set_condition_via(cmap, wl, "QuotaReserved", True, "QuotaReserved",
                           now)
        if was_evicted:
            # A readmitted workload is no longer evicted (status flips,
            # so the transition time moves).
            _set_condition_via(cmap, wl, "Evicted", False, "QuotaReserved",
                               now)
        # Admitted syncs at admit time when the workload carries every
        # check the CQ requires AND all of its recorded check states are
        # Ready (scheduler.go:502-505 HasAllChecks + SyncAdmittedCondition
        # — a Pending state blocks Admitted even on a checkless CQ).
        states = wl.admission_check_states
        admitted_now = False
        if not states:
            if not cq.admission_checks:
                _set_condition_via(cmap, wl, "Admitted", True, "Admitted",
                                   now)
                admitted_now = True
        elif cq.admission_checks <= states.keys() and all(
                s.state == "Ready" for s in states.values()):
            _set_condition_via(cmap, wl, "Admitted", True, "Admitted", now)
            admitted_now = True
        pending.append((e, wait_started, triples, admitted_now))
        return True

    def _flush_assumes(self, pending: list,
                       snapshot: Optional[Snapshot] = None,
                       usage_csr=None) -> int:
        """End-of-cycle bulk commit of every reserved entry: one locked
        cache pass, then the apply callback per success (assume-before-
        apply, exactly the reference's admit() order), queued mirror
        deltas, one scatter-add into the solver usage tensor, metrics.
        Returns how many actually assumed."""
        if not pending:
            return 0
        # Pass the entry's own info when the flattened triples exist — in
        # exactly that case (no reclaim scaling, spec counts) the admission
        # usage equals the spec-based totals the info already memoized, so
        # the cache can account it without constructing a fresh info.
        # All-fast batches (every admission flattened; the common shape)
        # additionally satisfy the native commit loop's contract — the
        # info IS the entry whose cluster_queue the admission names.
        items = []
        all_fast = True
        for e, _, triples, admitted_now in pending:
            if triples is None:
                all_fast = False
                items.append((e.info.obj, triples, None, admitted_now))
            else:
                items.append((e.info.obj, triples, e.info, admitted_now))
        note_bulk = getattr(self.batch_solver, "note_admissions", None)
        # usage_idx coordinates are only valid in the encoding they were
        # decoded against; after a mid-pipeline structural change the
        # solver's encoding (and usage tensor) rotated to a new index
        # space — fall back to the name-keyed usage dicts then.
        idx_ok = note_bulk is not None and snapshot is not None and getattr(
            self.batch_solver, "encoding_matches", lambda s: False)(snapshot)
        # CSR commit: when every reserved entry rode THIS solve (fast
        # triples + a live CSR row) and no topology ledger needs
        # per-admission charging, the whole cycle's usage lands in the
        # cache as ONE aggregated coordinate pass (and one arena
        # scatter-add) instead of a nested dict walk per workload.
        csr_items = None
        names = None
        if (self._csr_assume and all_fast and idx_ok
                and usage_csr is not None
                and not self.cache.topology.flavors
                and hasattr(self.cache, "assume_workloads_csr")):
            names = getattr(self.batch_solver, "encoding_names",
                            lambda: None)()
        if names is not None:
            cq_names, flavor_names, resource_names, cq_index = names
            csr_items = []
            for e, _, triples, admitted_now in pending:
                ci = cq_index.get(e.info.cluster_queue)
                if ci is None or e.solve_row < 0:
                    csr_items = None
                    break
                csr_items.append((e.info.obj, triples, e.info, ci,
                                  admitted_now))
        with TRACER.phase("admit.flush.assume") as asp:
            if csr_items is not None:
                import numpy as np
                from kueue_tpu.solver.schema import csr_gather
                rows = np.fromiter(
                    (e.solve_row for e, _, _, _ in pending),
                    np.int64, count=len(pending))
                ent, _ci, fi, ri, val = csr_gather(usage_csr, rows)
                results = self.cache.assume_workloads_csr(
                    csr_items, (ent, fi, ri, val), cq_names,
                    flavor_names, resource_names,
                    arena=getattr(self.batch_solver, "admit_arena", None))
                asp.set("entries", len(pending))
                asp.set("csr_rows", int(len(ent)))
            else:
                results = self.cache.assume_workloads(items, fast=all_fast)
                asp.set("entries", len(pending))
                asp.set("csr_rows", 0)
        now = self.clock()
        note_items = []
        csr_rows: List[int] = []
        csr_cqs: List[str] = []
        forget_verdict = getattr(self.batch_solver, "forget_verdict", None)
        admitted = 0
        wait_samples = []
        admit_counts: Dict[tuple, int] = {}
        for (e, wait_started, triples, _adm), assumed in zip(pending, results):
            wl = e.info.obj
            if isinstance(assumed, str):
                # Defensive (duplicate assume / CQ deleted mid-tick):
                # identical rollback to the old per-entry assume failure.
                wl.admission = None
                wl.set_condition("QuotaReserved", False, reason="Pending",
                                 message=assumed, now=now)
                e.status = NOMINATED
                e.inadmissible_msg = f"Failed to admit workload: {assumed}"
                continue
            if not self.apply_admission(wl):
                # Roll the assume and the reservation back so it can
                # requeue (the reference applies admission to a deep copy
                # instead); the mirror/solver never saw this admission.
                self.cache.forget_workload(wl)
                wl.admission = None
                wl.set_condition("QuotaReserved", False, reason="Pending",
                                 message="admission apply failed", now=now)
                e.status = NOMINATED
                self._requeue_and_update(e)
                continue
            e.status = ASSUMED
            if forget_verdict is not None:
                # The head left the queue: its cached verdicts are dead
                # weight (and would pin the Assignment objects).
                forget_verdict(wl.uid)
            self._mirror.note_admission(wl, assumed)
            # Mirror EXACTLY what the cache accounted: for partial
            # admission that is the spec-count totals (scaled back up,
            # workload.go:230-234 — the job integration later reclaims
            # the difference), not the reduced assignment usage. When the
            # flattened triples exist (no reclaim, spec counts — the
            # accounted usage IS the assignment usage) pass the decode's
            # CSR row (one vectorized scatter-add for the whole cycle) or
            # integer coordinates so the solver skips the dict walk.
            if triples is not None and idx_ok and usage_csr is not None \
                    and e.solve_row >= 0:
                csr_rows.append(e.solve_row)
                csr_cqs.append(e.info.cluster_queue)
            else:
                idx = e.assignment.usage_idx \
                    if triples is not None and idx_ok else None
                note_items.append((
                    e.info.cluster_queue,
                    None if idx is not None else assumed.usage(), idx))
            admitted += 1
            self.metrics.admitted += 1
            key = (e.info.cluster_queue,)
            admit_counts[key] = admit_counts.get(key, 0) + 1
            wait_samples.append((key, max(0.0, now - wait_started)))
        if admit_counts:
            REGISTRY.admitted_workloads_total.inc_bulk(admit_counts.items())
            REGISTRY.admission_wait_time_seconds.observe_bulk(wait_samples)
        if csr_rows:
            self.batch_solver.note_admissions_csr(usage_csr, csr_rows,
                                                  csr_cqs)
        if note_items:
            if note_bulk is not None:
                note_bulk(note_items)
            else:
                single = getattr(self.batch_solver, "note_admission", None)
                if single is not None:
                    for cq_name, frq, _ in note_items:
                        single(cq_name, frq)
        return admitted

    # -- requeue (scheduler.go:590-607) --------------------------------------

    def _requeue_and_update(self, e: Entry) -> None:
        self._requeue_sweep((e,))

    def _requeue_sweep(self, entries, quiescent: bool = False) -> None:
        """Requeue losers, then strip dangling reservations — the
        reference's order (requeueAndUpdate): the queue manager's
        has_quota_reservation guard must observe the reservation still
        set, so a reserved entry is deliberately NOT re-inserted. Batched
        under one queue-manager lock for the post-cycle sweep.

        `quiescent`: the admit cycle replayed a provably-identical
        no-action outcome, so every loser's Pending condition already
        carries exactly the status/reason/message this sweep would write
        — the heap re-insert still runs (the heads were popped), the
        per-loser condition writes are skipped."""
        to_requeue = []
        for e in entries:
            if e.status != NOT_NOMINATED \
                    and e.requeue_reason == RequeueReason.GENERIC:
                e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
            to_requeue.append((e.info, e.requeue_reason))
        if to_requeue:
            self.queues.requeue_workloads(to_requeue)
        if quiescent:
            self.metrics.inadmissible += len(entries)
            return
        now = None
        inadmissible = 0
        for e in entries:
            if e.status in (NOT_NOMINATED, SKIPPED):
                wl = e.info.obj
                if now is None:
                    now = self.clock()
                # UnsetQuotaReservationWithCondition (scheduler.go:594-600):
                # the Pending condition carries the inadmissible message
                # whether or not a reservation existed — it is the status
                # surface explaining WHY the workload is not admitted.
                # One condition-map fetch serves the reservation read and
                # the Pending write (this loop runs per loser per tick).
                cmap = wl._cond_map()
                c = cmap.get("QuotaReserved")
                if c is not None and c.status:
                    wl.admission = None
                _set_condition_via(cmap, wl, "QuotaReserved", False,
                                   "Pending", now,
                                   message=e.inadmissible_msg)
                inadmissible += 1
        self.metrics.inadmissible += inadmissible


def _set_condition_via(cmap: dict, wl: Workload, ctype: str, status: bool,
                       reason: str, now: float, message: str = "") -> None:
    """Workload.set_condition with the condition map already in hand
    (admission hot path — one map read serves several condition writes).
    In-place updates keep `cmap` valid; appends invalidate it by length,
    exactly like set_condition itself."""
    wl._cond_mut += 1
    c = cmap.get(ctype)
    if c is None:
        wl.conditions.append(
            Condition(ctype, status, reason, message,
                      last_transition_time=now))
    else:
        if c.status != status:
            c.last_transition_time = now
        c.status, c.reason, c.message = status, reason, message


def _assignment_still_fits(assignment: Assignment, cq: CachedClusterQueue,
                           ) -> bool:
    """Re-validate a FIT assignment against current snapshot state using
    the referee's own quota arithmetic (_fits_resource_quota), including
    cohort, borrowing-limit, lending and hierarchical paths."""
    from kueue_tpu.solver.referee import _fits_resource_quota

    for flavor, resources in assignment.usage.items():
        for resource, val in resources.items():
            rg = cq.rg_by_resource.get(resource)
            quota = None
            if rg is not None:
                for fq in rg.flavors:
                    if fq.name == flavor:
                        quota = fq.resources_dict.get(resource)
                        break
            mode, _, _ = _fits_resource_quota(cq, flavor, resource, val, quota)
            if mode != FIT:
                return False
    return True


# -- cohort cycle-usage helpers (scheduler.go:134-173) -----------------------


def _has_common_flavor_resources(cohort_usage: Optional[FlavorResourceQuantities],
                                 assignment: FlavorResourceQuantities) -> bool:
    if not cohort_usage:
        return False
    for flavor, resources in assignment.items():
        cr = cohort_usage.get(flavor)
        if cr is None:
            continue
        if any(r in cr for r in resources):
            return True
    return False


def _common_usage_sum(cohort_usage: FlavorResourceQuantities,
                      assignment: FlavorResourceQuantities,
                      ) -> FlavorResourceQuantities:
    out: FlavorResourceQuantities = {}
    for flavor, resources in assignment.items():
        cr = cohort_usage.get(flavor)
        if cr is None:
            continue
        common = {r: v + cr[r] for r, v in resources.items() if r in cr}
        if common:
            out[flavor] = common
    return out


def _resources_to_reserve(e: Entry,
                          cq: CachedClusterQueue) -> FlavorResourceQuantities:
    """How much of the assignment usage actually reserves cohort quota this
    cycle (scheduler.go:353-387)."""
    if e.assignment.representative_mode != PREEMPT:
        return e.assignment.usage
    return preempt_reserve(e.assignment.usage, e.assignment.borrowing, cq)


def preempt_reserve(usage: FlavorResourceQuantities, borrowing: bool,
                    cq: CachedClusterQueue) -> FlavorResourceQuantities:
    """The PREEMPT-mode reserve arithmetic of `_resources_to_reserve`,
    exposed on raw (usage, borrowing) inputs so the cross-replica
    coordinator (parallel/replica.py) folds exactly what the in-process
    cycle would."""
    reserved: FlavorResourceQuantities = {}
    for flavor, resources in usage.items():
        reserved[flavor] = {}
        for resource, val in resources.items():
            rg = cq.rg_by_resource.get(resource)
            nominal, borrowing_limit = 0, None
            if rg is not None:
                for fq in rg.flavors:
                    if fq.name == flavor:
                        quota = fq.resources_dict.get(resource)
                        if quota is not None:
                            nominal = quota.nominal
                            borrowing_limit = quota.borrowing_limit
                        break
            used = cq.usage.get(flavor, {}).get(resource, 0)
            if not borrowing:
                reserved[flavor][resource] = max(0, min(val, nominal - used))
            elif borrowing_limit is None:
                reserved[flavor][resource] = val
            else:
                reserved[flavor][resource] = min(
                    val, nominal + borrowing_limit - used)
    return reserved


def _has_retry_or_rejected_checks(wl: Workload) -> bool:
    return any(s.state in ("Retry", "Rejected")
               for s in wl.admission_check_states.values())
