from kueue_tpu.server.api_server import APIServer

__all__ = ["APIServer"]
