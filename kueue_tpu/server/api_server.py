"""HTTP API server: the framework's out-of-process surface.

The reference is a *server*: the apiserver talks to it over HTTPS webhooks,
it embeds a visibility apiserver serving pending-workload listings
(pkg/visibility/server.go:49-68), it exposes Prometheus metrics, and
MultiKueue managers reach worker clusters through their apiservers with
watches (multikueuecluster.go:73-260). This module is that boundary for
the TPU-native runtime: one HTTP listener serving

  - the object API (create/get/list/delete + status) for every kueue kind,
    JSON documents in the same manifest format `api/serialization` decodes,
    so `kubectl get -o json`-shaped payloads round-trip;
  - a chunked watch stream (`/apis/.../watch/workloads`) with JSON-lines
    events — the informer protocol analog, used by the MultiKueue HTTP
    remote for watch-based mirroring;
  - batch/v1 Jobs (create + status + finish), so a remote manager can run
    a job adapter against this process like the reference's jobAdapter
    drives a worker cluster;
  - the visibility API (`/apis/visibility.kueue.x-k8s.io/v1alpha1/...`)
    straight from the queue manager's heap snapshots
    (pkg/visibility/api/rest/pending_workloads_cq.go:60-91);
  - Prometheus text `/metrics` and `/healthz`/`/readyz`.

Concurrency: mutating routes and the scheduler tick share one runtime
lock (the reference's two big RWMutexes, cache.go:73 / manager.go:64, are
the same discipline); reads of the Store are internally locked.
"""

from __future__ import annotations

import json
import queue as queue_mod
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from kueue_tpu.api import serialization
from kueue_tpu.controllers.store import (
    DELETED,
    KIND_ADMISSION_CHECK,
    KIND_CLUSTER_QUEUE,
    KIND_COHORT,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    KIND_WORKLOAD_PRIORITY_CLASS,
    Event,
    Store,
)
from kueue_tpu.controllers.multikueue import PREBUILT_WORKLOAD_LABEL
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.tracing import TRACER
from kueue_tpu.webhooks import ValidationError

GROUP_PREFIX = "/apis/kueue.x-k8s.io/v1beta1"
COHORT_PREFIX = "/apis/kueue.x-k8s.io/v1alpha1"
VISIBILITY_PREFIX = "/apis/visibility.kueue.x-k8s.io/v1alpha1"
BATCH_PREFIX = "/apis/batch/v1"

# plural resource name <-> kind (the discovery mapping)
PLURALS: Dict[str, str] = {
    "clusterqueues": KIND_CLUSTER_QUEUE,
    "localqueues": KIND_LOCAL_QUEUE,
    "resourceflavors": KIND_RESOURCE_FLAVOR,
    "workloads": KIND_WORKLOAD,
    "workloadpriorityclasses": KIND_WORKLOAD_PRIORITY_CLASS,
    "admissionchecks": KIND_ADMISSION_CHECK,
    "cohorts": KIND_COHORT,
}
NAMESPACED = {KIND_WORKLOAD, KIND_LOCAL_QUEUE}


def _match_label_selector(selector: str, labels: Dict[str, str]) -> bool:
    """k8s `labelSelector=k=v,k2=v2` equality clauses."""
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        key, _, value = clause.partition("=")
        if labels.get(key.strip()) != value.strip():
            return False
    return True


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kueue-tpu"
    # Headers and body flush as separate segments; with Nagle on, the
    # second waits ~40ms for the client's delayed ACK, capping a
    # keep-alive connection at ~25 requests/s.
    disable_nagle_algorithm = True

    # Set by APIServer via the server object.
    @property
    def api(self) -> "APIServer":
        return self.server.api  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet by default
        if self.api.verbose:
            super().log_message(fmt, *args)

    # -- helpers -----------------------------------------------------------

    def _send_json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, text: str, code: int = 200,
                   content_type: str = "text/plain; charset=utf-8") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._send_json({"kind": "Status", "status": "Failure",
                         "code": code, "message": message}, code)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    def _route(self, path: str) -> Optional[Tuple[str, Optional[str], Optional[str]]]:
        """Resolve an object-API path to (kind, namespace, name)."""
        for prefix in (GROUP_PREFIX, COHORT_PREFIX):
            if path.startswith(prefix + "/"):
                rest = path[len(prefix) + 1:].strip("/")
                break
        else:
            return None
        parts = [p for p in rest.split("/") if p]
        ns: Optional[str] = None
        if parts and parts[0] == "namespaces" and len(parts) >= 3:
            ns = parts[1]
            parts = parts[2:]
        if not parts or parts[0] not in PLURALS:
            return None
        kind = PLURALS[parts[0]]
        name = parts[1] if len(parts) > 1 else None
        return kind, ns, name

    @staticmethod
    def _key(kind: str, ns: Optional[str], name: str) -> str:
        if kind in NAMESPACED:
            return f"{ns or 'default'}/{name}"
        return name

    # -- GET ---------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib casing)
        parsed = urlparse(self.path)
        path, params = parsed.path.rstrip("/"), parse_qs(parsed.query)
        try:
            if path in ("/healthz", "/readyz"):
                self._send_text("ok")
            elif path == "/metrics":
                # Export only — gauges are refreshed by the serve loop on
                # a throttle (__main__), never under a scrape: a scrape
                # racing a tick must not stall the scheduler for the
                # O(workloads) gauge walk.
                self._send_text(REGISTRY.export_text(),
                                content_type="text/plain; version=0.0.4")
            elif path == "/debug/traces":
                # Chrome-trace export of the tracer's retained ticks
                # (ring + always-kept slowest set) — save the body to a
                # file and load it in Perfetto / chrome://tracing. Reads
                # the tracer's own lock only, never the runtime lock: a
                # trace pull must not stall the scheduler. `?slowest=true`
                # returns just the slowest retained tick.
                slowest = (params.get("slowest") or ["false"])[0] == "true"
                if self.api.trace_export is not None:
                    # Replica deployments serve the MERGED trace: every
                    # worker process's ring dump rebased onto one
                    # timeline with the coordinator's reconcile rounds
                    # bound to the replicas' RTT spans as flow events.
                    self._send_json(self.api.trace_export(slowest))
                else:
                    self._send_json(
                        TRACER.export_chrome(slowest_only=slowest))
            elif path.startswith(VISIBILITY_PREFIX):
                self._get_visibility(path, params)
            elif path.startswith(BATCH_PREFIX):
                self._get_job(path)
            elif "/watch/" in path:
                self._watch(path)
            else:
                route = self._route(path)
                if route is None:
                    self._error(404, f"unknown path {path}")
                    return
                kind, ns, name = route
                if name is None:
                    self._list(kind, ns, params)
                else:
                    # Copy-on-write read view: the store publishes an
                    # encoded doc at write time, so reads never wait on
                    # the runtime lock (or see a mid-tick mutation).
                    doc = self.api.store.encoded_get(
                        kind, self._key(kind, ns, name))
                    if doc is None:
                        self._error(404, f"{kind} {name} not found")
                    else:
                        if kind == "LocalQueue" and self.api.fw is not None:
                            # LocalQueue status derives from workload
                            # churn, not LQ writes — enrich on read from
                            # the cache (its own lock; no runtime-lock
                            # wait). reference: localqueue_controller.go
                            # status sync from cache.go:607-658.
                            lq_ns = ns or "default"
                            status = self.api.fw.cache.local_queue_status(
                                f"{lq_ns}/{name}")
                            if status is not None:
                                doc = dict(doc)
                                status["pendingWorkloads"] = \
                                    self.api.fw.queues.pending_in_local_queue(
                                        lq_ns, name)
                                doc["status"] = status
                        self._send_json(doc)
        except BrokenPipeError:
            pass
        except Exception as exc:  # pragma: no cover - defensive
            self._error(500, f"{type(exc).__name__}: {exc}")

    def _list(self, kind: str, ns: Optional[str], params) -> None:
        selector = (params.get("labelSelector") or [None])[0]
        # Copy-on-write read view; see do_GET.
        items = self.api.store.encoded_list(kind, namespace=ns)
        if selector:
            items = [d for d in items
                     if _match_label_selector(
                         selector, (d.get("metadata") or {}).get("labels")
                         or {})]
        self._send_json({"kind": f"{kind}List", "items": items})

    def _get_visibility(self, path: str, params) -> None:
        """GET .../clusterqueues/<cq>/pendingworkloads and
        .../namespaces/<ns>/localqueues/<lq>/pendingworkloads
        (pending_workloads_cq.go:60-91)."""
        rest = [p for p in path[len(VISIBILITY_PREFIX):].split("/") if p]
        limit = int((params.get("limit") or [1000])[0])
        offset = int((params.get("offset") or [0])[0])
        # Admission explainability: ?explain=true attaches each pending
        # workload's recorded scheduling attempts (flavors tried with
        # verdicts, topology placement, final reason) to the listing.
        explain = (params.get("explain") or ["false"])[0] == "true"
        vis = self.api.visibility
        if vis is None:
            self._error(503, "visibility not enabled")
            return
        if len(rest) == 3 and rest[0] == "clusterqueues" \
                and rest[2] == "pendingworkloads":
            with self.api.runtime_lock:  # heap snapshot races ticks
                infos = vis.pending_workloads_in_cq(rest[1], offset=offset,
                                                    limit=limit,
                                                    explain=explain)
        elif len(rest) == 5 and rest[0] == "namespaces" \
                and rest[2] == "localqueues" and rest[4] == "pendingworkloads":
            with self.api.runtime_lock:
                infos = vis.pending_workloads_in_lq(rest[1], rest[3],
                                                    offset=offset,
                                                    limit=limit,
                                                    explain=explain)
        else:
            self._error(404, f"unknown visibility path {path}")
            return
        items = []
        for i in infos:
            item = {"name": i.name, "namespace": i.namespace,
                    "localQueueName": i.local_queue,
                    "priority": i.priority,
                    "positionInClusterQueue": i.position_in_cluster_queue,
                    "positionInLocalQueue": i.position_in_local_queue}
            if i.decisions is not None:
                item["decisions"] = i.decisions
            items.append(item)
        self._send_json({"kind": "PendingWorkloadsSummary", "items": items})

    def _get_job(self, path: str) -> None:
        if self.api.fw is None:
            self._error(501, "job endpoints are served per replica; "
                             "not available on the coordinator")
            return
        rest = [p for p in path[len(BATCH_PREFIX):].split("/") if p]
        if len(rest) != 4 or rest[0] != "namespaces" or rest[2] != "jobs":
            self._error(404, f"unknown path {path}")
            return
        ns, name = rest[1], rest[3]
        with self.api.runtime_lock:
            entry = self.api.fw.job_reconciler.jobs.get(f"{ns}/{name}")
            if entry is None:
                self._error(404, f"job {ns}/{name} not found")
                return
            job, wl_key = entry
            self._send_json({
                "kind": "Job",
                "metadata": {"name": name, "namespace": ns},
                "spec": {"parallelism": getattr(job, "parallelism", None),
                         "suspend": job.is_suspended()},
                "status": {"ready": getattr(job, "ready_pods", 0),
                           "succeeded": getattr(job, "succeeded", 0),
                           "failed": getattr(job, "failed", 0)},
                "workloadKey": wl_key})

    def _watch(self, path: str) -> None:
        """Chunked JSON-lines watch stream (the informer list+watch
        protocol analog). Replays current objects as ADDED, then streams.

        Events are encoded inside the watcher callback: it fires while the
        mutator holds the runtime lock, so the object can't be mutated
        mid-encode by a concurrent scheduler tick."""
        plural = path.rsplit("/", 1)[-1]
        kind = PLURALS.get(plural)
        if kind is None:
            self._error(404, f"cannot watch {plural}")
            return
        lines: "queue_mod.Queue[bytes]" = queue_mod.Queue()

        def on_event(ev: Event) -> None:
            doc = {"type": ev.type, "resourceVersion": ev.resource_version,
                   "object": serialization.encode(ev.kind, ev.obj)}
            lines.put((json.dumps(doc) + "\n").encode())

        with self.api.runtime_lock:  # initial replay races ticks otherwise
            self.api.store.watch(kind, on_event, send_initial=True)
            # End-of-replay marker (k8s watch bookmark analog): enqueued
            # under the same lock, so it lands exactly after the ADDED
            # replay and before any live event. Clients stage the replay
            # and only serve from their mirror once this arrives.
            lines.put(b'{"type": "BOOKMARK"}\n')
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        try:
            while not self.api.stopping.is_set():
                try:
                    line = lines.get(timeout=1.0)
                except queue_mod.Empty:
                    write_chunk(b"\n")  # heartbeat flushes out dead pipes
                    continue
                write_chunk(line)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.api.store.unwatch(kind, on_event)

    # -- POST / PUT / DELETE ----------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        path = urlparse(self.path).path.rstrip("/")
        try:
            body = self._read_body()
        except json.JSONDecodeError as exc:
            self._error(400, f"invalid JSON: {exc}")
            return
        try:
            if path.startswith(BATCH_PREFIX):
                self._post_job(path, body)
                return
            if path.endswith("/finish"):
                self._finish_workload(path)
                return
            route = self._route(path)
            if route is None:
                self._error(404, f"unknown path {path}")
                return
            kind, ns, _ = route
            if kind == KIND_WORKLOAD and body.get("kind") == "WorkloadList":
                # The vectorized ingest lane: a whole submission burst
                # decodes in one sweep and lands through ONE
                # create_batch — one validation pass, one batched
                # watch/journal/sink flush — instead of N per-object
                # POST round trips.
                wls = serialization.decode_workload_batch(
                    body.get("items") or [])
                with self.api.runtime_lock:
                    created = self.api.store.create_batch(KIND_WORKLOAD, wls)
                self._send_json(
                    {"kind": "WorkloadList",
                     "items": [{"metadata": {"name": wl.name,
                                             "namespace": wl.namespace,
                                             "uid": wl.uid}}
                               for wl in created]}, 201)
                return
            decoded_kind, obj = serialization.decode(body)
            if decoded_kind != kind:
                self._error(400, f"kind mismatch: path says {kind}, "
                                 f"body says {decoded_kind}")
                return
            if kind == KIND_WORKLOAD:
                serialization.decode_workload_status(body, obj)
            with self.api.runtime_lock:
                self.api.store.create(kind, obj)
            self._send_json(serialization.encode(kind, obj), 201)
        except ValidationError as exc:
            self._error(422, str(exc))
        except serialization.DecodeError as exc:
            # Before ValueError: DecodeError subclasses it.
            self._error(400, str(exc))
        except ValueError as exc:
            self._error(409, str(exc))

    def _post_job(self, path: str, body: dict) -> None:
        if self.api.fw is None:
            self._error(501, "job endpoints are served per replica; "
                             "not available on the coordinator")
            return
        rest = [p for p in path[len(BATCH_PREFIX):].split("/") if p]
        # POST /apis/batch/v1/namespaces/<ns>/jobs — create + submit
        if len(rest) == 3 and rest[0] == "namespaces" and rest[2] == "jobs":
            body.setdefault("metadata", {}).setdefault("namespace", rest[1])
            _, job = serialization.decode(body)
            labels = (body.get("metadata") or {}).get("labels") or {}
            prebuilt = labels.get(PREBUILT_WORKLOAD_LABEL)
            with self.api.runtime_lock:
                if prebuilt:
                    # Bind to an existing (mirrored) workload instead of
                    # creating a second one — the reference's
                    # prebuilt-workload-name jobframework support that
                    # MultiKueue workers rely on (ensureOneWorkload's
                    # prebuilt branch, reconciler.go:481-496).
                    wl_key = f"{job.namespace}/{prebuilt}"
                    if wl_key not in self.api.fw.workloads:
                        self._error(404, f"prebuilt workload {wl_key} "
                                         "not found")
                        return
                    job.prebuilt_name = prebuilt
                self.api.fw.submit_job(job)
            self._send_json({"kind": "Job", "metadata": {
                "name": job.name, "namespace": job.namespace}}, 201)
            return
        # POST .../jobs/<name>/complete — the remote job ran to completion
        # (the analog of the worker cluster's kubelet finishing the pods).
        if len(rest) == 5 and rest[0] == "namespaces" and rest[2] == "jobs" \
                and rest[4] == "complete":
            ns, name = rest[1], rest[3]
            with self.api.runtime_lock:
                entry = self.api.fw.job_reconciler.jobs.get(f"{ns}/{name}")
                if entry is None:
                    self._error(404, f"job {ns}/{name} not found")
                    return
                job, wl_key = entry
                job.succeeded = getattr(job, "completions", 1)
                wl = self.api.fw.workloads.get(wl_key)
                if wl is not None:
                    self.api.fw.finish(wl)
                self.api.sync_status()
            self._send_json({"status": "Success"})
            return
        self._error(404, f"unknown path {path}")

    def _finish_workload(self, path: str) -> None:
        """POST .../workloads/<name>/finish — mark the workload Finished
        (the status write a worker cluster's own controllers would make)."""
        route = self._route(path[: -len("/finish")])
        if route is None or route[0] != KIND_WORKLOAD or route[2] is None:
            self._error(404, f"unknown path {path}")
            return
        if self.api.fw is None:
            self._error(501, "workload finish is served per replica; "
                             "not available on the coordinator")
            return
        kind, ns, name = route
        with self.api.runtime_lock:
            wl = self.api.fw.workloads.get(self._key(kind, ns, name))
            if wl is None:
                self._error(404, f"workload {name} not found")
                return
            self.api.fw.finish(wl)
            self.api.sync_status()
        self._send_json({"status": "Success"})

    def do_DELETE(self) -> None:  # noqa: N802
        path = urlparse(self.path).path.rstrip("/")
        route = self._route(path)
        if route is None or route[2] is None:
            self._error(404, f"unknown path {path}")
            return
        kind, ns, name = route
        with self.api.runtime_lock:
            obj = self.api.store.delete(kind, self._key(kind, ns, name))
        if obj is None:
            self._error(404, f"{kind} {name} not found")
        else:
            self._send_json({"status": "Success"})


class APIServer:
    """Thread-hosted HTTP server wrapping a Store + Framework."""

    def __init__(self, store: Store, framework, visibility=None,
                 host: str = "127.0.0.1", port: int = 0,
                 runtime_lock: Optional[threading.RLock] = None,
                 sync_status=None, verbose: bool = False,
                 trace_export=None):
        self.store = store
        # None in multi-process replica mode: the coordinator serves the
        # object store + merged traces, per-workload runtime endpoints
        # (jobs, finish, LocalQueue status enrichment) live in the
        # replicas and answer 501 here.
        self.fw = framework
        self.visibility = visibility
        # Optional slowest->doc hook replacing the process-local TRACER
        # export at GET /debug/traces (replica mode: merged trace).
        self.trace_export = trace_export
        self.runtime_lock = runtime_lock or threading.RLock()
        self.verbose = verbose
        self.stopping = threading.Event()
        # Publishes workload status to the store after mutations so GET
        # reflects the runtime's view (StoreAdapter.sync_status).
        self._sync_status = sync_status
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.api = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    def sync_status(self) -> None:
        if self._sync_status is not None:
            self._sync_status()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
