"""Solvers for the per-tick admission problem.

`referee` is the sequential implementation, decision-equivalent to the
reference Go scheduler; `schema` encodes a cache snapshot plus pending
workloads into dense integer tensors consumed by the batched JAX models in
`kueue_tpu.models`.
"""

from kueue_tpu.solver.modes import NO_FIT, PREEMPT, FIT
from kueue_tpu.solver.referee import Assignment, PodSetAssignmentResult, assign_flavors
