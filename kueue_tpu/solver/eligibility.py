"""Host-side flavor eligibility: taints/tolerations and node affinity.

This is the "string world" boundary: eligibility is pure string matching and
is computed on the host into boolean masks that the tensor solver consumes.
Semantics mirror the reference flavor selector, which replicates
kube-scheduler's NodeAffinity filter
(reference: pkg/scheduler/flavorassigner/flavorassigner.go:396-410,498-542).
"""

from __future__ import annotations

from typing import Iterable, Optional, Set, Tuple

from kueue_tpu.api.types import PodSet, ResourceFlavor, Taint, Toleration


def find_untolerated_taint(taints: Iterable[Taint],
                           tolerations: Iterable[Toleration]) -> Optional[Taint]:
    """First NoSchedule/NoExecute taint not tolerated, if any."""
    tols = list(tolerations)
    for taint in taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tols):
            return taint
    return None


def _affinity_matches(podset: PodSet, flavor_labels: dict,
                      allowed_keys: Set[str]) -> bool:
    # Node-selector map, restricted to the group's label keys: all must match.
    for k, v in podset.node_selector:
        if k in allowed_keys and flavor_labels.get(k) != v:
            return False
    # Required affinity terms are ORed; expressions within a term are ANDed.
    # A term that becomes empty after key filtering makes the affinity match
    # everything (flavorassigner.go:522-529).
    terms = []
    for term in podset.affinity_terms:
        kept = tuple(e for e in term if e.key in allowed_keys)
        if not kept:
            terms = []
            break
        terms.append(kept)
    if terms:
        return any(all(e.matches(flavor_labels) for e in term) for term in terms)
    return True


def flavor_eligible(podset: PodSet, flavor: ResourceFlavor,
                    allowed_keys: Set[str]) -> Tuple[bool, str]:
    """Whether this PodSet may be placed on this flavor; returns (ok, reason)."""
    # Only the pod's own tolerations count; a flavor's `tolerations` are
    # injected into pods at admission, not used for eligibility
    # (flavorassigner.go:396-398).
    taint = find_untolerated_taint(flavor.node_taints, podset.tolerations)
    if taint is not None:
        return False, f"untolerated taint {taint.key} in flavor {flavor.name}"
    if not _affinity_matches(podset, flavor.labels_dict, allowed_keys):
        return False, f"flavor {flavor.name} doesn't match node affinity"
    return True, ""
