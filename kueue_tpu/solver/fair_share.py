"""Weighted dominant-resource share values (KEP-1714, implemented natively).

The share value of a ClusterQueue is a DRF variant: for each resource, total
usage above nominal quota (summed across flavors) divided by the cohort's
lendable capacity for that resource; the share is the maximum of these
ratios, divided by the CQ's fair-sharing weight
(keps/1714-fair-sharing/README.md "Share value function and weights").

Scaled to integer parts-per-1024 so comparisons are exact and the batched
device model (`kueue_tpu.models.fair_share`) produces identical values.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

from kueue_tpu.core.cache import CachedClusterQueue, FlavorResourceQuantities

SHARE_SCALE = 1024
INFINITE_SHARE = math.inf


def dominant_resource_share(cq: CachedClusterQueue,
                            delta: Optional[FlavorResourceQuantities] = None,
                            ) -> Tuple[float, str]:
    """Share value of `cq` (optionally as-if `delta` usage were added).

    Returns (value, dominant_resource). 0 when the CQ borrows nothing or has
    no cohort; infinite when it borrows with weight 0.
    """
    if cq.cohort is None:
        return 0.0, ""

    # Usage above nominal per resource, summed across flavors.
    above: Dict[str, int] = {}
    for rg in cq.resource_groups:
        for fq in rg.flavors:
            fusage = cq.usage.get(fq.name, {})
            for rname, quota in fq.resources:
                used = fusage.get(rname, 0)
                if delta is not None:
                    used += delta.get(fq.name, {}).get(rname, 0)
                if used > quota.nominal:
                    above[rname] = above.get(rname, 0) + used - quota.nominal

    # Lendable capacity per resource across the cohort — for hierarchical
    # trees (KEP-79), across the whole structure under the root.
    lendable: Dict[str, int] = {}
    if cq.cohort.is_hierarchical():
        requestable = cq.cohort.tree_cap()
    else:
        requestable = cq.cohort.requestable_resources
    for fname, resources in requestable.items():
        for rname, val in resources.items():
            lendable[rname] = lendable.get(rname, 0) + val

    share = 0.0
    dominant = ""
    for rname, t in above.items():
        cap = lendable.get(rname, 0)
        if cap <= 0:
            if t > 0:
                share = INFINITE_SHARE
                dominant = rname
            continue
        ratio = (t * SHARE_SCALE) // cap
        if ratio > share:
            share = float(ratio)
            dominant = rname
    if share == 0.0:
        return 0.0, dominant
    if cq.fair_weight <= 0:
        return INFINITE_SHARE, dominant
    return share / cq.fair_weight, dominant
