"""Flavor assignment modes, ordered by preference
(reference: pkg/scheduler/flavorassigner/flavorassigner.go:199-209)."""

NO_FIT = 0
PREEMPT = 1
FIT = 2

MODE_NAMES = {NO_FIT: "NoFit", PREEMPT: "Preempt", FIT: "Fit"}
