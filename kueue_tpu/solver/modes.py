"""Flavor assignment modes, ordered by preference
(reference: pkg/scheduler/flavorassigner/flavorassigner.go:199-209),
and the registry of preemption victim-search engines.

Every implementation of `minimalPreemptions` (preemption.go:172-231) is
registered here with enough metadata for the three consumers that must
never drift out of sync:

  * the preemption goldens (tests/test_preemption_goldens.py) parametrize
    over EVERY registered engine — a new engine cannot land unverified;
  * the kueueverify trace engine (kueue_tpu/analysis/trace_rules.py)
    lowers every `traceable` engine's kernel to a jaxpr and runs the
    TRC01-04 verification rules over the equations;
  * tests/test_engine_coverage.py introspects this registry and fails when
    either consumer is missing an engine.
"""

from dataclasses import dataclass
from typing import Tuple

NO_FIT = 0
PREEMPT = 1
FIT = 2

MODE_NAMES = {NO_FIT: "NoFit", PREEMPT: "Preempt", FIT: "Fit"}


@dataclass(frozen=True)
class EngineSpec:
    """One registered victim-search engine.

    `kind`: "host" (pure-Python referee), "native" (C++ batch scan), or
    "jax" (XLA/Pallas kernel). `batched` engines solve a whole tick's
    searches in one call and are subject to head-count bucketing (the
    TRC03 one-compile-per-bucket contract). `traceable` engines lower to
    a jaxpr and join the kueueverify roster. `optional_import` marks
    engines whose toolchain may be absent (the Pallas kernel on hosts
    without jax.experimental.pallas) — consumers skip them when the
    import fails, but must cover them whenever it succeeds."""

    name: str
    kind: str
    module: str
    entry: str
    batched: bool = False
    traceable: bool = False
    optional_import: bool = False


@dataclass(frozen=True)
class SolveEntrySpec:
    """One batched flavor-fit solve entry point.

    The victim-search engines above have a registry because three
    consumers must stay in sync; the SOLVE side now has the same shape
    problem — single-device `solve_core`, the packed byte-buffer kernel,
    the cohort-sharded per-shard body, and the topology fit all lower to
    jaxprs in the kueueverify roster, and
    tests/test_engine_coverage.py::test_trace_roster_covers_every_solve_entry
    fails when a new entry point lands untraced."""

    name: str
    module: str
    entry: str


SOLVE_ENTRYPOINTS: Tuple[SolveEntrySpec, ...] = (
    SolveEntrySpec("flavor-fit",
                   "kueue_tpu.models.flavor_fit", "solve_core"),
    SolveEntrySpec("flavor-fit-packed",
                   "kueue_tpu.models.flavor_fit", "_solve_kernel_packed"),
    # The KEP-79 variant of solve_core: the hierarchical cohort-forest
    # pytree swaps the flat-pool arithmetic for the ancestor-path
    # T-invariant walk — a materially different jaxpr, lowered and
    # verified separately (the carried-over "hier solve_core in the
    # trace roster" ROADMAP item).
    SolveEntrySpec("flavor-fit-hier",
                   "kueue_tpu.models.flavor_fit", "solve_core"),
    # Heterogeneity-aware solve mode (kueue_tpu/hetero): the
    # throughput-override variant of solve_core plus the Gavel
    # price-iteration score kernel.
    SolveEntrySpec("flavor-fit-hetero",
                   "kueue_tpu.models.flavor_fit", "solve_core"),
    SolveEntrySpec("hetero-scores",
                   "kueue_tpu.hetero.solve", "hetero_scores_core"),
    SolveEntrySpec("cohort-shard-solve",
                   "kueue_tpu.parallel.mesh", "shard_solve_body"),
    SolveEntrySpec("topology-fit",
                   "kueue_tpu.topology.fit", "solve_topology_core"),
)


@dataclass(frozen=True)
class SolveModeSpec:
    """One registered flavor-assignment solve MODE (tpuSolver.mode).

    A mode is a decision POLICY over the same quota constraints —
    "default" is the reference's ordered first-fit; "hetero" is the
    Gavel-style max-effective-throughput policy (kueue_tpu/hetero).
    `entrypoints` names the SOLVE_ENTRYPOINTS kernels the mode
    dispatches: the coverage meta-test
    (tests/test_engine_coverage.py::test_every_solve_mode_is_registered)
    fails CI when a mode's kernels are missing from the registry or the
    kueueverify trace roster — an unregistered mode cannot land."""

    name: str
    entrypoints: Tuple[str, ...]
    kill_switch: str = ""


SOLVE_MODES: Tuple[SolveModeSpec, ...] = (
    SolveModeSpec("default",
                  ("flavor-fit", "flavor-fit-packed", "flavor-fit-hier",
                   "cohort-shard-solve", "topology-fit")),
    SolveModeSpec("hetero",
                  ("flavor-fit-hetero", "hetero-scores",
                   "cohort-shard-solve"),
                  kill_switch="KUEUE_TPU_NO_HETERO"),
)


def solve_mode_names() -> Tuple[str, ...]:
    return tuple(m.name for m in SOLVE_MODES)


ENGINES: Tuple[EngineSpec, ...] = (
    EngineSpec("host", "host",
               "kueue_tpu.scheduler.preemption", "_minimal_preemptions"),
    EngineSpec("scan-jax", "jax",
               "kueue_tpu.ops.preemption_scan", "scan_kernel",
               traceable=True),
    EngineSpec("scan-pallas", "jax",
               "kueue_tpu.ops.preemption_pallas", "scan_kernel_pallas",
               traceable=True, optional_import=True),
    EngineSpec("batch-native", "native",
               "kueue_tpu.ops.preemption_batch", "run_batch",
               batched=True),
    EngineSpec("batch-jax", "jax",
               "kueue_tpu.ops.preemption_batch", "_packed_batch_kernel",
               batched=True, traceable=True),
)


def engine_importable(spec: EngineSpec) -> bool:
    """Whether the engine's implementation module imports on this host —
    the shared probe consumers use to decide if an `optional_import`
    engine may be skipped (goldens parametrization, coverage meta-test).
    Broad except by design: a Pallas toolchain failing at import time for
    ANY reason means the engine cannot run here."""
    import importlib

    try:
        importlib.import_module(spec.module)
        return True
    except Exception:
        return False
