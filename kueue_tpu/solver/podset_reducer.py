"""Partial-admission pod-count search.

Counterpart of reference pkg/scheduler/flavorassigner/podset_reducer.go:
binary-search the largest proportional reduction of PodSet counts (towards
min_count) that still fits.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, TypeVar

from kueue_tpu.api.types import PodSet

R = TypeVar("R")


def search(pod_sets: Sequence[PodSet],
           fits: Callable[[List[int]], Tuple[Optional[R], bool]],
           ) -> Tuple[Optional[R], bool]:
    full_counts = [ps.count for ps in pod_sets]
    deltas = [ps.count - (ps.min_count if ps.min_count is not None else ps.count)
              for ps in pod_sets]
    total_delta = sum(deltas)
    if total_delta == 0:
        return None, False

    def counts_for(i: int) -> List[int]:
        return [full_counts[k] - (deltas[k] * i) // total_delta
                for k in range(len(deltas))]

    last_good_idx = 0
    last_r: Optional[R] = None

    # Smallest i in [0, total_delta] with fits(counts_for(i)) true
    # (Go sort.Search semantics; i==0 is the full count).
    lo, hi = 0, total_delta + 1
    while lo < hi:
        mid = (lo + hi) // 2
        r, ok = fits(counts_for(mid))
        if ok:
            last_good_idx = mid
            last_r = r
            hi = mid
        else:
            lo = mid + 1
    return last_r, lo == last_good_idx
