"""Partial-admission pod-count search.

Counterpart of reference pkg/scheduler/flavorassigner/podset_reducer.go:
binary-search the largest proportional reduction of PodSet counts (towards
min_count) that still fits.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, TypeVar

from kueue_tpu.api.types import PodSet

R = TypeVar("R")


class SearchState:
    """One binary search over proportional count reductions, steppable
    from outside (Go sort.Search over [0, total_delta]; i==0 is the full
    count). The sequential `search` below and the scheduler's batched
    lockstep rounds (scheduler._batch_partial_admission) drive the SAME
    probe sequence and found-semantics through this object, so the two
    paths cannot drift."""

    __slots__ = ("full_counts", "deltas", "total_delta", "lo", "hi",
                 "last_good_idx", "last_r", "mid")

    def __init__(self, pod_sets: Sequence[PodSet]):
        self.full_counts = [ps.count for ps in pod_sets]
        self.deltas = [
            ps.count - (ps.min_count if ps.min_count is not None else ps.count)
            for ps in pod_sets]
        self.total_delta = sum(self.deltas)
        self.lo = 0
        self.hi = self.total_delta + 1
        self.last_good_idx = 0
        self.last_r: Optional[R] = None
        self.mid = 0

    def searchable(self) -> bool:
        return self.total_delta > 0

    def counts_for(self, i: int) -> List[int]:
        return [self.full_counts[k] - (self.deltas[k] * i) // self.total_delta
                for k in range(len(self.deltas))]

    def active(self) -> bool:
        return self.lo < self.hi

    def probe(self) -> List[int]:
        """The next probe's counts; call exactly once per advance."""
        self.mid = (self.lo + self.hi) // 2
        return self.counts_for(self.mid)

    def advance(self, r: Optional[R], ok: bool) -> None:
        if ok:
            self.last_good_idx = self.mid
            self.last_r = r
            self.hi = self.mid
        else:
            self.lo = self.mid + 1

    def result(self) -> Tuple[Optional[R], bool]:
        return self.last_r, self.lo == self.last_good_idx


def search(pod_sets: Sequence[PodSet],
           fits: Callable[[List[int]], Tuple[Optional[R], bool]],
           ) -> Tuple[Optional[R], bool]:
    state = SearchState(pod_sets)
    if not state.searchable():
        return None, False
    while state.active():
        r, ok = fits(state.probe())
        state.advance(r, ok)
    return state.result()
