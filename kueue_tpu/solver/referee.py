"""Sequential reference solver ("the referee").

Implements the exact decision semantics of the reference flavor assigner
(pkg/scheduler/flavorassigner/flavorassigner.go) against this framework's
data model. The batched JAX models in `kueue_tpu.models` are verified
decision-equivalent to this implementation, and the scheduler falls back to
it when the device solve is unavailable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu import features
from kueue_tpu.api.types import FlavorFungibilityPolicy, BorrowWithinCohortPolicy
from kueue_tpu.core.cache import CachedClusterQueue, FlavorResourceQuantities
from kueue_tpu.core.workload import (
    AssignmentClusterQueueState,
    PodSetResources,
    WorkloadInfo,
)
from kueue_tpu.solver.eligibility import flavor_eligible
from kueue_tpu.solver.modes import FIT, NO_FIT, PREEMPT

PODS_RESOURCE = "pods"


@dataclass(slots=True)
class FlavorAssignment:
    name: str
    mode: int
    tried_flavor_idx: int = 0
    borrow: bool = False


@dataclass(slots=True)
class PodSetAssignmentResult:
    name: str
    flavors: Dict[str, FlavorAssignment] = field(default_factory=dict)
    reasons: List[str] = field(default_factory=list)
    error: Optional[str] = None
    requests: Dict[str, int] = field(default_factory=dict)
    count: int = 0
    # Lazily memoized representative_mode: assigners (referee /
    # flavor_fit decode) finish mutating before any property read, and
    # nothing mutates a result afterwards — the scheduler reads the mode
    # several times per entry per tick on the hot path.
    _mode: Optional[int] = field(default=None, init=False, repr=False)

    @property
    def representative_mode(self) -> int:
        mode = self._mode
        if mode is None:
            if self.error is None and not self.reasons:
                mode = FIT
            elif not self.flavors:
                mode = NO_FIT
            else:
                mode = min(fa.mode for fa in self.flavors.values())
            self._mode = mode
        return mode


@dataclass(slots=True)
class Assignment:
    pod_sets: List[PodSetAssignmentResult] = field(default_factory=list)
    borrowing: bool = False
    usage: FlavorResourceQuantities = field(default_factory=dict)
    last_state: Optional[AssignmentClusterQueueState] = None
    # Integer twin of `usage` in solver-encoding coordinates —
    # ([flavor_idx], [resource_idx], [value]) lists, filled by the batched
    # decode so index-space consumers (staleness re-validation, the usage
    # tensor scatter) skip the name→index dict walks. None on
    # referee-built assignments.
    usage_idx: Optional[tuple] = field(default=None, repr=False)
    # Topology-aware scheduling (kueue_tpu/topology): per-podset
    # TopologyCandidate verdicts, filled by the topology stage; None when
    # no podset carries a topology request (the no-topology no-op).
    topology: Optional[list] = field(default=None, repr=False)
    # (flavor, level name, pods) when a required-topology podset needs
    # preemption — steers victim selection toward freeing one contiguous
    # domain (scheduler/preemption.py).
    topology_hint: Optional[tuple] = field(default=None, repr=False)
    _mode: Optional[int] = field(default=None, init=False, repr=False)
    _msg: Optional[str] = field(default=None, init=False, repr=False)

    @property
    def representative_mode(self) -> int:
        """Worst mode across pod sets (flavorassigner.go:61-78)."""
        mode = self._mode
        if mode is None:
            if not self.pod_sets:
                mode = NO_FIT
            else:
                mode = min(ps.representative_mode for ps in self.pod_sets)
            self._mode = mode
        return mode

    def message(self) -> str:
        # Memoized under the representative_mode contract (assigners
        # finish mutating reasons before the scheduler's first read): a
        # replayed NoFit verdict re-reads its message every tick.
        msg = self._msg
        if msg is None:
            parts = []
            for ps in self.pod_sets:
                if ps.error is not None:
                    return (f"failed to assign flavors to pod set "
                            f"{ps.name}: {ps.error}")
                if ps.reasons:
                    parts.append("couldn't assign flavors to pod set %s: %s"
                                 % (ps.name, ", ".join(sorted(ps.reasons))))
            msg = self._msg = "; ".join(parts)
        return msg


def assign_flavors(wi: WorkloadInfo, cq: CachedClusterQueue,
                   resource_flavors: Dict[str, "ResourceFlavor"],
                   counts: Optional[List[int]] = None,
                   topology=None) -> Assignment:
    """Assign a flavor to every requested resource of every pod set.

    Mirrors FlavorAssigner.Assign (flavorassigner.go:253-329), including the
    resume-from-last-flavor state keyed on allocatable generations
    (flavorassigner.go:244-247).

    `topology` (a (TopologyStage, leaf-occupancy view) pair, or None) runs
    the topology-aware placement stage over the finished assignment — the
    sequential-path twin of the scheduler's batched stage invocation.
    """
    if wi.last_assignment is not None and _last_assignment_outdated(wi, cq):
        wi.last_assignment = None

    if counts is None:
        requests = wi.total_requests
    else:
        requests = [wi.total_requests[i].scaled_to(c) for i, c in enumerate(counts)]

    assignment = Assignment(
        usage={},
        last_state=AssignmentClusterQueueState(
            cluster_queue_generation=cq.allocatable_generation,
            cohort_generation=(cq.cohort.allocatable_generation
                               if cq.cohort is not None else 0),
        ),
    )

    for ps_idx, podset in enumerate(requests):
        ps_requests = dict(podset.requests)
        if PODS_RESOURCE in cq.rg_by_resource:
            ps_requests[PODS_RESOURCE] = podset.count

        psa = PodSetAssignmentResult(
            name=podset.name, requests=ps_requests, count=podset.count)

        for res_name in ps_requests:
            if res_name in psa.flavors:
                # Same resource group as an already-assigned resource.
                continue
            flavors, reasons, error = _find_flavor_for_podset_resource(
                wi, cq, resource_flavors, ps_idx, ps_requests, res_name,
                assignment.usage)
            if error is not None or not flavors:
                psa.flavors = {}
                psa.reasons = reasons
                psa.error = error
                break
            psa.flavors.update(flavors)
            psa.reasons.extend(reasons)

        _append_podset(assignment, ps_requests, psa)
        if psa.error is not None or (ps_requests and not psa.flavors):
            break
    if topology is not None:
        stage, used_by_flavor = topology
        stage.apply([wi], [assignment], used_by_flavor, use_device=False)
    return assignment


def _last_assignment_outdated(wi: WorkloadInfo, cq: CachedClusterQueue) -> bool:
    la = wi.last_assignment
    return (cq.allocatable_generation > la.cluster_queue_generation
            or (cq.cohort is not None
                and cq.cohort.allocatable_generation > la.cohort_generation))


def _append_podset(assignment: Assignment, requests: Dict[str, int],
                   psa: PodSetAssignmentResult) -> None:
    """Accumulate usage + resume state (flavorassigner.go:342-356)."""
    flavor_idx: Dict[str, int] = {}
    assignment.pod_sets.append(psa)
    for resource, fa in psa.flavors.items():
        if fa.borrow:
            assignment.borrowing = True
        assignment.usage.setdefault(fa.name, {})
        assignment.usage[fa.name][resource] = (
            assignment.usage[fa.name].get(resource, 0) + requests[resource])
        flavor_idx[resource] = fa.tried_flavor_idx
    assignment.last_state.last_tried_flavor_idx.append(flavor_idx)


def _find_flavor_for_podset_resource(
        wi: WorkloadInfo, cq: CachedClusterQueue,
        resource_flavors: Dict[str, "ResourceFlavor"],
        ps_idx: int, requests: Dict[str, int], res_name: str,
        assignment_usage: FlavorResourceQuantities,
) -> Tuple[Dict[str, FlavorAssignment], List[str], Optional[str]]:
    """Try the resource group's flavors in order for all grouped resources
    (flavorassigner.go:363-476). Returns (assignments, reasons, error)."""
    rg = cq.rg_by_resource.get(res_name)
    if rg is None:
        return {}, [f"resource {res_name} unavailable in ClusterQueue"], None

    grouped = {r: v for r, v in requests.items() if r in rg.covered_resources}
    podset = wi.obj.pod_sets[ps_idx]
    allowed_keys = cq.label_keys(rg, resource_flavors)

    reasons: List[str] = []
    best_assignment: Dict[str, FlavorAssignment] = {}
    best_mode = NO_FIT
    assigned_flavor_idx = -1
    fungibility = features.enabled(features.FLAVOR_FUNGIBILITY)

    idx = 0
    if wi.last_assignment is not None:
        idx = wi.last_assignment.next_flavor_to_try(ps_idx, res_name)

    num_flavors = len(rg.flavors)
    while idx < num_flavors:
        fq = rg.flavors[idx]
        flavor = resource_flavors.get(fq.name)
        if flavor is None:
            reasons.append(f"flavor {fq.name} not found")
            idx += 1
            continue
        ok, why = flavor_eligible(podset, flavor, allowed_keys)
        if not ok:
            reasons.append(why)
            idx += 1
            continue

        assigned_flavor_idx = idx
        needs_borrowing = False
        assignments: Dict[str, FlavorAssignment] = {}
        representative_mode = FIT
        quotas = fq.resources_dict
        for rname, val in grouped.items():
            quota = quotas.get(rname)
            prev = assignment_usage.get(fq.name, {}).get(rname, 0)
            mode, borrow, reason = _fits_resource_quota(
                cq, fq.name, rname, val + prev, quota)
            if reason is not None:
                reasons.append(reason)
            representative_mode = min(representative_mode, mode)
            needs_borrowing = needs_borrowing or borrow
            if representative_mode == NO_FIT:
                break
            assignments[rname] = FlavorAssignment(
                name=fq.name, mode=mode, borrow=borrow)

        if fungibility:
            if not _should_try_next_flavor(
                    representative_mode, cq.flavor_fungibility, needs_borrowing):
                best_assignment = assignments
                best_mode = representative_mode
                break
            if representative_mode > best_mode:
                best_assignment = assignments
                best_mode = representative_mode
        else:
            if representative_mode > best_mode:
                best_assignment = assignments
                best_mode = representative_mode
                if best_mode == FIT:
                    return best_assignment, [], None
        idx += 1

    if fungibility:
        for fa in best_assignment.values():
            if assigned_flavor_idx == num_flavors - 1:
                # Whole list exhausted: restart from the first flavor next time
                # (flavorassigner.go:462-470).
                fa.tried_flavor_idx = -1
            else:
                fa.tried_flavor_idx = assigned_flavor_idx
        if best_mode == FIT:
            return best_assignment, [], None
    return best_assignment, reasons, None


def _should_try_next_flavor(representative_mode: int, fungibility,
                            needs_borrowing: bool) -> bool:
    """flavorassigner.go:478-496."""
    policy_preempt = fungibility.when_can_preempt
    policy_borrow = fungibility.when_can_borrow
    if representative_mode == PREEMPT and policy_preempt == FlavorFungibilityPolicy.PREEMPT:
        if not needs_borrowing or policy_borrow == FlavorFungibilityPolicy.BORROW:
            return False
    if representative_mode == FIT and needs_borrowing \
            and policy_borrow == FlavorFungibilityPolicy.BORROW:
        return False
    if representative_mode == FIT and not needs_borrowing:
        return False
    return True


def _fits_resource_quota(cq: CachedClusterQueue, flavor: str, resource: str,
                         val: int, quota) -> Tuple[int, bool, Optional[str]]:
    """Mode for one (flavor, resource) given CQ and cohort state
    (flavorassigner.go:550-600). Hierarchical cohort trees (KEP-79) swap
    the flat cohort-capacity arithmetic for the tree's T-invariant walk
    (core/hierarchy.py); flat 2-level cohorts keep the reference's exact
    seat-based math."""
    borrow = False
    used = cq.usage.get(flavor, {}).get(resource, 0)
    nominal = quota.nominal if quota is not None else 0
    borrowing_limit = quota.borrowing_limit if quota is not None else None
    hierarchical = cq.cohort is not None and cq.cohort.is_hierarchical()

    mode = NO_FIT
    if val <= nominal:
        # Could fit if quota is reclaimed from the cohort or CQ workloads
        # are preempted.
        mode = PREEMPT

    if not hierarchical:
        cohort_available = nominal
        if cq.cohort is not None:
            cohort_available = cq.requestable_cohort_quota(flavor, resource)

    bwc = cq.preemption.borrow_within_cohort
    if (bwc is not None and bwc.policy != BorrowWithinCohortPolicy.NEVER) \
            or features.enabled(features.FAIR_SHARING):
        # Preemption-with-borrowing can admit beyond nominal quota; fair
        # sharing (KEP-1714) implies it globally, since share-based
        # preemption targets borrowers to make room for borrowing requests.
        if hierarchical:
            from kueue_tpu.core.hierarchy import hierarchical_lack
            could_ever_fit = hierarchical_lack(
                cq, flavor, resource, val, ignore_usage=True) <= 0
        else:
            could_ever_fit = val <= cohort_available
        if (borrowing_limit is None or val <= nominal + borrowing_limit) \
                and could_ever_fit:
            mode = PREEMPT
            borrow = val > nominal

    if borrowing_limit is not None and used + val > nominal + borrowing_limit:
        return mode, borrow, (f"borrowing limit for {resource} in flavor "
                              f"{flavor} exceeded")

    if hierarchical:
        from kueue_tpu.core.hierarchy import hierarchical_lack
        lack = hierarchical_lack(cq, flavor, resource, val)
    else:
        cohort_used = used
        if cq.cohort is not None:
            cohort_used = cq.used_cohort_quota(flavor, resource)
        lack = cohort_used + val - cohort_available
    if lack <= 0:
        return FIT, used + val > nominal, None

    if cq.cohort is None:
        if mode == NO_FIT:
            msg = f"insufficient quota for {resource} in flavor {flavor} in ClusterQueue"
        else:
            msg = f"insufficient unused quota for {resource} in flavor {flavor}, {lack} more needed"
    else:
        msg = (f"insufficient unused quota in cohort for {resource} in flavor "
               f"{flavor}, {lack} more needed")
    return mode, borrow, msg
