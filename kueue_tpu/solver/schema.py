"""Dense tensor encoding of the per-tick admission problem.

This replaces the reference's per-workload pointer-chasing over the cache
snapshot (pkg/cache/snapshot.go + flavorassigner's per-flavor loops) with a
TPU-friendly dense layout: every quantity is an integer tensor indexed by a
global (ClusterQueue, Flavor, Resource) vocabulary, so the whole batch of
pending workloads is solved by one XLA program
(`kueue_tpu.models.flavor_fit`).

Axes:
  W  workloads (padded to a bucket size)
  P  pod sets per workload (padded)
  C  cluster queues
  F  flavors   (global vocabulary)
  R  resources (global vocabulary)
  G  resource groups per CQ (padded)
  S  flavor slots per group (padded); slot order is the assignment
     preference order
  K  cohorts (every CQ belongs to one; cohort-less CQs get singletons,
     which is arithmetically identical -- see fits math in the model)

The "string world" (taints, tolerations, node affinity, namespace
selectors) never reaches the device: it is folded into the boolean
eligibility mask `elig[W,P,F]` here on the host
(reference: flavorassigner.go:396-410 and :498-542).

All quantities are int64 (canonical units); NO_LIMIT encodes a nil
borrowingLimit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu import features
from kueue_tpu.api.types import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
)
from kueue_tpu.core.cache import CachedClusterQueue
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.solver.eligibility import flavor_eligible

PODS_RESOURCE = "pods"

# Large sentinel for "no borrowing limit"; keeps nominal+limit < 2^63.
NO_LIMIT = np.int64(1) << 62


def _pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass
class CQEncoding:
    """Static (per-generation) encoding of the ClusterQueue/cohort side."""

    cq_names: List[str]
    cq_index: Dict[str, int]
    flavor_names: List[str]
    flavor_index: Dict[str, int]
    resource_names: List[str]
    resource_index: Dict[str, int]
    cohort_names: List[str]

    nominal: np.ndarray        # [C,F,R] i64
    borrow_limit: np.ndarray   # [C,F,R] i64 (NO_LIMIT when nil)
    guaranteed: np.ndarray     # [C,F,R] i64 (0 unless LendingLimit)
    lendable: np.ndarray       # [C,F,R] i64 (lendingLimit if set+enabled else nominal)
    cohort_id: np.ndarray      # [C] i32
    group_of_resource: np.ndarray  # [C,R] i32, -1 when not covered
    slot_flavor: np.ndarray    # [C,G,S] i32 global flavor idx, -1 pad
    num_flavors: np.ndarray    # [C,G] i32
    bwc_enabled: np.ndarray    # [C] bool
    borrow_policy_is_borrow: np.ndarray    # [C] bool (whenCanBorrow == Borrow)
    preempt_policy_is_preempt: np.ndarray  # [C] bool (whenCanPreempt == Preempt)

    num_cohorts: int
    num_groups: int
    num_slots: int

    def cohort_requestable(self) -> np.ndarray:
        """[K,F,R] sum of members' lendable quota (snapshot.go:160-178)."""
        k = self.num_cohorts
        out = np.zeros((k,) + self.lendable.shape[1:], dtype=np.int64)
        np.add.at(out, self.cohort_id, self.lendable)
        return out


@dataclass
class UsageTensors:
    """The fast-changing side: per-CQ usage and its cohort aggregation."""

    usage: np.ndarray         # [C,F,R] i64
    cohort_usage: np.ndarray  # [K,F,R] i64: sum of max(0, usage-guaranteed)
    cohort_requestable: np.ndarray  # [K,F,R] i64


@dataclass
class WorkloadTensors:
    """The batch of pending workloads to solve."""

    wl_cq: np.ndarray        # [W] i32
    req: np.ndarray          # [W,P,R] i64
    has_req: np.ndarray      # [W,P,R] bool
    podset_valid: np.ndarray  # [W,P] bool
    podset_unsat: np.ndarray  # [W,P] bool (requests a resource outside the vocab)
    # Eligibility is per (group, slot): affinity matching is restricted to
    # each group's label keys, so one flavor can be eligible in one group
    # and ineligible in another (flavorassigner.go:498-542).
    elig: np.ndarray         # [W,P,G,S] bool
    resume_slot: np.ndarray  # [W,P,G] i32 (first slot to try)
    wl_valid: np.ndarray     # [W] bool (padding rows are False)
    num_real: int


def encode_cluster_queues(snapshot: Snapshot) -> CQEncoding:
    cq_names = sorted(snapshot.cluster_queues)
    cq_index = {n: i for i, n in enumerate(cq_names)}
    flavor_names = sorted(snapshot.resource_flavors)
    flavor_index = {n: i for i, n in enumerate(flavor_names)}

    resources = set()
    max_groups = 1
    max_slots = 1
    for cq in snapshot.cluster_queues.values():
        max_groups = max(max_groups, len(cq.resource_groups))
        for rg in cq.resource_groups:
            resources.update(rg.covered_resources)
            max_slots = max(max_slots, len(rg.flavors))
    resource_names = sorted(resources)
    resource_index = {n: i for i, n in enumerate(resource_names)}

    C, F, R = len(cq_names), len(flavor_names), len(resource_names)
    G, S = max_groups, max_slots

    nominal = np.zeros((C, F, R), dtype=np.int64)
    borrow_limit = np.full((C, F, R), NO_LIMIT, dtype=np.int64)
    guaranteed = np.zeros((C, F, R), dtype=np.int64)
    lendable = np.zeros((C, F, R), dtype=np.int64)
    cohort_id = np.zeros(C, dtype=np.int32)
    group_of_resource = np.full((C, R), -1, dtype=np.int32)
    slot_flavor = np.full((C, G, S), -1, dtype=np.int32)
    num_flavors = np.zeros((C, G), dtype=np.int32)
    bwc_enabled = np.zeros(C, dtype=bool)
    borrow_is_borrow = np.zeros(C, dtype=bool)
    preempt_is_preempt = np.zeros(C, dtype=bool)

    lending_on = features.enabled(features.LENDING_LIMIT)

    cohort_names: List[str] = []
    cohort_idx: Dict[str, int] = {}
    for ci, name in enumerate(cq_names):
        cq = snapshot.cluster_queues[name]
        cohort = cq.cohort.name if cq.cohort is not None else f"__solo__/{name}"
        if cohort not in cohort_idx:
            cohort_idx[cohort] = len(cohort_names)
            cohort_names.append(cohort)
        cohort_id[ci] = cohort_idx[cohort]

        bwc = cq.preemption.borrow_within_cohort
        # Fair sharing implies preempt-while-borrowing (see referee
        # _fits_resource_quota).
        bwc_enabled[ci] = (
            (bwc is not None and bwc.policy != BorrowWithinCohortPolicy.NEVER)
            or features.enabled(features.FAIR_SHARING))
        borrow_is_borrow[ci] = (cq.flavor_fungibility.when_can_borrow
                                == FlavorFungibilityPolicy.BORROW)
        preempt_is_preempt[ci] = (cq.flavor_fungibility.when_can_preempt
                                  == FlavorFungibilityPolicy.PREEMPT)

        for gi, rg in enumerate(cq.resource_groups):
            num_flavors[ci, gi] = len(rg.flavors)
            for r in rg.covered_resources:
                group_of_resource[ci, resource_index[r]] = gi
            for si, fquotas in enumerate(rg.flavors):
                fi = flavor_index.get(fquotas.name, -1)
                slot_flavor[ci, gi, si] = fi
                if fi < 0:
                    continue
                for rname, quota in fquotas.resources:
                    ri = resource_index[rname]
                    nominal[ci, fi, ri] = quota.nominal
                    if quota.borrowing_limit is not None:
                        borrow_limit[ci, fi, ri] = quota.borrowing_limit
                    if lending_on and quota.lending_limit is not None:
                        lendable[ci, fi, ri] = quota.lending_limit
                        guaranteed[ci, fi, ri] = quota.nominal - quota.lending_limit
                    else:
                        lendable[ci, fi, ri] = quota.nominal

    return CQEncoding(
        cq_names=cq_names, cq_index=cq_index,
        flavor_names=flavor_names, flavor_index=flavor_index,
        resource_names=resource_names, resource_index=resource_index,
        cohort_names=cohort_names,
        nominal=nominal, borrow_limit=borrow_limit, guaranteed=guaranteed,
        lendable=lendable, cohort_id=cohort_id,
        group_of_resource=group_of_resource, slot_flavor=slot_flavor,
        num_flavors=num_flavors, bwc_enabled=bwc_enabled,
        borrow_policy_is_borrow=borrow_is_borrow,
        preempt_policy_is_preempt=preempt_is_preempt,
        num_cohorts=len(cohort_names), num_groups=G, num_slots=S,
    )


def encode_usage(snapshot: Snapshot, enc: CQEncoding) -> UsageTensors:
    C = len(enc.cq_names)
    F = len(enc.flavor_names)
    R = len(enc.resource_names)
    usage = np.zeros((C, F, R), dtype=np.int64)
    for ci, name in enumerate(enc.cq_names):
        cq = snapshot.cluster_queues[name]
        for fname, resources in cq.usage.items():
            fi = enc.flavor_index.get(fname)
            if fi is None:
                continue
            for rname, val in resources.items():
                ri = enc.resource_index.get(rname)
                if ri is not None:
                    usage[ci, fi, ri] = val
    above_guaranteed = np.maximum(usage - enc.guaranteed, 0)
    cohort_usage = np.zeros((enc.num_cohorts, F, R), dtype=np.int64)
    np.add.at(cohort_usage, enc.cohort_id, above_guaranteed)
    return UsageTensors(
        usage=usage,
        cohort_usage=cohort_usage,
        cohort_requestable=enc.cohort_requestable(),
    )


def encode_workloads(workloads: Sequence[WorkloadInfo], snapshot: Snapshot,
                     enc: CQEncoding,
                     counts: Optional[Sequence[Optional[Sequence[int]]]] = None,
                     pad_to: Optional[int] = None) -> WorkloadTensors:
    """Encode pending workloads against the CQ encoding.

    Taint/affinity eligibility and the resume-from-last-flavor slot are
    computed here, host-side. `counts` optionally overrides pod counts per
    workload (partial admission).
    """
    n = len(workloads)
    W = pad_to if pad_to is not None else _pad_pow2(max(n, 1))
    P = 1
    for wi in workloads:
        P = max(P, len(wi.total_requests))
    F = len(enc.flavor_names)
    R = len(enc.resource_names)
    G = enc.num_groups

    S = enc.num_slots
    wl_cq = np.zeros(W, dtype=np.int32)
    req = np.zeros((W, P, R), dtype=np.int64)
    has_req = np.zeros((W, P, R), dtype=bool)
    podset_valid = np.zeros((W, P), dtype=bool)
    podset_unsat = np.zeros((W, P), dtype=bool)
    elig = np.zeros((W, P, G, S), dtype=bool)
    resume_slot = np.zeros((W, P, G), dtype=np.int32)
    wl_valid = np.zeros(W, dtype=bool)

    for w, wi in enumerate(workloads):
        cq = snapshot.cluster_queues[wi.cluster_queue]
        ci = enc.cq_index[wi.cluster_queue]
        wl_cq[w] = ci
        wl_valid[w] = True

        # Stale resume state is dropped exactly like the referee
        # (flavorassigner.go:244-247).
        last = wi.last_assignment
        if last is not None:
            outdated = (cq.allocatable_generation > last.cluster_queue_generation
                        or (cq.cohort is not None
                            and cq.cohort.allocatable_generation
                            > last.cohort_generation))
            if outdated:
                last = None

        totals = wi.total_requests
        if counts is not None and counts[w] is not None:
            totals = [totals[i].scaled_to(c) for i, c in enumerate(counts[w])]

        group_keys = [cq.label_keys(rg, snapshot.resource_flavors)
                      for rg in cq.resource_groups]

        for p, ps in enumerate(totals):
            podset_valid[w, p] = True
            requests = dict(ps.requests)
            if PODS_RESOURCE in cq.rg_by_resource:
                requests[PODS_RESOURCE] = ps.count
            for rname, val in requests.items():
                ri = enc.resource_index.get(rname)
                if ri is None:
                    # A resource outside the global vocabulary is covered by
                    # no CQ: the podset can never be satisfied.
                    podset_unsat[w, p] = True
                    continue
                req[w, p, ri] = val
                has_req[w, p, ri] = True

            # Eligibility per (group, slot): each group's label keys scope
            # the affinity match.
            podset = wi.obj.pod_sets[p]
            for gi, rg in enumerate(cq.resource_groups):
                for si, fquotas in enumerate(rg.flavors):
                    flavor = snapshot.resource_flavors.get(fquotas.name)
                    if flavor is None:
                        continue
                    ok, _ = flavor_eligible(podset, flavor, group_keys[gi])
                    elig[w, p, gi, si] = ok
                # Resume slot for this group: any covered requested
                # resource carries the group's shared index.
                if last is not None:
                    for rname in rg.covered_resources:
                        if rname in requests:
                            resume_slot[w, p, gi] = \
                                last.next_flavor_to_try(p, rname)
                            break

    return WorkloadTensors(
        wl_cq=wl_cq, req=req, has_req=has_req, podset_valid=podset_valid,
        podset_unsat=podset_unsat, elig=elig, resume_slot=resume_slot,
        wl_valid=wl_valid, num_real=n)
