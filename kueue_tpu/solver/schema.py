"""Dense tensor encoding of the per-tick admission problem.

This replaces the reference's per-workload pointer-chasing over the cache
snapshot (pkg/cache/snapshot.go + flavorassigner's per-flavor loops) with a
TPU-friendly dense layout: every quantity is an integer tensor indexed by a
global (ClusterQueue, Flavor, Resource) vocabulary, so the whole batch of
pending workloads is solved by one XLA program
(`kueue_tpu.models.flavor_fit`).

Axes:
  W  workloads (padded to a bucket size)
  P  pod sets per workload (padded)
  C  cluster queues
  F  flavors   (global vocabulary)
  R  resources (global vocabulary)
  G  resource groups per CQ (padded)
  S  flavor slots per group (padded); slot order is the assignment
     preference order
  K  cohorts (every CQ belongs to one; cohort-less CQs get singletons,
     which is arithmetically identical -- see fits math in the model)

The "string world" (taints, tolerations, node affinity, namespace
selectors) never reaches the device: it is folded into the boolean
eligibility mask `elig[W,P,F]` here on the host
(reference: flavorassigner.go:396-410 and :498-542).

All quantities are int64 (canonical units); NO_LIMIT encodes a nil
borrowingLimit.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


import numpy as np

from kueue_tpu import features
from kueue_tpu import knobs
from kueue_tpu.api.types import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
)
from kueue_tpu.core.cache import CachedClusterQueue
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.solver.eligibility import flavor_eligible

PODS_RESOURCE = "pods"

# Large sentinel for "no borrowing limit"; keeps nominal+limit < 2^63.
NO_LIMIT = np.int64(1) << 62


@dataclass
class HierarchyEncoding:
    """Dense encoding of a hierarchical cohort forest (KEP-79).

    Nodes are every cohort reachable from a member ClusterQueue (including
    spec-only ancestors). The per-tick T values are computed ON DEVICE from
    the usage tensor: leaf contributions via one segment-sum, then one
    clamped scatter-add per tree level (deepest first); the per-workload
    feasibility is a D-step delta walk along `cq_path`
    (core/hierarchy.py is the host referee for these semantics).
    """

    node_names: List[str]
    node_own_nominal: np.ndarray   # [K2,F,R] i64
    node_blim: np.ndarray          # [K2,F,R] i64 (NO_LIMIT; 0 at roots)
    node_lend: np.ndarray          # [K2,F,R] i64 (NO_LIMIT when unset)
    cq_node: np.ndarray            # [C] i32: direct cohort node, -1 none
    cq_lend: np.ndarray            # [C,F,R] i64 (NO_LIMIT when unset)
    cq_hier: np.ndarray            # [C] bool: CQ is in a hierarchical tree
    cq_path: np.ndarray            # [C,D] i32 ancestor nodes, -1 padded
    # Per tree level, deepest first: (nodes, parents) index arrays for the
    # bottom-up T aggregation.
    levels: List[Tuple[np.ndarray, np.ndarray]]


def _pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass
class CQEncoding:
    """Static (per-generation) encoding of the ClusterQueue/cohort side."""

    cq_names: List[str]
    cq_index: Dict[str, int]
    flavor_names: List[str]
    flavor_index: Dict[str, int]
    resource_names: List[str]
    resource_index: Dict[str, int]
    cohort_names: List[str]

    nominal: np.ndarray        # [C,F,R] i64
    borrow_limit: np.ndarray   # [C,F,R] i64 (NO_LIMIT when nil)
    guaranteed: np.ndarray     # [C,F,R] i64 (0 unless LendingLimit)
    lendable: np.ndarray       # [C,F,R] i64 (lendingLimit if set+enabled else nominal)
    cohort_id: np.ndarray      # [C] i32
    group_of_resource: np.ndarray  # [C,R] i32, -1 when not covered
    slot_flavor: np.ndarray    # [C,G,S] i32 global flavor idx, -1 pad
    num_flavors: np.ndarray    # [C,G] i32
    bwc_enabled: np.ndarray    # [C] bool
    borrow_policy_is_borrow: np.ndarray    # [C] bool (whenCanBorrow == Borrow)
    preempt_policy_is_preempt: np.ndarray  # [C] bool (whenCanPreempt == Preempt)
    configured: np.ndarray     # [C,F,R] bool: the (flavor,resource) pairs the
    #                            CQ tracks usage for (clusterqueue.go:473-485)
    # Hierarchical cohort forest (None when every cohort is flat).
    hier: Optional["HierarchyEncoding"]

    num_cohorts: int
    num_groups: int
    num_slots: int

    # Lazy memos (the encoding is immutable once built).
    # Per-CQ eligibility [G,S] for "trivial" podsets (no tolerations, node
    # selectors or affinity terms) — the common case; _encode_row copies
    # this instead of running the per-flavor string matching.
    _trivial_elig: Dict[str, np.ndarray] = field(
        default_factory=dict, repr=False, compare=False)
    # Stacked [C,G,S] view of the trivial masks (lazily filled row by row
    # alongside _trivial_elig) + per-CQ fill flags: encode_workloads
    # gathers all simple workloads' eligibility in ONE fancy-index read.
    _trivial_stack: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    _trivial_filled: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    _cohort_requestable: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    _cohort_perm: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    _cohort_starts: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False)
    # Per-CQ (flavor, [(resource, flat fr index)]) walk plan for the
    # mirror's arena flush: the usage-dict KEY SET is fixed per
    # structure (CachedClusterQueue.update materializes every configured
    # pair; accounting only mutates values), so the name->index
    # resolution is done once per CQ per encoding generation.
    _flush_pairs: Dict[int, list] = field(
        default_factory=dict, repr=False, compare=False)

    def flush_pairs(self, ci: int, cq) -> list:
        pairs = self._flush_pairs.get(ci)
        if pairs is None:
            R = len(self.resource_names)
            pairs = []
            for fname, resources in cq.usage.items():
                fi = self.flavor_index.get(fname)
                if fi is None:
                    continue
                row = [(rname, fi * R + self.resource_index[rname])
                       for rname in resources
                       if rname in self.resource_index]
                if row:
                    pairs.append((fname, row))
            self._flush_pairs[ci] = pairs
        return pairs

    def _cohort_sort(self):
        """Members sorted by cohort id, for C-speed segment reductions."""
        if self._cohort_perm is None:
            perm = np.argsort(self.cohort_id, kind="stable")
            sorted_ids = self.cohort_id[perm]
            starts = np.searchsorted(sorted_ids, np.arange(self.num_cohorts))
            self._cohort_perm = perm
            self._cohort_starts = starts
        return self._cohort_perm, self._cohort_starts

    def cohort_sum(self, per_cq: np.ndarray) -> np.ndarray:
        """[C,...] -> [K,...] sum over cohort members."""
        perm, starts = self._cohort_sort()
        return np.add.reduceat(per_cq[perm], starts, axis=0)

    def cohort_requestable(self) -> np.ndarray:
        """[K,F,R] sum of members' lendable quota (snapshot.go:160-178)."""
        if self._cohort_requestable is None:
            self._cohort_requestable = self.cohort_sum(self.lendable)
        return self._cohort_requestable


class UsageTensors:
    """The fast-changing side: per-CQ usage and its cohort aggregation.

    The cohort aggregates are lazy: the packed device kernel recomputes them
    on device (segment_sum in `_solve_kernel_packed`), so the per-tick
    dispatch path never touches them host-side; consumers that do read them
    (fair-share scoring, the unpacked kernel entry) pay on first access."""

    __slots__ = ("usage", "_enc", "_cohort_usage", "_cohort_requestable")

    def __init__(self, usage: np.ndarray, enc: Optional["CQEncoding"] = None,
                 cohort_usage: Optional[np.ndarray] = None,
                 cohort_requestable: Optional[np.ndarray] = None):
        self.usage = usage            # [C,F,R] i64
        self._enc = enc
        self._cohort_usage = cohort_usage
        self._cohort_requestable = cohort_requestable

    @property
    def cohort_usage(self) -> np.ndarray:
        """[K,F,R] i64: sum of max(0, usage-guaranteed) over members."""
        if self._cohort_usage is None:
            above = np.maximum(self.usage - self._enc.guaranteed, 0)
            self._cohort_usage = self._enc.cohort_sum(above)
        return self._cohort_usage

    @property
    def cohort_requestable(self) -> np.ndarray:
        """[K,F,R] i64 (snapshot.go:160-178)."""
        if self._cohort_requestable is None:
            self._cohort_requestable = self._enc.cohort_requestable()
        return self._cohort_requestable


@dataclass
class WorkloadTensors:
    """The batch of pending workloads to solve."""

    wl_cq: np.ndarray        # [W] i32
    req: np.ndarray          # [W,P,R] i64
    has_req: np.ndarray      # [W,P,R] bool
    podset_valid: np.ndarray  # [W,P] bool
    podset_unsat: np.ndarray  # [W,P] bool (requests a resource outside the vocab)
    # Eligibility is per (group, slot): affinity matching is restricted to
    # each group's label keys, so one flavor can be eligible in one group
    # and ineligible in another (flavorassigner.go:498-542).
    elig: np.ndarray         # [W,P,G,S] bool
    resume_slot: np.ndarray  # [W,P,G] i32 (first slot to try)
    wl_valid: np.ndarray     # [W] bool (padding rows are False)
    num_real: int


def encode_cluster_queues(snapshot: Snapshot) -> CQEncoding:
    cq_names = sorted(snapshot.cluster_queues)
    cq_index = {n: i for i, n in enumerate(cq_names)}
    flavor_names = sorted(snapshot.resource_flavors)
    flavor_index = {n: i for i, n in enumerate(flavor_names)}

    resources = set()
    max_groups = 1
    max_slots = 1
    for cq in snapshot.cluster_queues.values():
        max_groups = max(max_groups, len(cq.resource_groups))
        for rg in cq.resource_groups:
            resources.update(rg.covered_resources)
            max_slots = max(max_slots, len(rg.flavors))
    resource_names = sorted(resources)
    resource_index = {n: i for i, n in enumerate(resource_names)}

    C, F, R = len(cq_names), len(flavor_names), len(resource_names)
    G, S = max_groups, max_slots

    nominal = np.zeros((C, F, R), dtype=np.int64)
    borrow_limit = np.full((C, F, R), NO_LIMIT, dtype=np.int64)
    guaranteed = np.zeros((C, F, R), dtype=np.int64)
    lendable = np.zeros((C, F, R), dtype=np.int64)
    configured = np.zeros((C, F, R), dtype=bool)
    cohort_id = np.zeros(C, dtype=np.int32)
    group_of_resource = np.full((C, R), -1, dtype=np.int32)
    slot_flavor = np.full((C, G, S), -1, dtype=np.int32)
    num_flavors = np.zeros((C, G), dtype=np.int32)
    bwc_enabled = np.zeros(C, dtype=bool)
    borrow_is_borrow = np.zeros(C, dtype=bool)
    preempt_is_preempt = np.zeros(C, dtype=bool)

    lending_on = features.enabled(features.LENDING_LIMIT)

    cohort_names: List[str] = []
    cohort_idx: Dict[str, int] = {}
    for ci, name in enumerate(cq_names):
        cq = snapshot.cluster_queues[name]
        cohort = cq.cohort.name if cq.cohort is not None else f"__solo__/{name}"
        if cohort not in cohort_idx:
            cohort_idx[cohort] = len(cohort_names)
            cohort_names.append(cohort)
        cohort_id[ci] = cohort_idx[cohort]

        bwc = cq.preemption.borrow_within_cohort
        # Fair sharing implies preempt-while-borrowing (see referee
        # _fits_resource_quota).
        bwc_enabled[ci] = (
            (bwc is not None and bwc.policy != BorrowWithinCohortPolicy.NEVER)
            or features.enabled(features.FAIR_SHARING))
        borrow_is_borrow[ci] = (cq.flavor_fungibility.when_can_borrow
                                == FlavorFungibilityPolicy.BORROW)
        preempt_is_preempt[ci] = (cq.flavor_fungibility.when_can_preempt
                                  == FlavorFungibilityPolicy.PREEMPT)

        for gi, rg in enumerate(cq.resource_groups):
            num_flavors[ci, gi] = len(rg.flavors)
            for r in rg.covered_resources:
                group_of_resource[ci, resource_index[r]] = gi
            for si, fquotas in enumerate(rg.flavors):
                fi = flavor_index.get(fquotas.name, -1)
                slot_flavor[ci, gi, si] = fi
                if fi < 0:
                    continue
                for rname, quota in fquotas.resources:
                    ri = resource_index[rname]
                    configured[ci, fi, ri] = True
                    nominal[ci, fi, ri] = quota.nominal
                    if quota.borrowing_limit is not None:
                        borrow_limit[ci, fi, ri] = quota.borrowing_limit
                    if lending_on and quota.lending_limit is not None:
                        lendable[ci, fi, ri] = quota.lending_limit
                        guaranteed[ci, fi, ri] = quota.nominal - quota.lending_limit
                    else:
                        lendable[ci, fi, ri] = quota.nominal

    return CQEncoding(
        cq_names=cq_names, cq_index=cq_index,
        flavor_names=flavor_names, flavor_index=flavor_index,
        resource_names=resource_names, resource_index=resource_index,
        cohort_names=cohort_names,
        nominal=nominal, borrow_limit=borrow_limit, guaranteed=guaranteed,
        lendable=lendable, cohort_id=cohort_id,
        group_of_resource=group_of_resource, slot_flavor=slot_flavor,
        num_flavors=num_flavors, bwc_enabled=bwc_enabled,
        borrow_policy_is_borrow=borrow_is_borrow,
        preempt_policy_is_preempt=preempt_is_preempt,
        configured=configured,
        hier=_encode_hierarchy(snapshot, cq_names, flavor_index,
                               resource_index, F, R),
        num_cohorts=len(cohort_names), num_groups=G, num_slots=S,
    )


def _encode_hierarchy(snapshot: Snapshot, cq_names: List[str],
                      flavor_index: Dict[str, int],
                      resource_index: Dict[str, int],
                      F: int, R: int) -> Optional[HierarchyEncoding]:
    """Dense cohort-forest encoding; None when every cohort is flat."""
    cohorts = {}
    hier_cqs = []
    roots = {}
    for name in cq_names:
        cohort = snapshot.cluster_queues[name].cohort
        if cohort is None:
            continue
        if cohort.is_hierarchical():
            hier_cqs.append(name)
        root = cohort.root()
        roots.setdefault(root.name, root)
    if not hier_cqs:
        return None
    # Whole trees, downward from each root: spec-only subtrees carrying
    # quota but no member CQs still contribute to the T aggregation.
    stack = list(roots.values())
    while stack:
        node = stack.pop()
        cohorts.setdefault(node.name, node)
        stack.extend(node.children)

    node_names = sorted(cohorts)
    node_index = {n: i for i, n in enumerate(node_names)}
    K2 = len(node_names)
    own_nominal = np.zeros((K2, F, R), dtype=np.int64)
    blim = np.full((K2, F, R), NO_LIMIT, dtype=np.int64)
    lend = np.full((K2, F, R), NO_LIMIT, dtype=np.int64)
    depth = np.zeros(K2, dtype=np.int32)
    parent = np.full(K2, -1, dtype=np.int32)
    for ni, name in enumerate(node_names):
        node = cohorts[name]
        if node.parent is not None:
            parent[ni] = node_index[node.parent.name]
        d = 0
        p = node.parent
        while p is not None:
            d += 1
            p = p.parent
        depth[ni] = d
        if node.spec is not None:
            for rg in node.spec.resource_groups:
                for fq in rg.flavors:
                    fi = flavor_index.get(fq.name)
                    if fi is None:
                        continue
                    for rname, quota in fq.resources:
                        ri = resource_index.get(rname)
                        if ri is None:
                            continue
                        own_nominal[ni, fi, ri] = quota.nominal
                        if quota.borrowing_limit is not None:
                            blim[ni, fi, ri] = quota.borrowing_limit
                        if quota.lending_limit is not None:
                            lend[ni, fi, ri] = quota.lending_limit
        if node.parent is None:
            # A root cannot borrow from anyone above (KEP-79 API comment).
            blim[ni] = 0

    C = len(cq_names)
    cq_node = np.full(C, -1, dtype=np.int32)
    cq_lend = np.full((C, F, R), NO_LIMIT, dtype=np.int64)
    cq_hier = np.zeros(C, dtype=bool)
    max_depth = int(depth.max()) + 1
    cq_path = np.full((C, max_depth), -1, dtype=np.int32)
    for ci, name in enumerate(cq_names):
        cq = snapshot.cluster_queues[name]
        if cq.cohort is None:
            continue
        cq_node[ci] = node_index[cq.cohort.name]
        cq_hier[ci] = cq.cohort.is_hierarchical()
        node = cq.cohort
        d = 0
        while node is not None:
            cq_path[ci, d] = node_index[node.name]
            node = node.parent
            d += 1
        if not cq_hier[ci]:
            continue
        # CQ-level lending limits participate in the tree math whenever the
        # tree is hierarchical (core/hierarchy.py _cq_t).
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                fi = flavor_index.get(fq.name)
                if fi is None:
                    continue
                for rname, quota in fq.resources:
                    ri = resource_index.get(rname)
                    if ri is not None and quota.lending_limit is not None:
                        cq_lend[ci, fi, ri] = quota.lending_limit

    levels: List[Tuple[np.ndarray, np.ndarray]] = []
    for d in range(max_depth - 1, 0, -1):
        nodes = np.nonzero(depth == d)[0].astype(np.int32)
        if len(nodes):
            levels.append((nodes, parent[nodes]))

    return HierarchyEncoding(
        node_names=node_names, node_own_nominal=own_nominal,
        node_blim=blim, node_lend=lend, cq_node=cq_node, cq_lend=cq_lend,
        cq_hier=cq_hier, cq_path=cq_path, levels=levels)


def encode_usage(snapshot: Snapshot, enc: CQEncoding) -> UsageTensors:
    C = len(enc.cq_names)
    F = len(enc.flavor_names)
    R = len(enc.resource_names)
    usage = np.zeros((C, F, R), dtype=np.int64)
    for ci, name in enumerate(enc.cq_names):
        cq = snapshot.cluster_queues[name]
        for fname, resources in cq.usage.items():
            fi = enc.flavor_index.get(fname)
            if fi is None:
                continue
            for rname, val in resources.items():
                ri = enc.resource_index.get(rname)
                if ri is not None:
                    usage[ci, fi, ri] = val
    return UsageTensors(usage, enc)


class UsageEncoder:
    """Incremental [C,F,R] usage tensor keyed on cache usage versions.

    The reference deep-copies every ClusterQueue's usage maps on every tick
    (snapshot.go:95-129) — the scaling hazard SURVEY §6 calls out at 50k
    workloads. Here the dense usage tensor persists across ticks and only
    rows whose `usage_version` moved since the last refresh are re-read from
    the snapshot; cohort aggregates are recomputed vectorized only when
    something changed.

    `apply_delta` is the scheduler's fast path: an admission's exact usage
    delta (Assignment.usage) is applied to the row and the version advanced
    in lockstep with the cache's single bump from assume/forget
    (cache.go:498-546), so the next refresh sees a clean hit. Any drift
    falls back to a full row re-read — versions, not trust, decide.
    """

    # When true (KUEUE_TPU_DEBUG_DRIFT=1, or set per-instance), every
    # refresh re-reads ALL rows and asserts the incrementally-maintained
    # tensor matches — catches any apply_delta/version drift at the cost
    # of the full encode this class exists to avoid. Debug builds only.
    debug_verify = knobs.flag("KUEUE_TPU_DEBUG_DRIFT")

    def __init__(self, enc: CQEncoding):
        self.enc = enc
        C, F, R = enc.nominal.shape
        self.usage = np.zeros((C, F, R), dtype=np.int64)
        self._versions: List[Optional[int]] = [None] * C
        # Usage-dependency generations for the fingerprinted nominate
        # cache: one counter per cohort (a head's fit can read every
        # member row of its cohort — the device kernel segment-sums them)
        # bumped on ANY member-row movement, plus one global counter for
        # hierarchical trees (a tree walk can read nodes across the
        # forest, so hier heads key on everything moving or nothing).
        self.cohort_gens = np.zeros(enc.num_cohorts + 1, dtype=np.int64)
        self.global_gen = 0

    def _bump_gen(self, ci: int) -> None:
        self.cohort_gens[self.enc.cohort_id[ci]] += 1
        self.global_gen += 1

    def verify(self, snapshot: Snapshot) -> None:
        """Assert the incremental tensor equals a from-scratch encode.
        Raises AssertionError naming the drifted ClusterQueues."""
        fresh = encode_usage(snapshot, self.enc).usage
        if np.array_equal(fresh, self.usage):
            return
        bad = [self.enc.cq_names[ci]
               for ci in np.nonzero((fresh != self.usage).any(axis=(1, 2)))[0]]
        raise AssertionError(
            f"UsageEncoder drift: incremental usage rows for {bad} do not "
            "match the snapshot (apply_delta out of lockstep with the "
            "cache version bump)")

    def refresh(self, snapshot: Snapshot) -> UsageTensors:
        enc = self.enc
        flavor_index = enc.flavor_index
        resource_index = enc.resource_index
        versions = self._versions
        usage = self.usage
        for ci, name in enumerate(enc.cq_names):
            cq = snapshot.cluster_queues[name]
            if cq.usage_version == versions[ci]:
                continue
            row = usage[ci]
            old_row = row.copy()
            row[:] = 0
            for fname, resources in cq.usage.items():
                fi = flavor_index.get(fname)
                if fi is None:
                    continue
                frow = row[fi]
                for rname, val in resources.items():
                    ri = resource_index.get(rname)
                    if ri is not None:
                        frow[ri] = val
            if not np.array_equal(row, old_row):
                # Generations track usage VALUES, not version churn: the
                # preemption simulation's remove/add pairs (and any other
                # restore-exactly mutation) bump versions while leaving
                # the row intact — a head's fit verdict only depends on
                # the values, so its fingerprint must not move.
                self._bump_gen(ci)
            versions[ci] = cq.usage_version
        if self.debug_verify:
            # After the loop every row claims to be current; any mismatch
            # is a version-skipped row that drifted (apply_delta bug).
            self.verify(snapshot)
        return UsageTensors(usage, enc)

    def apply_delta(self, cq_name: str, frq, sign: int = 1) -> None:
        """Fold one workload's usage (Assignment.usage) into the tensor,
        mirroring the cache mutation of assume/forget."""
        enc = self.enc
        ci = enc.cq_index.get(cq_name)
        if ci is None:
            return
        self._bump_gen(ci)
        row = self.usage[ci]
        conf = enc.configured[ci]
        for fname, resources in frq.items():
            fi = enc.flavor_index.get(fname)
            if fi is None:
                continue
            for rname, val in resources.items():
                ri = enc.resource_index.get(rname)
                # Only configured pairs are tracked (clusterqueue.go:473-485).
                if ri is not None and conf[fi, ri]:
                    row[fi, ri] += sign * val
        if self._versions[ci] is not None:
            self._versions[ci] += 1

    def apply_delta_batch(self, items, sign: int = 1) -> None:
        """Fold a whole cycle's workload usages into the tensor with ONE
        scatter-add — the bulk twin of apply_delta for the end-of-cycle
        admission commit. `items` rows are (cq_name, frq) or
        (cq_name, frq, usage_idx): index-carrying rows (the batched
        decode's integer coordinates) skip the name→index walks; their
        frq may be None."""
        enc = self.enc
        cq_index = enc.cq_index
        f_index = enc.flavor_index
        r_index = enc.resource_index
        configured = enc.configured
        cis: list = []
        fis: list = []
        ris: list = []
        vals: list = []
        versions = self._versions
        for item in items:
            idx = item[2] if len(item) > 2 else None
            cq_name = item[0]
            ci = cq_index.get(cq_name)
            if ci is None:
                continue
            # One version bump per workload, matching the cache's
            # usage_version bump per assume — the refresh compares the
            # two for the row-skip fast path.
            if versions[ci] is not None:
                versions[ci] += 1
            self._bump_gen(ci)
            if idx is not None:
                i_f, i_r, i_v = idx
                k = len(i_f)
                cis.extend([ci] * k)
                fis.extend(i_f)
                ris.extend(i_r)
                vals.extend(i_v if sign == 1 else [sign * v for v in i_v])
                continue
            conf = configured[ci]
            for fname, resources in item[1].items():
                fi = f_index.get(fname)
                if fi is None:
                    continue
                for rname, val in resources.items():
                    ri = r_index.get(rname)
                    if ri is not None and conf[fi, ri]:
                        cis.append(ci)
                        fis.append(fi)
                        ris.append(ri)
                        vals.append(sign * val)
        if cis:
            ci_a = np.asarray(cis)
            fi_a = np.asarray(fis)
            ri_a = np.asarray(ris)
            # Only configured (flavor,resource) pairs are tracked
            # (clusterqueue.go:473-485); dict-walk rows were gated inline
            # and pass trivially.
            m = configured[ci_a, fi_a, ri_a]
            if m.all():
                np.add.at(self.usage, (ci_a, fi_a, ri_a), vals)
            else:
                np.add.at(self.usage, (ci_a[m], fi_a[m], ri_a[m]),
                          np.asarray(vals)[m])

    def apply_batch(self, delta: np.ndarray, cq_indices: np.ndarray) -> None:
        """Fold a whole tick's admitted usage (models/flavor_fit.py
        fit_usage_delta) into the tensor: one vectorized add, one version
        advance per touched ClusterQueue."""
        self.usage += delta
        versions = self._versions
        for ci in cq_indices.tolist():
            if versions[ci] is not None:
                versions[ci] += 1
            self._bump_gen(ci)


class _Row:
    """One workload's usage-independent encoded columns (cacheable)."""

    __slots__ = ("ci", "req", "has_req", "unsat", "elig",
                 "requests_per_podset")

    def __init__(self, ci, req, has_req, unsat, elig,
                 requests_per_podset):
        self.ci = ci
        self.req = req                      # [p, R] int64
        self.has_req = has_req              # [p, R] bool
        self.unsat = unsat                  # [p] bool
        self.elig = elig                    # [p, G, S] bool
        # resource-name presence per podset, for the resume-slot walk
        self.requests_per_podset = requests_per_podset


def _encode_row(wi: WorkloadInfo, cq, snapshot: Snapshot, enc: CQEncoding,
                totals) -> _Row:
    R = len(enc.resource_names)
    G = enc.num_groups
    S = enc.num_slots
    p_count = len(totals)
    req = np.zeros((p_count, R), dtype=np.int64)
    has_req = np.zeros((p_count, R), dtype=bool)
    unsat = np.zeros(p_count, dtype=bool)
    elig = np.zeros((p_count, G, S), dtype=bool)
    requests_per_podset = []

    group_keys = None
    for p, ps in enumerate(totals):
        requests = dict(ps.requests)
        if PODS_RESOURCE in cq.rg_by_resource:
            requests[PODS_RESOURCE] = ps.count
        requests_per_podset.append(frozenset(requests))
        for rname, val in requests.items():
            ri = enc.resource_index.get(rname)
            if ri is None:
                # A resource outside the global vocabulary is covered by
                # no CQ: the podset can never be satisfied.
                unsat[p] = True
                continue
            req[p, ri] = val
            has_req[p, ri] = True

        # Eligibility per (group, slot): each group's label keys scope
        # the affinity match. A podset with no tolerations / selectors /
        # affinity (the common case) shares the CQ's precomputed trivial
        # mask — only flavor taints matter for it, and those are
        # podset-independent.
        podset = wi.obj.pod_sets[p]
        if not (podset.tolerations or podset.node_selector
                or podset.affinity_terms):
            elig[p] = _trivial_elig(cq, snapshot, enc)
            continue
        if group_keys is None:
            group_keys = [cq.label_keys(rg, snapshot.resource_flavors)
                          for rg in cq.resource_groups]
        for gi, rg in enumerate(cq.resource_groups):
            for si, fquotas in enumerate(rg.flavors):
                flavor = snapshot.resource_flavors.get(fquotas.name)
                if flavor is None:
                    continue
                ok, _ = flavor_eligible(podset, flavor, group_keys[gi])
                elig[p, gi, si] = ok
    return _Row(enc.cq_index[wi.cluster_queue], req, has_req, unsat,
                elig, requests_per_podset)


_EMPTY_PODSET = None


def _trivial_elig(cq, snapshot: Snapshot, enc: CQEncoding) -> np.ndarray:
    """Per-CQ [G,S] eligibility of a podset with no tolerations/selectors/
    affinity: only the flavors' own taints can exclude it."""
    m = enc._trivial_elig.get(cq.name)
    if m is None:
        global _EMPTY_PODSET
        if _EMPTY_PODSET is None:
            from kueue_tpu.api.types import PodSet
            _EMPTY_PODSET = PodSet(name="", count=1)
        m = np.zeros((enc.num_groups, enc.num_slots), dtype=bool)
        for gi, rg in enumerate(cq.resource_groups):
            keys = cq.label_keys(rg, snapshot.resource_flavors)
            for si, fquotas in enumerate(rg.flavors):
                flavor = snapshot.resource_flavors.get(fquotas.name)
                if flavor is None:
                    continue
                ok, _ = flavor_eligible(_EMPTY_PODSET, flavor, keys)
                m[gi, si] = ok
        enc._trivial_elig[cq.name] = m
        ci = enc.cq_index.get(cq.name)
        if ci is not None:
            if enc._trivial_stack is None:
                enc._trivial_stack = np.zeros(
                    (len(enc.cq_names), enc.num_groups, enc.num_slots),
                    dtype=bool)
                enc._trivial_filled = np.zeros(len(enc.cq_names), dtype=bool)
            enc._trivial_stack[ci] = m
            enc._trivial_filled[ci] = True
    return m


class WorkloadRowCache:
    """Encoded rows keyed by workload identity AND content.

    The eligibility columns are host-side string matching
    (taints/affinity x flavors) — the expensive part of encode_workloads.
    They depend only on the workload's podsets and the CQ structure, so:

    - identity path: a backlog workload re-heading across ticks hits by
      (uid, WorkloadInfo.rev) — rev is a never-recycled monotonic stamp
      (id() addresses are recycled after GC; a strong reference would pin
      finished workloads until the wholesale clear);
    - content path: a NEW workload whose (ClusterQueue, per-podset totals,
      node selectors, affinity, tolerations) signature was encoded before
      shares the existing row — real clusters submit repeated job shapes,
      so steady-state arrival flux encodes each distinct shape once
      instead of once per workload.

    Rows are read-only after construction (encode_workloads only copies
    out of them), so sharing one row across workloads is safe. The cache
    lives for one CQ-encoding generation (structural changes rebuild it).
    """

    MAX_ENTRIES = 200_000  # backstop; cleared wholesale

    def __init__(self):
        self._by_wi: dict = {}       # uid -> (rev, row)
        self._by_content: dict = {}  # content sig -> row

    @staticmethod
    def _sig(wi: WorkloadInfo):
        sig = wi.row_sig
        if sig is None:
            try:
                sig = (wi.cluster_queue, tuple(
                    (t.count, tuple(sorted(t.requests.items())),
                     ps.node_selector, ps.affinity_terms, ps.tolerations)
                    for t, ps in zip(wi.total_requests, wi.obj.pod_sets)))
            except TypeError:
                sig = False  # unhashable custom field; identity path only
            wi.row_sig = sig
        return sig or None

    def get(self, wi: WorkloadInfo) -> Optional[_Row]:
        hit = self._by_wi.get(wi.obj.uid)
        if hit is not None and hit[0] == wi.rev:
            return hit[1]
        sig = self._sig(wi)
        if sig is not None:
            row = self._by_content.get(sig)
            if row is not None:
                self._by_wi[wi.obj.uid] = (wi.rev, row)
                return row
        return None

    def put(self, wi: WorkloadInfo, row: _Row) -> None:
        if len(self._by_wi) >= self.MAX_ENTRIES:
            self._by_wi.clear()
        if len(self._by_content) >= self.MAX_ENTRIES:
            self._by_content.clear()
        self._by_wi[wi.obj.uid] = (wi.rev, row)
        sig = self._sig(wi)
        if sig is not None:
            self._by_content[sig] = row


def encode_workloads(workloads: Sequence[WorkloadInfo], snapshot: Snapshot,
                     enc: CQEncoding,
                     counts: Optional[Sequence[Optional[Sequence[int]]]] = None,
                     pad_to: Optional[int] = None,
                     row_cache: Optional[WorkloadRowCache] = None,
                     min_podsets: int = 1,
                     ) -> WorkloadTensors:
    """Encode pending workloads against the CQ encoding.

    Taint/affinity eligibility and the resume-from-last-flavor slot are
    computed here, host-side. `counts` optionally overrides pod counts per
    workload (partial admission; bypasses the row cache). `min_podsets`
    floors the P axis: the solver passes the largest podset count it has
    seen this encoding generation, so a tick whose batch happens to be
    all single-podset does not shrink P and recompile the kernel (the
    P-axis twin of the W-axis pow2 bucketing; caught by the bench's
    cold-dispatch guard on the cohortlend mix).
    """
    n = len(workloads)
    W = pad_to if pad_to is not None else _pad_pow2(max(n, 1))
    # One pass resolves every workload's totals (memoized property — hoist
    # so the main loop reads the list, not the property again).
    all_totals = [wi.total_requests for wi in workloads]
    P = max(1, min_podsets)
    for t in all_totals:
        if len(t) > P:
            P = len(t)
    R = len(enc.resource_names)
    G = enc.num_groups
    S = enc.num_slots

    wl_cq = np.zeros(W, dtype=np.int32)
    req = np.zeros((W, P, R), dtype=np.int64)
    has_req = np.zeros((W, P, R), dtype=bool)
    podset_valid = np.zeros((W, P), dtype=bool)
    podset_unsat = np.zeros((W, P), dtype=bool)
    elig = np.zeros((W, P, G, S), dtype=bool)
    resume_slot = np.zeros((W, P, G), dtype=np.int32)
    wl_valid = np.zeros(W, dtype=bool)
    wl_valid[:n] = True

    cqs_by_name = snapshot.cluster_queues
    cache_hit = None if row_cache is None else row_cache.get
    cache_put = None if row_cache is None else row_cache.put
    cq_index = enc.cq_index
    r_index = enc.resource_index
    # Fast path (the dominant shape at scale): a workload whose podsets
    # carry no tolerations / node selectors / affinity writes straight
    # into the batch tensors — no per-row numpy allocations, no cache
    # signature — each podset's eligibility is the CQ's cached trivial
    # mask and its requests are 2-3 scalars folded below by ONE
    # fancy-index store. Covers any podset count (real clusters submit
    # mostly selector-free jobs; multi-podset PyTorchJob/JobSet shapes
    # included).
    fast_ws: List[int] = []
    fast_cis: List[int] = []
    trivial_filled = enc._trivial_filled
    t_ws: List[int] = []
    t_ps: List[int] = []
    t_ris: List[int] = []
    t_vals: List[int] = []
    e_ws: List[int] = []
    e_ps: List[int] = []
    e_cis: List[int] = []
    row_ws: List[int] = []
    rows: List[_Row] = []
    rows_append = rows.append
    p_counts: List[int] = []
    pc_append = p_counts.append
    for w, wi in enumerate(workloads):
        cq = cqs_by_name[wi.cluster_queue]
        totals = all_totals[w]
        scaled = counts is not None and counts[w] is not None
        if scaled:
            totals = [totals[i].scaled_to(c) for i, c in enumerate(counts[w])]

        # Stale resume state is dropped exactly like the referee
        # (flavorassigner.go:244-247).
        last = wi.last_assignment
        if last is not None:
            cohort = cq.cohort
            if (cq.allocatable_generation > last.cluster_queue_generation
                    or (cohort is not None
                        and cohort.allocatable_generation
                        > last.cohort_generation)):
                last = None

        if not scaled:
            pod_sets = wi.obj.pod_sets
            for ps in pod_sets:
                if ps.tolerations or ps.node_selector or ps.affinity_terms:
                    break
            else:
                ci = cq_index[wi.cluster_queue]
                fast_ws.append(w)
                fast_cis.append(ci)
                if trivial_filled is None or not trivial_filled[ci]:
                    _trivial_elig(cq, snapshot, enc)  # fills the stack row
                    trivial_filled = enc._trivial_filled
                track_pods = PODS_RESOURCE in cq.rg_by_resource
                groups = cq.resource_groups if last is not None else None
                for p, tp in enumerate(totals):
                    requests = tp.requests
                    e_ws.append(w)
                    e_ps.append(p)
                    e_cis.append(ci)
                    for rname, val in requests.items():
                        ri = r_index.get(rname)
                        if ri is None:
                            podset_unsat[w, p] = True
                            continue
                        t_ws.append(w)
                        t_ps.append(p)
                        t_ris.append(ri)
                        t_vals.append(val)
                    if track_pods:
                        ri = r_index.get(PODS_RESOURCE)
                        if ri is None:
                            podset_unsat[w, p] = True
                        else:
                            t_ws.append(w)
                            t_ps.append(p)
                            t_ris.append(ri)
                            t_vals.append(tp.count)
                    if groups is not None:
                        for gi, rg in enumerate(groups):
                            for rname in rg.covered_resources:
                                if rname in requests or (
                                        track_pods
                                        and rname == PODS_RESOURCE):
                                    resume_slot[w, p, gi] = \
                                        last.next_flavor_to_try(p, rname)
                                    break
                continue

        row = None if scaled or cache_hit is None else cache_hit(wi)
        if row is None:
            row = _encode_row(wi, cq, snapshot, enc, totals)
            if not scaled and cache_put is not None:
                cache_put(wi, row)
        row_ws.append(w)
        rows_append(row)
        p_count = len(totals)
        pc_append(p_count)

        if last is not None:
            for p in range(p_count):
                requested = row.requests_per_podset[p]
                for gi, rg in enumerate(cq.resource_groups):
                    # Resume slot for this group: any covered requested
                    # resource carries the group's shared index.
                    for rname in rg.covered_resources:
                        if rname in requested:
                            resume_slot[w, p, gi] = \
                                last.next_flavor_to_try(p, rname)
                            break

    if fast_ws:
        wl_cq[np.asarray(fast_ws)] = fast_cis
        if e_ws:
            # Guarded separately: a zero-podset workload contributes to
            # fast_ws but no (w, p) rows, and an all-empty batch would
            # fancy-index with float64 arrays.
            ew = np.asarray(e_ws)
            ep = np.asarray(e_ps)
            podset_valid[ew, ep] = True
            elig[ew, ep] = enc._trivial_stack[np.asarray(e_cis)]
        if t_ws:
            tw = np.asarray(t_ws)
            tp_ = np.asarray(t_ps)
            tr = np.asarray(t_ris)
            req[tw, tp_, tr] = t_vals
            has_req[tw, tp_, tr] = True

    # Batched assembly of the cached/slow rows. The common case — every
    # row a single podset — is one np.stack per field instead of six
    # indexed assignments per workload.
    if rows:
        if P == 1 and all(c == 1 for c in p_counts):
            idx = np.asarray(row_ws)
            wl_cq[idx] = [row.ci for row in rows]
            req[idx, 0] = np.stack([row.req[0] for row in rows])
            has_req[idx, 0] = np.stack([row.has_req[0] for row in rows])
            podset_valid[idx, 0] = True
            podset_unsat[idx, 0] = [row.unsat[0] for row in rows]
            elig[idx, 0] = np.stack([row.elig[0] for row in rows])
        else:
            for w, row, p_count in zip(row_ws, rows, p_counts):
                wl_cq[w] = row.ci
                req[w, :p_count] = row.req
                has_req[w, :p_count] = row.has_req
                podset_valid[w, :p_count] = True
                podset_unsat[w, :p_count] = row.unsat
                elig[w, :p_count] = row.elig

    return WorkloadTensors(
        wl_cq=wl_cq, req=req, has_req=has_req, podset_valid=podset_valid,
        podset_unsat=podset_unsat, elig=elig, resume_slot=resume_slot,
        wl_valid=wl_valid, num_real=n)


def batch_usage_csr(out: Dict[str, np.ndarray], wt: WorkloadTensors):
    """Vectorized admission-usage coordinates of a whole solved batch.

    One numpy pass over the solver's output tensors computes, for every
    decoded workload, the deduplicated (cq, flavor, resource) -> value
    usage coordinates that `decode_assignments` builds per-assignment as
    `usage_idx` — in CSR form over the batch:

        (indptr[n+1], ci, fi, ri, val)

    where row w's pairs live at `indptr[w]:indptr[w+1]`. The admission
    cycle's staleness re-validation and the end-of-cycle usage commit
    consume slices of these arrays instead of walking per-workload Python
    lists (the decode/flush loops BENCH_r05 showed interpreter-bound).
    The mask mirrors the decode exactly: podsets past the first failure
    are never counted (flavorassigner.go:323-327), and same-(flavor,
    resource) pairs across podsets are summed like the per-assignment
    dedup."""
    n = wt.num_real
    ps_ok = out["ps_ok"][:n]
    P = ps_ok.shape[1]
    not_ok = ~ps_ok
    has_fail = not_ok.any(axis=1)
    first_fail = np.where(has_fail, not_ok.argmax(axis=1), P)
    res_flavor = out["res_flavor"][:n]
    R = res_flavor.shape[2]
    decode_mask = (ps_ok
                   & (np.arange(P)[None, :] <= first_fail[:, None])
                   )[:, :, None] & (res_flavor >= 0)
    ws, pp, rr = np.nonzero(decode_mask)
    if not len(ws):
        return (np.zeros(n + 1, dtype=np.int64),
                np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    fi = res_flavor[ws, pp, rr].astype(np.int64)
    vals = wt.req[:n][ws, pp, rr]
    F = int(fi.max()) + 1
    key = (ws.astype(np.int64) * F + fi) * R + rr
    ukey, inv = np.unique(key, return_inverse=True)
    # Integer-exact per-pair sum (bincount's float weights would round
    # above 2^53; quantities are canonical int64 units).
    uval = np.zeros(len(ukey), dtype=np.int64)
    np.add.at(uval, inv, vals)
    uw = ukey // (F * R)
    ufi = (ukey // R) % F
    uri = ukey % R
    uci = wt.wl_cq[:n][uw].astype(np.int64)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr, uw + 1, 1)
    np.cumsum(indptr, out=indptr)
    return indptr, uci, ufi, uri, uval


def csr_gather(csr, rows):
    """Concatenate the CSR slices of `rows` (vectorized): returns
    (ent, ci, fi, ri, val) where `ent` maps each pair back to its
    position in `rows`."""
    indptr, ci, fi, ri, val = csr
    rows = np.asarray(rows, dtype=np.int64)
    starts = indptr[rows]
    counts = indptr[rows + 1] - starts
    total = int(counts.sum())
    ent = np.repeat(np.arange(len(rows)), counts)
    if total == 0:
        z = np.empty(0, dtype=np.int64)
        return ent, z, z, z, z
    # Standard CSR multi-slice gather: per output element, its source
    # index = the row's start + the offset within the row.
    cum = np.concatenate(([0], np.cumsum(counts)[:-1]))
    pos = np.repeat(starts - cum, counts) + np.arange(total)
    return ent, ci[pos], fi[pos], ri[pos], val[pos]


class WorkloadArena:
    """Persistent workload tensor arena: the incremental twin of
    `encode_workloads`.

    The per-tick encode rebuilt every head's row from scratch even though
    <1% of the backlog changes between ticks (BENCH_r05: tensorize.encode
    6.7ms of a 60ms tick). The arena keeps one padded row per PENDING
    workload alive across ticks in pooled `[cap,P,R]` request /
    eligibility / cq-index tensors with a free-list of rows, and applies
    per-workload dirty deltas driven by the queue manager's events
    (add/update encode a row, delete frees it, requeue is a no-op — the
    row persists). A tick's batch is then ONE vectorized gather of its
    heads' rows into the canonical `[W,...]` bucket tensors, byte-identical
    to a from-scratch `encode_workloads` (pinned by the differential
    goldens and the `debug_verify` mode below).

    Row validity keys on `(uid, WorkloadInfo.rev)` — the same
    never-recycled identity contract as `WorkloadRowCache`; any
    admission-relevant change flows through the queue manager, which
    re-wraps the workload in a fresh info (new rev) and fires an update
    event. A gather that meets an unknown/stale row simply re-encodes it
    in place (counted in `rows_encoded`, never a correctness event).

    The resume-from-last-flavor slots are per-tick state
    (`wi.last_assignment` moves on every solve), so they are NOT pooled:
    the gather recomputes them for exactly the heads that carry
    non-stale resume state, from the per-row memoized requested-resource
    sets.

    Lifecycle: one arena per CQ-encoding generation. A structural change
    (flavors/CQs/cohorts, feature-gate flip) rotates the encoding and
    FULLY REBUILDS the arena (`full_rebuilds` counts these; bench.py
    asserts zero inside the measured window). Bucket rotation (W growth/
    shrink) does not touch the pool — the gather pads to whatever bucket
    the tick needs.
    """

    # Debug mode (KUEUE_TPU_DEBUG_ARENA=1, or set per-instance): every
    # gather ALSO runs the from-scratch encode and asserts tensor
    # equality — the UsageEncoder.debug_verify discipline applied to the
    # workload side.
    debug_verify = knobs.flag("KUEUE_TPU_DEBUG_ARENA")

    def __init__(self, enc: CQEncoding, snapshot: Snapshot,
                 capacity: int = 1024):
        self.enc = enc
        # Structural read-only view for event-time encodes (resource
        # groups / flavors / label keys only — usage staleness is
        # irrelevant, and any structural change rotates the encoding and
        # rebuilds this arena).
        self._snapshot = snapshot
        self._lock = threading.Lock()
        R = len(enc.resource_names)
        self.R = R
        self.G = enc.num_groups
        self.S = enc.num_slots
        self.P = 1
        self.cap = 0
        self._rows: Dict[str, int] = {}      # uid -> row
        self._free: List[int] = []
        self._rev: List[int] = []            # row -> info rev
        self._uid: List[Optional[str]] = []  # row -> uid
        self._req_sets: List[tuple] = []     # row -> requests_per_podset
        # Cohort-mesh shard view (parallel/mesh.ShardAssignment): when
        # bound, the same note/forget events that keep rows fresh also
        # maintain the per-shard pending-row counts — the backlog-balance
        # evidence the shard bench reads without scanning the pool.
        self._shard_of_cq: Optional[np.ndarray] = None
        self.shard_counts: Optional[np.ndarray] = None
        self._grow(max(8, capacity))
        # Cumulative stats (BatchSolver folds them into BENCH json):
        # `rows_reused` / `rows_missed` split the GATHER path (reuse vs
        # in-tick re-encode — the reuse-ratio gate reads these);
        # `rows_encoded` counts every row encode wherever it ran (seed,
        # queue events, gather misses) — the dirty-delta volume.
        self.rows_reused = 0
        self.rows_missed = 0
        self.rows_encoded = 0

    # -- pool plumbing ------------------------------------------------------

    def _grow(self, new_cap: int) -> None:
        """Extend the row pool (never shrinks; rows keep their index)."""
        old = self.cap
        P, R, G, S = self.P, self.R, self.G, self.S
        wl_cq = np.zeros(new_cap, dtype=np.int32)
        req = np.zeros((new_cap, P, R), dtype=np.int64)
        has_req = np.zeros((new_cap, P, R), dtype=bool)
        unsat = np.zeros((new_cap, P), dtype=bool)
        elig = np.zeros((new_cap, P, G, S), dtype=bool)
        p_count = np.zeros(new_cap, dtype=np.int32)
        if old:
            wl_cq[:old] = self.wl_cq
            req[:old] = self.req
            has_req[:old] = self.has_req
            unsat[:old] = self.unsat
            elig[:old] = self.elig
            p_count[:old] = self.p_count
        self.wl_cq, self.req, self.has_req = wl_cq, req, has_req
        self.unsat, self.elig, self.p_count = unsat, elig, p_count
        self._free.extend(range(new_cap - 1, old - 1, -1))
        self._rev.extend([-1] * (new_cap - old))
        self._uid.extend([None] * (new_cap - old))
        self._req_sets.extend([()] * (new_cap - old))
        self.cap = new_cap

    def _grow_podsets(self, new_p: int) -> None:
        """Widen the pool's P axis in place (a multi-podset shape arrived);
        existing rows keep their content — the new columns are the zero
        padding a from-scratch encode would produce."""
        P, R, G, S = self.P, self.R, self.G, self.S
        cap = self.cap

        def widen(a, shape):
            out = np.zeros(shape, dtype=a.dtype)
            out[:, :P] = a
            return out

        self.req = widen(self.req, (cap, new_p, R))
        self.has_req = widen(self.has_req, (cap, new_p, R))
        self.unsat = widen(self.unsat, (cap, new_p))
        self.elig = widen(self.elig, (cap, new_p, G, S))
        self.P = new_p

    # -- dirty deltas (queue-manager events + gather misses) ----------------

    def note(self, wi: WorkloadInfo) -> None:
        """Encode (or refresh) one pending workload's row — the queue
        manager's add/update event. Runs OFF the measured tick (submit /
        requeue-update paths), so the tick's gather is all row reuse."""
        with self._lock:
            self._note_locked(wi, self._snapshot)

    def bind_shards(self, shard_of_cq: np.ndarray, n_shards: int) -> None:
        """Attach a cohort-mesh shard assignment: per-shard pending-row
        counts are (re)derived now and maintained incrementally by every
        note/forget event from here on."""
        with self._lock:
            self._shard_of_cq = shard_of_cq
            counts = np.zeros(n_shards, dtype=np.int64)
            for row in self._rows.values():
                counts[shard_of_cq[self.wl_cq[row]]] += 1
            self.shard_counts = counts

    def forget(self, uid: str) -> None:
        """Free a workload's row (queue-manager delete event)."""
        with self._lock:
            row = self._rows.pop(uid, None)
            if row is not None:
                if self.shard_counts is not None:
                    self.shard_counts[
                        self._shard_of_cq[self.wl_cq[row]]] -= 1
                self._rev[row] = -1
                self._uid[row] = None
                self._req_sets[row] = ()
                self._free.append(row)

    def seed(self, infos: Sequence[WorkloadInfo]) -> None:
        """Bulk-encode a backlog (arena rebuild): every pending workload
        gets a row NOW, off the measured path, so the next ticks' heads
        are pure reuse even when admissions keep revealing
        never-popped-before heap heads."""
        with self._lock:
            snapshot = self._snapshot
            for wi in infos:
                self._note_locked(wi, snapshot)

    def _note_locked(self, wi: WorkloadInfo,
                     snapshot: Snapshot) -> Optional[int]:
        cq = snapshot.cluster_queues.get(wi.cluster_queue)
        if cq is None:
            # Unknown CQ: either inactive (the workload can never be a
            # solvable head while it stays so) or newer than this
            # encoding generation (the rotation will rebuild the arena).
            return None
        totals = wi.total_requests
        p = len(totals)
        if p > self.P:
            self._grow_podsets(p)
        uid = wi.obj.uid
        row = self._rows.get(uid)
        counts = self.shard_counts
        if row is None:
            if not self._free:
                self._grow(self.cap * 2)
            row = self._free.pop()
            self._rows[uid] = row
        elif counts is not None:
            # Refresh of an existing row: its CQ (hence shard) may move.
            counts[self._shard_of_cq[self.wl_cq[row]]] -= 1
        enc_row = _encode_row(wi, cq, snapshot, self.enc, totals)
        if counts is not None:
            counts[self._shard_of_cq[enc_row.ci]] += 1
        self.wl_cq[row] = enc_row.ci
        self.req[row] = 0
        self.has_req[row] = False
        self.unsat[row] = False
        self.elig[row] = False
        if p:
            self.req[row, :p] = enc_row.req
            self.has_req[row, :p] = enc_row.has_req
            self.unsat[row, :p] = enc_row.unsat
            self.elig[row, :p] = enc_row.elig
        self.p_count[row] = p
        self._rev[row] = wi.rev
        self._uid[row] = uid
        self._req_sets[row] = tuple(enc_row.requests_per_podset)
        self.rows_encoded += 1
        return row

    # -- the tick's batch ---------------------------------------------------

    def gather(self, workloads: Sequence[WorkloadInfo], snapshot: Snapshot,
               min_podsets: int = 1):
        """Assemble the padded batch tensors for this tick's heads from
        the pooled rows. Returns (WorkloadTensors, stats) where stats
        carries `rows_dirty` (rows (re-)encoded by this gather — misses),
        and `rows_total`. Byte-identical to
        `encode_workloads(workloads, snapshot, enc, min_podsets=...)`."""
        n = len(workloads)
        with self._lock:
            # Event-time encodes use the arena's pinned structural view;
            # gather-time misses must use the CALLER's snapshot (the one
            # the tick solves against) exactly like encode_workloads.
            self._snapshot = snapshot
            dirty = 0
            rows_py: List[int] = []
            rows_append = rows_py.append
            rows_map = self._rows
            revs = self._rev
            cqs_by_name = snapshot.cluster_queues
            # Heads carrying live resume state, collected inline (the
            # same staleness drop as encode_workloads /
            # flavorassigner.go:244-247) so the second pass below walks
            # only the few losers instead of the whole batch.
            resume_entries: List[tuple] = []
            for i, wi in enumerate(workloads):
                row = rows_map.get(wi.obj.uid)
                if row is None or revs[row] != wi.rev:
                    row = self._note_locked(wi, snapshot)
                    if row is None:
                        # encode_workloads would KeyError on an unknown
                        # CQ too; solvable heads always have one.
                        raise KeyError(wi.cluster_queue)
                    dirty += 1
                rows_append(row)
                last = wi.last_assignment
                if last is not None:
                    cq = cqs_by_name[wi.cluster_queue]
                    cohort = cq.cohort
                    if not (cq.allocatable_generation
                            > last.cluster_queue_generation
                            or (cohort is not None
                                and cohort.allocatable_generation
                                > last.cohort_generation)):
                        resume_entries.append((i, row, cq, last))
            self.rows_reused += n - dirty
            self.rows_missed += dirty
            rows = np.asarray(rows_py, dtype=np.int64)

            W = _pad_pow2(max(n, 1))
            P = max(1, min_podsets)
            if n:
                pc = self.p_count[rows]
                p_max = int(pc.max()) if n else 0
                if p_max > P:
                    P = p_max
            if P > self.P:
                # The sticky P floor can outgrow the pool (a multi-podset
                # shape seen only by the counts path, which bypasses the
                # arena); widen so the slice below stays exact.
                self._grow_podsets(P)
            R, G, S = self.R, self.G, self.S

            wl_cq = np.zeros(W, dtype=np.int32)
            req = np.zeros((W, P, R), dtype=np.int64)
            has_req = np.zeros((W, P, R), dtype=bool)
            podset_valid = np.zeros((W, P), dtype=bool)
            podset_unsat = np.zeros((W, P), dtype=bool)
            elig = np.zeros((W, P, G, S), dtype=bool)
            resume_slot = np.zeros((W, P, G), dtype=np.int32)
            wl_valid = np.zeros(W, dtype=bool)
            wl_valid[:n] = True
            if n:
                wl_cq[:n] = self.wl_cq[rows]
                req[:n] = self.req[rows, :P]
                has_req[:n] = self.has_req[rows, :P]
                podset_unsat[:n] = self.unsat[rows, :P]
                podset_valid[:n] = np.arange(P)[None, :] < pc[:, None]
                elig[:n] = self.elig[rows, :P]

            req_sets = self._req_sets
            for i, row, cq, last in resume_entries:
                for p, requested in enumerate(req_sets[row]):
                    for gi, rg in enumerate(cq.resource_groups):
                        for rname in rg.covered_resources:
                            if rname in requested:
                                resume_slot[i, p, gi] = \
                                    last.next_flavor_to_try(p, rname)
                                break

        wt = WorkloadTensors(
            wl_cq=wl_cq, req=req, has_req=has_req,
            podset_valid=podset_valid, podset_unsat=podset_unsat,
            elig=elig, resume_slot=resume_slot, wl_valid=wl_valid,
            num_real=n)
        if self.debug_verify:
            self.verify(wt, workloads, snapshot, min_podsets)
        return wt, {"rows_dirty": dirty, "rows_total": n}

    def verify(self, wt: WorkloadTensors,
               workloads: Sequence[WorkloadInfo], snapshot: Snapshot,
               min_podsets: int) -> None:
        """Assert a gathered batch equals the from-scratch encode; raises
        AssertionError naming the first diverging tensor field."""
        ref = encode_workloads(workloads, snapshot, self.enc,
                               min_podsets=min_podsets)
        for name in ("wl_cq", "req", "has_req", "podset_valid",
                     "podset_unsat", "elig", "resume_slot", "wl_valid"):
            a = getattr(wt, name)
            b = getattr(ref, name)
            if a.shape != b.shape or not np.array_equal(a, b):
                raise AssertionError(
                    f"WorkloadArena drift: gathered `{name}` does not "
                    "match the from-scratch encode (event/row staleness "
                    "bug — a queue mutation bypassed the arena events)")


class AdmittedArena:
    """Persistent admitted-set tensor arena: one pooled usage row per
    workload currently HOLDING quota (assumed or admitted).

    The admitted set was the last per-tick dict-walk surface after PR 5
    made the pending side arena-resident: the batched preemption victim
    search re-derived every candidate's usage vector from its
    `usage_triples` per search per tick, and the snapshot mirror's
    lockstep flush re-applied per-workload usage dicts item by item.
    This arena keeps each quota-holder's committed (cq, flavor, resource,
    value) usage as one dense `[cap, F*R]` int64 row (restricted to the
    pairs its ClusterQueue is configured to track — exactly what the
    cache accounts, clusterqueue.go:473-485) plus the per-ClusterQueue
    sum `usage_cfr [C,F,R]`, both maintained incrementally from the
    cache's assume/add/forget/delete events
    (`Cache.register_admitted_sink`).

    Consumers:
      * `ops/preemption_batch.run_batch` gathers candidate usage rows
        with one fancy-index read instead of a triples walk per
        candidate;
      * `SnapshotMirror` rewrites a flushed ClusterQueue's usage dict
        from `usage_cfr` (and folds the lending-clamped cohort delta)
        instead of walking every pending item's triples.

    Lifecycle mirrors `WorkloadArena`: one arena per CQ-encoding
    generation, fully re-seeded from the cache on encoding rotation.
    Kill switch: `KUEUE_TPU_NO_ADMIT_ARENA=1` (or
    `BatchSolver(use_admit_arena=False)`) restores the dict walks.
    Debug: `KUEUE_TPU_DEBUG_ADMIT_ARENA=1` re-derives `usage_cfr` from
    the cache dicts after every mutation batch and asserts equality.
    """

    debug_verify = knobs.flag("KUEUE_TPU_DEBUG_ADMIT_ARENA")

    def __init__(self, enc: CQEncoding, capacity: int = 1024):
        self.enc = enc
        C, F, R = enc.nominal.shape
        self.FR = F * R
        self.R = R
        self._lock = threading.Lock()
        self._rows: Dict[str, int] = {}     # workload key -> row
        self._free: List[int] = []
        self.cap = 0
        self.use_fr = np.zeros((0, self.FR), dtype=np.int64)
        self.row_ci = np.zeros(0, dtype=np.int32)
        self.usage_cfr = np.zeros((C, F, R), dtype=np.int64)
        self._cfr_flat = self.usage_cfr.reshape(C, self.FR)
        # Cohort-mesh shard view: per-shard admitted-row counts kept in
        # lockstep with the same assume/add/forget/delete sink events
        # that feed the usage rows (the admitted-balance evidence of the
        # shard bench); per-shard usage sums derive from usage_cfr on
        # demand (shard_usage).
        self._shard_of_cq: Optional[np.ndarray] = None
        self.shard_counts: Optional[np.ndarray] = None
        self._grow(max(8, capacity))
        self.rows_noted = 0

    def bind_shards(self, shard_of_cq: np.ndarray, n_shards: int) -> None:
        with self._lock:
            self._shard_of_cq = shard_of_cq
            counts = np.zeros(n_shards, dtype=np.int64)
            for row in self._rows.values():
                counts[shard_of_cq[self.row_ci[row]]] += 1
            self.shard_counts = counts

    def shard_usage(self) -> Optional[np.ndarray]:
        """[n_shards, F*R] committed usage summed per shard (derived from
        the per-CQ sums — one segment add, read once per bench window)."""
        if self._shard_of_cq is None or self.shard_counts is None:
            return None
        with self._lock:
            out = np.zeros((len(self.shard_counts), self.FR),
                           dtype=np.int64)
            np.add.at(out, self._shard_of_cq[:len(self._cfr_flat)],
                      self._cfr_flat)
        return out

    def _grow(self, new_cap: int) -> None:
        old = self.cap
        use_fr = np.zeros((new_cap, self.FR), dtype=np.int64)
        row_ci = np.full(new_cap, -1, dtype=np.int32)
        if old:
            use_fr[:old] = self.use_fr
            row_ci[:old] = self.row_ci
        self.use_fr, self.row_ci = use_fr, row_ci
        self._free.extend(range(new_cap - 1, old - 1, -1))
        self.cap = new_cap

    def _alloc(self, key: str) -> int:
        if not self._free:
            self._grow(self.cap * 2)
        row = self._free.pop()
        self._rows[key] = row
        return row

    # -- cache events (called under the cache lock; keep O(row)) ------------

    def note_admitted(self, wi) -> None:
        """One workload began holding quota (assume/add). Re-noting an
        existing key replaces its row (delete+add update shape)."""
        enc = self.enc
        ci = enc.cq_index.get(wi.cluster_queue)
        if ci is None:
            # Newer than this encoding generation; the rotation reseeds.
            return
        f_index = enc.flavor_index
        r_index = enc.resource_index
        conf = enc.configured[ci]
        R = self.R
        with self._lock:
            key = wi.key
            row = self._rows.get(key)
            counts = self.shard_counts
            if row is None:
                row = self._alloc(key)
                if counts is not None:
                    counts[self._shard_of_cq[ci]] += 1
            else:
                self._cfr_flat[self.row_ci[row]] -= self.use_fr[row]
                if counts is not None:
                    counts[self._shard_of_cq[self.row_ci[row]]] -= 1
                    counts[self._shard_of_cq[ci]] += 1
            rowv = self.use_fr[row]
            rowv[:] = 0
            for fname, rname, v in wi.usage_triples:
                fi = f_index.get(fname)
                if fi is None:
                    continue
                ri = r_index.get(rname)
                if ri is not None and conf[fi, ri]:
                    rowv[fi * R + ri] += v
            self.row_ci[row] = ci
            self._cfr_flat[ci] += rowv
            self.rows_noted += 1

    def note_batch(self, keys: Sequence[str], cis: Sequence[int],
                   ent: np.ndarray, fi: np.ndarray, ri: np.ndarray,
                   val: np.ndarray) -> None:
        """Bulk twin of note_admitted for the admission cycle's CSR
        commit: `keys[j]` holds the coordinate slice `ent == j` of the
        (deduped, configured-by-construction) decode coordinates — the
        whole cycle's admitted usage lands in ONE scatter-add."""
        R = self.R
        with self._lock:
            rows = np.empty(len(keys), dtype=np.int64)
            counts = self.shard_counts
            shard_of = self._shard_of_cq
            for j, key in enumerate(keys):
                row = self._rows.get(key)
                if row is None:
                    row = self._alloc(key)
                    if counts is not None:
                        counts[shard_of[cis[j]]] += 1
                else:
                    self._cfr_flat[self.row_ci[row]] -= self.use_fr[row]
                    if counts is not None:
                        counts[shard_of[self.row_ci[row]]] -= 1
                        counts[shard_of[cis[j]]] += 1
                self.use_fr[row] = 0
                self.row_ci[row] = cis[j]
                rows[j] = row
            if len(ent):
                fr = fi * R + ri
                np.add.at(self.use_fr, (rows[ent], fr), val)
                np.add.at(self._cfr_flat,
                          (np.asarray(cis, dtype=np.int64)[ent], fr), val)
            self.rows_noted += len(keys)

    def forget_admitted(self, key: str) -> None:
        """The workload released its quota (forget/delete)."""
        with self._lock:
            row = self._rows.pop(key, None)
            if row is None:
                return
            ci = self.row_ci[row]
            if self.shard_counts is not None:
                self.shard_counts[self._shard_of_cq[ci]] -= 1
            self._cfr_flat[ci] -= self.use_fr[row]
            self.use_fr[row] = 0
            self.row_ci[row] = -1
            self._free.append(row)

    def seed(self, cluster_queues: Dict[str, CachedClusterQueue]) -> None:
        """Re-seed the whole admitted set from the cache (arena rebuild
        on encoding rotation; runs off the measured tick path)."""
        for cq in cluster_queues.values():
            for wi in cq.workloads.values():
                self.note_admitted(wi)

    # -- consumers ----------------------------------------------------------

    def rows_for(self, infos) -> Optional[np.ndarray]:
        """Pooled row indices of `infos` (preemption candidates), or None
        when any candidate has no row (caller falls back to the triples
        walk — a correctness no-op, the rows are an accelerator)."""
        rows_map = self._rows
        with self._lock:
            out = np.empty(len(infos), dtype=np.int64)
            for i, wi in enumerate(infos):
                row = rows_map.get(wi.key)
                if row is None:
                    return None
                out[i] = row
        return out

    def cq_usage_row(self, ci: int) -> np.ndarray:
        """The [F*R] committed-usage sum of one ClusterQueue (a live
        view; copy before holding across mutations)."""
        return self._cfr_flat[ci]

    def verify(self, cluster_queues: Dict[str, CachedClusterQueue]) -> None:
        """Assert usage_cfr equals a from-scratch re-derivation of the
        cache's accounted usage (debug mode)."""
        enc = self.enc
        fresh = np.zeros_like(self.usage_cfr)
        for name, cq in cluster_queues.items():
            ci = enc.cq_index.get(name)
            if ci is None:
                continue
            for fname, resources in cq.usage.items():
                fi = enc.flavor_index.get(fname)
                if fi is None:
                    continue
                for rname, v in resources.items():
                    ri = enc.resource_index.get(rname)
                    if ri is not None:
                        fresh[ci, fi, ri] = v
        if not np.array_equal(fresh, self.usage_cfr):
            bad = [enc.cq_names[ci] for ci in np.nonzero(
                (fresh != self.usage_cfr).any(axis=(1, 2)))[0]]
            raise AssertionError(
                f"AdmittedArena drift: usage rows for {bad} do not match "
                "the cache dicts (a cache mutation bypassed the admitted "
                "sink events)")
