"""Topology-aware scheduling (slice/rack-packed admission).

Models a per-flavor placement hierarchy (block -> rack -> host levels with
per-leaf pod capacity, `api.types.TopologySpec`), encodes it into padded
dense tensors alongside the solver's CQEncoding (`topology.encoding`), and
assigns each admissible PodSet the lowest topology domain that fits its
pods (`topology.fit` — a vectorized best-fit-level search with a host
referee twin). Leaf occupancy lives in `topology.state.TopologyLedger`,
owned by the admitted-workload cache and charged/released on the same
assume/forget/delete transitions as quota.

When no ResourceFlavor declares a topology, every entry point returns
None/no-ops and the scheduler's existing code paths are byte-identical.
"""

import jax

# Integer slot arithmetic is exact int64, like models/ and ops/.
jax.config.update("jax_enable_x64", True)

from kueue_tpu.topology.encoding import TopologyEncoding, build_topology_encoding
from kueue_tpu.topology.fit import TopologyStage
from kueue_tpu.topology.state import TopologyCycle, TopologyLedger

__all__ = [
    "TopologyEncoding",
    "build_topology_encoding",
    "TopologyStage",
    "TopologyCycle",
    "TopologyLedger",
]
