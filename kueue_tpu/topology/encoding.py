"""Dense tensor encoding of per-flavor topology trees.

The string-world TopologySpec (levels + leaf paths) is folded on the host
into integer tensors the vectorized fit search consumes, exactly like
`solver/schema.py` folds taints/affinity into the eligibility mask:

  T  topology-declaring flavors (a subset of the global flavor vocabulary)
  L  levels (padded to the deepest flavor)
  E  leaves per flavor (padded)
  D  domains per (flavor, level) (padded)

A domain at level l is the set of leaves sharing path[:l+1]; domain
indices at each level are assigned in sorted-path order, so the encoding
(and therefore every tie-break downstream) is deterministic. The encoding
is immutable once built and keyed on the snapshot's structure version by
its consumers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from kueue_tpu.api.types import ResourceFlavor, TopologySpec


class TopologyEncoding:
    """Padded dense view of every topology-declaring flavor."""

    __slots__ = ("flavor_names", "flavor_index", "specs", "L", "E", "D",
                 "num_levels", "leaf_valid", "leaf_cap", "leaf_domain",
                 "num_domains", "domain_paths")

    def __init__(self, flavor_names: List[str], specs: List[TopologySpec]):
        self.flavor_names = flavor_names
        self.flavor_index = {n: i for i, n in enumerate(flavor_names)}
        self.specs = specs
        T = len(flavor_names)
        L = max(len(s.levels) for s in specs)
        E = max(len(s.leaves) for s in specs)
        self.L, self.E = L, E

        num_levels = np.zeros(T, dtype=np.int32)
        leaf_valid = np.zeros((T, E), dtype=bool)
        leaf_cap = np.zeros((T, E), dtype=np.int64)
        # [t][l][d] -> the domain's path prefix (for decode/events).
        domain_paths: List[List[List[Tuple[str, ...]]]] = []
        # Two passes: domain counts first (for the padded D), then ids.
        per_level_domains: List[List[Dict[Tuple[str, ...], int]]] = []
        D = 1
        for t, spec in enumerate(specs):
            num_levels[t] = len(spec.levels)
            levels_doms: List[Dict[Tuple[str, ...], int]] = []
            paths_t: List[List[Tuple[str, ...]]] = []
            for li in range(len(spec.levels)):
                prefixes = sorted({leaf.path[:li + 1] for leaf in spec.leaves
                                   if len(leaf.path) > li})
                levels_doms.append({p: d for d, p in enumerate(prefixes)})
                paths_t.append(prefixes)
                D = max(D, len(prefixes))
            per_level_domains.append(levels_doms)
            domain_paths.append(paths_t)
            for e, leaf in enumerate(spec.leaves):
                leaf_valid[t, e] = True
                leaf_cap[t, e] = leaf.capacity
        self.D = D

        leaf_domain = np.full((T, L, E), -1, dtype=np.int32)
        num_domains = np.zeros((T, L), dtype=np.int32)
        for t, spec in enumerate(specs):
            for li in range(len(spec.levels)):
                doms = per_level_domains[t][li]
                num_domains[t, li] = len(doms)
                for e, leaf in enumerate(spec.leaves):
                    if len(leaf.path) > li:
                        leaf_domain[t, li, e] = doms[leaf.path[:li + 1]]

        self.num_levels = num_levels
        self.leaf_valid = leaf_valid
        self.leaf_cap = leaf_cap
        self.leaf_domain = leaf_domain
        self.num_domains = num_domains
        self.domain_paths = domain_paths

    # -- helpers ------------------------------------------------------------

    def stack_used(self, used_by_flavor: Dict[str, np.ndarray]) -> np.ndarray:
        """[T, E] i64 leaf occupancy padded from the ledger view; missing
        flavors read as empty."""
        out = np.zeros((len(self.flavor_names), self.E), dtype=np.int64)
        for t, name in enumerate(self.flavor_names):
            arr = used_by_flavor.get(name)
            if arr is not None:
                n = min(len(arr), self.E)
                out[t, :n] = arr[:n]
        return out

    def domain_leaf_indices(self, t: int, level: int,
                            domain: int) -> np.ndarray:
        """Leaf indices (into the flavor's spec.leaves) of one domain."""
        return np.nonzero(self.leaf_domain[t, level] == domain)[0]

    def domain_path(self, t: int, level: int,
                    domain: int) -> Tuple[str, ...]:
        return self.domain_paths[t][level][domain]

    def domain_index(self, t: int, level: int,
                     path: Tuple[str, ...]) -> Optional[int]:
        """Domain index at `level` for a path prefix; None when unknown."""
        try:
            paths = self.domain_paths[t][level]
        except IndexError:
            return None
        lo = 0
        hi = len(paths)
        # paths are sorted; binary search keeps this O(log D).
        while lo < hi:
            mid = (lo + hi) // 2
            if paths[mid] < path:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(paths) and paths[lo] == path:
            return lo
        return None


def build_topology_encoding(
        resource_flavors: Dict[str, ResourceFlavor],
) -> Optional[TopologyEncoding]:
    """The dense encoding of every topology-declaring flavor, or None when
    no flavor declares one (the provable no-op gate: with None, the
    scheduler never constructs a stage and no existing code path moves)."""
    names = sorted(n for n, rf in resource_flavors.items()
                   if rf.topology is not None and rf.topology.leaves)
    if not names:
        return None
    return TopologyEncoding(
        names, [resource_flavors[n].topology for n in names])
