"""Vectorized best-fit-level search over per-flavor topology trees.

Given each PodSet's assigned flavor and pod count, find the LOWEST (deepest)
topology domain whose free pod-slot capacity fits the whole PodSet:

  * `topology_required: <level>` — every pod must land within ONE domain at
    the requested level (or deeper, which is contained in it). No such
    domain at all (even empty) => the PodSet can never fit (NO_FIT); a
    domain exists but none is currently free enough => inadmissible this
    tick (or preemption-eligible when the quota solve already said PREEMPT).
  * `topology_preferred: <level>` — best effort: try the requested level
    and deeper, fall back up the hierarchy, and finally place unconstrained.

The batched search is one jitted program following the `models/flavor_fit`
masking idiom — no data-dependent branching, all mask/reduction — so the
whole tick's topology-requesting PodSets solve in one dispatch on the
device path. `fit_host` is the sequential referee twin (numpy, identical
tie-breaks) used by the referee solver path and the admission cycle's
re-validation, and the two are pinned decision-equivalent by the goldens.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kueue_tpu.api.types import TopologyAssignment
from kueue_tpu.solver.modes import NO_FIT, PREEMPT
from kueue_tpu.topology.encoding import TopologyEncoding

_BIG = np.int64(1) << 62


def _pad_pow2(n: int, floor: int = 4) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def solve_topology_core(leaf_cap, leaf_valid, leaf_domain, num_domains,
                        num_levels, leaf_used, ti, count, req_level,
                        required, item_valid, *, shapes):
    """Batched best-fit-level search; returns (level, domain, ok_now,
    could_ever) per item. level/domain are -1 for "no domain" (which for
    `preferred` items means unconstrained placement, for `required` items
    a failure)."""
    T, L, E, D, N = shapes

    free = jnp.where(leaf_valid, jnp.maximum(leaf_cap - leaf_used, 0), 0)
    cap = jnp.where(leaf_valid, leaf_cap, 0)

    # Per-(flavor, level) domain totals via one flat segment-sum: leaf e of
    # flavor t contributes to segment (t*L + l)*(D+1) + domain, with padded
    # leaves routed to the dead segment D.
    dom = jnp.where(leaf_domain >= 0, leaf_domain, D)            # [T,L,E]
    base = (jnp.arange(T)[:, None, None] * L
            + jnp.arange(L)[None, :, None]) * (D + 1)
    seg = (base + dom).reshape(-1)
    freeB = jnp.broadcast_to(free[:, None, :], (T, L, E)).reshape(-1)
    capB = jnp.broadcast_to(cap[:, None, :], (T, L, E)).reshape(-1)
    dom_free = jax.ops.segment_sum(
        freeB, seg, num_segments=T * L * (D + 1)).reshape(T, L, D + 1)[..., :D]
    dom_cap = jax.ops.segment_sum(
        capB, seg, num_segments=T * L * (D + 1)).reshape(T, L, D + 1)[..., :D]
    dom_valid = (jnp.arange(D)[None, None, :]
                 < num_domains[:, :, None])                      # [T,L,D]

    ts = jnp.maximum(ti, 0)
    f_free = dom_free[ts]                                        # [N,L,D]
    f_cap = dom_cap[ts]
    f_valid = dom_valid[ts] & item_valid[:, None, None] & (ti >= 0)[:, None, None]
    nl = num_levels[ts]                                          # [N]

    lix = jnp.arange(L)[None, :]
    need = count[:, None, None]
    fits_now = f_valid & (f_free >= need)                        # [N,L,D]
    fits_cap = f_valid & (f_cap >= need)
    level_fit = fits_now.any(axis=2)                             # [N,L]
    level_cap = fits_cap.any(axis=2)

    # Levels at/below (deeper than) the requested one; a fit in a deeper
    # domain also satisfies the requested level (containment).
    allowed_req = (lix >= req_level[:, None]) & (lix < nl[:, None])
    allowed_any = lix < nl[:, None]
    lvl_req = jnp.where(level_fit & allowed_req, lix, -1).max(axis=1)
    lvl_any = jnp.where(level_fit & allowed_any, lix, -1).max(axis=1)
    level = jnp.where(lvl_req >= 0, lvl_req,
                      jnp.where(required, -1, lvl_any))          # [N]
    could_ever = (level_cap & allowed_req).any(axis=1)

    # Best-fit domain at the chosen level: the FITTING domain with the least
    # free capacity (ties -> lowest index, i.e. lexicographically first
    # path — the deterministic tie-break the host twin mirrors).
    lvl_safe = jnp.maximum(level, 0)
    free_at = jnp.take_along_axis(
        f_free, lvl_safe[:, None, None], axis=1)[:, 0, :]        # [N,D]
    fits_at = jnp.take_along_axis(
        fits_now, lvl_safe[:, None, None], axis=1)[:, 0, :]
    score = jnp.where(fits_at, free_at, _BIG)
    domain = jnp.argmin(score, axis=1).astype(jnp.int32)
    domain = jnp.where(level >= 0, domain, -1)
    ok_now = level >= 0
    return (level.astype(jnp.int32), domain, ok_now,
            could_ever & item_valid & (ti >= 0))


_topology_kernel = functools.partial(
    jax.jit, static_argnames=("shapes",))(solve_topology_core)


def fit_host(enc: TopologyEncoding, used: np.ndarray, ti: int, count: int,
             req_level: int, required: bool,
             ) -> Tuple[int, int, bool, bool]:
    """Sequential referee twin of solve_topology_core for ONE item.
    Identical decision semantics and tie-breaks (deepest fitting level,
    then least-free fitting domain, then lowest domain index)."""
    nl = int(enc.num_levels[ti])
    free = np.where(enc.leaf_valid[ti],
                    np.maximum(enc.leaf_cap[ti] - used[ti], 0), 0)
    cap = np.where(enc.leaf_valid[ti], enc.leaf_cap[ti], 0)
    def _domain_sum(values: np.ndarray, li: int) -> np.ndarray:
        nd = int(enc.num_domains[ti, li])
        dom = enc.leaf_domain[ti, li]
        out = np.zeros(nd, dtype=np.int64)
        m = dom >= 0
        np.add.at(out, dom[m], values[m])
        return out

    could_ever = False
    # Could any domain at an allowed (required-or-deeper) level fit the
    # PodSet even empty? False => permanent NO_FIT for `required`.
    for li in range(nl - 1, req_level - 1, -1):
        if (_domain_sum(cap, li) >= count).any():
            could_ever = True
            break
    search = list(range(nl - 1, req_level - 1, -1))
    if not required:
        search += list(range(req_level - 1, -1, -1))
    for li in search:
        dom_free = _domain_sum(free, li)
        fitting = dom_free >= count
        if fitting.any():
            score = np.where(fitting, dom_free, _BIG)
            return li, int(np.argmin(score)), True, could_ever
    return -1, -1, False, could_ever


def pack_leaves(enc: TopologyEncoding, used: np.ndarray, ti: int, level: int,
                domain: int, count: int) -> List[Tuple[int, int]]:
    """Greedy best-fit packing of `count` pods onto the domain's leaves:
    most-loaded (least free, but non-full) leaves first, then leaf index —
    concentrates pods and leaves the largest contiguous holes elsewhere
    (the fragmentation-reducing policy the gauge tracks). Returns
    [(leaf index, pods)] and does NOT mutate `used`."""
    leaves = enc.domain_leaf_indices(ti, level, domain)
    free = np.maximum(enc.leaf_cap[ti, leaves] - used[ti, leaves], 0)
    order = np.lexsort((leaves, free))       # free asc, then index asc
    out: List[Tuple[int, int]] = []
    remaining = count
    for k in order:
        if remaining <= 0:
            break
        f = int(free[k])
        if f <= 0:
            continue
        take = min(f, remaining)
        out.append((int(leaves[k]), take))
        remaining -= take
    if remaining > 0:
        return []  # caller re-checked fit, so this only races cycle charges
    return out


@dataclass(slots=True)
class TopologyCandidate:
    """One PodSet's topology verdict from the fit stage (device or host).

    `level`/`domain` index the encoding (-1 = unconstrained placement —
    only reachable for `preferred` requests); `ok_now` is whether a domain
    currently fits; `could_ever` whether any allowed domain could fit the
    PodSet even empty (False => permanent NO_FIT for `required`)."""

    ti: int
    flavor: str
    req_level: int
    required: bool
    count: int
    level: int
    domain: int
    ok_now: bool
    could_ever: bool


class TopologyStage:
    """The topology pass over solved assignments — the stage `referee.py`
    (host path) and the scheduler's batched path invoke after flavor
    assignment. Mutates assignments in place: attaches per-podset
    `TopologyCandidate`s and downgrades modes per the contract above."""

    def __init__(self, enc: TopologyEncoding):
        self.enc = enc
        self._device_static = None
        # Compile-proof ticks for THIS kernel too: item counts pad to
        # pow2 buckets, so a churn-driven bucket rotation would compile
        # inside a measured tick. Imminent neighbor buckets queue here and
        # Scheduler.prewarm_idle compiles them between ticks.
        self._warm_n: set = set()
        self._pending_n: set = set()

    # -- batched (device) path ---------------------------------------------

    def _device_arrays(self):
        if self._device_static is None:
            e = self.enc
            self._device_static = tuple(jnp.asarray(x) for x in (
                e.leaf_cap, e.leaf_valid, e.leaf_domain, e.num_domains,
                e.num_levels))
        return self._device_static

    def _solve_items(self, items: List[tuple], used: np.ndarray,
                     use_device: bool) -> List[Tuple[int, int, bool, bool]]:
        """items: [(ti, count, req_level, required)]."""
        if not use_device or not items:
            return [fit_host(self.enc, used, ti, count, lvl, req)
                    for ti, count, lvl, req in items]
        n = len(items)
        N = _pad_pow2(n)
        self._warm_n.add(N)
        if n >= N - max(1, N // 8):
            if N * 2 not in self._warm_n:
                self._pending_n.add(N * 2)
        if N > 4 and n <= N // 2 + max(1, N // 8):
            if N // 2 not in self._warm_n:
                self._pending_n.add(N // 2)
        ti = np.full(N, -1, dtype=np.int32)
        count = np.zeros(N, dtype=np.int64)
        req_level = np.zeros(N, dtype=np.int32)
        required = np.zeros(N, dtype=bool)
        valid = np.zeros(N, dtype=bool)
        for i, (t, c, l, r) in enumerate(items):
            ti[i], count[i], req_level[i], required[i] = t, c, l, r
            valid[i] = True
        e = self.enc
        out = _topology_kernel(
            *self._device_arrays(), jnp.asarray(used),
            jnp.asarray(ti), jnp.asarray(count), jnp.asarray(req_level),
            jnp.asarray(required), jnp.asarray(valid),
            shapes=(len(e.flavor_names), e.L, e.E, e.D, N))
        level, domain, ok_now, could_ever = (np.asarray(x) for x in out)
        return [(int(level[i]), int(domain[i]), bool(ok_now[i]),
                 bool(could_ever[i])) for i in range(n)]

    def prewarm_idle(self) -> int:
        """Compile queued neighbor item-count buckets (all-zero inputs —
        compilation depends only on shapes). Call between ticks."""
        done = 0
        while self._pending_n:
            N = self._pending_n.pop()
            if N in self._warm_n:
                continue
            e = self.enc
            T = len(e.flavor_names)
            out = _topology_kernel(
                *self._device_arrays(),
                jnp.zeros((T, e.E), dtype=jnp.int64),
                jnp.full(N, -1, dtype=jnp.int32),
                jnp.zeros(N, dtype=jnp.int64),
                jnp.zeros(N, dtype=jnp.int32),
                jnp.zeros(N, dtype=bool), jnp.zeros(N, dtype=bool),
                shapes=(T, e.L, e.E, e.D, N))
            jax.block_until_ready(out)
            self._warm_n.add(N)
            done += 1
        return done

    # -- the stage -----------------------------------------------------------

    def placement_flavor(self, psa) -> Optional[str]:
        """The flavor whose nodes host this PodSet's pods: the first
        (sorted-resource order) assigned flavor that declares a topology."""
        index = self.enc.flavor_index
        for res in sorted(psa.flavors):
            fa = psa.flavors[res]
            name = fa.name if hasattr(fa, "name") else fa
            if name in index:
                return name
        return None

    def apply(self, workloads: Sequence, assignments: Sequence,
              used_by_flavor: Dict[str, np.ndarray],
              use_device: bool = False) -> None:
        """Run the fit search for every topology-requesting PodSet of the
        batch and fold the verdicts into the assignments."""
        used = self.enc.stack_used(used_by_flavor)
        items: List[tuple] = []
        slots: List[tuple] = []  # (assignment, podset idx, candidate seed)
        for wi, a in zip(workloads, assignments):
            pod_sets = wi.obj.pod_sets
            for p, psa in enumerate(a.pod_sets):
                if p >= len(pod_sets):
                    continue
                ps = pod_sets[p]
                req = ps.topology_required or ps.topology_preferred
                if req is None:
                    continue
                required = ps.topology_required is not None
                if psa.representative_mode == NO_FIT:
                    continue
                flavor = self.placement_flavor(psa)
                if flavor is None:
                    if required:
                        self._fail(a, psa,
                                   f"podset {psa.name}: no assigned flavor "
                                   f"declares a topology for required level "
                                   f"{req!r}")
                    continue
                ti = self.enc.flavor_index[flavor]
                lvl = self.enc.specs[ti].level_index(req)
                if lvl is None:
                    if required:
                        self._fail(a, psa,
                                   f"podset {psa.name}: flavor {flavor} has "
                                   f"no topology level {req!r}")
                    continue
                items.append((ti, psa.count, lvl, required))
                slots.append((wi, a, p, psa, ti, flavor, lvl, required))

        if not items:
            return
        results = self._solve_items(items, used, use_device)
        for (wi, a, p, psa, ti, flavor, lvl, required), \
                (level, domain, ok_now, could_ever) in zip(slots, results):
            cand = TopologyCandidate(
                ti=ti, flavor=flavor, req_level=lvl, required=required,
                count=psa.count, level=level, domain=domain, ok_now=ok_now,
                could_ever=could_ever)
            # getattr: native-decoded Assignments bypass __init__, leaving
            # the slot unset until the stage fills it.
            if getattr(a, "topology", None) is None:
                a.topology = [None] * len(a.pod_sets)
            while len(a.topology) < len(a.pod_sets):
                a.topology.append(None)
            a.topology[p] = cand
            if not required or ok_now:
                continue
            req_name = self.enc.specs[ti].levels[lvl]
            if not could_ever:
                self._fail(a, psa,
                           f"podset {psa.name}: no {req_name!r} domain of "
                           f"flavor {flavor} can ever fit {psa.count} pods")
            elif psa.representative_mode == PREEMPT:
                # Quota already demands preemption: keep PREEMPT and steer
                # the victim search toward freeing one contiguous domain.
                a.topology_hint = (flavor, req_name, psa.count)
            else:
                self._fail(
                    a, psa,
                    f"podset {psa.name}: insufficient free capacity in any "
                    f"{req_name!r} domain of flavor {flavor} "
                    f"({psa.count} pods)", mode=NO_FIT)

    @staticmethod
    def _fail(a, psa, reason: str, mode: int = NO_FIT) -> None:
        psa.reasons.append(reason)
        psa._mode = mode
        a._mode = None  # drop the memoized representative mode

    # -- admission-time re-check + leaf packing ------------------------------

    def charge(self, cycle_used: Dict[str, np.ndarray], cand,
               ps_name: str) -> Tuple[Optional[TopologyAssignment], bool]:
        """Re-validate a candidate against the cycle's leaf occupancy (an
        earlier admission this cycle may have consumed the domain), pack
        the pods onto leaves, and charge the cycle state. Returns
        (assignment-or-None, ok): (None, True) is a `preferred` PodSet
        placed unconstrained; (None, False) means the entry must be
        skipped this cycle."""
        enc = self.enc
        flavor = cand.flavor
        ti = cand.ti
        arr = cycle_used.get(flavor)
        if arr is None:
            arr = cycle_used[flavor] = np.zeros(
                len(enc.specs[ti].leaves), dtype=np.int64)
        used = np.zeros((len(enc.flavor_names), enc.E), dtype=np.int64)
        used[ti, :len(arr)] = arr
        level, domain, ok_now, _ = fit_host(
            enc, used, ti, cand.count, cand.req_level, cand.required)
        if not ok_now:
            if cand.required:
                return None, False
            return None, True  # preferred: place unconstrained, no charge
        counts = pack_leaves(enc, used, ti, level, domain, cand.count)
        if not counts and cand.count > 0:
            return (None, False) if cand.required else (None, True)
        for leaf, pods in counts:
            arr[leaf] += pods
        spec = enc.specs[ti]
        return TopologyAssignment(
            flavor=flavor,
            levels=spec.levels[:level + 1],
            domain=enc.domain_path(ti, level, domain),
            counts=tuple(counts)), True
