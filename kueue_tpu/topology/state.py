"""Topology leaf-occupancy ledger.

The quota books are per-(ClusterQueue, flavor, resource); topology slots
are per-flavor leaves shared by every ClusterQueue whose quota rides that
flavor (one node pool, many queues). The ledger is owned by the
admitted-workload cache and charged/released on exactly the same
transitions as quota (assume / add / forget / delete), reading each
admission's recorded `PodSetAssignment.topology_assignment` — so HA
journal replay, eviction, finish and MultiKueue mirrors all rebuild leaf
state for free through the cache paths they already traverse.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from kueue_tpu.api.types import Admission, ResourceFlavor


class TopologyLedger:
    """Per-flavor leaf occupancy (pods per leaf, spec.leaves order)."""

    __slots__ = ("flavors", "version")

    def __init__(self):
        self.flavors: Dict[str, np.ndarray] = {}
        self.version = 0

    def __bool__(self) -> bool:
        return bool(self.flavors)

    def set_flavor(self, rf: ResourceFlavor) -> None:
        """(Re)register a flavor. A topology-spec change resizes the leaf
        array; occupancy restarts from the admissions' recorded counts at
        the next cache rebuild (a structural change, like a CQ resource
        group rewrite, already invalidates resume state wholesale)."""
        spec = rf.topology
        if spec is None or not spec.leaves:
            if self.flavors.pop(rf.name, None) is not None:
                self.version += 1
            return
        cur = self.flavors.get(rf.name)
        n = len(spec.leaves)
        if cur is None or len(cur) != n:
            fresh = np.zeros(n, dtype=np.int64)
            if cur is not None:
                fresh[:min(len(cur), n)] = cur[:min(len(cur), n)]
            self.flavors[rf.name] = fresh
            self.version += 1

    def drop_flavor(self, name: str) -> None:
        if self.flavors.pop(name, None) is not None:
            self.version += 1

    def charge(self, admission: Optional[Admission], sign: int) -> None:
        """Fold one admission's topology assignments into the occupancy
        (sign=+1 on assume/add, -1 on forget/delete). No-op for
        assignments without topology placements."""
        if admission is None:
            return
        touched = False
        for psa in admission.pod_set_assignments:
            ta = psa.topology_assignment
            if ta is None:
                continue
            arr = self.flavors.get(ta.flavor)
            if arr is None:
                continue
            for leaf, pods in ta.counts:
                if 0 <= leaf < len(arr):
                    arr[leaf] += sign * pods
            touched = True
        if touched:
            self.version += 1

    def view(self) -> Dict[str, np.ndarray]:
        """Frozen copy for a tick snapshot."""
        return {name: arr.copy() for name, arr in self.flavors.items()}


class TopologyCycle:
    """The admission cycle's side-tracked leaf occupancy: a lazy copy of
    the live ledger that this cycle's charges mutate, so two admissions in
    one cycle cannot pack into the same free slots (the topology twin of
    `cycle_cohorts_usage`)."""

    __slots__ = ("used",)

    def __init__(self, ledger: TopologyLedger):
        self.used: Dict[str, np.ndarray] = {
            name: arr.copy() for name, arr in ledger.flavors.items()}
