"""kueue_tpu.tracing: span-based tick tracing + admission explainability.

One process-wide tracer (`TRACER`, the metrics-REGISTRY idiom) feeds
three consumers from the same measurements: the
`kueue_tick_phase_seconds` histogram, bench.py's `phase_means_ms`, and
the Chrome-trace export served at `GET /debug/traces` / written by
`--trace-out`. Disabled (the default) it compiles down to the plain
histogram observations the pipeline always made — zero ring-buffer
writes, byte-identical scheduling decisions (pinned by goldens).

Enable with `KUEUE_TPU_TRACE=1`, the `--trace-out` CLI flag, or
`TRACER.configure(enabled=True)`.
"""

from __future__ import annotations

from kueue_tpu import knobs
from kueue_tpu.tracing.tracer import (
    DEVICE_LANE,
    NULL_SPAN,
    TickTrace,
    Tracer,
    merge_chrome_traces,
    trace_now,
    validate_chrome_trace,
)

# Defined BEFORE the explain import below: explain reaches into
# solver/core modules whose import chain circles back to
# `from kueue_tpu.tracing import TRACER` — by then this name must exist
# on the partially initialized package.
TRACER = Tracer(enabled=knobs.flag("KUEUE_TPU_TRACE"))

from kueue_tpu.tracing.explain import ExplainStore, build_record  # noqa: E402

__all__ = [
    "DEVICE_LANE",
    "ExplainStore",
    "NULL_SPAN",
    "TRACER",
    "TickTrace",
    "Tracer",
    "build_record",
    "merge_chrome_traces",
    "trace_now",
    "validate_chrome_trace",
]
