"""Per-workload admission explainability.

Schedulers in the literature keep finding that per-decision records are
what make policy bugs and stragglers debuggable at scale (Gavel,
arxiv 2008.09213; topology-aware preemption for co-located LLM
workloads, arxiv 2411.11560). The reference surfaces only the final
Pending-condition message; this module retains the *story*: for every
scheduling attempt of every workload, which flavors were tried, the
fit/borrow/preempt verdict per (podSet, resource, flavor), the topology
domain chosen (or the level it blocked at), and the final outcome.

Records are stored as flat tuples on the hot path (the scheduler appends
one per entry per tick — the EventRecorder discipline) and materialized
into JSON-shaped dicts only on read, through the visibility API
(`?explain=true`) and the state Dumper.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from kueue_tpu.solver.modes import MODE_NAMES

# Final outcomes of one scheduling attempt.
ADMITTED = "Admitted"          # quota assumed this cycle
PREEMPTING = "Preempting"      # victims evicted; requeued pending quota
SKIPPED = "Skipped"            # lost an in-cycle race (cohort/topology/stale)
INADMISSIBLE = "Inadmissible"  # no nomination (quota/validation/namespace)


class ExplainStore:
    """Bounded per-workload decision-record retention.

    `per_workload` attempts are kept per workload key (newest win), at
    most `max_workloads` keys total with LRU eviction — memory stays
    O(max_workloads * per_workload) regardless of churn."""

    def __init__(self, per_workload: int = 8, max_workloads: int = 10_000):
        self.per_workload = per_workload
        self.max_workloads = max_workloads
        self._lock = threading.Lock()
        self._records: "OrderedDict[str, deque]" = OrderedDict()

    def record(self, key: str, rec: tuple) -> None:
        self.record_bulk(((key, rec),))

    def record_bulk(self, items) -> None:
        """Append [(key, rec)] under ONE lock acquisition — the scheduler
        lands one record per entry per tick (a thousand at scale), and
        the per-record lock/LRU churn dominated `record` otherwise."""
        with self._lock:
            records = self._records
            per = self.per_workload
            max_workloads = self.max_workloads
            for key, rec in items:
                dq = records.get(key)
                if dq is None:
                    dq = records[key] = deque(maxlen=per)
                    if len(records) > max_workloads:
                        records.popitem(last=False)
                else:
                    records.move_to_end(key)
                dq.append(rec)

    def record_repeats(self, keys, tick_seq: int, now: float) -> None:
        """Quiescent-tick collapse: the scheduler proved this attempt's
        outcome identical to each workload's previous one, so instead of
        rebuilding an identical record per head, the LAST record's
        tick/time advance and its repeat counter bumps (surfaced as
        `repeats` — "this exact decision held for N attempts"). Keys
        with no prior record (shouldn't happen on a quiescent tick) are
        ignored."""
        with self._lock:
            records = self._records
            for key in keys:
                dq = records.get(key)
                if not dq:
                    continue
                rec = dq[-1]
                reps = rec[9] if len(rec) > 9 else 1
                dq[-1] = (tick_seq, now) + tuple(rec[2:9]) + (reps + 1,)

    def forget(self, key: str) -> None:
        with self._lock:
            self._records.pop(key, None)

    def for_workload(self, key: str) -> List[dict]:
        """Materialized decision records, oldest attempt first."""
        with self._lock:
            dq = self._records.get(key)
            recs = list(dq) if dq is not None else []
        return [_materialize(r) for r in recs]

    def last_decision(self, key: str) -> Optional[dict]:
        with self._lock:
            dq = self._records.get(key)
            rec = dq[-1] if dq else None
        return _materialize(rec) if rec is not None else None

    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._records)

    def snapshot(self, limit: int = 1000) -> Dict[str, dict]:
        """{workload key: last decision} for the Dumper (bounded)."""
        with self._lock:
            items = [(k, dq[-1]) for k, dq in self._records.items() if dq]
        return {k: _materialize(r) for k, r in items[-limit:]}


def build_record(entry, tick_seq: int, now: float, outcome: str) -> tuple:
    """Compact decision tuple for a finished scheduler Entry; `outcome`
    is one of the module constants (the scheduler maps its own entry
    statuses — this module never imports it back, keeping the
    tracing→scheduler edge one-directional).

    Layout: (tick, time, cluster_queue, outcome, reason, flavors,
             topology, preempted, hetero) where `flavors` is a tuple of
    (pod_set, resource, flavor, verdict, borrow), `topology` a tuple
    of (pod_set, flavor, level, domain, ok) — or None each — and
    `hetero` the hetero solve mode's override detail (flavor,
    first_fit_flavor, throughput, score, score_rank, podset_idx) when
    the chosen flavor beat the first-fit twin, None otherwise."""
    a = entry.assignment
    flavors: tuple = ()
    topology = None
    if a is not None:
        tried = []
        for ps in a.pod_sets:
            for resource, fa in ps.flavors.items():
                tried.append((ps.name, resource, fa.name,
                              MODE_NAMES.get(fa.mode, str(fa.mode)),
                              fa.borrow))
        flavors = tuple(tried)
        cands = getattr(a, "topology", None)
        if cands:
            topo = []
            for p, cand in enumerate(cands):
                if cand is None:
                    continue
                ps_name = a.pod_sets[p].name if p < len(a.pod_sets) else ""
                topo.append((ps_name, cand.flavor, cand.level, cand.domain,
                             cand.ok_now))
            topology = tuple(topo) or None
    preempted = len(entry.preemption_targets) \
        if entry.preemption_targets else 0
    return (tick_seq, now, entry.info.cluster_queue, outcome,
            entry.inadmissible_msg, flavors, topology, preempted,
            getattr(entry, "hetero", None))


def _materialize(rec: tuple) -> dict:
    tick, now, cq, outcome, reason, flavors, topology, preempted = rec[:8]
    hetero = rec[8] if len(rec) > 8 else None
    repeats = rec[9] if len(rec) > 9 else 1
    out = {
        "tick": tick,
        "time": now,
        "clusterQueue": cq,
        "outcome": outcome,
        "reason": reason,
        "flavors": [
            {"podSet": ps, "resource": r, "flavor": f, "verdict": v,
             "borrow": b}
            for ps, r, f, v, b in flavors],
    }
    if topology is not None:
        out["topology"] = [
            {"podSet": ps, "flavor": f, "level": lvl, "domain": dom,
             "fits": ok}
            for ps, f, lvl, dom, ok in topology]
    if preempted:
        out["preemptionTargets"] = preempted
    if hetero is not None:
        flavor, ff_flavor, tput, score, rank, ps_idx = hetero
        out["hetero"] = {
            "flavor": flavor,
            "firstFitFlavor": ff_flavor,
            "throughput": tput,
            "score": score,
            "scoreRank": rank,
            "podSetIndex": ps_idx,
        }
    if repeats > 1:
        out["repeats"] = repeats
    return out
