"""Span-based tick tracer (the operator-facing half of SURVEY §5).

The reference exposes whole-tick latency histograms (metrics.go:70-79);
this build's `kueue_tick_phase_seconds` histogram already splits the tick
into host phases — but a histogram cannot show *one slow tick*, where
lock-wait or fsync time hides inside a phase, or which bucket shape a
dispatch compiled against. This module adds that lens: an OTel-shaped,
dependency-free span tracer threaded through the tick pipeline
(scheduler phases, solver dispatch/collect, snapshot maintenance,
queue-manager lock waits, durable-journal fsyncs), exported in the
Chrome trace-event JSON format, loadable in Perfetto / chrome://tracing.

Design constraints, in order:

  * DISABLED COSTS NOTHING. The default tracer is off; `span()` then
    returns a shared no-op singleton (zero allocations, zero ring-buffer
    writes) and `lock(lk)` returns the lock itself. Scheduling decisions
    are byte-identical either way — pinned by goldens.
  * ONE TIMING SOURCE. `phase(name)` both feeds the
    `kueue_tick_phase_seconds` histogram AND (when enabled) records a
    span, so metrics, bench.py's `phase_means_ms`, and exported traces
    all derive from the same measurement and can never drift apart.
    Raw `time.perf_counter()` phase timing in the pipeline is now a lint
    violation (kueuelint OBS01).
  * BOUNDED MEMORY, SLOWEST RETAINED. Finished ticks land in a ring
    buffer (tail sampling: the most recent `ring_size` ticks) plus a
    small always-kept set of the `keep_slowest` slowest ticks ever seen
    (head sampling) — the tick an operator wants to look at is the p99
    outlier, which a plain ring would have evicted long before the
    export request arrives.

Thread-safety: span *finish* appends under one lock; span timing itself
is lock-free. Spans finished while a tick is open attach to that tick
(whatever thread they ran on — API-server threads' lock waits show up in
the tick that stalled on them); spans outside any tick go to a bounded
"loose" buffer exported alongside.
"""

from __future__ import annotations

import json
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional

from kueue_tpu.metrics import REGISTRY

# The tracer IS the pipeline's sanctioned perf_counter consumer (OBS01
# makes every other raw use in scheduler/solver/controllers an error).
_perf = _time.perf_counter  # kueuelint: disable=OBS01


def trace_now() -> float:
    """The tracer's monotonic clock (perf_counter). Pipeline code that
    needs a raw timestamp on the tracer's timebase (e.g. the solver's
    dispatch anchor that bench latency injection replays against) takes
    it from here, so kueuelint OBS01 can insist every other raw
    perf_counter in the tick pipeline goes through a phase span."""
    return _perf()


class _NullSpan:
    """Shared do-nothing span: the disabled tracer's only product."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value) -> None:
        pass


NULL_SPAN = _NullSpan()

# Synthetic tid for spans that time the DEVICE-side solve window
# (dispatch -> fetch) rather than host execution: exporting them on their
# own Perfetto lane makes the stage pipelining visible — tick T's
# in-flight solve overlapping tick T+1's host-side ingest/encode spans.
DEVICE_LANE = 99


class _Span:
    """One timed region. Context-manager; `set()` attaches attributes."""

    __slots__ = ("tracer", "name", "attrs", "t0", "t1", "tid")

    def __init__(self, tracer: "Tracer", name: str):
        self.tracer = tracer
        self.name = name
        self.attrs: Optional[Dict] = None

    def set(self, key, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def __enter__(self):
        self.tid = threading.get_ident()
        self.t0 = _perf()
        return self

    def __exit__(self, *exc):
        self.t1 = _perf()
        self.tracer._record(self)
        return False


class _PhaseSpan(_Span):
    """A span that is also a `kueue_tick_phase_seconds` observation."""

    __slots__ = ()

    def __exit__(self, *exc):
        t1 = self.t1 = _perf()
        REGISTRY.tick_phase_seconds.observe(self.name, value=t1 - self.t0)
        self.tracer._record(self)
        return False


class _PhaseTimer:
    """The disabled-tracer phase: histogram observation only (exactly the
    pre-tracer timing code), no span record."""

    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name

    def set(self, key, value) -> None:
        pass

    def __enter__(self):
        self.t0 = _perf()
        return self

    def __exit__(self, *exc):
        REGISTRY.tick_phase_seconds.observe(self.name, value=_perf() - self.t0)
        return False


class _LockSpan:
    """Times the *acquisition wait* of a lock/condition, then holds it for
    the with-block (release on exit). Only built when tracing is enabled —
    the disabled path hands back the lock object itself."""

    __slots__ = ("tracer", "name", "lk")

    def __init__(self, tracer: "Tracer", lk, name: str):
        self.tracer = tracer
        self.lk = lk
        self.name = name

    def __enter__(self):
        sp = _Span(self.tracer, self.name)
        sp.tid = threading.get_ident()
        sp.t0 = _perf()
        self.lk.acquire()
        sp.t1 = _perf()
        self.tracer._record(sp)
        return self.lk

    def __exit__(self, *exc):
        self.lk.release()
        return False


class TickTrace:
    """One finished tick: its own span plus every span that closed while
    it was open (any thread)."""

    __slots__ = ("seq", "label", "t0", "duration", "wall", "spans")

    def __init__(self, seq: int, label: str, t0: float, duration: float,
                 wall: float, spans: List[_Span]):
        self.seq = seq
        self.label = label
        self.t0 = t0
        self.duration = duration
        self.wall = wall
        self.spans = spans


class _TickCtx:
    __slots__ = ("tracer", "span")

    def __init__(self, tracer: "Tracer", label: str):
        self.tracer = tracer
        self.span = _Span(tracer, label)

    def __enter__(self):
        self.tracer._tick_open(self.span.name)
        self.span.__enter__()
        return self.span

    def __exit__(self, *exc):
        self.span.__exit__(*exc)
        self.tracer._tick_close(self.span)
        return False


class Tracer:
    """Thread-safe span recorder with head+tail tick sampling."""

    def __init__(self, enabled: bool = False, ring_size: int = 256,
                 keep_slowest: int = 32, loose_size: int = 2048):
        self.enabled = enabled
        self.ring_size = ring_size
        self.keep_slowest = keep_slowest
        self._lock = threading.Lock()
        self._epoch = _perf()
        self._epoch_wall = _time.time()
        self._seq = 0
        self._recent: deque = deque(maxlen=ring_size)
        # (duration, seq, TickTrace) kept sorted ascending; index 0 is the
        # fastest of the retained-slowest set (the eviction candidate).
        self._slowest: List[tuple] = []
        self._loose: deque = deque(maxlen=loose_size)
        self._tick_spans: Optional[List[_Span]] = None
        self._tick_label = ""

    # -- configuration ------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  ring_size: Optional[int] = None,
                  keep_slowest: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = enabled
            if ring_size is not None:
                self.ring_size = ring_size
                self._recent = deque(self._recent, maxlen=ring_size)
            if keep_slowest is not None:
                self.keep_slowest = keep_slowest
                # Sorted ascending by duration: trim from the fast end.
                excess = len(self._slowest) - keep_slowest
                if excess > 0:
                    del self._slowest[:excess]

    def reset(self) -> None:
        """Drop every recorded tick/span (test isolation)."""
        with self._lock:
            self._recent.clear()
            self._slowest.clear()
            self._loose.clear()
            self._tick_spans = None
            self._seq = 0

    # -- span construction --------------------------------------------------

    def span(self, name: str):
        """A plain timed region; no-op singleton when disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name)

    def phase(self, name: str):
        """A tick-phase region: always observes
        `kueue_tick_phase_seconds{phase=name}` on exit; records a span too
        when tracing is enabled. The single timing source for scheduler /
        solver / snapshot phase code (kueuelint OBS01)."""
        if not self.enabled:
            return _PhaseTimer(name)
        return _PhaseSpan(self, name)

    def lock(self, lk, name: str):
        """`with tracer.lock(self._cond, "queue.lock_wait"):` — times the
        acquisition wait as a span. Disabled: returns the lock itself, so
        the instrumented code path is byte-for-byte the plain `with lk:`."""
        if not self.enabled:
            return lk
        return _LockSpan(self, lk, name)

    def record_span(self, name: str, t0: float, t1: float,
                    lane: Optional[int] = None,
                    attrs: Optional[Dict] = None) -> None:
        """Record an already-timed region — the device-solve window
        between `solve_async`'s dispatch and `collect`'s fetch, which no
        with-block can bracket because host code runs other stages in
        between. `lane` substitutes a synthetic tid (see DEVICE_LANE) so
        Perfetto renders it on its own track, where its overlap with the
        NEXT tick's host-side stage spans is visible."""
        if not self.enabled:
            return
        sp = _Span(self, name)
        sp.tid = lane if lane is not None else threading.get_ident()
        sp.t0 = t0
        sp.t1 = t1
        if attrs:
            sp.attrs = dict(attrs)
        self._record(sp)

    def tick(self, label: str = "tick"):
        """Open a tick grouping: spans finished while it is open attach to
        it, and the finished tick enters the ring/slowest buffers."""
        if not self.enabled:
            return NULL_SPAN
        return _TickCtx(self, label)

    # -- recording ----------------------------------------------------------

    def _record(self, span: _Span) -> None:
        with self._lock:
            sink = self._tick_spans
            if sink is not None:
                sink.append(span)
            else:
                self._loose.append(span)

    def _tick_open(self, label: str) -> None:
        with self._lock:
            # Nested/concurrent tick opens collapse into the outer tick
            # (only reachable through misuse; never lose spans over it).
            if self._tick_spans is None:
                self._tick_spans = []
                self._tick_label = label

    def _tick_close(self, span: _Span) -> None:
        with self._lock:
            spans = self._tick_spans
            if spans is None:
                return
            self._tick_spans = None
            self._seq += 1
            rec = TickTrace(self._seq, span.name, span.t0,
                            span.t1 - span.t0,
                            self._epoch_wall + (span.t0 - self._epoch), spans)
            self._recent.append(rec)
            slowest = self._slowest
            if len(slowest) < self.keep_slowest:
                slowest.append((rec.duration, rec.seq, rec))
                slowest.sort(key=lambda t: t[:2])
            elif slowest and rec.duration > slowest[0][0]:
                slowest[0] = (rec.duration, rec.seq, rec)
                slowest.sort(key=lambda t: t[:2])

    # -- introspection ------------------------------------------------------

    def ticks(self) -> List[TickTrace]:
        """Retained ticks, oldest first, slowest-set merged in (dedup by
        sequence number)."""
        with self._lock:
            by_seq = {rec.seq: rec for _, _, rec in self._slowest}
            for rec in self._recent:
                by_seq[rec.seq] = rec
            return [by_seq[s] for s in sorted(by_seq)]

    def slowest_tick(self) -> Optional[TickTrace]:
        with self._lock:
            if not self._slowest:
                return None
            return self._slowest[-1][2]  # sorted ascending by duration

    # -- export -------------------------------------------------------------

    def _event(self, span: _Span) -> dict:
        ev = {
            "name": span.name,
            "ph": "X",
            "ts": round((span.t0 - self._epoch) * 1e6, 3),
            "dur": round((span.t1 - span.t0) * 1e6, 3),
            "pid": 1,
            "tid": span.tid,
            "cat": "kueue",
        }
        if span.attrs:
            ev["args"] = dict(span.attrs)
        return ev

    def export_chrome(self, slowest_only: bool = False) -> dict:
        """The Chrome trace-event JSON object format
        (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
        `{"traceEvents": [...]}` with complete ("X") events — Perfetto and
        chrome://tracing nest same-tid events by time containment, so
        parent/child needs no explicit links. `slowest_only` exports just
        the single slowest retained tick (bench.py's artifact)."""
        with self._lock:
            loose = list(self._loose)
        if slowest_only:
            slow = self.slowest_tick()
            ticks, loose = ([slow] if slow is not None else []), []
        else:
            ticks = self.ticks()
        events = [{"ph": "M", "name": "process_name", "pid": 1, "ts": 0,
                   "args": {"name": "kueue-tpu"}},
                  # The device-solve lane's label: spans recorded with
                  # lane=DEVICE_LANE (tick.stage.solve) group here.
                  {"ph": "M", "name": "thread_name", "pid": 1,
                   "tid": DEVICE_LANE, "ts": 0,
                   "args": {"name": "device solve (in flight)"}}]
        for rec in ticks:
            for span in rec.spans:
                ev = self._event(span)
                ev.setdefault("args", {})["tick"] = rec.seq
                events.append(ev)
        for span in loose:
            events.append(self._event(span))
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tracer": "kueue-tpu",
                "enabled": self.enabled,
                "ticks_retained": len(ticks),
                "epoch_unix": self._epoch_wall,
            },
        }

    def export_json(self, slowest_only: bool = False) -> str:
        return json.dumps(self.export_chrome(slowest_only=slowest_only))


def merge_chrome_traces(docs) -> dict:
    """Merge per-process Chrome trace docs into ONE Perfetto-loadable
    trace (the multi-process replica runtime's `GET /debug/traces`).

    `docs` is [(pid, process_name, chrome_doc), ...] or, in multi-host
    mode, [(pid, process_name, chrome_doc, host_id), ...]. Each
    process's tracer timestamps run on its own perf_counter timebase;
    the export's `epoch_unix` anchors that timebase to the wall clock,
    so events are REBASED onto the earliest epoch. Every event's pid
    becomes its process's lane, labeled by process_name metadata; with
    a host id the lane is ALSO labeled with its host (process_name
    carries "name @host" and a process_labels metadata row carries the
    bare host id, so Perfetto groups and filters by host alongside
    pid/tid). The reconcile commit protocol becomes visible as flow
    events: each replica's in-cycle `admit.reconcile.rtt` span (args:
    round) emits a flow start ("s") that finishes ("f") on the
    coordinator's matching `reconcile.round` span — the cross-process
    round trip drawn as an arrow. Hosts' wall clocks may disagree
    (emulated hosts share one, real ones drift); the rebase is
    epoch-anchored per process, and any residual skew that would point
    a flow arrow BACKWARDS in merged time is clamped to the sink, so
    the arrows survive cross-host clock rebasing."""
    norm = [(d + (None,)) if len(d) == 3 else d for d in docs]
    epochs = [d.get("otherData", {}).get("epoch_unix")
              for _, _, d, _ in norm]
    known = [e for e in epochs if isinstance(e, (int, float))]
    base = min(known) if known else 0.0
    events: List[dict] = []
    # Coordinator round spans by round id, for the flow-event sinks.
    rounds: Dict[object, dict] = {}
    ticks_retained = 0
    hosts: List[str] = []
    for (pid, name, doc, host), epoch in zip(norm, epochs):
        shift = ((epoch - base) * 1e6
                 if isinstance(epoch, (int, float)) else 0.0)
        label = f"{name} @{host}" if host else name
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "ts": 0, "args": {"name": label}})
        if host:
            events.append({"ph": "M", "name": "process_labels",
                           "pid": pid, "ts": 0,
                           "args": {"labels": str(host)}})
            if host not in hosts:
                hosts.append(host)
        ticks_retained += doc.get("otherData", {}).get("ticks_retained", 0)
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") == "M":
                continue
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift, 3)
            events.append(ev)
            rnd = (ev.get("args") or {}).get("round")
            if rnd is not None and ev.get("name") == "reconcile.round":
                rounds[rnd] = ev
    flows = []
    for ev in events:
        rnd = (ev.get("args") or {}).get("round")
        if rnd is None or ev.get("name") != "admit.reconcile.rtt":
            continue
        sink = rounds.get(rnd)
        if sink is None:
            continue
        end_ts = round(sink["ts"] + sink.get("dur", 0), 3)
        # Clock-skew clamp: a flow must not start after it finishes in
        # MERGED time, or Perfetto drops the arrow.
        start_ts = min(ev["ts"], end_ts)
        flows.append({"ph": "s", "id": int(rnd), "name": "reconcile",
                      "cat": "kueue", "pid": ev["pid"], "tid": ev["tid"],
                      "ts": start_ts})
        flows.append({"ph": "f", "bp": "e", "id": int(rnd),
                      "name": "reconcile", "cat": "kueue",
                      "pid": sink["pid"], "tid": sink["tid"],
                      "ts": end_ts})
    events.extend(flows)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tracer": "kueue-tpu",
            "merged_processes": len(norm),
            "ticks_retained": ticks_retained,
            "epoch_unix": base,
            "hosts": hosts,
        },
    }


def validate_chrome_trace(doc) -> List[str]:
    """Schema check for the Chrome trace-event JSON object format; returns
    problem strings (empty == valid, loads in Perfetto). Dependency-free
    twin of a JSON-schema validation, used by tests and `make trace-smoke`."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["top level must be a JSON object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing/empty name")
        if ph not in ("X", "B", "E", "M", "i", "C", "s", "t", "f"):
            problems.append(f"{where}: unknown phase {ph!r}")
        if not isinstance(ev.get("pid"), int):
            problems.append(f"{where}: pid must be an int")
        if ph in ("s", "t", "f") and ev.get("id") is None:
            problems.append(f"{where}: flow event needs an id")
        if ph in ("X", "B", "E", "i", "C", "s", "t", "f"):
            if not isinstance(ev.get("tid"), int):
                problems.append(f"{where}: tid must be an int")
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: ts must be a non-negative number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: dur must be a non-negative number")
        args = ev.get("args")
        if args is not None and not isinstance(args, dict):
            problems.append(f"{where}: args must be an object")
    return problems
