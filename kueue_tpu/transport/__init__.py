"""Multi-host transport for the replica scheduler.

The pieces that let the PR 9 replica runtime leave the single machine:

  * framing       — length-prefixed JSON frames (the wire format IS
                    journal lines) with partial-read reassembly;
  * socket_channel— the reliable seq/ack/resume channel implementing
                    the existing ReplicaChannel seam over TCP, plus the
                    coordinator-side ChannelListener;
  * faults        — seeded injectable delay/drop/reorder for drills;
  * replication   — coordinator-owned async replication of per-host
                    journal segments (fail-over without a shared fs);
  * watchdog      — BarrierStallError: the stalling pid/host/round
                    surfaced instead of a silent hang;
  * elastic       — backlog-driven replica scaling + Aryl-style
                    capacity loaning over the group-reassignment seam;
  * security      — TLS contexts + shared-token auth for the listener
                    (rejected hellos counted and logged);
  * lease_channel — lease CAS over the channel protocol (LeaseService
                    riding the listener + ChannelLeaseStore client),
                    so coordinator election needs no shared filesystem.

Kill switch: KUEUE_TPU_NO_SOCKET=1 forces the pipe transport
everywhere (the runtime falls back to PR 9's multiprocessing pipes).
"""

from kueue_tpu.transport.elastic import ElasticController
from kueue_tpu.transport.faults import (
    FaultInjector,
    FaultPlan,
    parse_fault_env,
)
from kueue_tpu.transport.lease_channel import (
    ChannelLeaseStore,
    LeaseService,
    LeaseUnavailable,
)
from kueue_tpu.transport.framing import (
    FrameDecoder,
    FrameError,
    decode_message,
    encode_frame,
    encode_message,
)
from kueue_tpu.transport.replication import JournalReplicator, host_state_dir
from kueue_tpu.transport.security import (
    client_tls_context,
    generate_self_signed,
    openssl_available,
    server_tls_context,
)
from kueue_tpu.transport.socket_channel import (
    PEER_RESTART,
    ChannelClosed,
    ChannelListener,
    SocketChannel,
    WorkerDiedError,
)
from kueue_tpu.transport.watchdog import BarrierStallError, barrier_deadline

__all__ = [
    "BarrierStallError",
    "ChannelClosed",
    "ChannelLeaseStore",
    "ChannelListener",
    "ElasticController",
    "FaultInjector",
    "FaultPlan",
    "FrameDecoder",
    "FrameError",
    "JournalReplicator",
    "LeaseService",
    "LeaseUnavailable",
    "PEER_RESTART",
    "SocketChannel",
    "WorkerDiedError",
    "barrier_deadline",
    "client_tls_context",
    "decode_message",
    "encode_frame",
    "encode_message",
    "generate_self_signed",
    "host_state_dir",
    "openssl_available",
    "parse_fault_env",
    "server_tls_context",
]
